"""Shared CLI runner behind the three reference entry points.

CLI parity (SURVEY.md section 5.6): the three top-level scripts keep the
reference's names and flag surface - `--lr --momentum --batch-size --epochs
--nb-proc --failure-probability --failure-duration`
(`data_parallelism_train.py:259-271`) - with properly *typed* flags (the
reference passed raw strings to SGD, so non-default `--lr` crashed it;
SURVEY.md section 2 quirks). Framework-specific extensions are added behind
new flags, defaults preserving reference behaviour.
"""

from __future__ import annotations

import argparse
import json
import os
import time

from ..data.cifar10 import load_split
from ..utils import timers as T
from ..utils import tracing as TR
from ..utils.logfiles import write_phase_logs
from ..utils.metrics import init_run
from .engine import Engine, TrainConfig


def add_common_flags(p: argparse.ArgumentParser, *, epochs: int, batch_size: int):
    p.add_argument("--lr", dest="lr", type=float, default=0.001)
    p.add_argument("--momentum", dest="momentum", type=float, default=0.9)
    p.add_argument("--batch-size", dest="bs", type=int, default=batch_size)
    p.add_argument("--epochs", dest="epochs", type=int, default=epochs)
    # framework extensions (not in the reference CLI)
    p.add_argument("--seed", type=int, default=0, help="PRNG seed (reference was unseeded)")
    p.add_argument(
        "--sync-mode",
        choices=("epoch", "step"),
        default="epoch",
        help="epoch = faithful local SGD + epoch-edge parameter averaging "
        "(reference semantics); step = per-step gradient pmean (idiomatic DP)",
    )
    p.add_argument(
        "--no-momentum-reset",
        action="store_true",
        help="keep momentum across epochs (reference re-creates SGD per epoch)",
    )
    p.add_argument(
        "--grad-sync",
        choices=("end", "overlap"),
        default="end",
        help="per-step gradient-sync granularity under --sync-mode step: "
        "end = one pmean per leaf; overlap = one pmean per size-capped "
        "leaf bucket (--bucket-mb), independent collectives XLA can "
        "overlap with backward compute (no effect in epoch mode)",
    )
    p.add_argument(
        "--bucket-mb",
        type=float,
        default=4.0,
        help="gradient-bucket payload cap in MiB for --grad-sync overlap",
    )
    p.add_argument(
        "--precision",
        choices=("bf16", "fp8", "int8", "int8-kv"),
        default="bf16",
        help="low-precision fast path selector (shared flag surface with "
        "lm_train.py / the serve CLI). The CNN engine itself has no "
        "quantized kernels - only 'bf16' (the full-precision contract) "
        "runs here; 'fp8'/'int8' quantize the LM's attention matmuls "
        "(lm_train.py --precision) and 'int8-kv' the serving KV cache "
        "(python -m distributed_neural_network_tpu.serve --precision)",
    )
    p.add_argument(
        "--compilation-cache-dir",
        default=None,
        help="persistent XLA compilation cache directory "
        "(jax_compilation_cache_dir): repeat runs of the same program "
        "deserialize instead of recompiling - the --step-stats compile "
        "field then records the cache-hit time, and the StepStats "
        "summary carries the cache dir for provenance",
    )
    p.add_argument(
        "--input-mode",
        choices=("hbm", "stream"),
        default="hbm",
        help="hbm = dataset uploaded to device memory once, whole epochs "
        "compiled (default); stream = dataset stays in host RAM (uint8), "
        "batches assembled per step by the native C++ kernel - for "
        "datasets larger than HBM",
    )
    p.add_argument(
        "--stream-prefetch",
        type=int,
        default=2,
        help="stream mode: batches assembled this many steps ahead on a "
        "background thread (2 = double buffering, 0 = synchronous)",
    )
    p.add_argument("--data", choices=("auto", "pickle", "npz", "synthetic"), default="auto")
    p.add_argument("--data-root", default=None, help="dataset dir (default ./data)")
    p.add_argument(
        "--synthetic-size",
        type=int,
        default=None,
        help="synthetic train rows (test = 1/5 of it); default: CIFAR-10 sizes",
    )
    p.add_argument("--log-dir", default="log", help="phase-time log directory")
    p.add_argument("--metrics-jsonl", default=None, help="metrics JSONL path")
    p.add_argument(
        "--run-record",
        default=None,
        metavar="RECORD.json",
        help="write the goodput run record here (utils/goodput.py: "
        "goodput ratio + per-cause badput seconds; written through "
        "during the run; render/diff/gate with tools/goodput.py). "
        "Defaults to the DNN_TPU_RUN_RECORD env the elastic supervisor "
        "exports; a GOODPUT summary line is printed either way",
    )
    p.add_argument("--neptune", action="store_true", help="also log to Neptune (env creds)")
    p.add_argument("--eval-batch-size", type=int, default=None)
    p.add_argument(
        "--compute-dtype", choices=("float32", "bfloat16"), default="float32"
    )
    p.add_argument(
        "--kernels",
        choices=("xla", "pallas"),
        default="xla",
        help="pallas = fused Pallas classifier-head kernel (VMEM-resident "
        "weights; equivalent plain-jnp math off-TPU)",
    )
    p.add_argument("--eval-every", type=int, default=1)
    p.add_argument(
        "--checkpoint-dir",
        default=None,
        help="save params+momentum+history at epoch edges (SURVEY.md sec. 5.4)",
    )
    p.add_argument("--checkpoint-every", type=int, default=1, help="epochs between saves")
    p.add_argument("--checkpoint-keep", type=int, default=3, help="checkpoints retained")
    p.add_argument(
        "--checkpoint-backend", choices=("auto", "orbax", "npz"), default="auto"
    )
    p.add_argument(
        "--resume",
        action="store_true",
        help="resume from the latest checkpoint in --checkpoint-dir",
    )
    p.add_argument(
        "--elastic",
        action="store_true",
        help="elastic resume (parallel/reshard.py, docs/ROBUSTNESS.md): "
        "accept a checkpoint written under a DIFFERENT --nb-proc and "
        "reshard the per-device momentum stack onto this mesh (shrink: "
        "surviving workers keep their buffers; grow: new workers start "
        "with zero momentum). Without it a worker-count mismatch is a "
        "hard error",
    )
    p.add_argument(
        "--fused",
        action="store_true",
        help="run multi-epoch compiled spans (one dispatch per span) instead "
        "of one dispatch per phase per epoch - the fast path; phase timing "
        "then reports train+sync(+eval at --eval-every 1) as one TRAINING "
        "number. Silently downgraded to the per-epoch path when combined "
        "with --failure-duration > 0 (straggler sleeps can only interleave "
        "between epochs) or --input-mode stream",
    )
    # training-dynamics observatory (train/dynamics.py,
    # docs/OBSERVABILITY.md "Training dynamics")
    p.add_argument(
        "--dynamics",
        action="store_true",
        help="measure replica-divergence at each parameter-averaging "
        "sync (max/mean per-layer parameter distance across workers, "
        "in-jit, just before the average collapses it): live "
        "dynamics_replica_div_* gauges, dynamics/* metrics series, and "
        "a 'dynamics' trace track; disables --fused (the divergence "
        "rides the per-epoch sync dispatch)",
    )
    # self-healing guard layer (train/guard.py, docs/ROBUSTNESS.md)
    p.add_argument(
        "--guard",
        choices=("off", "warn", "skip", "rollback", "abort"),
        default="off",
        help="per-epoch training guard: warn = count/log anomalies "
        "(non-finite loss, EMA loss spikes); skip = drop an anomalous "
        "epoch's update (pre-epoch snapshot restored); rollback = restore "
        "the rolling snapshot and retry with LR backoff (bounded by "
        "--max-retries); abort = stop with an actionable error",
    )
    p.add_argument(
        "--guard-spike-zscore",
        type=float,
        default=6.0,
        help="loss-spike threshold in EMA standard deviations "
        "(anomaly when loss > mean + z*sigma; non-finite always counts)",
    )
    p.add_argument(
        "--snapshot-every",
        type=int,
        default=1,
        help="epochs between the guard's rolling in-memory host snapshots "
        "(a rollback rewinds at most this far)",
    )
    p.add_argument(
        "--max-retries",
        type=int,
        default=3,
        help="guard rollback budget before abort (refills after a stretch "
        "of healthy epochs)",
    )
    p.add_argument(
        "--on-sigterm",
        choices=("checkpoint", "ignore"),
        default="checkpoint",
        help="checkpoint = on SIGTERM/SIGINT finish the current epoch, "
        "write an emergency checkpoint (when --checkpoint-dir is set) and "
        "exit cleanly for exact resume; ignore = default signal behavior",
    )
    p.add_argument(
        "--profile-dir",
        default=None,
        help="capture a jax.profiler trace of the training run into this dir "
        "(SURVEY.md sec. 5.1 - the reference had only wall-clock brackets)",
    )
    # step-level telemetry (utils/tracing.py, docs/OBSERVABILITY.md)
    p.add_argument(
        "--trace-out",
        default=None,
        metavar="TRACE.json",
        help="write a Chrome trace-event JSON of the run (span per "
        "train_step/sync/eval, one track per phase) - open in Perfetto or "
        "chrome://tracing, summarize with tools/trace_summary.py",
    )
    p.add_argument(
        "--step-stats",
        action="store_true",
        help="collect per-step StepStats (compile vs steady-state step "
        "time, images/s, device memory, collective bytes, MFU), print the "
        "summary, and emit step/* series to --metrics-jsonl",
    )
    # live runtime observability (utils/obs.py + train/monitor.py,
    # docs/OBSERVABILITY.md "Live monitoring")
    p.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        metavar="PORT",
        help="serve live Prometheus metrics on http://127.0.0.1:PORT"
        "/metrics plus a /healthz JSON liveness/readiness endpoint "
        "(0 = ephemeral port, printed at startup); also starts the "
        "stall/recompile/checkpoint watchdog unless --watchdog off",
    )
    p.add_argument(
        "--metrics-linger",
        type=float,
        default=0.0,
        metavar="SEC",
        help="keep the metrics server up this many seconds after the run "
        "finishes (final scrape window for CI / external scrapers)",
    )
    p.add_argument(
        "--watchdog",
        choices=("on", "off"),
        default="on",
        help="with --metrics-port: background watchdog flagging stalled "
        "steps (no heartbeat for N x steady p95 step time), recompile "
        "storms, and checkpoint staleness as watchdog/* trace events + "
        "watchdog_*_total counters (train/monitor.py)",
    )
    p.add_argument(
        "--watchdog-escalate",
        choices=("none", "preempt"),
        default="none",
        help="preempt = a persistent stall requests the cooperative "
        "SIGTERM-style preemption path (emergency checkpoint at the next "
        "step boundary, then clean exit) instead of burning the "
        "reservation wedged; requires --on-sigterm checkpoint",
    )
    return p


def add_distributed_flags(p: argparse.ArgumentParser, *, nb_proc: int = 4):
    p.add_argument(
        "--nb-proc",
        dest="nb_proc",
        type=int,
        default=nb_proc,
        help="mesh data-axis size (reference: MPI world size)",
    )
    p.add_argument(
        "--failure-probability",
        dest="failure_probability",
        type=float,
        default=0.0,
        help="Probability of simulated process failure at each epoch",
    )
    p.add_argument(
        "--failure-duration",
        dest="failure_duration",
        type=float,
        default=0.0,
        help="Duration of simulated process failure in seconds",
    )
    p.add_argument(
        "--reference-compat",
        action="store_true",
        help="N-1 compute workers at --nb-proc N, as the reference's idle-parent "
        "topology (default: all N devices train)",
    )
    p.add_argument(
        "--sharding",
        choices=("manual", "auto"),
        default="manual",
        help="auto derives --nb-proc statically instead of taking it as "
        "given: the largest worker count that fits the visible devices "
        "AND divides the global batch (the engine's divisibility "
        "contract; analysis/autoshard.py auto_nb_proc) - the CNN "
        "engine's one free sharding choice, decided by the same "
        "declarative layer the LM mesh search uses",
    )
    return p


def config_from_args(args, regime: str) -> TrainConfig:
    return TrainConfig(
        lr=args.lr,
        momentum=args.momentum,
        batch_size=args.bs,
        epochs=args.epochs,
        nb_proc=getattr(args, "nb_proc", None),
        regime=regime,
        sync_mode=args.sync_mode,
        reset_momentum=not args.no_momentum_reset,
        failure_probability=getattr(args, "failure_probability", 0.0),
        failure_duration=getattr(args, "failure_duration", 0.0),
        seed=args.seed,
        eval_batch_size=args.eval_batch_size,
        compute_dtype=args.compute_dtype,
        kernels=getattr(args, "kernels", "xla"),
        reference_compat=getattr(args, "reference_compat", False),
        input_mode=getattr(args, "input_mode", "hbm"),
        stream_prefetch=getattr(args, "stream_prefetch", 2),
        grad_sync=getattr(args, "grad_sync", "end"),
        bucket_mb=getattr(args, "bucket_mb", 4.0),
        dynamics=getattr(args, "dynamics", False),
    )


def enable_compilation_cache(path: str) -> bool:
    """Point jax's persistent compilation cache at `path` (created on
    first write). Compile-time floor/size gates are zeroed so even the
    tiny smoke programs cache - the point here is measuring cache-hit
    compile time via StepStats, not saving only the big programs.
    Returns False (never raises) on jax versions without the knobs."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", path)
    except Exception:
        return False
    for knob, val in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(knob, val)
        except Exception:
            pass  # optional tuning knobs; the cache dir alone suffices
    return True


def honor_platform_env() -> None:
    """Re-assert JAX_PLATFORMS from the environment over plugin overrides.

    Some TPU plugin site hooks force their platform into jax.config at
    interpreter start, which makes `JAX_PLATFORMS=cpu` (the documented way to
    run these CLIs on N virtual CPU devices, SURVEY.md sec. 4) silently
    ineffective. If the user set the env var, it wins.
    """
    env = os.environ.get("JAX_PLATFORMS")
    if env:
        import jax

        if jax.config.jax_platforms != env:
            jax.config.update("jax_platforms", env)


def run_training(args, regime: str, *, log=print) -> Engine:
    """Load data, train, write phase logs - the shared main() body.

    Owns the live-observability lifecycle (`train/monitor.py`): the
    preemption guard and `--metrics-port` monitor (registry + /metrics +
    /healthz server + watchdog) are created up front, threaded through
    the engine/guard/checkpointer, and closed on every exit path - after
    an optional `--metrics-linger` window so external scrapers can read
    the final counters.
    """
    # goodput wall clock zero: before data load / rendezvous / compile so
    # the init bucket owns them (utils/goodput.py; no-op when it is the
    # process ledger already started by an outer harness)
    from ..utils.goodput import LEDGER as G_LEDGER

    G_LEDGER.reset()  # one ledger per run (tests reuse the process)
    G_LEDGER.start()
    if getattr(args, "run_record", None):
        G_LEDGER.arm(args.run_record)

    precision = getattr(args, "precision", "bf16")
    if precision != "bf16":
        raise SystemExit(
            f"--precision {precision}: the CNN engine has no quantized "
            "kernels (its conv/dense matmuls are full precision); the "
            "fp8/int8 fast path lives in the LM stack - lm_train.py "
            "--precision fp8|int8 for training, python -m "
            "distributed_neural_network_tpu.serve --precision int8-kv "
            "for the serving KV cache (docs/MEASUREMENT.md)"
        )

    honor_platform_env()
    from ..parallel.distributed import initialize as distributed_initialize

    if distributed_initialize():
        import jax

        log(
            f"(Multi-host: process {jax.process_index()}/{jax.process_count()}, "
            f"{jax.device_count()} global devices)"
        )
    cache_dir = getattr(args, "compilation_cache_dir", None)
    if cache_dir:
        if enable_compilation_cache(cache_dir):
            log(f"(Persistent compilation cache: {cache_dir})")
        else:
            log(
                "(WARNING: this jax version has no persistent compilation "
                "cache config; --compilation-cache-dir ignored)"
            )
            cache_dir = None
    if getattr(args, "sharding", "manual") == "auto":
        import jax

        from ..analysis.autoshard import auto_nb_proc

        chosen = auto_nb_proc(args.bs, jax.device_count())
        log(
            f"(--sharding auto: nb_proc {getattr(args, 'nb_proc', None)} "
            f"-> {chosen}: largest worker count dividing batch {args.bs} "
            f"on {jax.device_count()} device(s))"
        )
        args.nb_proc = chosen
    cfg = config_from_args(args, regime)
    timers = T.PhaseTimers()

    trace_out = getattr(args, "trace_out", None)
    want_stats = getattr(args, "step_stats", False)
    tracer = TR.Tracer(enabled=bool(trace_out))
    # fleet identity (multi-process groups, e.g. under the elastic
    # supervisor): rank-stamped process metadata + per-rank trace shards
    # tools/trace_merge.py can merge (utils/tracing.py)
    rank = TR.detect_rank()
    if rank is not None:
        import socket as _socket

        tracer.set_process(rank=rank, hostname=_socket.gethostname())
        if trace_out:
            trace_out = TR.rank_trace_path(trace_out, rank)
            args.trace_out = trace_out
            log(f"(per-rank trace shard: {trace_out})")

    from .guard import PreemptionGuard
    from .monitor import WatchdogConfig, attach_monitor

    preemption = None
    if getattr(args, "on_sigterm", "ignore") == "checkpoint":
        preemption = PreemptionGuard(log=log).install()
    monitor = attach_monitor(
        metrics_port=getattr(args, "metrics_port", None),
        tracer=tracer,
        preemption=preemption,
        watchdog=getattr(args, "watchdog", "on") == "on",
        config=WatchdogConfig(
            escalate_after_polls=(
                5
                if getattr(args, "watchdog_escalate", "none") == "preempt"
                and preemption is not None
                else 0
            ),
        ),
        # on-demand /profile captures land next to the Chrome trace; the
        # whole-run --profile-dir capture is a separate (exclusive) path
        profile_dir=(
            os.path.dirname(os.path.abspath(trace_out)) if trace_out
            else None
        ),
        rank=rank,
        log=log,
    )
    try:
        return _run_training_body(
            args, regime, log=log, cfg=cfg, timers=timers, tracer=tracer,
            preemption=preemption, monitor=monitor, cache_dir=cache_dir,
            trace_out=trace_out, want_stats=want_stats,
        )
    finally:
        linger = getattr(args, "metrics_linger", 0.0) or 0.0
        if monitor.server is not None and linger > 0:
            log(f"(metrics server lingering {linger:g}s for final scrapes)")
            time.sleep(linger)
        if preemption is not None:
            preemption.uninstall()
        monitor.close()


def _run_training_body(
    args, regime: str, *, log, cfg, timers, tracer, preemption, monitor,
    cache_dir, trace_out, want_stats,
) -> Engine:
    registry = monitor.registry
    syn = getattr(args, "synthetic_size", None)
    with tracer.span(TR.DATA_LOADING, track="host"), timers.phase(T.DATA_LOADING):
        train_split = load_split(
            True,
            root=args.data_root,
            source=args.data,
            seed=args.seed,
            synthetic_size=syn,
            # streaming keeps the train split as uint8 in host RAM; the
            # native kernel normalizes per batch
            normalize_images=cfg.input_mode != "stream",
        )
        test_split = load_split(
            False,
            root=args.data_root,
            source=args.data,
            seed=args.seed,
            synthetic_size=max(1, syn // 5) if syn else None,
        )
    log(
        f"(Loaded train dataset of length {len(train_split)} "
        f"[source={train_split.source}], test length {len(test_split)})"
    )

    run = init_run(jsonl_path=args.metrics_jsonl, neptune=args.neptune)
    run["parameters"] = {
        "learning_rate": cfg.lr,
        "optimizer": "SGD",
        "model_name": {"single": "nodistmodel"}.get(regime, "distmodel"),
        "epochs": cfg.epochs,
        "batch_size": cfg.batch_size,
        "regime": regime,
        "sync_mode": cfg.sync_mode,
        "nb_proc": cfg.nb_proc,
        "seed": cfg.seed,
    }

    t0 = time.perf_counter()
    engine = Engine(
        cfg, train_split, test_split, tracer=tracer, registry=registry
    )
    from ..utils.goodput import LEDGER as G_LEDGER

    G_LEDGER.describe(
        config={
            "regime": regime, "epochs": cfg.epochs,
            "batch_size": cfg.batch_size, "lr": cfg.lr,
            "nb_proc": cfg.nb_proc, "sync_mode": cfg.sync_mode,
            "seed": cfg.seed, "compute_dtype": cfg.compute_dtype,
            "input_mode": cfg.input_mode, "kernels": cfg.kernels,
        },
        mesh={
            "axes": {"data": engine.n_workers},
            "devices": engine.n_workers,
            "desc": f"data{engine.n_workers}",
            "optimizer": "sgd",
        },
    )

    stats = None
    if want_stats or trace_out:
        import jax

        from .measure import peak_flops

        flops, flops_src = engine.flops_per_epoch()
        stats = TR.StepStats(
            item_label="images",
            # step/* series ride the existing metrics sinks; without
            # --step-stats the trace still embeds the aggregate summary
            sink=run if want_stats else None,
            n_devices=engine.n_workers,
            comm_bytes_per_step=TR.collective_bytes_per_sync(
                engine.params, engine.n_workers
            ),
            flops_per_step=flops,
            flops_source=flops_src,
            peak_flops_per_device=peak_flops(
                jax.devices()[0].device_kind, cfg.compute_dtype
            ),
            grad_sync=cfg.grad_sync if cfg.sync_mode == "step" else None,
            compilation_cache_dir=cache_dir,
            registry=registry,
        )
        engine.step_stats = stats
        if cfg.sync_mode == "step" and cfg.grad_sync == "overlap":
            # put the bucket plan in-band in the trace (the collectives
            # run inside the compiled epoch where spans can't see them)
            from ..parallel.collectives import plan_buckets

            layout = plan_buckets(
                engine.params, bucket_bytes=int(cfg.bucket_mb * 2**20)
            )
            stats.comm_bucket_bytes = [int(b) for b in layout.bucket_bytes()]
            TR.record_bucket_plan(
                tracer, stats.comm_bucket_bytes, schedule="overlap",
                op="pmean", axis_size=engine.n_workers,
            )

    checkpointer = None
    start_epoch = 0
    if getattr(args, "resume", False) and not getattr(args, "checkpoint_dir", None):
        raise SystemExit("--resume requires --checkpoint-dir")
    if getattr(args, "checkpoint_dir", None):
        from ..utils.checkpoint import Checkpointer

        checkpointer = Checkpointer(
            args.checkpoint_dir,
            every=args.checkpoint_every,
            keep=args.checkpoint_keep,
            backend=args.checkpoint_backend,
            registry=registry,
        )
        if args.resume:
            start_epoch = checkpointer.restore_latest(
                engine, elastic=getattr(args, "elastic", False), log=log
            )
            if start_epoch:
                log(f"(Resumed from checkpoint: next epoch {start_epoch})")
            else:
                log(
                    f"(WARNING: --resume found no checkpoint in "
                    f"{args.checkpoint_dir} [backend={checkpointer.backend_name}]; "
                    "starting from scratch - check the dir and "
                    "--checkpoint-backend match the original run)"
                )

    # self-healing layer (train/guard.py): per-epoch policy guard; the
    # cooperative preemption guard was installed by run_training before
    # the monitor (its escalation path needs it)
    from .guard import GuardConfig, TrainingGuard

    guard = None
    if getattr(args, "guard", "off") != "off":
        guard = TrainingGuard(
            GuardConfig(
                policy=args.guard,
                spike_zscore=getattr(args, "guard_spike_zscore", 6.0),
                snapshot_every=getattr(args, "snapshot_every", 1),
                max_retries=getattr(args, "max_retries", 3),
                # one observation per epoch: arm the spike detector after
                # a few epochs rather than the step-scale default
                warmup_steps=3,
            ),
            tracer=tracer, step_stats=stats, registry=registry, log=log,
        )

    profile_dir = getattr(args, "profile_dir", None)
    if profile_dir:
        import jax

        jax.profiler.start_trace(profile_dir)
    if monitor.recompiles is not None:
        # cache-miss counting on the engine's compiled epoch step: the
        # watchdog turns a burst of misses into the recompile-storm flag
        monitor.recompiles.swap(engine._train_fn)
        engine.recompiles = monitor.recompiles

    try:
        engine.run(
            timers=timers,
            run=run,
            log=log,
            eval_every=args.eval_every,
            checkpointer=checkpointer,
            start_epoch=start_epoch,
            fused=getattr(args, "fused", False),
            guard=guard,
            preemption=preemption,
        )
    finally:
        if profile_dir:
            import jax

            try:
                # a failed fused dispatch may have consumed (donated) params;
                # never let the fence mask the original exception or skip
                # stop_trace/close below
                from ..utils.timers import hard_block

                hard_block(engine.params)
            except Exception:
                pass
            jax.profiler.stop_trace()
            log(f"(Profiler trace written to {profile_dir})")
        if checkpointer is not None:
            checkpointer.close()
    wall = time.perf_counter() - t0

    if guard is not None:
        log(f"(guard summary: {json.dumps(guard.summary())})")

    # goodput close-out: conservation-asserted breakdown + run record
    goodput_rec = G_LEDGER.finalize(metrics={
        "final_train_loss": engine.history[-1].train_loss
        if engine.history else None,
        "final_val_acc": engine.history[-1].val_acc
        if engine.history else None,
        "epochs": cfg.epochs,
        "preempted": bool(preemption.requested) if preemption else False,
    })
    log("GOODPUT " + json.dumps({
        "goodput_ratio": goodput_rec["goodput_ratio"],
        "wall_s": goodput_rec["wall_s"],
        "goodput_s": goodput_rec["goodput_s"],
        "badput_s": {k: v for k, v in goodput_rec["badput_s"].items()
                     if v > 0},
        "steps": goodput_rec["steps"],
        "record": G_LEDGER.path,
    }))

    if stats is not None and want_stats:
        for line in stats.report().splitlines():
            log(line)
    if trace_out:
        tracer.export(trace_out, step_stats=stats, goodput=goodput_rec)
        log(
            f"(Chrome trace written to {trace_out}; open in Perfetto / "
            "chrome://tracing, or summarize with tools/trace_summary.py)"
        )
    run.stop()

    # the reference's five epoch-phase accumulators, live on /metrics as
    # phase_seconds_total{phase=...} (utils/obs.py) - not just log/*.txt
    from ..utils.obs import publish_phase_timers

    publish_phase_timers(registry, timers)

    # the canonical phase-summary block (utils/timers.py report(); the
    # reference's stdout phrasing, shared with every other entry point)
    for line in timers.report().splitlines():
        log(line)
    log(f"Total wall-clock: {wall:.3f} s")

    if args.log_dir:
        nb_proc = getattr(args, "nb_proc", None) or 1
        parent, children = write_phase_logs(
            args.log_dir,
            bs=cfg.batch_size,
            epochs=cfg.epochs,
            nb_proc=nb_proc,
            timers=timers,
        )
        log(f"(Phase logs written: {parent}, {children})")

    best = max(
        (m for m in engine.history if m.val_acc is not None),
        key=lambda m: m.val_acc,
        default=None,
    )
    summary = {
        "regime": regime,
        "epochs": cfg.epochs,
        "guard": getattr(args, "guard", "off"),
        "preempted": bool(preemption.requested) if preemption else False,
        "final_train_loss": engine.history[-1].train_loss if engine.history else None,
        "final_val_acc": engine.history[-1].val_acc if engine.history else None,
        "best_val_acc": best.val_acc if best else None,
        "wall_clock_s": round(wall, 3),
        "data_source": train_split.source,
    }
    log("SUMMARY " + json.dumps(summary))
    return engine


def main(argv=None) -> int:
    """`python -m distributed_neural_network_tpu.train.cli` - the smoke /
    telemetry harness behind the three top-level scripts.

    Same flag surface plus `--regime`; defaults are deliberately tiny
    (synthetic data, 2048 rows, all available devices) so a bare
    `python -m ... --epochs 1 --trace-out trace.json --step-stats` runs in
    seconds on a CPU host. Full-scale runs use the top-level entry points
    (single_proc_train.py / model_replication_train.py /
    data_parallelism_train.py), whose defaults mirror the reference.
    """
    import argparse as _argparse

    parser = _argparse.ArgumentParser(
        prog="python -m distributed_neural_network_tpu.train.cli",
        description=main.__doc__,
        formatter_class=_argparse.RawDescriptionHelpFormatter,
    )
    add_common_flags(parser, epochs=2, batch_size=16)
    add_distributed_flags(parser, nb_proc=None)
    parser.add_argument(
        "cmd",
        nargs="?",
        choices=("smoke",),
        default=None,
        help="optional subcommand alias: 'smoke' names the default tiny "
        "synthetic run explicitly (CI: python -m ...train.cli smoke "
        "--metrics-port 0)",
    )
    parser.add_argument(
        "--regime",
        choices=("single", "data_parallel", "replication"),
        default="data_parallel",
    )
    # tiny-by-default: the module runner is for smoke runs and telemetry
    # capture, not baseline numbers (--data/--synthetic-size override)
    parser.set_defaults(data="synthetic", synthetic_size=2048)
    args = parser.parse_args(argv)
    run_training(args, args.regime)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
