"""Subpackage: train."""
