"""Elastic multi-process supervisor: rendezvous, failure detection, and
shrink/grow group restarts across real process boundaries.

The reference's multi-process story is ``mpiexec -n N`` plus a fault
*simulator* (`data_parallelism_train.py:41-46`) - a dead worker is a
``time.sleep``, and a REAL dead worker kills the whole mpiexec group. The
elastic machinery this repo grew in PR 6 (`parallel/reshard.py`,
`train/elastic.py`) removed the mesh-shape restriction from checkpoints,
but had only ever been exercised *inside one process*
(``--chaos-shrink-at-step``). This module is the missing process layer -
the single-node analog of a cluster manager's job controller:

- **Rendezvous.** The supervisor owns coordinator port allocation
  (`reserve_port`) and spawns N workers, each joining the JAX runtime
  through the standard env-var handshake (`parallel/distributed.py
  initialize()` - bounded retry/backoff on the worker side). A group that
  dies *before* every worker has come up (port stolen between allocation
  and bind, a straggler host) is a **rendezvous failure**: the whole
  group is torn down and relaunched at the same size on a FRESH port,
  under its own bounded retry budget - the bind-race fix that
  `tests/test_multiprocess.py` used to be exposed to.
- **Failure detection.** Workers are monitored via exit codes and a
  heartbeat file each one writes (`utils/obs.py HeartbeatFileWriter`, fed
  by the PR 5 metrics registry: writer liveness + last step). A non-zero
  exit, a delivered signal, or (optionally) a stale heartbeat marks the
  worker dead.
- **Shrink restart.** On a worker death the survivors get SIGTERM -
  triggering the PR 3 cooperative-preemption path (finish the step, write
  an emergency checkpoint, exit 0) when they are not wedged in a
  collective with the dead peer - then SIGKILL after a grace window. The
  group relaunches with the surviving worker count; the worker command's
  ``{nprocs}``/``{devices}`` tokens re-substitute, so an
  ``lm_train.py --resume --elastic`` workload reshards the newest
  consistent checkpoint onto the smaller mesh and continues with the
  global batch and data cursor intact (`train/elastic.py`).
- **Grow/rejoin.** When the group runs below target and capacity returns
  (``capacity_fn``; full target on a single node), a *planned* restart -
  graceful SIGTERM, emergency checkpoints, relaunch at the larger size -
  rejoins the freed slots. Opt-in via ``grow_after_s`` (the healthy-time
  hysteresis that stops a flapping host from thrashing the group).
- **Restart budget.** Failure restarts consume a bounded budget with
  exponential backoff between attempts; a crash-looping group exhausts it
  and fails FAST with the last failure named (`SUPERVISOR ABORT`), never
  flapping forever. Rendezvous retries are budgeted separately (they are
  startup races, not workload crashes).

Process-level chaos (`parallel/fault.py ProcessChaos`: kill rank R with
SIGKILL/SIGTERM once its heartbeat reaches step S; rank 0 = coordinator
death) is driven from this loop, so the whole
detect -> checkpoint -> reshard -> resume story is exercised end to end
across genuine process boundaries (`tools/launch.py --chaos-kill-*`,
tests/test_supervisor.py, the supervisor-chaos-smoke CI job).

- **Goodput accounting** (utils/goodput.py): every worker gets a
  ``DNN_TPU_RUN_RECORD`` path next to its heartbeat/flight files; the
  supervisor aggregates the per-rank write-through run records plus its
  own restart-gap measurements (death -> respawn, with a failure-
  relaunched generation's init+compile reclassified into the
  ``restart_gap`` bucket) into one fleet record - exported live as
  ``goodput_ratio`` / ``badput_seconds_total{cause}``, written to
  ``run_dir/run_record.json``, and embedded in ``postmortem.json`` and
  the ``SUPERVISOR_SUMMARY`` line (docs/OBSERVABILITY.md "Goodput
  accounting"; gate with ``tools/goodput.py --check``).
- **Fleet federation + postmortems** (the observability layer on top):
  `FleetFederation` turns the per-worker heartbeat files and (when
  workers open ``--metrics-port``) their scraped ``/metrics`` endpoints
  into rank-labeled fleet metrics with per-step straggler attribution
  (``fleet_worker_step{rank}``, ``fleet_step_skew_seconds``,
  ``fleet_straggler_rank``); on every failure restart or abort the
  supervisor bundles each rank's crash flight-recorder dump
  (`utils/obs.py FlightRecorder`, pointed at ``run_dir/flight/...`` via
  ``DNN_TPU_FLIGHT_FILE``) plus exit causes into ``postmortem.json``
  (docs/OBSERVABILITY.md "Fleet observability").

Everything here is stdlib-only (no jax import): the supervisor must keep
running when a worker's runtime is wedged, and the unit tests drive it
with plain-python dummy workers. Live metrics ride the same registry as
everything else (`utils/obs.py`): ``supervisor_group_size``,
``worker_failures_total{signal}``, ``elastic_restarts_total{direction}``,
``supervisor_restart_seconds``, the ``fleet_*`` family - rendered by
`tools/live_top.py`'s fleet view.
Semantics: docs/ROBUSTNESS.md "Elastic supervisor".
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request
from dataclasses import dataclass

HEARTBEAT_ENV = "DNN_TPU_HEARTBEAT_FILE"
FLIGHT_ENV = "DNN_TPU_FLIGHT_FILE"
RUN_RECORD_ENV = "DNN_TPU_RUN_RECORD"

# exit code a SUPERVISED worker uses for "preempted cleanly" (emergency
# checkpoint written, exiting on request) - EX_TEMPFAIL. Exit 0 means the
# workload is DONE; without a distinct code the supervisor could not tell
# a finished worker from one that was asked to step aside and must be
# relaunched (lm_train.py returns this when DNN_TPU_SUPERVISOR is set).
PREEMPT_RC = 75

# restart-latency histogram bounds: sub-second dummy-worker relaunches up
# to multi-minute real-group teardowns (grace + SIGKILL + rendezvous)
RESTART_SECONDS_BUCKETS = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0,
)


def reserve_port(host: str = "127.0.0.1") -> int:
    """A currently-free TCP port from the OS.

    The OS hands out a distinct ephemeral port per call, which makes the
    classic allocate->close->bind race *rare*, not impossible - another
    process can still take it before the coordinator binds. The fix is
    not a cleverer allocator but ownership: the supervisor reserves a
    FRESH port for every group launch and treats a group that dies during
    rendezvous as retryable (`SupervisorConfig.rendezvous_retries`), so a
    lost race costs one relaunch instead of a failed run.
    `tests/test_multiprocess.py` reuses this allocator + the retry idiom
    instead of rolling its own.
    """
    with socket.socket() as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        return int(s.getsockname()[1])


def read_heartbeat(path: str) -> dict | None:
    """Parse one heartbeat file (`utils/obs.py HeartbeatFileWriter`
    schema: {"t", "beat_unix", "step", "pid", "rank", "hostname",
    "metrics_url"} - the last three are fleet-attribution additions and
    absent from old files, which stay parseable); None when absent or
    torn (the writer publishes atomically, but the first write may not
    have landed yet)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def signal_label(returncode: int) -> str:
    """Prometheus-friendly failure label: killed-by-signal exits name the
    signal (SIGKILL/SIGTERM/...), a clean preemption exit is "preempt",
    plain failures are exit:<code>."""
    if returncode == PREEMPT_RC:
        return "preempt"
    if returncode < 0:
        try:
            return signal.Signals(-returncode).name
        except ValueError:
            return f"signal:{-returncode}"
    return f"exit:{returncode}"


# step-skew histogram bounds (seconds): sub-poll-resolution lockstep up
# to a multi-minute wedged straggler
SKEW_SECONDS_BUCKETS = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 300.0,
)


class FleetFederation:
    """Aggregate per-rank worker telemetry into the SUPERVISOR's registry
    - the fleet view a single-process `/metrics` endpoint cannot give.

    Two input channels per worker, both already flowing:

    - the **heartbeat file** (`utils/obs.py HeartbeatFileWriter`:
      begin_step + step + rank + metrics_url), read every supervisor
      poll. Step ARRIVALS are timestamped here: the first poll at which
      rank r reports step >= S is r's arrival at S (resolution = the
      poll cadence, fine for straggler work - a stall is seconds, a
      poll is 0.2 s). From arrivals come ``fleet_worker_step{rank}``,
      ``fleet_worker_step_seconds{rank}`` (per-rank step time), and two
      attribution signals: **begin-step divergence** - in a
      synchronized SPMD group a host-wedged rank sits at begin_step S
      while its peers (already dispatched, blocked in the collective)
      report S+1, so the minimum-begin rank is the straggler even
      though COMPLETIONS are delayed equally on every rank - and
      **arrival skew**: once every live rank has arrived at step S, the
      arrival spread is one ``fleet_step_skew_seconds`` histogram
      observation and spreads over ``attrib_min_skew_s`` attribute the
      last arriver (``fleet_straggler_rank`` gauge +
      ``fleet_straggler_total{rank}``; lockstep spreads with no begin
      divergence set the gauge to -1).
    - the worker's **/metrics endpoint** when it opened one
      (``--metrics-port``; the URL is advertised in the heartbeat file),
      scraped every ``scrape_interval_s``: a whitelist of families is
      re-exported with a ``rank`` label (``fleet_train_loss{rank}``,
      ``fleet_train_steps_total{rank}``, ...), and the worker's
      ``train_step_seconds`` histogram sum/count deltas refine the
      heartbeat-derived step-time gauge with fenced wall time.

    Validated against the `parallel/fault.py` stall injector: a
    ``--chaos-stall-step`` rank arrives late at its next step and is
    flagged as the straggler (tests/test_fleet_obs.py, the fleet-obs CI
    smoke). Stdlib-only, like everything else in this module.
    """

    SCRAPE_FAMILIES = (
        "train_loss",
        "train_steps_total",
        "train_throughput_items_per_s",
        "recompiles_total",
        "watchdog_stall_total",
        "guard_rollbacks_total",
        # per-rank goodput (utils/goodput.py ledger export) -> the fleet
        # view shows each worker's own efficiency next to the aggregate
        "goodput_ratio",
    )

    def __init__(
        self,
        registry,
        *,
        scrape_interval_s: float = 2.0,
        http_timeout_s: float = 1.0,
        attrib_min_skew_s: float = 0.25,
    ):
        self.registry = registry
        self.scrape_interval_s = float(scrape_interval_s)
        self.http_timeout_s = float(http_timeout_s)
        self.attrib_min_skew_s = float(attrib_min_skew_s)
        self._m_step = registry.gauge(
            "fleet_worker_step", "Last heartbeat step, per rank"
        )
        self._m_up = registry.gauge(
            "fleet_worker_up", "1 while the rank's process is alive"
        )
        self._m_step_s = registry.gauge(
            "fleet_worker_step_seconds",
            "Per-rank step time (heartbeat arrivals, refined by scrape)",
        )
        self._m_straggler = registry.gauge(
            "fleet_straggler_rank",
            "Rank attributed straggler of the newest completed step "
            "(-1 = none / lockstep)",
        )
        self._m_straggler_total = registry.counter(
            "fleet_straggler_total",
            "Steps on which a rank was attributed straggler, by rank",
        )
        self._m_skew_last = registry.gauge(
            "fleet_last_step_skew_seconds",
            "Arrival spread (max-min) of the newest completed step",
        )
        self._m_skew = registry.histogram(
            "fleet_step_skew_seconds",
            "Per-step cross-rank arrival spread (max-min)",
            buckets=SKEW_SECONDS_BUCKETS,
        )
        self._m_scrapes = registry.counter(
            "fleet_scrapes_total", "Worker /metrics scrapes attempted"
        )
        self._m_scrape_errors = registry.counter(
            "fleet_scrape_errors_total", "Worker /metrics scrapes failed"
        )
        self._m_straggler.set(-1)
        # per-rank (step, t) of the newest arrival; per-step {rank: t}
        self._arrival: dict[int, tuple[int, float]] = {}
        self._step_t: dict[int, dict[int, float]] = {}
        self._begin: dict[int, int] = {}
        self._last_begin_attrib: tuple | None = None
        self._last_scrape: dict[int, float] = {}
        self._scrape_hist: dict[int, tuple[float, float]] = {}

    def observe(self, rank: int, hb: dict, *, alive: bool = True,
                now: float | None = None) -> None:
        """One rank's heartbeat doc, once per poll."""
        now = time.time() if now is None else now
        r = str(rank)
        self._m_up.labels(rank=r).set(1 if alive else 0)
        begin = hb.get("begin_step")
        if begin is not None:
            self._begin[rank] = int(begin)
        step = hb.get("step")
        if step is None:
            return
        step = int(step)
        self._m_step.labels(rank=r).set(step)
        last = self._arrival.get(rank)
        if last is None or step > last[0]:
            if last is not None:
                per = (now - last[1]) / (step - last[0])
                self._m_step_s.labels(rank=r).set(per)
            self._arrival[rank] = (step, now)
            self._step_t.setdefault(step, {})[rank] = now

    def finish_poll(self, live_ranks) -> None:
        """Close out this poll's attribution. Two signals, by failure
        shape:

        - **begin-step divergence** (synchronized SPMD wedges): a rank
          stalled host-side sits at begin_step S while its peers -
          whose NEXT steps are already dispatched and merely blocked in
          the collective - report S+1; the minimum-begin rank is the
          straggler. Completion times cannot tell them apart (the
          collective delays everyone equally), begins can.
        - **arrival skew** (unsynchronized phases, distinct processes):
          once every live rank has arrived at step S, the arrival
          spread feeds the skew histogram, and spreads over
          ``attrib_min_skew_s`` attribute the last arriver.
        """
        live = set(live_ranks)
        if not live:
            return
        begins = {
            r: self._begin[r] for r in live if r in self._begin
        }
        lagging = None
        if len(begins) > 1 and max(begins.values()) > min(begins.values()):
            lagging = min(begins, key=lambda r: begins[r])
            self._m_straggler.set(lagging)
            key = (lagging, begins[lagging])
            if key != self._last_begin_attrib:
                self._last_begin_attrib = key
                self._m_straggler_total.labels(rank=str(lagging)).inc()
        for step in sorted(self._step_t):
            t = self._step_t[step]
            if not live <= set(t):
                continue
            if len(live) > 1:
                # skew/straggler only exist across >= 2 ranks; a group
                # shrunk to one rank keeps its last attribution instead
                # of being reset by meaningless single-rank "steps"
                ts = {r: t[r] for r in live}
                skew = max(ts.values()) - min(ts.values())
                self._m_skew.observe(skew)
                self._m_skew_last.set(skew)
                if skew >= self.attrib_min_skew_s:
                    straggler = max(ts, key=lambda r: ts[r])
                    self._m_straggler.set(straggler)
                    self._m_straggler_total.labels(
                        rank=str(straggler)
                    ).inc()
                elif lagging is None:
                    # lockstep arrivals only clear the gauge when no
                    # begin-divergence attribution is live this poll
                    self._m_straggler.set(-1)
            del self._step_t[step]
        # bound memory: a rank that died mid-step leaves its steps open
        if len(self._step_t) > 128:
            for step in sorted(self._step_t)[:-64]:
                del self._step_t[step]

    def drop_rank(self, rank: int) -> None:
        """Forget a dead rank's arrival state (a relaunch re-learns it)."""
        self._arrival.pop(rank, None)
        self._begin.pop(rank, None)
        self._last_scrape.pop(rank, None)
        self._scrape_hist.pop(rank, None)
        self._m_up.labels(rank=str(rank)).set(0)

    # ------------------------------------------------------------ scraping

    def maybe_scrape(self, rank: int, url: str,
                     now: float | None = None) -> bool:
        """Scrape one worker's /metrics (rate-limited) and re-export the
        whitelisted families with a rank label. Returns True on a scrape
        attempt (tests drive cadence with the now parameter)."""
        now = time.time() if now is None else now
        last = self._last_scrape.get(rank)
        if last is not None and now - last < self.scrape_interval_s:
            return False
        self._last_scrape[rank] = now
        self._m_scrapes.inc()
        try:
            with urllib.request.urlopen(
                url.rstrip("/") + "/metrics", timeout=self.http_timeout_s
            ) as r:
                text = r.read().decode()
        except (urllib.error.URLError, OSError, ValueError):
            self._m_scrape_errors.inc()
            return True
        self.ingest(rank, text)
        return True

    def ingest(self, rank: int, text: str) -> None:
        """Fold one scraped exposition body into the fleet registry."""
        from ..utils.obs import parse_prom_samples

        fams = parse_prom_samples(text)
        r = str(rank)
        for name in self.SCRAPE_FAMILIES:
            fam = fams.get(name)
            if not fam:
                continue
            counter = name.endswith("_total")
            m = (
                self.registry.counter(f"fleet_{name}")
                if counter else self.registry.gauge(f"fleet_{name}")
            )
            for key, val in fam.items():
                labels = dict(key)
                labels["rank"] = r
                child = m.labels(**labels)
                # re-exported counters move monotonically even if a
                # scrape raced a worker restart
                (child.set_max if counter else child.set)(val)
        # refine the per-rank step time with the worker's own fenced
        # step-seconds histogram (sum/count delta since the last scrape)
        s = sum((fams.get("train_step_seconds_sum") or {}).values())
        c = sum((fams.get("train_step_seconds_count") or {}).values())
        if c > 0:
            ps, pc = self._scrape_hist.get(rank, (0.0, 0.0))
            if c > pc:
                self._m_step_s.labels(rank=r).set((s - ps) / (c - pc))
            self._scrape_hist[rank] = (s, c)


@dataclass
class SupervisorPolicy:
    """The failure-response POLICY half of the supervisor's knobs: what
    the supervisor DOES when workers die (how many restarts, at what
    backoff, down to what size, growing back after how long) - as
    opposed to HOW it runs processes (ports, polling, device flags,
    `SupervisorConfig` below).

    Extracted as its own type so the fleet digital twin
    (`analysis/fleetsim.py`) replays EXACTLY the struct the real
    supervisor executes: one config type, two consumers - a policy tuned
    in simulation is the object a launch runs, field for field, and a
    knob added here is automatically a searchable dimension there.
    """

    nprocs: int
    min_procs: int = 1
    # failure-restart budget for the whole run; exhausted -> fail fast
    max_restarts: int = 3
    restart_backoff_s: float = 1.0
    backoff_cap_s: float = 30.0
    # SIGTERM -> SIGKILL grace when stopping survivors (long enough for a
    # healthy worker to finish its step + emergency checkpoint)
    grace_s: float = 10.0
    # 0 = never grow; > 0 = after a shrunk group has been healthy this
    # long AND capacity_fn() reports free slots, do a planned grow restart
    grow_after_s: float = 0.0

    def __post_init__(self):
        if self.nprocs < 1:
            raise ValueError(f"nprocs must be >= 1, got {self.nprocs}")
        if not 1 <= self.min_procs <= self.nprocs:
            raise ValueError(
                f"min_procs must be in [1, nprocs={self.nprocs}], got "
                f"{self.min_procs}"
            )
        if self.max_restarts < 0:
            raise ValueError("max_restarts must be >= 0")
        for name in ("restart_backoff_s", "grace_s"):
            if getattr(self, name) <= 0:
                raise ValueError(f"{name} must be > 0")

    def backoff_for(self, attempt: int) -> float:
        """Backoff pause before failure restart number ``attempt``
        (1-based): exponential from ``restart_backoff_s``, capped."""
        return min(
            self.restart_backoff_s * (2 ** (max(int(attempt), 1) - 1)),
            self.backoff_cap_s,
        )

    def policy_dict(self) -> dict:
        """The policy as a plain JSON-safe dict (fleetsim records embed
        it so a simulated ranking names the exact knobs it ranked)."""
        import dataclasses

        return {
            f.name: getattr(self, f.name)
            for f in dataclasses.fields(SupervisorPolicy)
        }

    @classmethod
    def from_policy_dict(cls, doc: dict) -> "SupervisorPolicy":
        """Inverse of `policy_dict`; unknown keys are ignored so a
        config-shaped dict (or an older record) loads as its policy."""
        import dataclasses

        known = {f.name for f in dataclasses.fields(SupervisorPolicy)}
        return cls(**{k: v for k, v in doc.items() if k in known})


@dataclass
class SupervisorConfig(SupervisorPolicy):
    """Knobs for `Supervisor`; `tools/launch.py` maps them 1:1 to flags.
    Extends `SupervisorPolicy` (the failure-response knobs the fleetsim
    twin shares) with the process-runner half: devices, rendezvous,
    heartbeat staleness, polling."""

    devices_per_proc: int = 1
    # force_host_devices: append --xla_force_host_platform_device_count to
    # each worker's XLA_FLAGS (the CPU dev/CI mode); off for real
    # accelerators where the local device count is the hardware's
    force_host_devices: bool = True
    # startup races (coordinator port lost, worker died before the full
    # group ever heartbeat) retry on a fresh port under their own budget
    rendezvous_retries: int = 2
    rendezvous_timeout_s: float = 120.0
    # after a failure is detected, wait this long (or until everyone has
    # exited) before freezing the failure set: a gang crash's deaths
    # straddle poll boundaries, and without the settle a whole-group
    # crash can be misread as a partial one (spurious below-min-procs
    # abort instead of a same-size restart)
    failure_settle_s: float = 0.5
    # 0 = exit codes only; > 0 additionally treats a worker whose TRAINING
    # heartbeat (beat_unix) is older than this as dead (armed only after
    # the worker's first beat - compilation produces none)
    heartbeat_timeout_s: float = 0.0
    poll_s: float = 0.2
    host: str = "127.0.0.1"

    def __post_init__(self):
        super().__post_init__()
        if self.devices_per_proc < 1:
            raise ValueError(
                f"devices_per_proc must be >= 1, got {self.devices_per_proc}"
            )
        if self.rendezvous_retries < 0:
            raise ValueError("rendezvous_retries must be >= 0")
        if self.poll_s <= 0:
            raise ValueError("poll_s must be > 0")
        if self.failure_settle_s < 0:
            raise ValueError("failure_settle_s must be >= 0")

    def policy(self) -> SupervisorPolicy:
        """The pure-policy view of this config (a `SupervisorPolicy`
        copy - what `analysis/fleetsim.py` simulates)."""
        return SupervisorPolicy.from_policy_dict(self.policy_dict())


@dataclass
class _Worker:
    rank: int
    proc: subprocess.Popen
    hb_path: str
    log_path: str
    log_file: object
    flight_path: str = ""
    returncode: int | None = None
    ever_beat: bool = False

    def poll(self) -> int | None:
        if self.returncode is None:
            rc = self.proc.poll()
            if rc is not None:
                self.returncode = rc
                try:
                    self.log_file.close()
                except Exception:
                    pass
        return self.returncode

    def alive(self) -> bool:
        return self.poll() is None

    def kill(self, sig: int) -> None:
        if self.alive():
            try:
                self.proc.send_signal(sig)
            except (ProcessLookupError, OSError):
                pass


class Supervisor:
    """Spawn and babysit one elastic training group (see module docs).

    ``command`` is the worker argv; every element may carry the tokens
    ``{rank}`` (this worker's process id), ``{nprocs}`` (the CURRENT
    group size), and ``{devices}`` (nprocs * devices_per_proc - what an
    ``lm_train.py --dp {devices}`` mesh should span), re-substituted on
    every (re)launch so a shrink/grow restart reshapes the workload.
    ``capacity_fn() -> int`` reports how many worker slots are currently
    available (defaults to the full target - the single-node case);
    ``chaos`` is a `parallel/fault.py ProcessChaos` plan driven from the
    monitor loop. `run()` blocks until the group completes (rc 0), the
    restart budget is exhausted (rc 3), or rendezvous never succeeds
    (rc 4), and prints one machine-readable ``SUPERVISOR_SUMMARY {json}``
    line either way.
    """

    def __init__(
        self,
        command: list,
        config: SupervisorConfig,
        *,
        run_dir: str,
        chaos=None,
        base_env: dict | None = None,
        registry=None,
        capacity_fn=None,
        federation: FleetFederation | None = None,
        log=print,
    ):
        self.command = [str(c) for c in command]
        self.cfg = config
        self.run_dir = os.path.abspath(run_dir)
        self.chaos = chaos
        self.base_env = dict(base_env if base_env is not None else os.environ)
        self.capacity_fn = capacity_fn or (lambda: config.nprocs)
        self.log = log
        if registry is None:
            from ..utils.obs import MetricsRegistry

            registry = MetricsRegistry()
        self.registry = registry
        self._m_size = registry.gauge(
            "supervisor_group_size", "Live worker count of the elastic group"
        )
        self._m_target = registry.gauge(
            "supervisor_target_size", "Configured target worker count"
        )
        self._m_budget = registry.gauge(
            "supervisor_restart_budget_remaining",
            "Failure restarts left before the group fails fast",
        )
        self._m_failures = registry.counter(
            "worker_failures_total",
            "Worker deaths observed, by signal/exit label",
        )
        self._m_restarts = registry.counter(
            "elastic_restarts_total",
            "Group restarts, by direction (shrink/grow/rendezvous)",
        )
        self._m_restart_s = registry.histogram(
            "supervisor_restart_seconds",
            "Failure detection -> group respawned latency",
            buckets=RESTART_SECONDS_BUCKETS,
        )
        self._m_postmortems = registry.counter(
            "supervisor_postmortems_total",
            "Postmortem bundles written (failure restarts + aborts)",
        )
        # fleet goodput accounting (utils/goodput.py): the supervisor
        # aggregates the per-worker write-through run records plus its
        # own restart-gap measurements and re-exports the fleet view
        self._m_goodput = registry.gauge(
            "goodput_ratio",
            "Fleet fraction of capacity-seconds spent in steady steps",
        )
        self._m_badput = registry.counter(
            "badput_seconds_total",
            "Fleet capacity-seconds lost to non-goodput causes, by cause",
        )
        self._m_gap_last = registry.gauge(
            "supervisor_restart_gap_seconds",
            "Newest worker-death -> first-post-restart-step window",
        )
        # per-rank fleet metrics + straggler attribution + /metrics
        # federation, on the same registry tools/launch.py serves
        self.federation = (
            federation if federation is not None
            else FleetFederation(registry)
        )
        self.postmortem_path = os.path.join(self.run_dir, "postmortem.json")
        self.postmortems_written = 0
        self.workers: list[_Worker] = []
        self.generation = -1
        self.n = config.nprocs
        self.port: int | None = None
        self.restarts_used = 0
        self.rendezvous_used = 0
        self.failures: list[dict] = []
        self._group_started = 0.0
        self._healthy_since: float | None = None
        # goodput bookkeeping: supervisor-measured restart gaps
        # (death -> respawn, in capacity-seconds at the relaunched size),
        # the generations that exist BECAUSE of a failure restart (their
        # ranks' init+compile reclassify into restart_gap at aggregation),
        # and the open death -> first-post-restart-step window
        self.restart_gaps: list[dict] = []
        self.restart_generations: set[int] = set()
        self._gap_open: float | None = None
        self._goodput_published = 0.0
        self.fleet_goodput: dict | None = None
        # a reused run dir must not leak the previous run's liveness or
        # crash state into this one (mirrors the checkpointers' stale
        # step_*.tmp sweep): a relaunch reading an old heartbeat would
        # see a phantom live worker, an old flight dump would corrupt the
        # next postmortem, an old run record the goodput aggregation
        swept = self._sweep_stale_run_dir()
        if swept:
            self.log(
                f"(supervisor: swept {swept} stale heartbeat/flight/"
                f"record/postmortem file(s) from reused {self.run_dir})"
            )
        os.makedirs(os.path.join(self.run_dir, "hb"), exist_ok=True)
        os.makedirs(os.path.join(self.run_dir, "logs"), exist_ok=True)
        os.makedirs(os.path.join(self.run_dir, "flight"), exist_ok=True)
        os.makedirs(os.path.join(self.run_dir, "records"), exist_ok=True)
        self._m_target.set(config.nprocs)
        self._m_budget.set(config.max_restarts)

    def _sweep_stale_run_dir(self) -> int:
        """Remove a previous run's state files from this run dir (the
        subdirs this supervisor owns, plus postmortem.json and the fleet
        run_record.json); never raises - a sweep failure must not block
        the launch. Logs are kept (they are the previous run's evidence,
        and generation-numbered names make them non-ambiguous)."""
        swept = 0
        for sub in ("hb", "flight", "records"):
            d = os.path.join(self.run_dir, sub)
            try:
                names = os.listdir(d)
            except OSError:
                continue
            for name in names:
                if not (name.endswith(".json") or ".json.tmp" in name):
                    continue
                try:
                    os.unlink(os.path.join(d, name))
                    swept += 1
                except OSError:
                    pass
        for name in ("postmortem.json", "run_record.json"):
            try:
                os.unlink(os.path.join(self.run_dir, name))
                swept += 1
            except OSError:
                pass
        return swept

    # ------------------------------------------------------------- spawn

    def _worker_argv(self, rank: int, n: int) -> list:
        devices = n * self.cfg.devices_per_proc
        sub = {
            "{rank}": str(rank), "{nprocs}": str(n),
            "{devices}": str(devices),
        }
        out = []
        for arg in self.command:
            for k, v in sub.items():
                arg = arg.replace(k, v)
            out.append(arg)
        return out

    def _worker_env(self, rank: int, n: int, port: int, hb_path: str,
                    flight_path: str, record_path: str = "") -> dict:
        env = dict(self.base_env)
        if self.cfg.force_host_devices:
            # replace (not append) any inherited device-count flag: the
            # supervisor's parent env often carries its own (conftest,
            # dev shells), and the WORKER's count must win unambiguously
            kept = [
                f for f in env.get("XLA_FLAGS", "").split()
                if not f.startswith("--xla_force_host_platform_device_count")
            ]
            kept.append(
                "--xla_force_host_platform_device_count="
                f"{self.cfg.devices_per_proc}"
            )
            env["XLA_FLAGS"] = " ".join(kept)
        env["JAX_COORDINATOR_ADDRESS"] = f"{self.cfg.host}:{port}"
        env["JAX_NUM_PROCESSES"] = str(n)
        env["JAX_PROCESS_ID"] = str(rank)
        env[HEARTBEAT_ENV] = hb_path
        # per-worker crash flight recorder (utils/obs.py FLIGHT): the
        # worker's write-through dump lands here and is bundled into
        # postmortem.json on failure - even after a SIGKILL
        env[FLIGHT_ENV] = flight_path
        if record_path:
            # per-worker goodput run record (utils/goodput.py LEDGER):
            # write-through like the flight dump, aggregated fleet-wide
            env[RUN_RECORD_ENV] = record_path
        env["DNN_TPU_SUPERVISOR"] = "1"
        env["DNN_TPU_SUPERVISOR_GEN"] = str(self.generation)
        return env

    def _spawn_group(self, n: int) -> None:
        self.generation += 1
        self.n = n
        self.port = reserve_port(self.cfg.host)
        self.workers = []
        g = self.generation
        for rank in range(n):
            hb_path = os.path.join(
                self.run_dir, "hb", f"gen{g}_rank{rank}.json"
            )
            log_path = os.path.join(
                self.run_dir, "logs", f"gen{g}_rank{rank}.log"
            )
            flight_path = os.path.join(
                self.run_dir, "flight", f"gen{g}_rank{rank}.json"
            )
            record_path = os.path.join(
                self.run_dir, "records", f"gen{g}_rank{rank}.json"
            )
            log_file = open(log_path, "w")
            argv = self._worker_argv(rank, n)
            proc = subprocess.Popen(
                argv,
                env=self._worker_env(
                    rank, n, self.port, hb_path, flight_path, record_path
                ),
                stdout=log_file,
                stderr=subprocess.STDOUT,
            )
            self.workers.append(
                _Worker(rank, proc, hb_path, log_path, log_file,
                        flight_path)
            )
        self._group_started = time.monotonic()
        self._healthy_since = None
        self._m_size.set(n)
        self.log(
            f"(supervisor: gen {g} - {n} worker(s) x "
            f"{self.cfg.devices_per_proc} device(s), coordinator "
            f"{self.cfg.host}:{self.port}, logs {self.run_dir}/logs)"
        )

    # -------------------------------------------------------------- stop

    def _stop_group(self, *, reason: str) -> None:
        """SIGTERM every living worker (the cooperative emergency-
        checkpoint path), SIGKILL whatever outlives the grace window."""
        living = [w for w in self.workers if w.alive()]
        if living:
            self.log(
                f"(supervisor: stopping {len(living)} worker(s) - {reason}; "
                f"SIGTERM, then SIGKILL after {self.cfg.grace_s:g}s)"
            )
        for w in living:
            w.kill(signal.SIGTERM)
        deadline = time.monotonic() + self.cfg.grace_s
        while time.monotonic() < deadline and any(
            w.alive() for w in self.workers
        ):
            time.sleep(min(self.cfg.poll_s, 0.1))
        for w in self.workers:
            if w.alive():
                self.log(
                    f"(supervisor: rank {w.rank} ignored SIGTERM for "
                    f"{self.cfg.grace_s:g}s; SIGKILL)"
                )
                w.kill(signal.SIGKILL)
        for w in self.workers:
            try:
                w.proc.wait(timeout=30)
            except subprocess.TimeoutExpired:  # pragma: no cover - defensive
                pass
            w.poll()

    def _tail(self, w: _Worker, lines: int = 20) -> str:
        try:
            with open(w.log_path, errors="replace") as f:
                return "".join(f.readlines()[-lines:])
        except OSError:
            return "(no log)"

    # --------------------------------------------------------- postmortem

    def _write_postmortem(self, failed: list, *, reason: str) -> str | None:
        """Bundle the stopped generation into ``postmortem.json``: per
        rank the exit cause, last heartbeat, flight-recorder dump (the
        write-through ring survives even a SIGKILL - utils/obs.py
        FlightRecorder), and a log tail; plus the run-level failure
        history. Written atomically on every failure restart and on
        SUPERVISOR ABORT - the newest bundle describes the newest crash.
        Never raises (a postmortem must not break the restart path)."""
        from ..utils.obs import read_flight_dump

        failed_ranks = {w.rank for w in failed}
        workers = []
        for w in self.workers:
            rc = w.poll()
            workers.append({
                "rank": w.rank,
                "pid": w.proc.pid,
                "generation": self.generation,
                "returncode": rc,
                "cause": signal_label(rc) if rc is not None else None,
                "failed": w.rank in failed_ranks,
                "ever_beat": w.ever_beat,
                "heartbeat": read_heartbeat(w.hb_path),
                "flight": read_flight_dump(w.flight_path),
                "log_tail": self._tail(w, 10),
            })
        doc = {
            "version": 1,
            "written_unix": time.time(),
            "reason": reason,
            "generation": self.generation,
            "group_size": self.n,
            "target_nprocs": self.cfg.nprocs,
            "restarts_used": self.restarts_used,
            "rendezvous_used": self.rendezvous_used,
            "failures": list(self.failures),
            # fleet goodput accounting as of this crash (the killed
            # rank's write-through record is already folded in)
            "goodput": self._publish_goodput(),
            "workers": workers,
        }
        tmp = self.postmortem_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, default=str)
            os.replace(tmp, self.postmortem_path)
        except OSError:
            return None
        self.postmortems_written += 1
        self._m_postmortems.inc()
        self.log(f"(supervisor: postmortem bundle -> {self.postmortem_path})")
        return self.postmortem_path

    # ------------------------------------------------------------ monitor

    def _observe(self) -> dict:
        """One poll: worker liveness + heartbeat steps + fleet federation
        (per-rank gauges, step-arrival skew, /metrics scrapes); fires due
        chaos."""
        steps: dict[int, int | None] = {}
        beating: list[int] = []
        for w in self.workers:
            # read even for dead workers: the file's existence proves the
            # worker got through rendezvous, however briefly it lived
            hb = read_heartbeat(w.hb_path)
            if hb is not None:
                w.ever_beat = True
                if not w.alive():
                    self.federation.drop_rank(w.rank)
                    continue
                self.federation.observe(w.rank, hb, alive=True)
                if hb.get("step") is not None:
                    beating.append(w.rank)
                url = hb.get("metrics_url")
                if url:
                    self.federation.maybe_scrape(w.rank, url)
                steps[w.rank] = hb.get("step")
                if self.cfg.heartbeat_timeout_s > 0:
                    beat = hb.get("beat_unix")
                    if (
                        beat is not None
                        and time.time() - float(beat)
                        > self.cfg.heartbeat_timeout_s
                    ):
                        self.log(
                            f"(supervisor: rank {w.rank} heartbeat is "
                            f"{time.time() - float(beat):.1f}s stale "
                            f"(budget {self.cfg.heartbeat_timeout_s:g}s); "
                            "declaring it dead)"
                        )
                        w.kill(signal.SIGKILL)
        self.federation.finish_poll(beating)
        if self._gap_open is not None and beating:
            # first post-restart step: the issue-defined restart window
            # (worker death -> first step of the relaunched group)
            self._m_gap_last.set(time.monotonic() - self._gap_open)
            self._gap_open = None
        now = time.monotonic()
        if now - self._goodput_published >= 5.0:
            self._goodput_published = now
            self._publish_goodput()
        if self.chaos is not None:
            for rank, sig in self.chaos.due(steps):
                for w in self.workers:
                    if w.rank == rank and w.alive():
                        self.log(
                            f"(supervisor chaos: sending "
                            f"{signal.Signals(sig).name} to rank {rank}"
                            + (" [the coordinator process]"
                               if rank == 0 else "")
                            + f" at step {steps.get(rank)})"
                        )
                        w.kill(sig)
        return steps

    def _group_ready(self) -> bool:
        return all(w.ever_beat for w in self.workers)

    # ------------------------------------------------------------- goodput

    def _publish_goodput(self) -> dict | None:
        """Aggregate every generation's per-rank run records (partial
        write-through ones from killed workers included) plus the
        supervisor-measured restart gaps into ONE fleet record
        (`utils/goodput.py fleet_goodput_record`), re-exported as
        ``goodput_ratio`` / ``badput_seconds_total{cause}`` on the
        supervisor's registry and stashed for the postmortem bundle and
        SUPERVISOR_SUMMARY. Never raises."""
        from ..utils.goodput import (
            BADPUT_CAUSES,
            fleet_goodput_record,
            validate_record,
        )

        records = []
        d = os.path.join(self.run_dir, "records")
        try:
            names = sorted(os.listdir(d))
        except OSError:
            names = []
        for name in names:
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(d, name)) as f:
                    records.append(validate_record(json.load(f), name))
            except (OSError, ValueError):
                continue  # torn/partial write or a non-record file
        if not records and not self.restart_gaps:
            return None
        try:
            fleet = fleet_goodput_record(
                records,
                restart_gaps=self.restart_gaps,
                restart_generations=self.restart_generations,
            )
        except ValueError:
            return None
        self.fleet_goodput = fleet
        if fleet.get("goodput_ratio") is not None:
            self._m_goodput.set(fleet["goodput_ratio"])
        for cause in BADPUT_CAUSES:
            v = (fleet.get("badput_s") or {}).get(cause, 0.0)
            if v > 0:
                self._m_badput.labels(cause=cause).set_max(v)
        return fleet

    def _goodput_brief(self) -> dict | None:
        """The compact fleet-goodput block for log-line summaries."""
        fleet = self.fleet_goodput
        if fleet is None:
            return None
        return {
            "goodput_ratio": fleet.get("goodput_ratio"),
            "wall_s": fleet.get("wall_s"),
            "goodput_s": fleet.get("goodput_s"),
            "badput_s": {
                k: v for k, v in (fleet.get("badput_s") or {}).items()
                if v > 0
            },
            "n_records": fleet.get("n_records"),
        }

    # --------------------------------------------------------------- run

    def run(self) -> int:
        self._spawn_group(self.n)
        rc = self._loop()
        self._summary(rc)
        return rc

    def _loop(self) -> int:
        cfg = self.cfg
        while True:
            time.sleep(cfg.poll_s)
            self._observe()
            exited = [w for w in self.workers if not w.alive()]
            failed = [w for w in exited if w.returncode != 0]
            if failed:
                rc = self._handle_failure(failed)
                if rc is not None:
                    return rc
                continue
            if len(exited) == len(self.workers):
                self.log(
                    f"(supervisor: all {self.n} worker(s) exited cleanly)"
                )
                return 0
            ready = self._group_ready()
            if not ready and (
                time.monotonic() - self._group_started
                > cfg.rendezvous_timeout_s
            ):
                self.log(
                    "(supervisor: group did not finish rendezvous within "
                    f"{cfg.rendezvous_timeout_s:g}s)"
                )
                rc = self._handle_failure([], rendezvous_timeout=True)
                if rc is not None:
                    return rc
                continue
            if ready:
                if self._healthy_since is None:
                    self._healthy_since = time.monotonic()
                grow_rc = self._maybe_grow()
                if grow_rc is not None:
                    return grow_rc

    def _maybe_grow(self) -> int | None:
        cfg = self.cfg
        if cfg.grow_after_s <= 0 or self.n >= cfg.nprocs:
            return None
        if (
            self._healthy_since is None
            or time.monotonic() - self._healthy_since < cfg.grow_after_s
        ):
            return None
        capacity = min(int(self.capacity_fn()), cfg.nprocs)
        if capacity <= self.n:
            return None
        self.log(
            f"(supervisor: capacity is back ({capacity} slot(s)); planned "
            f"grow restart {self.n} -> {capacity} - graceful SIGTERM so "
            "every worker writes its emergency checkpoint first)"
        )
        t0 = time.monotonic()
        self._stop_group(reason="planned grow restart")
        bad = [
            w for w in self.workers
            if w.returncode not in (0, None, PREEMPT_RC)
        ]
        if bad:
            # a worker that cannot even stop cleanly is a real failure -
            # fall through to the failure path (budgeted) instead of
            # growing on top of a corrupt group
            return self._handle_failure(bad)
        self._m_restarts.labels(direction="grow").inc()
        self._spawn_group(capacity)
        self._m_restart_s.observe(time.monotonic() - t0)
        return None

    def _handle_failure(
        self, failed: list, *, rendezvous_timeout: bool = False
    ) -> int | None:
        """Tear the group down and decide: relaunch (None) or abort (rc)."""
        cfg = self.cfg
        t0 = time.monotonic()
        if failed and not rendezvous_timeout and cfg.failure_settle_s > 0:
            # settle: a gang crash's other deaths may be microseconds
            # behind the one this poll caught - wait briefly (or until
            # nobody is left) and re-collect, so the failure set is the
            # EVENT's, not one poll's worth of it
            deadline = time.monotonic() + cfg.failure_settle_s
            while time.monotonic() < deadline and any(
                w.alive() for w in self.workers
            ):
                time.sleep(min(cfg.poll_s, 0.05))
            failed = [
                w for w in self.workers
                if w.poll() is not None and w.returncode != 0
            ]
        rendezvous = rendezvous_timeout or not self._group_ready()
        for w in failed:
            label = signal_label(w.returncode)
            self._m_failures.labels(signal=label).inc()
            self.failures.append(
                {"gen": self.generation, "rank": w.rank, "cause": label}
            )
            self.log(
                f"(supervisor: rank {w.rank} died [{label}]"
                + (" during rendezvous" if rendezvous else "")
                + f"; last output:\n{self._tail(w)})"
            )
        self._stop_group(
            reason="worker failure" if failed else "rendezvous timeout"
        )
        # deaths BY OUR OWN STOP are collateral (cooperative exit 0 /
        # PREEMPT_RC, or our SIGTERM/SIGKILL): not new failures. A worker
        # that exits with its own non-zero code during the teardown,
        # though, crashed in the same event - its death just straddled a
        # poll. Folding those in keeps a whole-group crash detected as
        # one (same-size restart) instead of racing the poll cadence
        # into a spurious below-min-procs abort.
        if failed:
            collateral = {0, None, PREEMPT_RC,
                          -int(signal.SIGTERM), -int(signal.SIGKILL)}
            late = [
                w for w in self.workers
                if w not in failed and w.returncode not in collateral
            ]
            for w in late:
                label = signal_label(w.returncode)
                self._m_failures.labels(signal=label).inc()
                self.failures.append(
                    {"gen": self.generation, "rank": w.rank, "cause": label}
                )
                self.log(
                    f"(supervisor: rank {w.rank} also died [{label}] "
                    "during the group stop - counting it into the same "
                    "failure)"
                )
            failed = failed + late
        self._write_postmortem(
            failed,
            reason="rendezvous failure" if rendezvous else "worker failure",
        )
        if rendezvous:
            self.rendezvous_used += 1
            if self.rendezvous_used > cfg.rendezvous_retries:
                self.log(
                    "SUPERVISOR ABORT: rendezvous failed "
                    f"{self.rendezvous_used} time(s) (budget "
                    f"{cfg.rendezvous_retries}); the group never came up. "
                    "Check the worker logs for the real error (import "
                    "failure, bad flags, unreachable coordinator) - "
                    f"{self.run_dir}/logs"
                )
                return 4
            self._m_restarts.labels(direction="rendezvous").inc()
            self.log(
                f"(supervisor: rendezvous retry "
                f"{self.rendezvous_used}/{cfg.rendezvous_retries} on a "
                "fresh port)"
            )
            self._spawn_group(self.n)
            self._m_restart_s.observe(time.monotonic() - t0)
            return None
        self.restarts_used += 1
        self._m_budget.set(max(cfg.max_restarts - self.restarts_used, 0))
        last = self.failures[-1] if self.failures else {"cause": "unknown"}
        if self.restarts_used > cfg.max_restarts:
            self.log(
                f"SUPERVISOR ABORT: restart budget ({cfg.max_restarts}) "
                f"exhausted after {self.restarts_used} failure(s); last "
                f"failure: rank {last.get('rank')} [{last.get('cause')}] "
                f"in gen {last.get('gen')}. The group is crash-looping - "
                "inspect the worker logs "
                f"({self.run_dir}/logs), fix the cause, and relaunch; the "
                "newest consistent checkpoint is intact."
            )
            return 3
        if len(failed) >= len(self.workers):
            # the WHOLE group died at once (e.g. a coordinator crash took
            # everyone down): there is no survivor count to shrink onto,
            # but the newest checkpoint still allows a same-size restart
            new_n = self.n
        else:
            new_n = self.n - len(failed)
            if new_n < cfg.min_procs:
                self.log(
                    f"SUPERVISOR ABORT: only {new_n} worker(s) survive "
                    f"but --min-procs is {cfg.min_procs}; not enough "
                    "capacity to continue. Last failure: rank "
                    f"{last.get('rank')} [{last.get('cause')}]."
                )
                return 3
        pause = cfg.backoff_for(self.restarts_used)
        direction = "shrink" if new_n < self.n else "same"
        self.log(
            f"(supervisor: restart {self.restarts_used}/{cfg.max_restarts} "
            f"[{direction}] {self.n} -> {new_n} worker(s) after "
            f"{pause:.1f}s backoff; resuming from the newest consistent "
            "checkpoint)"
        )
        time.sleep(pause)
        self._m_restarts.labels(direction=direction).inc()
        self._spawn_group(new_n)
        gap = time.monotonic() - t0
        self._m_restart_s.observe(gap)
        # goodput: death-detection -> respawn is capacity the fleet lost
        # with NO worker process alive - the supervisor-side half of the
        # restart_gap bucket (the relaunched generation's init+compile is
        # the other half, reclassified at aggregation; utils/goodput.py
        # fleet_goodput_record). The death -> first-post-restart-step
        # window closes in _observe once the new group heartbeats a step.
        # backoff_s is recorded separately so distribution extraction
        # (utils/goodput.py extract_distributions) can report the gap NET
        # of the policy's own pause - the fleetsim twin re-adds whatever
        # backoff the SIMULATED policy chooses instead of baking this
        # run's schedule into the empirical sample
        self.restart_gaps.append({
            "seconds": round(gap, 3), "group_size": new_n,
            "generation": self.generation, "detected_unix": time.time(),
            "backoff_s": round(pause, 3),
        })
        self.restart_generations.add(self.generation)
        self._gap_open = t0
        return None

    def _summary(self, rc: int) -> None:
        fleet = self._publish_goodput()
        if fleet is not None:
            # the final fleet-level record, checkable by tools/goodput.py
            # (render / --diff / --check against a baseline)
            from ..utils.goodput import _atomic_write_json

            path = os.path.join(self.run_dir, "run_record.json")
            if _atomic_write_json(path, fleet):
                self.log(f"(supervisor: fleet goodput record -> {path})")
        self.log("SUPERVISOR_SUMMARY " + json.dumps({
            "exit": {0: "ok", 3: "budget", 4: "rendezvous"}.get(rc, "error"),
            "rc": rc,
            "target_nprocs": self.cfg.nprocs,
            "final_size": self.n,
            "generations": self.generation + 1,
            "restarts": self.restarts_used,
            "rendezvous_retries": self.rendezvous_used,
            "worker_failures": self.failures,
            "postmortems": self.postmortems_written,
            "postmortem_path": (
                self.postmortem_path if self.postmortems_written else None
            ),
            "goodput": self._goodput_brief(),
        }))


class ReplicaSupervisor:
    """Fleet operator for SERVE replicas (serve/fleet.py): the
    training `Supervisor`'s failure handling - restart budget,
    per-rank exponential backoff, worker_failures_total by signal,
    postmortem.json bundles - without the gang semantics. Serve
    replicas are independent processes (no JAX coordinator, no
    rendezvous, a death never restarts the survivors), so the unit of
    restart is ONE rank, and `scale_to()` grows/retires individual
    ranks on the autoscaler's orders.

    ``command`` is the replica argv; ``{rank}`` substitutes per
    worker. Each rank gets a STABLE heartbeat path
    (``run_dir/hb/rank{N}.json``) so the fleet router's discovery
    survives restarts: the relaunched process rewrites the same file
    with its fresh PID + metrics URL. A replica exiting for ANY reason
    the supervisor didn't order (including rc 0) is a failure -
    serving processes have no "done".

    Drive it with `tick()` from the operator loop
    (tools/serve_fleet.py); `stop()` SIGTERMs everyone (the drain-on-
    SIGTERM path in the serve CLI) and SIGKILLs past the grace window.
    """

    def __init__(
        self,
        command: list,
        policy: SupervisorPolicy,
        *,
        run_dir: str,
        base_env: dict | None = None,
        registry=None,
        log=print,
    ):
        self.command = [str(c) for c in command]
        self.policy = policy
        self.run_dir = os.path.abspath(run_dir)
        self.base_env = dict(
            base_env if base_env is not None else os.environ
        )
        self.log = log
        if registry is None:
            from ..utils.obs import MetricsRegistry

            registry = MetricsRegistry()
        self.registry = registry
        self._m_size = registry.gauge(
            "supervisor_group_size", "Live replica count"
        )
        self._m_target = registry.gauge(
            "supervisor_target_size", "Target replica count"
        )
        self._m_budget = registry.gauge(
            "supervisor_restart_budget_remaining",
            "Failure restarts left before dead ranks stay down",
        )
        self._m_failures = registry.counter(
            "worker_failures_total",
            "Replica deaths observed, by signal/exit label",
        )
        self._m_restarts = registry.counter(
            "elastic_restarts_total",
            "Replica spawns by direction (grow/shrink/restart)",
        )
        self._m_postmortems = registry.counter(
            "supervisor_postmortems_total",
            "Postmortem bundles written on replica crashes",
        )
        self.postmortem_path = os.path.join(
            self.run_dir, "postmortem.json"
        )
        self.postmortems_written = 0
        self.workers: dict[int, _Worker] = {}
        self.target = policy.nprocs
        self.restarts_used = 0
        self.failures: list[dict] = []
        self._attempts: dict[int, int] = {}   # per-rank failure count
        self._spawn_seq: dict[int, int] = {}  # per-rank launch count
        self._pending: dict[int, float] = {}  # rank -> respawn due time
        swept = self._sweep_stale()
        if swept:
            self.log(
                f"(replica-supervisor: swept {swept} stale state "
                f"file(s) from reused {self.run_dir})"
            )
        for sub in ("hb", "logs", "flight", "records"):
            os.makedirs(os.path.join(self.run_dir, sub), exist_ok=True)
        self._m_target.set(self.target)
        self._m_budget.set(policy.max_restarts)

    @property
    def hb_dir(self) -> str:
        """The router's ``watch_dir`` (heartbeat-file discovery)."""
        return os.path.join(self.run_dir, "hb")

    def _sweep_stale(self) -> int:
        swept = 0
        for sub in ("hb", "flight", "records"):
            d = os.path.join(self.run_dir, sub)
            try:
                names = os.listdir(d)
            except OSError:
                continue
            for name in names:
                if name.endswith(".json") or ".json.tmp" in name:
                    try:
                        os.unlink(os.path.join(d, name))
                        swept += 1
                    except OSError:
                        pass
        try:
            os.unlink(self.postmortem_path)
            swept += 1
        except OSError:
            pass
        return swept

    # ------------------------------------------------------------- spawn

    def _argv(self, rank: int) -> list:
        return [a.replace("{rank}", str(rank)) for a in self.command]

    def _spawn_rank(self, rank: int) -> None:
        seq = self._spawn_seq.get(rank, 0)
        self._spawn_seq[rank] = seq + 1
        hb_path = os.path.join(self.hb_dir, f"rank{rank}.json")
        log_path = os.path.join(
            self.run_dir, "logs", f"rank{rank}_launch{seq}.log"
        )
        flight_path = os.path.join(
            self.run_dir, "flight", f"rank{rank}.json"
        )
        record_path = os.path.join(
            self.run_dir, "records", f"rank{rank}.json"
        )
        env = dict(self.base_env)
        env[HEARTBEAT_ENV] = hb_path
        env[FLIGHT_ENV] = flight_path
        env[RUN_RECORD_ENV] = record_path
        env["DNN_TPU_SUPERVISOR"] = "1"
        env["DNN_TPU_REPLICA_ID"] = f"rank{rank}"
        env["JAX_PROCESS_ID"] = str(rank)
        log_file = open(log_path, "w")
        proc = subprocess.Popen(
            self._argv(rank), env=env,
            stdout=log_file, stderr=subprocess.STDOUT,
        )
        self.workers[rank] = _Worker(
            rank, proc, hb_path, log_path, log_file, flight_path
        )
        self._m_size.set(len(self.workers))
        self.log(
            f"(replica-supervisor: rank{rank} launch {seq} -> "
            f"pid {proc.pid}, log {log_path})"
        )

    def start(self) -> "ReplicaSupervisor":
        for rank in range(self.target):
            if rank not in self.workers:
                self._spawn_rank(rank)
        return self

    # ----------------------------------------------------------- monitor

    def tick(self) -> None:
        """One non-blocking poll: detect deaths, write postmortems,
        schedule backed-off restarts, fire due respawns. The operator
        loop calls this every poll interval."""
        now = time.monotonic()
        for rank, w in list(self.workers.items()):
            rc = w.poll()
            if rc is None:
                continue
            # any exit the supervisor didn't order is a failure -
            # planned retirements leave self.workers BEFORE the kill
            label = signal_label(rc)
            self._m_failures.labels(signal=label).inc()
            self.failures.append({
                "rank": rank, "returncode": rc, "cause": label,
                "unix": time.time(),
            })
            self._write_postmortem(
                w, reason=f"replica rank{rank} died ({label})"
            )
            del self.workers[rank]
            self._m_size.set(len(self.workers))
            if rank >= self.target:
                continue
            if self.restarts_used >= self.policy.max_restarts:
                self.log(
                    f"(replica-supervisor: rank{rank} died ({label}) "
                    f"with the restart budget exhausted "
                    f"({self.policy.max_restarts}); leaving it down)"
                )
                continue
            self.restarts_used += 1
            self._m_budget.set(
                self.policy.max_restarts - self.restarts_used
            )
            attempt = self._attempts.get(rank, 0) + 1
            self._attempts[rank] = attempt
            delay = self.policy.backoff_for(attempt)
            self._pending[rank] = now + delay
            self.log(
                f"(replica-supervisor: rank{rank} died ({label}); "
                f"restart {self.restarts_used}/"
                f"{self.policy.max_restarts} in {delay:g}s)"
            )
        for rank, due in list(self._pending.items()):
            if rank >= self.target:
                del self._pending[rank]
                continue
            if now >= due and rank not in self.workers:
                del self._pending[rank]
                self._spawn_rank(rank)
                self._m_restarts.labels(direction="restart").inc()

    # ------------------------------------------------------------- scale

    def scale_to(self, n: int, *, drain=None) -> None:
        """Grow or shrink to ``n`` replicas. Shrink retires the
        highest ranks: ``drain("rankN")`` (the router's graceful-drain
        orchestration, migrating live sequences to survivors) runs
        best-effort first, then SIGTERM -> grace -> SIGKILL. A retired
        rank's heartbeat file is removed so discovery forgets it."""
        n = max(int(n), 0)
        old, self.target = self.target, n
        self._m_target.set(n)
        if n > old:
            for rank in range(old, n):
                if rank not in self.workers:
                    self._pending.pop(rank, None)
                    self._spawn_rank(rank)
                    self._m_restarts.labels(direction="grow").inc()
            return
        for rank in range(n, old):
            self._pending.pop(rank, None)
            w = self.workers.pop(rank, None)
            self._m_size.set(len(self.workers))
            if w is None:
                continue
            if drain is not None:
                try:
                    drain(f"rank{rank}")
                except Exception as e:
                    self.log(
                        f"(replica-supervisor: drain of rank{rank} "
                        f"failed ({e}); retiring anyway)"
                    )
            self._retire(w)
            self._m_restarts.labels(direction="shrink").inc()

    def _retire(self, w: _Worker) -> None:
        w.kill(signal.SIGTERM)
        deadline = time.monotonic() + self.policy.grace_s
        while time.monotonic() < deadline and w.alive():
            time.sleep(0.05)
        if w.alive():
            self.log(
                f"(replica-supervisor: rank{w.rank} ignored SIGTERM "
                f"for {self.policy.grace_s:g}s; SIGKILL)"
            )
            w.kill(signal.SIGKILL)
        try:
            w.proc.wait(timeout=30)
        except subprocess.TimeoutExpired:  # pragma: no cover
            pass
        w.poll()
        try:
            os.unlink(w.hb_path)
        except OSError:
            pass

    def stop(self) -> dict:
        """Planned shutdown of every replica (not a failure); returns
        the summary doc the CLI prints as FLEET_SUMMARY's supervisor
        block."""
        for rank in sorted(self.workers):
            self._retire(self.workers.pop(rank))
        self._m_size.set(0)
        return {
            "target": self.target,
            "restarts_used": self.restarts_used,
            "replica_failures": list(self.failures),
            "postmortems": self.postmortems_written,
            "postmortem_path": (
                self.postmortem_path if self.postmortems_written
                else None
            ),
        }

    # -------------------------------------------------------- postmortem

    def _tail(self, w: _Worker, lines: int = 10) -> str:
        try:
            with open(w.log_path, errors="replace") as f:
                return "".join(f.readlines()[-lines:])
        except OSError:
            return "(no log)"

    def _write_postmortem(self, w: _Worker, *, reason: str) -> None:
        """One crashed replica's evidence bundle (same shape as the
        training supervisor's: heartbeat + flight dump survive even a
        SIGKILL). Never raises."""
        from ..utils.obs import read_flight_dump

        rc = w.poll()
        doc = {
            "version": 1,
            "kind": "serve_replica",
            "written_unix": time.time(),
            "reason": reason,
            "target": self.target,
            "restarts_used": self.restarts_used,
            "failures": list(self.failures),
            "workers": [{
                "rank": w.rank,
                "pid": w.proc.pid,
                "returncode": rc,
                "cause": signal_label(rc) if rc is not None else None,
                "failed": True,
                "heartbeat": read_heartbeat(w.hb_path),
                "flight": read_flight_dump(w.flight_path),
                "log_tail": self._tail(w),
            }],
        }
        tmp = self.postmortem_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, default=str)
            os.replace(tmp, self.postmortem_path)
        except OSError:
            return
        self.postmortems_written += 1
        self._m_postmortems.inc()
        self.log(
            f"(replica-supervisor: postmortem -> {self.postmortem_path})"
        )


def main(argv=None) -> int:  # pragma: no cover - thin alias
    """`python -m distributed_neural_network_tpu.train.supervisor` =
    tools/launch.py (kept import-light; the CLI lives in tools/)."""
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    sys.path.insert(0, os.path.join(repo, "tools"))
    import launch

    return launch.main(argv)


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
