"""Runtime watchdog: stall, recompile-storm, and checkpoint-staleness
detection over the live metrics registry (`utils/obs.py`).

The guard layer (`train/guard.py`) judges what a step REPORTS (loss,
grad-norm, finite flags); this module judges whether steps are HAPPENING
at all - the failure class the pjit-at-scale infrastructure paper (arxiv
2204.06514) localizes with fleet heartbeat monitoring: a wedged collective,
a dead host thread, a silent recompile storm re-tracing every step, or a
checkpointer that quietly stopped writing. None of those raise; they just
stop the world (or burn it at 100x cost), invisibly, until someone reads a
trace after the fact.

Three detectors on one polling thread (default 1 s cadence, off the
training loop's critical path):

- **stall**: the training loop heartbeats the registry at each step
  boundary (`registry.beat(step)`); the watchdog sizes its threshold from
  the observed steady beat intervals - no heartbeat for
  ``stall_factor x p95`` (floored by ``min_stall_s``) flags a stall. One
  flag per episode (latched until the next beat), emitted as a
  ``watchdog/stall`` tracer instant event + ``watchdog_stall_total``
  counter; an optional escalation path requests a cooperative
  SIGTERM-style preemption (`train/guard.py PreemptionGuard.request`) so
  the run writes its emergency checkpoint and exits instead of burning
  its reservation wedged.
- **recompile storm**: `RecompileDetector.observe()` (one
  ``fn._cache_size()`` read per step at the call site) counts compile
  cache growth after the first compile into ``recompiles_total``; the
  watchdog flags when more than ``recompile_storm`` recompiles land
  within ``recompile_window_s`` - the classic unstable-static-argument
  bug that silently turns every step into a compile.
- **checkpoint staleness**: the checkpointer publishes
  ``checkpoint_last_save_timestamp_seconds`` (`utils/checkpoint.py`);
  an age beyond ``checkpoint_stale_s`` flags once per stale episode.

`attach_monitor()` is the shared CLI wiring (`--metrics-port`, both
`lm_train.py` and `train/cli.py`): registry + `/metrics`+`/healthz`
server + watchdog, one handle to close on exit.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass

from ..utils import obs as O
from ..utils import tracing as TR
from ..utils.obs import flight_event

WATCHDOG_STALL = "watchdog/stall"
WATCHDOG_RECOMPILE = "watchdog/recompile_storm"
WATCHDOG_CKPT_STALE = "watchdog/checkpoint_stale"


@dataclass
class WatchdogConfig:
    """Detection knobs. The stall threshold is ADAPTIVE - N x the steady
    p95 beat interval - so the same config works for 5 ms CPU smoke steps
    and multi-minute fused spans; ``min_stall_s`` floors it against noise
    on sub-millisecond steps, ``max_stall_s`` caps it so a run whose p95
    was poisoned by one giant outlier still gets flagged eventually."""

    poll_interval_s: float = 1.0
    stall_factor: float = 10.0
    min_stall_s: float = 5.0
    max_stall_s: float = 600.0
    # beats observed before the stall detector arms (compile-step and
    # first-steps intervals are legitimately wild)
    warmup_beats: int = 3
    # recompile storm: more than this many recompiles inside the window
    recompile_storm: int = 3
    recompile_window_s: float = 60.0
    # checkpoint considered stale after this many seconds without a save
    # (0 disables; only meaningful when a checkpointer publishes saves)
    checkpoint_stale_s: float = 0.0
    # escalate a persistent stall (this many consecutive flagged polls
    # AFTER the first flag) into PreemptionGuard.request(); 0 disables
    escalate_after_polls: int = 0

    def __post_init__(self):
        if self.poll_interval_s <= 0:
            raise ValueError(
                f"poll_interval_s must be > 0, got {self.poll_interval_s}"
            )
        if self.stall_factor <= 1.0:
            raise ValueError(
                f"stall_factor must be > 1, got {self.stall_factor}"
            )
        if self.min_stall_s < 0 or self.max_stall_s < self.min_stall_s:
            raise ValueError(
                f"need 0 <= min_stall_s <= max_stall_s, got "
                f"{self.min_stall_s}/{self.max_stall_s}"
            )


class RecompileDetector:
    """Cache-miss counting on a jitted step function.

    ``observe()`` after each call reads the function's compile-cache size
    (``fn._cache_size()``, present on modern jax jit wrappers; detection
    degrades to a no-op where absent) and counts growth beyond the first
    compile into ``recompiles_total`` + a ``watchdog/recompile`` tracer
    instant. The watchdog thread turns a burst of these into the storm
    flag. ``swap(fn)`` rebinds after a deliberate rebuild (the guard's LR
    backoff recompile is intentional and must not count).
    """

    def __init__(self, fn=None, *, registry=O.NULL_REGISTRY,
                 tracer=TR.NULL_TRACER):
        self.registry = registry
        self.tracer = tracer
        self.counter = registry.counter(
            "recompiles_total",
            "Compile-cache misses of the jitted train step after the "
            "first compile",
        )
        self.events: list[float] = []  # unix times, read by the watchdog
        self._lock = threading.Lock()
        self._fn = None
        self._baseline = None
        if fn is not None:
            self.swap(fn)

    @staticmethod
    def cache_size(fn) -> int | None:
        get = getattr(fn, "_cache_size", None)
        if get is None:
            return None
        try:
            return int(get())
        except Exception:
            return None

    def swap(self, fn) -> None:
        """Track a (new) jitted fn; its current cache size becomes the
        baseline so deliberate rebuilds don't count as misses."""
        self._fn = fn
        self._baseline = self.cache_size(fn)

    def observe(self, step: int | None = None) -> int:
        """Call after a step completes; returns recompiles counted so
        far this run. First growth from 0 is THE compile, not a miss."""
        size = self.cache_size(self._fn)
        if size is None:
            return len(self.events)
        if self._baseline is None or size <= self._baseline:
            self._baseline = size if self._baseline is None else self._baseline
            return len(self.events)
        grew = size - self._baseline
        if self._baseline == 0:
            grew -= 1  # the first compile is expected
        self._baseline = size
        if grew <= 0:
            return len(self.events)
        now = time.time()
        with self._lock:
            self.events.extend([now] * grew)
        self.counter.inc(grew)
        self.tracer.instant(
            "watchdog/recompile", track="watchdog",
            step=step, new_entries=grew, cache_size=size,
        )
        flight_event(
            "recompile", step=step, new_entries=grew, cache_size=size
        )
        return len(self.events)

    def recent(self, window_s: float) -> int:
        cut = time.time() - window_s
        with self._lock:
            return sum(1 for t in self.events if t >= cut)


class Watchdog:
    """The polling thread. start()/stop(), or use as a context manager."""

    def __init__(
        self,
        registry,
        *,
        config: WatchdogConfig | None = None,
        tracer=TR.NULL_TRACER,
        recompiles: RecompileDetector | None = None,
        preemption=None,
        log=print,
    ):
        self.registry = registry
        self.cfg = config if config is not None else WatchdogConfig()
        self.tracer = tracer
        self.recompiles = recompiles
        self.preemption = preemption
        self.log = log
        self.stall_counter = registry.counter(
            "watchdog_stall_total",
            "Stalled-step episodes flagged by the watchdog",
        )
        self.storm_counter = registry.counter(
            "watchdog_recompile_storm_total",
            "Recompile-storm episodes flagged by the watchdog",
        )
        self.ckpt_stale_counter = registry.counter(
            "watchdog_checkpoint_stale_total",
            "Checkpoint-staleness episodes flagged by the watchdog",
        )
        self.threshold_gauge = registry.gauge(
            "watchdog_stall_threshold_seconds",
            "Current adaptive stall threshold (stall_factor x steady p95)",
        )
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # episode latches
        self._stall_flagged_at_step: int | None = None
        self._stall_polls = 0
        self._escalated = False
        self._storm_flagged = False
        self._ckpt_flagged_for: float | None = None

    # ------------------------------------------------------------ control

    def start(self) -> "Watchdog":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="watchdog", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------------- detect

    def stall_threshold_s(self) -> float | None:
        """stall_factor x p95 of the recent beat intervals, clamped to
        [min_stall_s, max_stall_s]; None while under warmup_beats."""
        intervals = self.registry.beat_intervals()
        if len(intervals) < self.cfg.warmup_beats:
            return None
        p95 = TR.percentile(intervals, 95)
        return min(
            max(self.cfg.stall_factor * p95, self.cfg.min_stall_s),
            self.cfg.max_stall_s,
        )

    def _model_health(self) -> dict:
        """Last model-health gauges (train/dynamics.py DynamicsSink), for
        the stall flight event: a hang's postmortem should show whether
        the model was already sick (exploding grads, spiking loss) when
        the heartbeat stopped. Empty when the run has no --dynamics."""
        out = {}
        for key, name in (
            ("last_grad_norm", "dynamics_grad_norm"),
            ("last_upd_ratio_max", "dynamics_upd_ratio_max"),
            ("last_loss_zscore", "guard_spike_zscore"),
        ):
            g = self.registry.get(name)
            if g is not None:
                out[key] = round(g.value, 6)
        return out

    def check_once(self) -> dict:
        """One poll of all three detectors (the thread body; callable
        directly from tests). Returns {stall, storm, ckpt_stale} bools of
        NEW flags raised by this poll."""
        raised = {"stall": False, "storm": False, "ckpt_stale": False}
        # ---- stall
        thr = self.stall_threshold_s()
        if thr is not None:
            self.threshold_gauge.set(thr)
            age = self.registry.heartbeat_age()
            step = self.registry.last_step()
            if age is not None and age > thr:
                # goodput accounting: the no-heartbeat window is stall
                # badput. Re-reported each poll as [now-age, now]; the
                # ledger's sweep coalesces the growing episode and any
                # overhang into the step that finally completes
                # (utils/goodput.py - instrumented intervals outrank the
                # coarse stall window)
                from ..utils.goodput import LEDGER

                LEDGER.add_ending_now("stall", age)
                if self._stall_flagged_at_step != step:
                    self._stall_flagged_at_step = step
                    self._stall_polls = 0
                    self._escalated = False
                    self.stall_counter.inc()
                    self.tracer.instant(
                        WATCHDOG_STALL, track="watchdog", step=step,
                        heartbeat_age_s=round(age, 3),
                        threshold_s=round(thr, 3),
                    )
                    flight_event(
                        "watchdog_stall", step=step,
                        heartbeat_age_s=round(age, 3),
                        threshold_s=round(thr, 3),
                        **self._model_health(),
                    )
                    self.log(
                        f"(watchdog: STALL - no step heartbeat for "
                        f"{age:.1f}s, threshold {thr:.1f}s "
                        f"[{self.cfg.stall_factor}x steady p95], last "
                        f"step {step})"
                    )
                    raised["stall"] = True
                else:
                    self._stall_polls += 1
                    if (
                        self.cfg.escalate_after_polls > 0
                        and self.preemption is not None
                        and not self._escalated
                        and self._stall_polls >= self.cfg.escalate_after_polls
                    ):
                        self._escalated = True
                        self.tracer.instant(
                            WATCHDOG_STALL, track="watchdog", step=step,
                            action="escalate",
                        )
                        flight_event(
                            "watchdog_escalate", step=step,
                            action="preempt",
                        )
                        self.log(
                            "(watchdog: stall persists - requesting "
                            "cooperative preemption [emergency checkpoint "
                            "at the next step boundary])"
                        )
                        self.preemption.request("WATCHDOG")
            elif self._stall_flagged_at_step is not None and (
                age is None or age <= thr
            ):
                # heartbeat came back: close the episode
                self._stall_flagged_at_step = None
                self._stall_polls = 0
                self._escalated = False
        # ---- recompile storm
        if self.recompiles is not None:
            n = self.recompiles.recent(self.cfg.recompile_window_s)
            if n > self.cfg.recompile_storm and not self._storm_flagged:
                self._storm_flagged = True
                self.storm_counter.inc()
                self.tracer.instant(
                    WATCHDOG_RECOMPILE, track="watchdog",
                    recompiles_in_window=n,
                    window_s=self.cfg.recompile_window_s,
                )
                flight_event(
                    "watchdog_recompile_storm", recompiles_in_window=n,
                    window_s=self.cfg.recompile_window_s,
                )
                self.log(
                    f"(watchdog: RECOMPILE STORM - {n} recompiles within "
                    f"{self.cfg.recompile_window_s:.0f}s; a step input's "
                    "shape/dtype/static arg is changing per call)"
                )
                raised["storm"] = True
            elif n <= self.cfg.recompile_storm:
                self._storm_flagged = False
        # ---- checkpoint staleness
        if self.cfg.checkpoint_stale_s > 0:
            g = self.registry.get("checkpoint_last_save_timestamp_seconds")
            last = g.value if g is not None else 0.0
            if last > 0:
                age = time.time() - last
                if (
                    age > self.cfg.checkpoint_stale_s
                    and self._ckpt_flagged_for != last
                ):
                    self._ckpt_flagged_for = last
                    self.ckpt_stale_counter.inc()
                    self.tracer.instant(
                        WATCHDOG_CKPT_STALE, track="watchdog",
                        checkpoint_age_s=round(age, 1),
                        threshold_s=self.cfg.checkpoint_stale_s,
                    )
                    flight_event(
                        "watchdog_checkpoint_stale",
                        checkpoint_age_s=round(age, 1),
                        threshold_s=self.cfg.checkpoint_stale_s,
                    )
                    self.log(
                        f"(watchdog: checkpoint is {age:.0f}s old "
                        f"[threshold {self.cfg.checkpoint_stale_s:.0f}s] "
                        "- the checkpointer may have stopped writing)"
                    )
                    raised["ckpt_stale"] = True
        return raised

    def _run(self) -> None:
        while not self._stop.wait(self.cfg.poll_interval_s):
            try:
                self.check_once()
            except Exception as e:  # a detector bug must never kill a run
                self.log(f"(watchdog: internal error {type(e).__name__}: "
                         f"{e}; continuing)")


# ------------------------------------------------- on-demand profiling


class ProfileController:
    """On-demand `jax.profiler` capture, armed from the live HTTP layer.

    ``GET /profile?steps=N`` (utils/obs.py ObsServer) calls ``request(N)``;
    the capture then starts at the NEXT step boundary and stops N steps
    later - step boundaries are delivered via the registry's beat hook
    (`MetricsRegistry.beat_hook`), which both training loops already
    drive, so no step-loop signature changes anywhere. Each capture
    writes ``profile_step{S}_x{N}`` under ``out_dir`` (next to the
    Chrome trace when the run has one) for TensorBoard/XProf.

    The idle fast path is two attribute reads per step. All profiler
    errors (an already-active whole-run ``--profile-dir`` trace, an
    unwritable dir) are caught, recorded on ``error``, and reported by
    the next ``/profile`` response - never raised into the step loop.
    """

    def __init__(self, out_dir: str, *, log=print):
        self.out_dir = os.path.abspath(out_dir)
        self.log = log
        self._lock = threading.Lock()
        self._pending = 0
        self._stop_at: int | None = None
        self._active_dir: str | None = None
        self.captures = 0
        self.last_dir: str | None = None
        self.error: str | None = None

    def request(self, steps: int) -> dict:
        """Arm a capture for the next ``steps`` steps (the /profile body)."""
        with self._lock:
            if self._pending or self._stop_at is not None:
                return {
                    "ok": False,
                    "error": "a profile capture is already pending/active",
                    "dir": self._active_dir,
                }
            self._pending = int(steps)
        doc = {
            "ok": True, "steps": int(steps), "out_dir": self.out_dir,
            "note": "capture starts at the next step boundary",
            "captures_completed": self.captures,
        }
        if self.error:
            doc["last_error"] = self.error
        return doc

    def on_step(self, step) -> None:
        """Step-boundary hook (registry beat). Starts/stops captures."""
        if not self._pending and self._stop_at is None:
            return
        with self._lock:
            pending, stop_at = self._pending, self._stop_at
            if pending and stop_at is None:
                self._pending = 0
                i = int(step) if step is not None else 0
                d = os.path.join(
                    self.out_dir, f"profile_step{i}_x{pending}"
                )
                try:
                    import jax

                    os.makedirs(d, exist_ok=True)
                    jax.profiler.start_trace(d)
                except Exception as e:
                    self.error = f"{type(e).__name__}: {e}"
                    self.log(f"(profile: start failed - {self.error})")
                    return
                self._stop_at = i + pending
                self._active_dir = d
                self.log(
                    f"(profile: capturing {pending} step(s) -> {d})"
                )
                return
            if stop_at is not None and step is not None \
                    and int(step) >= stop_at:
                self._finish_locked()

    def _finish_locked(self) -> None:
        d = self._active_dir
        self._stop_at = None
        self._active_dir = None
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:
            self.error = f"{type(e).__name__}: {e}"
            self.log(f"(profile: stop failed - {self.error})")
            return
        self.captures += 1
        self.last_dir = d
        flight_event("profile_capture", dir=d)
        self.log(f"(profile: capture complete - {d})")

    def close(self) -> None:
        """Stop a capture left active at run end (trace stays valid)."""
        with self._lock:
            if self._stop_at is not None:
                self._finish_locked()
            self._pending = 0


# ----------------------------------------------------------- CLI wiring


class Monitor:
    """registry + server + watchdog + heartbeat + flight + profiler,
    one close()."""

    def __init__(self, registry, server=None, watchdog=None,
                 recompiles: RecompileDetector | None = None,
                 heartbeat=None, flight=None, profiler=None):
        self.registry = registry
        self.server = server
        self.watchdog = watchdog
        self.recompiles = recompiles
        self.heartbeat = heartbeat
        self.flight = flight
        self.profiler = profiler
        self._closed = False

    @property
    def url(self) -> str | None:
        return self.server.url if self.server is not None else None

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self.profiler is not None:
            self.profiler.close()
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.server is not None:
            self.server.close()
        if self.heartbeat is not None:
            self.heartbeat.close()
        if self.flight is not None:
            # final write-through: the ring's last state with the clean
            # cause recorded (a crash never reaches here - the
            # per-event write-through already has the file current)
            self.flight.dump(cause="close")


def attach_monitor(
    *,
    metrics_port: int | None,
    tracer=TR.NULL_TRACER,
    preemption=None,
    watchdog: bool = True,
    config: WatchdogConfig | None = None,
    profile_dir: str | None = None,
    rank: int | None = None,
    log=print,
) -> Monitor:
    """The shared `--metrics-port` wiring for both CLIs.

    ``metrics_port=None`` returns a fully inert monitor around
    ``NULL_REGISTRY`` (every publish site stays a no-op) - UNLESS the
    process runs under the elastic supervisor (`train/supervisor.py`
    exports DNN_TPU_HEARTBEAT_FILE): then a real registry is built
    regardless, with a `utils/obs.py HeartbeatFileWriter` mirroring its
    heartbeat state into the supervisor's per-worker file. A port (0 =
    ephemeral) additionally starts the HTTP server and (unless
    ``watchdog=False``) the watchdog thread. The caller logs
    ``monitor.url`` and closes the monitor on exit.

    Goodput: a DNN_TPU_RUN_RECORD env (exported per worker by the
    supervisor, or set by hand) arms the process goodput ledger's
    write-through run record (`utils/goodput.py LEDGER` - SIGKILL-safe,
    like the flight recorder), and any real registry gets the ledger's
    ``goodput_ratio`` / ``badput_seconds_total{cause}`` export.

    Fleet extensions: a supervisor-exported DNN_TPU_FLIGHT_FILE arms the
    process flight recorder's write-through dump (`utils/obs.py FLIGHT`);
    ``rank`` stamps the heartbeat file (and the flight dump) so
    attribution survives file relocation; the heartbeat also advertises
    this worker's ``metrics_url`` when a server is up - the federation
    scraper's handshake. ``profile_dir`` (with a server) wires the
    ``/profile?steps=N`` on-demand `jax.profiler` endpoint
    (`ProfileController`), driven from the registry's beat hook.
    """
    flight = None
    fl_path = os.environ.get(O.FLIGHT_ENV)
    if fl_path:
        O.FLIGHT.configure(fl_path, rank=rank)
        flight = O.FLIGHT
        flight_event("run_start", pid=os.getpid())
        log(f"(flight recorder: {fl_path})")
    from ..utils import goodput as GP

    rec_path = os.environ.get(GP.RUN_RECORD_ENV)
    if rec_path:
        GP.LEDGER.arm(rec_path)
        log(f"(goodput run record: {rec_path})")
    hb_path = os.environ.get("DNN_TPU_HEARTBEAT_FILE")
    if metrics_port is None and not hb_path:
        return Monitor(O.NULL_REGISTRY, flight=flight)
    registry = O.MetricsRegistry()
    GP.LEDGER.publish(registry)
    server = prof = None
    if metrics_port is not None:
        if profile_dir:
            prof = ProfileController(profile_dir, log=log)
            registry.beat_hook = prof.on_step
        server = O.ObsServer(registry, port=metrics_port, profiler=prof)
    hb = None
    if hb_path:
        hb = O.HeartbeatFileWriter(
            registry, hb_path, rank=rank,
            metrics_url=server.url if server is not None else None,
        )
        log(f"(supervisor heartbeat file: {hb_path})")
    if server is None:
        return Monitor(registry, heartbeat=hb, flight=flight)
    rec = RecompileDetector(registry=registry, tracer=tracer)
    dog = None
    if watchdog:
        dog = Watchdog(
            registry, config=config, tracer=tracer, recompiles=rec,
            preemption=preemption, log=log,
        ).start()
    log(
        f"(metrics server: {server.url}/metrics , {server.url}/healthz"
        + (f" , {server.url}/profile" if prof is not None else "")
        + (" ; watchdog on)" if dog is not None else " ; watchdog off)")
    )
    return Monitor(
        registry, server, dog, rec, heartbeat=hb, flight=flight,
        profiler=prof,
    )
