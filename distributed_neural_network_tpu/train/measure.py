"""Shared benchmark harness for bench.py, report.py and lm_train.py.

One implementation of "train the data-parallel CIFAR workload and time the
train+sync phases" so the entry points cannot drift: split loading,
warm-up policy, the fused-span fast path with its outside-the-timer final
eval (mirroring the reference's child train-time metric, which excludes the
parent's eval - SURVEY.md section 6), and the phase accounting. Also the LM
throughput/MFU measurement (`measure_lm_training`) and the MFU accounting
(`model_flops_per_token`, `peak_flops`) shared by lm_train.py and bench.py.
"""

from __future__ import annotations

import os
import time

import jax

from ..data.cifar10 import load_split
from ..utils import timers as T
from .engine import Engine, TrainConfig

# peak TFLOP/s by device kind for the MFU denominator; None = unknown kind.
# bf16 is the MXU-native rate; f32 matmuls run at roughly half of it on
# TPU (the MXU computes f32 via bf16x3-style passes), so MFU for f32 runs
# is reported against the halved peak (ADVICE r2: quoting the bf16 peak
# silently understated f32 utilization).
PEAK_TFLOPS_BF16 = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}
F32_PEAK_FACTOR = 0.5

# peak HBM bandwidth (bytes/s) by device kind - the decode-utilization
# denominator (decode streams every parameter once per generation step).
# Kept next to PEAK_TFLOPS_BF16 so a new device generation is added to
# both tables in one place.
PEAK_HBM_BYTES = {
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5p": 2765e9,
    "TPU v6 lite": 1640e9,
    "TPU v6e": 1640e9,
}


def peak_flops(device_kind: str, dtype: str = "bfloat16") -> float | None:
    """Per-device peak FLOP/s for the MFU denominator, dtype-adjusted."""
    peak = PEAK_TFLOPS_BF16.get(device_kind)
    if peak is None:
        return None
    return peak * (F32_PEAK_FACTOR if dtype == "float32" else 1.0)


def peak_hbm_bandwidth(device_kind: str) -> float | None:
    """Per-device peak HBM bandwidth (bytes/s); None for unknown kinds."""
    return PEAK_HBM_BYTES.get(device_kind)


def model_flops_per_token(cfg, seq_len: int) -> float:
    """Model FLOPs per trained token (fwd + 2x bwd), PaLM-appendix style.

    Per layer, per token (forward): 8*d^2 (QKV+out projections) +
    4*seq*d (attention scores+values, causal NOT halved - the standard
    convention) + 4*d*ff (MLP; for MoE, the top-k activated experts).
    Plus 2*d*vocab for the LM head. Backward = 2x forward; remat recompute
    is excluded (MFU counts model FLOPs, not hardware FLOPs).
    """
    d, f, v, L = cfg.d_model, cfg.d_ff, cfg.vocab_size, cfg.n_layers
    mlp = 4 * d * f * (cfg.moe_top_k if cfg.n_experts else 1)
    per_layer = 8 * d * d + 4 * seq_len * d + mlp
    return 3.0 * (L * per_layer + 2 * d * v)


# set after the first real span execution in this process: the backend
# init it absorbs is session-level, not per-program (r5 measurement)
_session_warm = False


def measure_dp_training(
    *,
    nb_proc: int | None = None,
    batch_size: int = 16,
    epochs: int = 25,
    data: str = "auto",
    synthetic_size: int | None = None,
    sync_mode: str = "epoch",
    compute_dtype: str = "float32",
    kernels: str = "xla",
    fused: bool = True,
    input_mode: str = "hbm",
    stream_prefetch: int = 2,
) -> dict:
    """Run the data-parallel regime and return measured results.

    Returns {devices, batch_size, epochs, val_acc, val_loss, train_s,
    source}. train_s = training + parameter-sync wall-clock (compile time
    excluded via AOT warm-up; eval outside), the reference-comparable
    metric.
    """
    # requested size passes through; the engine rejects infeasible counts
    # with a clear error rather than silently measuring a smaller mesh
    n = nb_proc if nb_proc else jax.device_count()
    train_split = load_split(
        True, source=data, synthetic_size=synthetic_size,
        # stream mode keeps uint8 host storage; the native kernel
        # normalizes per batch (data/stream.py)
        normalize_images=input_mode != "stream",
    )
    test_split = load_split(
        False, source=data,
        synthetic_size=max(1, synthetic_size // 5) if synthetic_size else None,
    )
    cfg = TrainConfig(
        batch_size=batch_size, epochs=epochs, nb_proc=n,
        regime="data_parallel", sync_mode=sync_mode,
        compute_dtype=compute_dtype, kernels=kernels,
        input_mode=input_mode, stream_prefetch=stream_prefetch,
    )
    timers = T.PhaseTimers()
    engine = Engine(cfg, train_split, test_split)
    if input_mode == "stream":
        fused = False  # streaming supports the per-epoch path only
    if fused:
        # one dispatch for the whole run; AOT compile, then measure.
        # The 1-epoch warm-up span absorbs SESSION-level first-execution
        # cost (measured r5: ~22 s of backend/runtime init landed inside
        # whichever row ran first in a claim session - the headline bs16
        # row read 18.7 s first-in-session vs 3.2 s after any prior real
        # execution; AOT compile alone does not trigger the init, a real
        # execution does). Once per process: the init is session-level,
        # so later rows in the same worker skip the throwaway epoch.
        engine.compile_span(epochs, eval_inside=False)
        global _session_warm
        if not _session_warm:
            engine.compile_span(1, eval_inside=False)
            engine.run_span(0, 1, eval_inside=False, timers=T.PhaseTimers())
            engine.reset_state()
            _session_warm = True
        engine.run_span(0, epochs, eval_inside=False, timers=timers)
        vl, va = engine._eval_fn(
            engine.params, engine.test_images, engine.test_labels,
            engine.test_weights,
        )
        final = engine.history[-1]
        final.val_loss, final.val_acc = float(vl), float(va)
    else:
        # per-epoch dispatch: warm up one epoch, rewind, measure
        engine.run_epoch(0, timers=T.PhaseTimers())
        engine.reset_state()
        for epoch in range(epochs):
            engine.run_epoch(epoch, timers=timers)
        final = engine.history[-1]
    return {
        "devices": n,
        "platform": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "batch_size": batch_size,
        "epochs": epochs,
        "val_acc": final.val_acc,
        "val_loss": final.val_loss,
        "train_s": timers.get(T.TRAINING) + timers.get(T.COMMUNICATION),
        "train_phase_s": round(timers.get(T.TRAINING), 3),
        "sync_phase_s": round(timers.get(T.COMMUNICATION), 3),
        "source": train_split.source,
    }


def measure_dp_scaling(
    *,
    ns=(1, 2, 4, 8),
    batch_size: int = 16,
    epochs: int = 3,
    synthetic_size: int = 4096,
) -> dict:
    """Relative data-parallel scaling curve on the virtual CPU mesh
    (r3 VERDICT missing item 3: multi-device performance evidence is
    single-device only; one chip is all the environment provides, so the
    sync-cost SHAPE is characterized on the mesh the tests use).

    Fixed total work (same dataset, same global batch sequence), mesh
    size n swept: each device trains total//n contiguous rows per epoch
    with epoch-edge pmean sync - the reference's own Table 1 experiment
    (/root/reference/data_parallelism_train.py:49-53,238-244). On this
    host the n virtual devices share ONE core, so ideal wall-clock is
    FLAT in n (the same total FLOPs, serialized); any growth of
    t_n / t_1 is parallelization overhead - per-device dispatch,
    collective sync, and the padded last batch per shard. That overhead
    curve is the transferable signal: on real n-chip hardware wall-clock
    divides by n modulo exactly this overhead (plus ICI latency the CPU
    mesh cannot see; stated in the row note). The per-epoch (unfused)
    path is measured so the training/sync phase split is attributable.

    Contrast with the reference's Table 1, where time GROWS 375 -> 1642 s
    from 3 -> 8 procs (oversubscribed cores + serialized parent sync):
    here the same sweep holds near-flat, which IS the framework's
    scaling story expressed within a one-core environment.
    """
    if not ns or ns[0] != 1:
        raise ValueError(
            f"ns must start at 1 (the overhead_vs_n1 baseline), got {ns}"
        )
    points = []
    for n in ns:
        if n > jax.device_count():
            continue  # skip just this point; ns need not be sorted
        r = measure_dp_training(
            nb_proc=n, batch_size=batch_size, epochs=epochs,
            data="synthetic", synthetic_size=synthetic_size, fused=False,
        )
        points.append({
            "n": n,
            "train_s": round(r["train_s"], 3),
            "train_phase_s": r["train_phase_s"],
            "sync_phase_s": r["sync_phase_s"],
        })
    t1 = points[0]["train_s"]
    for p in points:
        p["overhead_vs_n1"] = round(p["train_s"] / max(t1, 1e-9), 3)
        p["sync_frac"] = round(
            p["sync_phase_s"] / max(p["train_s"], 1e-9), 4
        )
    return {
        "devices": jax.device_count(),
        "platform": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "batch_size": batch_size,
        "epochs": epochs,
        "rows_total": synthetic_size,
        "host_cores": os.cpu_count(),
        "points": points,
        "overhead_vs_n1_max": max(p["overhead_vs_n1"] for p in points),
        "note": (
            "fixed total work on one shared host core: ideal wall is flat "
            "in n; overhead_vs_n1 is the measured parallelization+sync "
            "cost. Real n-chip wall divides by n modulo this curve (ICI "
            "latency not visible on a CPU mesh)."
        ),
    }


def _lm_axis_sweep(
    sizes, *, cfg, make_mesh, axis_key, batch, seq_len, vocab, steps,
    attn_impl="ring", point_extras=None,
):
    """Shared body of the sp/ep scaling sweeps: per mesh size, build the
    mesh and a fresh sharded model, compile one LM train step, hard-fence
    a warm-up, time `steps` steps, and normalize wall against the size-1
    baseline (the first sweep entry, enforced). Returns the points list;
    each point carries `{axis_key: n, wall_s, tokens_per_s, final_loss,
    overhead_vs_{axis_key}1}` plus `point_extras(n)` if given.
    (`measure_dp_scaling` stays engine-based: the CNN regime times the
    train/sync phase split, which this LM-step loop has no notion of.)"""
    from ..models import transformer as tfm
    from ..utils.timers import hard_block
    from . import lm as lmtrain

    if not sizes or sizes[0] != 1:
        raise ValueError(
            f"{axis_key} sweep must start at 1 (the "
            f"overhead_vs_{axis_key}1 baseline), got {sizes}"
        )
    points = []
    for n in sizes:
        if n > jax.device_count():
            continue
        mesh = make_mesh(n)
        params, _ = lmtrain.shard_params(
            tfm.init_params(jax.random.key(0), cfg), cfg, mesh
        )
        mom = lmtrain.init_lm_momentum(params, mesh)
        step = lmtrain.make_lm_train_step(cfg, mesh, lr=0.01,
                                          attn_impl=attn_impl)
        tokens, targets = lmtrain.make_copy_task(
            jax.random.key(1), batch=batch, seq_len=seq_len, vocab=vocab
        )
        if attn_impl == "zigzag" and n > 1:
            # zigzag consumes tokens in zigzag SHARD order (the caller
            # permutes - parallel/ring.py zigzag_order; pinned by
            # tests/test_transformer.py): without this each sp trains a
            # differently-permuted objective and the loss column - the
            # sweep's semantics check - drifts per sp
            from ..parallel.ring import zigzag_order

            perm = zigzag_order(seq_len, n)
            tokens, targets = tokens[:, perm], targets[:, perm]
        params, mom, loss = step(params, mom, tokens, targets)  # compile
        hard_block(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            params, mom, loss = step(params, mom, tokens, targets)
        hard_block(loss)
        dt = time.perf_counter() - t0
        point = {
            axis_key: n,
            "wall_s": round(dt, 3),
            "tokens_per_s": round(batch * seq_len * steps / dt),
            "final_loss": round(float(loss), 4),
        }
        if point_extras:
            point.update(point_extras(n))
        points.append(point)
    t1 = points[0]["wall_s"]
    for p in points:
        p[f"overhead_vs_{axis_key}1"] = round(
            p["wall_s"] / max(t1, 1e-9), 3)
    return points


def measure_sp_scaling(
    *,
    sps=(1, 2, 4, 8),
    d_model: int = 128,
    n_layers: int = 4,
    n_heads: int = 8,
    d_ff: int = 512,
    vocab: int = 2048,
    seq_len: int = 2048,
    batch: int = 2,
    steps: int = 3,
    attn_impl: str = "ring",
) -> dict:
    """Ring-attention sequence-parallel scaling shape on the virtual CPU
    mesh - the SP analog of `measure_dp_scaling` (long-context evidence
    beyond the single-chip hardware this environment provides).

    Fixed GLOBAL sequence, sp swept: each device holds seq_len/sp tokens
    and the ring rotates K/V blocks sp-1 times per attention
    (parallel/ring.py). On n virtual devices sharing ONE host core,
    total model FLOPs are identical at every sp, so ideal wall-clock is
    flat; growth of t_sp / t_1 is the sequence-parallel overhead
    (per-device dispatch, ring permutes, per-hop softmax-merge). On real
    chips wall divides by sp modulo exactly this curve plus ICI latency
    (which a CPU mesh cannot see - stated in the row note).
    """
    from ..models import transformer as tfm
    from . import lm as lmtrain

    cfg = tfm.TransformerConfig(
        vocab_size=vocab, d_model=d_model, n_heads=n_heads,
        n_layers=n_layers, d_ff=d_ff,
    )
    # at sp=1 the step builder drops the sequence axis (lm.py: seq axis
    # None) and the same attn_impl runs as plain local attention - the
    # baseline is the identical program minus the ring, exactly the
    # overhead being measured
    points = _lm_axis_sweep(
        sps, cfg=cfg, make_mesh=lambda sp: lmtrain.create_lm_mesh(1, sp, 1),
        axis_key="sp", batch=batch, seq_len=seq_len, vocab=vocab,
        steps=steps, attn_impl=attn_impl,
    )
    return {
        "devices": jax.device_count(),
        "platform": jax.default_backend(),
        "attn_impl": attn_impl,
        "d_model": d_model, "n_layers": n_layers, "seq_len": seq_len,
        "batch": batch, "steps": steps,
        "host_cores": os.cpu_count(),
        "points": points,
        "overhead_vs_sp1_max": max(p["overhead_vs_sp1"] for p in points),
        "note": (
            "fixed global sequence on one shared host core: ideal wall "
            f"is flat in sp; overhead_vs_sp1 is the measured {attn_impl} "
            "sequence-parallel cost. Real sp-chip wall divides by sp "
            "modulo this curve (ICI latency not visible on a CPU mesh)."
        ),
    }


def fit_tick_model(results, *, n_layers, mb_rows, seq_len, steps,
                   pp_n: int = 4) -> dict:
    """Fit T = ticks * (w*c + o) to measured pp-bubble configs.

    Separates the schedule bubble from per-tick dispatch overhead: w =
    layers/tick, c = per-layer cost, o = fixed per-tick overhead - two
    unknowns over len(results) configs, least squares. Annotates each
    result with `bubble_overhead_adjusted` = 1 - (v*M useful ticks of
    model time) / MEASURED time (dividing model useful by model total
    would cancel the fit and always reproduce the analytic number -
    review r3 caught exactly that tautology), and returns the tick_model
    dict.

    The physical model requires c, o >= 0: when the unconstrained
    optimum has a negative component, the constrained (NNLS) optimum is
    one of the two single-parameter boundary fits (o=0 c-only, c=0
    o-only) - the lower-SSE one is chosen rather than assuming which
    coordinate went negative, and both optima are reported
    (`boundary_solution`). A slightly negative unconstrained o is
    expected on a shared host (later ticks run warmer caches), so the
    o=0 boundary is a finding - per-tick overhead statistically zero -
    not a fallback. Pure function of the measured configs: unit-tested
    in tests/test_pipeline.py without running a measurement."""
    import numpy as np

    ticks = np.array([r["interleave"] * r["microbatches"] + pp_n - 1
                      for r in results], np.float64)
    work = np.array([n_layers / (r["interleave"] * pp_n)
                     for r in results], np.float64)
    t_meas = np.array([
        r["microbatches"] * mb_rows * seq_len * steps / r["tokens_per_s"]
        for r in results
    ])
    A = np.stack([ticks * work, ticks], axis=1)
    (c_un, o_un), res, *_ = np.linalg.lstsq(A, t_meas, rcond=None)
    c_fit, o_fit = float(c_un), float(o_un)
    boundary = None
    if o_fit < 0 or c_fit < 0:
        tw = ticks * work
        cands = [(max(float(tw @ t_meas / (tw @ tw)), 0.0), 0.0),
                 (0.0, max(float(ticks @ t_meas / (ticks @ ticks)), 0.0))]
        c_fit, o_fit = min(
            cands, key=lambda co: float(
                ((A @ np.array(co)) - t_meas) ** 2 @ np.ones_like(t_meas)))
        boundary = {"per_layer_s_unconstrained": round(float(c_un), 6),
                    "per_tick_overhead_s_unconstrained": round(
                        float(o_un), 6)}
    pred = A @ np.array([c_fit, o_fit])
    fit_err = float(np.abs(pred - t_meas).max() / t_meas.max())
    for r, w, t_i in zip(results, work, t_meas):
        useful = r["interleave"] * r["microbatches"] * (w * c_fit + o_fit)
        r["bubble_overhead_adjusted"] = round(1.0 - useful / t_i, 4)
    return {
        "per_layer_s": round(float(c_fit), 6),
        "per_tick_overhead_s": round(float(o_fit), 6),
        "rel_fit_err": round(fit_err, 4),
        "n_configs": len(results),
        **({"boundary_solution": boundary} if boundary else {}),
    }


def measure_pp_bubble(
    *,
    d_model: int = 256,
    n_layers: int = 8,
    n_heads: int = 8,
    d_ff: int = 1024,
    vocab: int = 512,
    seq_len: int = 128,
    mb_rows: int = 2,
    steps: int = 6,
    warmup: int = 1,
) -> dict:
    """Measure the pp=4 pipeline bubble empirically (VERDICT r2 item 4).

    Runs the pipeline train step at fixed microbatch SIZE (mb_rows rows)
    and varying (M microbatches, v interleave), so tokens/s is
    proportional to 1 - bubble: every config does identical per-token
    work and differs only in how many bubble ticks the schedule pays.
    Reports per-config tokens/s plus the empirically derived bubble
    (1 - tok/s / ideal, where ideal extrapolates the best config by its
    own analytic bubble). Needs >= 4 devices - meant for the 4-device
    virtual CPU mesh (the bench row sets JAX_PLATFORMS=cpu); relative
    throughput, not absolute, is the measurement.
    """
    import jax.numpy as jnp

    from ..models import transformer as tfm
    from ..parallel import pipeline as ppl

    cfg = tfm.TransformerConfig(
        vocab_size=vocab, d_model=d_model, n_heads=n_heads,
        n_layers=n_layers, d_ff=d_ff,
    )
    mesh = ppl.create_pp_mesh(1, 4, 1)
    base = tfm.init_params(jax.random.key(0), cfg)
    from ..train import lm as lmtrain
    from ..utils.timers import hard_block

    results = []
    # 7 configs over 2 fit parameters (r4 VERDICT weak #6: 4 points for
    # a 2-parameter model was underdetermined and the clamp kicked in);
    # spans analytic bubble 0.158 (M=16,v=1) .. 0.6 (M=2,v=1). v=4 is
    # infeasible at L=8/pp=4 (half a layer per chunk) and v=2 needs
    # M % 4 == 0 (parallel/pipeline.py), so extra spread comes from the
    # M axis at v=1 plus M=16 at v=2.
    for m, v in ((2, 1), (4, 1), (8, 1), (16, 1), (4, 2),
                 (8, 2), (16, 2)):
        batch = m * mb_rows
        # copy per config: the donated train step consumes its params, and
        # device_put aliases (rather than copies) leaves whose placement
        # already matches - donating an alias would delete `base`'s leaf
        params, _ = ppl.shard_pp_params(
            jax.tree.map(jnp.array, base), cfg, mesh, interleave=v
        )
        mom = jax.tree.map(jnp.zeros_like, params)
        step = ppl.make_pp_train_step(
            cfg, mesh, n_microbatches=m, lr=0.01, interleave=v
        )
        tokens, targets = lmtrain.make_copy_task(
            jax.random.key(1), batch=batch, seq_len=seq_len, vocab=vocab
        )
        for _ in range(max(warmup, 1)):  # >=1: the fence needs a loss
            params, mom, loss = step(params, mom, tokens, targets)
        hard_block(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            params, mom, loss = step(params, mom, tokens, targets)
        hard_block(loss)
        dt = time.perf_counter() - t0
        pp_n = 4
        results.append({
            "microbatches": m, "interleave": v,
            "tokens_per_s": round(batch * seq_len * steps / dt),
            "bubble_analytic": round((pp_n - 1) / (v * m + pp_n - 1), 4),
        })
    best = max(results, key=lambda r: r["tokens_per_s"])
    ideal = best["tokens_per_s"] / (1.0 - best["bubble_analytic"])
    for r in results:
        r["bubble_measured"] = round(1.0 - r["tokens_per_s"] / ideal, 4)

    tick_model = fit_tick_model(
        results, n_layers=n_layers, mb_rows=mb_rows, seq_len=seq_len,
        steps=steps,
    )
    return {
        "pp": 4, "d_model": d_model, "n_layers": n_layers,
        "seq_len": seq_len, "mb_rows": mb_rows,
        "devices": jax.device_count(), "platform": jax.default_backend(),
        "configs": results,
        "tick_model": tick_model,
        "note": (
            "bubble_measured compares raw tokens/s against the best "
            "config extrapolated by its analytic bubble; CPU-mesh "
            "per-tick dispatch overhead inflates it for long schedules "
            "(high M at v=1). bubble_overhead_adjusted = 1 - (model "
            "time of the v*M useful ticks, from the fitted T*(w*c+o) "
            "tick model) / MEASURED time: it tracks bubble_analytic "
            "only if the schedule really pays v*M+P-1 ticks "
            "(rel_fit_err is the model's residual)."
        ),
    }


def measure_lm_decode(
    *,
    d_model: int = 512,
    n_layers: int = 8,
    n_heads: int = 8,
    d_ff: int = 2048,
    vocab: int = 32768,
    batch: int = 16,
    prompt_len: int = 128,
    gen_short: int = 128,
    gen_long: int = 512,
    dtype: str = "bfloat16",
    repeats: int = 3,
) -> dict:
    """KV-cache decode throughput (models/transformer.py `generate`).

    `generate` scans prompt_len + max_new_tokens cached steps over a
    STATIC cache of that total size - every step attends the full padded
    cache - so per-step cost is a function of the total length, and an
    honest rate is the per-step AVERAGE at a stated cache size, not a
    cross-length "marginal" (a two-length diff mixes c(short) and
    c(long) and understates throughput). Reported: average ms/step and
    tokens/s at each of the two cache sizes (prompt + gen_short /
    gen_long); the spread IS the measured cache-length scaling. Compile
    time is excluded by a jitted warm-up per static length, and the
    fence round-trip is subtracted (utils/timers.py fence_rtt).

    Decode is HBM-bandwidth-bound, not FLOP-bound: each step streams
    every parameter once (the batch shares the read), so the utilization
    lens is bytes/s against peak HBM bandwidth - `hbm_util_pct`
    (params_bytes * steps/s / peak_bw) at the LONG cache size. MFU
    against the MXU peak would be misleadingly tiny here and is
    deliberately not reported.
    """
    import numpy as np

    import jax.numpy as jnp

    from ..models import transformer as tfm
    from ..utils.timers import fence_rtt, hard_block

    cfg = tfm.TransformerConfig(
        vocab_size=vocab, d_model=d_model, n_heads=n_heads,
        n_layers=n_layers, d_ff=d_ff,
        dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32,
    )
    params = tfm.init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(
        jax.random.key(1), (batch, prompt_len), 0, vocab, jnp.int32
    )

    def timed(n_new: int) -> float:
        # jit per static length: generate re-traces on every bare call
        # (~seconds of host time); under jit the repeats are cache hits
        # measuring device time only
        g = jax.jit(
            lambda p, pr: tfm.generate(p, pr, cfg, max_new_tokens=n_new)
        )
        out = g(params, prompt)
        hard_block(out)  # warm-up: compile for this static length
        rtt = fence_rtt(out)
        best = float("inf")
        for _ in range(max(repeats, 1)):
            t0 = time.perf_counter()
            out = g(params, prompt)
            hard_block(out)
            best = min(best, time.perf_counter() - t0 - rtt)
        return max(best, 1e-9)

    def stats(n_new: int, t: float) -> dict:
        steps = prompt_len + n_new  # the scan length (generate)
        return {
            "cache_len": steps,
            "wall_s": round(t, 3),
            "ms_per_step": round(t / steps * 1e3, 3),
            "tokens_per_s": round(batch * steps / t),
        }

    short = stats(gen_short, timed(gen_short))
    long_ = stats(gen_long, timed(gen_long))
    steps_s = 1e3 / long_["ms_per_step"]

    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    bytes_per_param = 2 if dtype == "bfloat16" else 4
    dev = jax.devices()[0]
    # decode streams params once per step, so params_bytes * steps/s
    # bounds achievable throughput (PEAK_HBM_BYTES table above)
    hbm_bw = peak_hbm_bandwidth(dev.device_kind)
    hbm_util = (
        round(n_params * bytes_per_param * steps_s / hbm_bw * 100.0, 2)
        if hbm_bw else None
    )
    return {
        "d_model": d_model, "n_layers": n_layers, "n_heads": n_heads,
        "vocab": vocab, "batch": batch, "prompt_len": prompt_len,
        "gen_short": gen_short, "gen_long": gen_long, "dtype": dtype,
        "device_kind": dev.device_kind,
        "platform": jax.default_backend(),
        # provenance: which per-step attention path produced this row -
        # merge-by-id would otherwise let a DNN_TPU_DECODE_IMPL=pallas
        # run silently replace the XLA numbers under the same row id
        "decode_impl": (
            "pallas" if os.environ.get("DNN_TPU_DECODE_IMPL", "auto")
            in ("pallas", "pallas-interpret") else "xla"
        ),
        # headline decode rate: per-step average at the LONG cache size
        # (conservative; the short-cache row shows the scaling)
        "decode_tokens_per_s": long_["tokens_per_s"],
        "decode_steps_per_s": round(steps_s, 1),
        "ms_per_step": long_["ms_per_step"],
        "at_cache_short": short,
        "at_cache_long": long_,
        "n_params": n_params,
        "hbm_util_pct": hbm_util,
    }


def measure_lm_training(
    *,
    d_model: int = 512,
    n_layers: int = 8,
    n_heads: int = 8,
    d_ff: int = 2048,
    vocab: int = 32768,
    seq_len: int = 2048,
    batch: int = 16,
    steps: int = 20,
    warmup: int = 2,
    attn: str = "flash",
    dtype: str = "bfloat16",
    remat: bool = False,
    remat_attn: bool = False,
    remat_policy: str = "",
    loss_chunks: int = 0,
    lr: float = 0.01,
    accum_steps: int = 1,
    grad_sync: str = "end",
    bucket_mb: float = 4.0,
    tracer=None,
    step_stats=None,
) -> dict:
    """Single-mesh LM throughput: tokens/s and MFU over `steps` timed steps.

    attn='flash' uses the tuned Pallas kernel on TPU (falls back to plain
    attention elsewhere - the returned dict records which path ran, so
    callers can fail loudly when the compiled kernel was required:
    VERDICT r2 weak #7). MFU follows `model_flops_per_token` with the
    dtype-adjusted peak; `hw_flops_per_step` adds the compiled
    executable's own cost_analysis() FLOPs when the backend reports them
    (None otherwise - utils/tracing.py compiled_flops).

    `tracer` (utils/tracing.py Tracer) records per-step `train_step` spans
    inside the timed loop WITHOUT fencing (dispatch time; fencing each
    step would change the measurement) plus a fenced `steady_window` span
    around the whole loop; `step_stats` (StepStats) gets one steady
    record per timed step from the same unfenced walls - trend data, not
    the headline (which stays the fenced-window tokens/s below).

    The row also carries the run's own goodput accounting
    (utils/goodput.py: a private ledger over setup -> warmup -> timed
    window): ``goodput_ratio`` and the non-zero ``badput_breakdown``
    seconds, so the bench matrix reports not just how fast the steady
    state is but how much of the measurement's wall-clock WAS steady
    state (init/compile being the honest overhead of short benches).
    """
    import jax.numpy as jnp

    from ..models import transformer as tfm
    from ..ops.flash import _on_tpu
    from ..utils.goodput import GOODPUT_CAUSE, GoodputLedger
    from . import lm as lmtrain

    # a private ledger (never the process singleton: rows must not leak
    # accounting into each other when several run in one process)
    ledger = GoodputLedger().start()

    cfg = tfm.TransformerConfig(
        vocab_size=vocab, d_model=d_model, n_heads=n_heads,
        n_layers=n_layers, d_ff=d_ff,
        dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32,
        remat=remat,
        remat_attn=remat_attn,
        remat_policy=remat_policy,
    )
    mesh = lmtrain.create_lm_mesh(1, 1, 1)
    params0 = tfm.init_params(jax.random.key(0), cfg)
    params, _ = lmtrain.shard_params(params0, cfg, mesh)
    mom = lmtrain.init_lm_momentum(params, mesh)
    step = lmtrain.make_lm_train_step(
        cfg, mesh, lr=lr, attn_impl=attn, loss_chunks=loss_chunks,
        accum_steps=accum_steps, grad_sync=grad_sync, bucket_mb=bucket_mb,
    )
    tokens, targets = lmtrain.make_copy_task(
        jax.random.key(1), batch=batch, seq_len=seq_len, vocab=vocab
    )
    from ..utils import tracing as tracing_mod
    from ..utils.timers import fence_rtt, hard_block

    if tracer is None:
        tracer = tracing_mod.NULL_TRACER
    hw_flops = tracing_mod.compiled_flops(step, params, mom, tokens, targets)

    # static cross-check (shardlint, analysis/): abstractly trace THE
    # compiled step being benched and total its collective payload, so
    # the bench row carries both the runtime ring estimate and the
    # analyzer's logical-payload count side by side (they use different
    # conventions; the point is that a schedule regression moves one
    # without the other). Trace-only - never affects the timed loop.
    static_comm = None
    try:
        from ..analysis.trace import collect_trace

        static_comm = collect_trace(
            jax.make_jaxpr(step)(params, mom, tokens, targets)
        ).total_collective_bytes()
    except Exception:
        pass
    if step_stats is not None and static_comm is not None:
        step_stats.static_comm_bytes_per_step = static_comm

    with tracer.span("warmup", track="train", steps=max(warmup, 1)):
        t_warm = time.perf_counter()
        for _ in range(max(warmup, 1)):
            params, mom, loss = step(params, mom, tokens, targets)
        hard_block(loss)
        # the warmup window absorbs compilation: one compile span on the
        # ledger (it also closes the setup-side init interval)
        ledger.step_span(0, time.perf_counter() - t_warm, is_compile=True)
    # the fence is a value fetch (block_until_ready alone is a no-op on the
    # axon tunnel); subtract its pure round-trip cost so the ~60-70 ms
    # tunnel RTT is not charged to the steps (utils/timers.py fence_rtt)
    rtt = fence_rtt(loss)
    timed = step
    if tracer.enabled or step_stats is not None:
        from . import lm as _lm

        # compile_first=False: the warm-up above absorbed compilation, so
        # every unfenced loop record is a steady-state dispatch wall
        timed = _lm.make_traced_step(
            step, tracer=tracer, step_stats=step_stats,
            items_per_step=batch * seq_len, fence=False,
            compile_first=False,
        )
    with tracer.span("steady_window", track="train", steps=steps):
        t0 = time.perf_counter()
        for _ in range(steps):
            params, mom, loss = timed(params, mom, tokens, targets)
        hard_block(loss)
        dt = max(time.perf_counter() - t0 - rtt, 1e-9)
    # the fenced steady window is the goodput; everything around it
    # (model build, warmup/compile, fences) is the bench's own badput
    ledger.step_span(
        1, dt, tokens=batch * seq_len * steps, is_compile=False
    )
    goodput_rec = ledger.finalize()
    tok_s = batch * seq_len * steps / dt
    flops_tok = model_flops_per_token(cfg, seq_len)
    dev = jax.devices()[0]
    peak = peak_flops(dev.device_kind, dtype)
    mfu = flops_tok * tok_s / peak * 100.0 if peak else None
    if step_stats is not None:
        step_stats.set_flops(
            hw_flops if hw_flops is not None
            else flops_tok * batch * seq_len,
            "cost_analysis" if hw_flops is not None else "analytic",
        )
        if step_stats.peak_flops_per_device is None:
            step_stats.peak_flops_per_device = peak
        step_stats.capture_memory(tracer)
    # committed-memory delta column for the grad_sync variant rows: the
    # overlap schedule's shard-carry should show up here (CPU returns None)
    snap = tracing_mod.device_memory_snapshot()
    mem_peak = (
        max(
            s.get("peak_bytes_in_use", s.get("bytes_in_use", 0))
            for s in snap.values()
        )
        if snap else None
    )
    return {
        "d_model": d_model, "n_layers": n_layers, "n_heads": n_heads,
        "d_ff": d_ff, "seq_len": seq_len,
        "vocab": vocab, "batch": batch, "steps": steps, "dtype": dtype,
        "attn": attn, "remat": remat, "remat_attn": remat_attn,
        "remat_policy": remat_policy,
        "accum_steps": accum_steps, "grad_sync": grad_sync,
        "mem_peak_bytes": mem_peak,
        # shardlint static logical payload per step (None when the trace
        # failed); the bench row's cross-check against StepStats'
        # comm_bytes_per_step runtime ring estimate
        "static_collective_bytes": static_comm,
        # provenance: WHICH flash kernel measured this row (r3's numbers
        # were the library kernel; r4+ defaults to the own kernels)
        "attn_kernel": (
            ("pallas-flash-"
             + os.environ.get("DNN_TPU_FLASH_IMPL", "own"))
            if attn == "flash" and _on_tpu()
            else "xla"
        ),
        "device_kind": dev.device_kind,
        "tokens_per_s": round(tok_s),
        "wall_s": round(dt, 3),
        # goodput accounting of this measurement's own wall-clock
        # (utils/goodput.py; steady window / total incl. setup+compile)
        "goodput_ratio": goodput_rec["goodput_ratio"],
        "badput_breakdown": {
            k: round(v, 3)
            for k, v in goodput_rec["badput_s"].items()
            if v > 0 and k != GOODPUT_CAUSE
        },
        "model_tflops_per_s": round(flops_tok * tok_s / 1e12, 2),
        "mfu_pct": round(mfu, 2) if mfu is not None else None,
        # provenance: hardware FLOPs per step straight from the compiled
        # executable's cost_analysis() (includes remat recompute, unlike
        # the model-FLOPs MFU numerator above); None where unreported
        "hw_flops_per_step": hw_flops,
        "final_loss": float(loss),
    }


def measure_guard_overhead(
    *,
    d_model: int = 512,
    n_layers: int = 8,
    n_heads: int = 8,
    d_ff: int = 2048,
    vocab: int = 32768,
    seq_len: int = 2048,
    batch: int = 16,
    steps: int = 20,
    warmup: int = 2,
    attn: str = "flash",
    dtype: str = "bfloat16",
    budget_pct: float = 1.0,
) -> dict:
    """Guard-overhead A/B: the identical LM config with guard off vs
    ``--guard warn`` (health bundle compiled into the step + one-step-
    lagged host observation, train/guard.py HealthPipe).

    Two claims, both asserted into the returned row:
    - ``within_budget``: the warn-mode steady-state step-time overhead is
      under `budget_pct` (default 1%) - the health bundle costs one O(1)
      finite-check on scalars the step already computes (plus one global
      grad-norm reduction when clipping is off, as here - the honest
      worst case) and the observation never fences the dispatch pipeline.
    - ``final_loss_bitwise_equal``: warn mode is observation-only - the
      guarded run's final loss is BIT-IDENTICAL to the unguarded run's
      (same seeds, same data, same update math).
    """
    import jax.numpy as jnp

    from ..models import transformer as tfm
    from . import lm as lmtrain
    from .guard import GuardConfig, HealthPipe, TrainingGuard

    cfg = tfm.TransformerConfig(
        vocab_size=vocab, d_model=d_model, n_heads=n_heads,
        n_layers=n_layers, d_ff=d_ff,
        dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32,
    )
    mesh = lmtrain.create_lm_mesh(1, 1, 1)
    params0 = tfm.init_params(jax.random.key(0), cfg)
    tokens, targets = lmtrain.make_copy_task(
        jax.random.key(1), batch=batch, seq_len=seq_len, vocab=vocab
    )
    from ..utils.timers import fence_rtt, hard_block

    def run(guard_on: bool):
        params, _ = lmtrain.shard_params(params0, cfg, mesh)
        mom = lmtrain.init_lm_momentum(params, mesh)
        step = lmtrain.make_lm_train_step(
            cfg, mesh, lr=0.01, attn_impl=attn, with_health=guard_on,
        )
        pipe = None
        if guard_on:
            pipe = HealthPipe(TrainingGuard(
                GuardConfig(policy="warn"), log=lambda *_: None,
            ))
        loss = None
        for i in range(max(warmup, 1)):
            out = step(params, mom, tokens, targets)
            params, mom, loss = out[0], out[1], out[2]
        hard_block(loss)
        rtt = fence_rtt(loss)
        t0 = time.perf_counter()
        for i in range(steps):
            out = step(params, mom, tokens, targets)
            params, mom, loss = out[0], out[1], out[2]
            if pipe is not None:
                pipe.push(i, out[3])
        if pipe is not None:
            pipe.flush()
        hard_block(loss)
        dt = max(time.perf_counter() - t0 - rtt, 1e-9)
        return dt, float(loss)

    base_dt, base_loss = run(False)
    guard_dt, guard_loss = run(True)
    overhead_pct = (guard_dt / base_dt - 1.0) * 100.0
    tok = batch * seq_len * steps
    return {
        "d_model": d_model, "n_layers": n_layers, "seq_len": seq_len,
        "batch": batch, "steps": steps, "dtype": dtype, "attn": attn,
        "device_kind": jax.devices()[0].device_kind,
        "base_tokens_per_s": round(tok / base_dt),
        "guard_tokens_per_s": round(tok / guard_dt),
        "overhead_pct": round(overhead_pct, 3),
        "budget_pct": budget_pct,
        "within_budget": overhead_pct < budget_pct,
        "final_loss": guard_loss,
        "final_loss_bitwise_equal": base_loss == guard_loss,
    }


def measure_dynamics_overhead(
    *,
    d_model: int = 512,
    n_layers: int = 8,
    n_heads: int = 8,
    d_ff: int = 2048,
    vocab: int = 32768,
    seq_len: int = 2048,
    batch: int = 16,
    steps: int = 20,
    warmup: int = 2,
    attn: str = "flash",
    dtype: str = "bfloat16",
    budget_pct: float = 1.0,
) -> dict:
    """Dynamics-observatory A/B: the identical LM config with
    ``--dynamics`` off vs on (per-layer norm bundle compiled into the
    step + the one-step-lagged DynamicsSink decode, train/dynamics.py).

    Two claims, both asserted into the returned row:
    - ``within_budget``: the steady-state step-time overhead is under
      `budget_pct` (default 1%) - the per-leaf squared-norm reductions
      are O(params) elementwise flops over tensors the backward already
      produced (vs the O(params * seq * batch) matmuls of the step), and
      the sink's decode rides the same lagged fetch cadence as the
      guard, never fencing the dispatch pipeline.
    - ``final_loss_bitwise_equal``: dynamics is observation-only - the
      bundle is an extra OUTPUT of the step, the update math is
      untouched, so the final loss is BIT-IDENTICAL to the plain run's.
    """
    import jax.numpy as jnp

    from ..models import transformer as tfm
    from ..parallel.rules import named_leaves
    from . import lm as lmtrain
    from .dynamics import DynamicsSink

    cfg = tfm.TransformerConfig(
        vocab_size=vocab, d_model=d_model, n_heads=n_heads,
        n_layers=n_layers, d_ff=d_ff,
        dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32,
    )
    mesh = lmtrain.create_lm_mesh(1, 1, 1)
    params0 = tfm.init_params(jax.random.key(0), cfg)
    tokens, targets = lmtrain.make_copy_task(
        jax.random.key(1), batch=batch, seq_len=seq_len, vocab=vocab
    )
    from ..utils.timers import fence_rtt, hard_block

    def run(dyn_on: bool):
        params, _ = lmtrain.shard_params(params0, cfg, mesh)
        mom = lmtrain.init_lm_momentum(params, mesh)
        step = lmtrain.make_lm_train_step(
            cfg, mesh, lr=0.01, attn_impl=attn, dynamics=dyn_on,
        )
        sink = None
        if dyn_on:
            sink = DynamicsSink([p for p, _ in named_leaves(params)])
        loss = None
        for i in range(max(warmup, 1)):
            out = step(params, mom, tokens, targets)
            params, mom, loss = out[0], out[1], out[2]
        hard_block(loss)
        rtt = fence_rtt(loss)
        t0 = time.perf_counter()
        for i in range(steps):
            out = step(params, mom, tokens, targets)
            params, mom, loss = out[0], out[1], out[2]
            if sink is not None:
                sink.push(i, out[3])
        if sink is not None:
            sink.flush()
        hard_block(loss)
        dt = max(time.perf_counter() - t0 - rtt, 1e-9)
        return dt, float(loss)

    base_dt, base_loss = run(False)
    dyn_dt, dyn_loss = run(True)
    overhead_pct = (dyn_dt / base_dt - 1.0) * 100.0
    tok = batch * seq_len * steps
    return {
        "d_model": d_model, "n_layers": n_layers, "seq_len": seq_len,
        "batch": batch, "steps": steps, "dtype": dtype, "attn": attn,
        "device_kind": jax.devices()[0].device_kind,
        "base_tokens_per_s": round(tok / base_dt),
        "dynamics_tokens_per_s": round(tok / dyn_dt),
        "overhead_pct": round(overhead_pct, 3),
        "budget_pct": budget_pct,
        "within_budget": overhead_pct < budget_pct,
        "final_loss": dyn_loss,
        "final_loss_bitwise_equal": base_loss == dyn_loss,
    }


def measure_watchdog_overhead(
    *,
    d_model: int = 512,
    n_layers: int = 8,
    n_heads: int = 8,
    d_ff: int = 2048,
    vocab: int = 32768,
    seq_len: int = 2048,
    batch: int = 16,
    steps: int = 20,
    warmup: int = 2,
    attn: str = "flash",
    dtype: str = "bfloat16",
    budget_pct: float = 1.0,
) -> dict:
    """Live-observability overhead A/B: the identical LM config with no
    monitoring vs the full ``--metrics-port`` stack live - metrics
    registry, /metrics + /healthz HTTP server thread, stall/recompile
    watchdog thread, the per-step publish sites (heartbeat, step
    counter, step-time histogram, one ``_cache_size()`` read), PLUS the
    fleet-observability extras a supervised worker carries: the
    heartbeat-FILE writer thread and the armed write-through crash
    flight recorder (`utils/obs.py HeartbeatFileWriter` / `FLIGHT`),
    PLUS the armed goodput ledger (`utils/goodput.py LEDGER`: per-step
    interval recording, registry export, and the write-through run
    record) - the FULL supervised-worker observability surface under
    the same <1% steady-step budget.

    Two claims, both asserted into the returned row:
    - ``within_budget``: steady-step overhead under `budget_pct` (default
      1%). The per-step cost is a handful of host-side float stores on
      pre-resolved metric children (utils/obs.py's lock-free fast path);
      the server and watchdog live on their own daemon threads, off the
      step loop's critical path.
    - ``final_loss_bitwise_equal``: monitoring is observation-only - the
      monitored run's final loss is BIT-IDENTICAL to the bare run's.
    """
    import jax.numpy as jnp

    from ..models import transformer as tfm
    from . import lm as lmtrain
    from .monitor import WatchdogConfig, attach_monitor

    cfg = tfm.TransformerConfig(
        vocab_size=vocab, d_model=d_model, n_heads=n_heads,
        n_layers=n_layers, d_ff=d_ff,
        dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32,
    )
    mesh = lmtrain.create_lm_mesh(1, 1, 1)
    params0 = tfm.init_params(jax.random.key(0), cfg)
    tokens, targets = lmtrain.make_copy_task(
        jax.random.key(1), batch=batch, seq_len=seq_len, vocab=vocab
    )
    from ..utils.timers import fence_rtt, hard_block

    def run(monitored: bool):
        params, _ = lmtrain.shard_params(params0, cfg, mesh)
        mom = lmtrain.init_lm_momentum(params, mesh)
        step = lmtrain.make_lm_train_step(
            cfg, mesh, lr=0.01, attn_impl=attn
        )
        monitor = None
        tmpdir = None
        env_keys = ("DNN_TPU_HEARTBEAT_FILE", "DNN_TPU_FLIGHT_FILE",
                    "DNN_TPU_RUN_RECORD")
        if monitored:
            # the FULL fleet stack: registry + server + watchdog as
            # before, PLUS the supervised-worker extras - heartbeat-file
            # writer thread, the armed (write-through) crash flight
            # recorder, and the armed goodput ledger with its run-record
            # write-through - so the <1% budget covers the whole
            # observability surface a supervised worker carries
            import tempfile

            from ..utils.goodput import LEDGER

            tmpdir = tempfile.mkdtemp(prefix="dnn_fleet_obs_bench_")
            os.environ["DNN_TPU_HEARTBEAT_FILE"] = os.path.join(
                tmpdir, "hb.json"
            )
            os.environ["DNN_TPU_FLIGHT_FILE"] = os.path.join(
                tmpdir, "flight.json"
            )
            os.environ["DNN_TPU_RUN_RECORD"] = os.path.join(
                tmpdir, "run_record.json"
            )
            LEDGER.reset()
            LEDGER.start()
            monitor = attach_monitor(
                metrics_port=0, config=WatchdogConfig(),
                log=lambda *_: None,
            )
            monitor.recompiles.swap(step)
        reg = monitor.registry if monitor is not None else None
        m_steps = m_wall = led = None
        if reg is not None:
            from ..utils.goodput import LEDGER as led

            m_steps = reg.counter("train_steps_total")
            m_wall = reg.histogram("train_step_seconds")
        loss = None
        try:
            for i in range(max(warmup, 1)):
                params, mom, loss = step(params, mom, tokens, targets)[:3]
            hard_block(loss)
            rtt = fence_rtt(loss)
            t0 = time.perf_counter()
            for i in range(steps):
                ts = time.perf_counter()
                params, mom, loss = step(params, mom, tokens, targets)[:3]
                if reg is not None:
                    # the exact per-step publish set --metrics-port wires
                    step_dt = time.perf_counter() - ts
                    reg.beat(i)
                    reg.mark_ready()
                    m_steps.inc()
                    m_wall.observe(step_dt)
                    monitor.recompiles.observe(i)
                    led.step_span(i, step_dt, tokens=batch * seq_len,
                                  is_compile=False)
            hard_block(loss)
            dt = max(time.perf_counter() - t0 - rtt, 1e-9)
        finally:
            if monitor is not None:
                monitor.close()
            if tmpdir is not None:
                from ..utils.goodput import LEDGER
                from ..utils.obs import FLIGHT

                LEDGER.finalize()
                LEDGER.reset()  # disarm the process-global ledger
                FLIGHT.reset()  # disarm the process-global recorder
                for k in env_keys:
                    os.environ.pop(k, None)
        return dt, float(loss)

    base_dt, base_loss = run(False)
    mon_dt, mon_loss = run(True)
    overhead_pct = (mon_dt / base_dt - 1.0) * 100.0
    tok = batch * seq_len * steps
    return {
        "d_model": d_model, "n_layers": n_layers, "seq_len": seq_len,
        "batch": batch, "steps": steps, "dtype": dtype, "attn": attn,
        "device_kind": jax.devices()[0].device_kind,
        "base_tokens_per_s": round(tok / base_dt),
        "monitored_tokens_per_s": round(tok / mon_dt),
        "overhead_pct": round(overhead_pct, 3),
        "budget_pct": budget_pct,
        "within_budget": overhead_pct < budget_pct,
        "final_loss": mon_loss,
        "final_loss_bitwise_equal": base_loss == mon_loss,
    }


def measure_zero_memory(
    *,
    d_model: int = 256,
    n_layers: int = 4,
    n_heads: int = 8,
    d_ff: int = 1024,
    vocab: int = 4096,
    seq_len: int = 256,
    batch: int = 8,
) -> dict:
    """Measured per-device optimizer-state footprint: replicated Adam vs
    ZeRO-1 Adam over the full data axis.

    The memory claim that motivates ZeRO-1 (`parallel/zero.py`: each
    device owns 1/dp of the O(params) optimizer state) is pinned here by
    counting the bytes of the ACTUAL committed device buffers
    (`Array.addressable_shards`), not shapes-on-paper - and counted
    again after one real compiled train step, so the artifact proves the
    state *stays* sharded through the jitted update (a lost
    out-sharding would silently re-replicate it). The reference has no
    counterpart: each of its MPI workers holds a full private optimizer
    (`data_parallelism_train.py:187` recreates torch SGD per epoch per
    rank), so its optimizer memory grows with replica count - this
    measurement shows the opposite slope on a mesh.

    Expected bytes are derived exactly (per-leaf ceil-padded shards,
    `parallel/zero.py leaf_shard_size`, f32 m+v plus the step counter) -
    measured == expected is the pass condition, asserted by
    tests/test_zero.py rather than here so the bench row still reports
    honest numbers if the invariant ever breaks.
    """
    from ..models import transformer as tfm
    from ..parallel.zero import leaf_shard_size
    from . import lm as lmtrain

    dp = jax.device_count()
    cfg = tfm.TransformerConfig(
        vocab_size=vocab, d_model=d_model, n_heads=n_heads,
        n_layers=n_layers, d_ff=d_ff,
    )
    mesh = lmtrain.create_lm_mesh(dp, 1, 1)

    def fresh_params():
        # per-optimizer: the compiled step donates params/state, so each
        # measurement needs its own live copies
        p, _ = lmtrain.shard_params(
            tfm.init_params(jax.random.key(0), cfg), cfg, mesh
        )
        return p

    tokens, targets = lmtrain.make_copy_task(
        jax.random.key(1), batch=batch, seq_len=seq_len, vocab=vocab
    )

    def bytes_per_device(tree) -> int:
        """Max committed bytes on any one device (replicated leaves count
        their full copy on every device; sharded leaves their shard)."""
        per: dict = {}
        for leaf in jax.tree.leaves(tree):
            for sh in leaf.addressable_shards:
                key = getattr(sh.device, "id", sh.device)
                per[key] = per.get(key, 0) + sh.data.nbytes
        return max(per.values()) if per else 0

    probe = fresh_params()
    param_bytes = bytes_per_device(probe)
    sizes = [int(p.size) for p in jax.tree.leaves(probe)]
    n_params = sum(sizes)
    del probe  # a memory-measuring utility should not hold a spare copy
    # exact expected ZeRO per-device state: f32 m+v shards per leaf
    # (ceil-padded), plus the replicated (): int32 step counter
    expected_zero = 2 * 4 * sum(
        leaf_shard_size(s, dp) for s in sizes
    ) + 4

    out = {}
    for optimizer in ("adam", "zero-adam"):
        params = fresh_params()
        mom = lmtrain.init_lm_momentum(params, mesh, optimizer)
        init_b = bytes_per_device(mom)
        step = lmtrain.make_lm_train_step(
            cfg, mesh, lr=0.01, optimizer=optimizer
        )
        p2, mom2, loss = step(params, mom, tokens, targets)
        jax.block_until_ready(loss)
        out[optimizer] = {
            "state_bytes_per_device": init_b,
            "state_bytes_per_device_post_step": bytes_per_device(mom2),
            "final_loss": round(float(loss), 4),
        }
    adam_b = out["adam"]["state_bytes_per_device"]
    zero_b = out["zero-adam"]["state_bytes_per_device"]
    return {
        "devices": dp,
        "platform": jax.default_backend(),
        "d_model": d_model, "n_layers": n_layers, "n_params": n_params,
        "param_bytes_per_device": param_bytes,
        "optimizers": out,
        "expected_zero_bytes_per_device": expected_zero,
        "reduction_x": round(adam_b / max(zero_b, 1), 2),
        "note": (
            "bytes are committed device buffers (addressable_shards), "
            "measured at init and again after one compiled step; "
            "reduction_x ~ dp modulo per-leaf ceil padding and the "
            "replicated step counter. The reference's optimizer memory "
            "multiplies with workers; this divides."
        ),
    }


def measure_fault_tolerance(
    *,
    probs=(0.0, 0.3, 0.6),
    epochs: int = 8,
    batch_size: int = 16,
    synthetic_size: int = 2000,
    lr: float = 0.05,
    seed: int = 0,
    straggler_duration: float = 0.25,
) -> dict:
    """The fault experiment the reference implemented but never ran
    (its report section 6.2: `simulate_failure` exists at
    `data_parallelism_train.py:41-46` yet no fault numbers were ever
    published). Sweeps `--failure-probability` at a fixed seed on the
    full mesh and measures what drop-and-continue actually costs.

    Two claims, both measured rather than asserted:

    - **Wall-clock is flat in p.** A dropped device is excluded from the
      epoch-edge parameter average by the live-mask (`parallel/fault.py`;
      weighted pmean over survivors) - nobody waits for it. In the
      reference the same event is a straggler sleep that stalls the
      WHOLE epoch behind the blocking recv
      (`data_parallelism_train.py:227`): its cost is p * duration *
      epochs of pure wall-clock, unmeasured in its report.
    - **Convergence survives.** Dropped devices discard their epoch's
      contribution (mean_live_frac is the surviving fraction), yet the
      run reaches the control's accuracy at the default settings even at
      p=0.6, and never diverges or deadlocks - including all-dead epochs
      (the mask degrades to keeping current params).

    Same seed everywhere: p=0 is the exact control (identical shuffles,
    identical init), so deltas are attributable to the masking alone.
    """
    n = jax.device_count()
    train_split = load_split(True, source="synthetic",
                             synthetic_size=synthetic_size)
    test_split = load_split(False, source="synthetic",
                            synthetic_size=max(1, synthetic_size // 5))
    # ONE engine, ONE compile for the whole sweep: failure_probability
    # only feeds the host-built live-masks run_span passes as runtime
    # arguments (engine.py run_span), so the compiled span is identical
    # at every p - the sweep mutates the config and resets state
    # (same seed -> same init/shuffles: p=0 stays the exact control).
    # This is also why the sweep cannot just call measure_dp_training
    # per point (each call would rebuild + re-AOT-compile its engine).
    cfg = TrainConfig(
        lr=lr, batch_size=batch_size, epochs=epochs, nb_proc=n,
        regime="data_parallel", seed=seed,
    )
    engine = Engine(cfg, train_split, test_split)
    engine.compile_span(epochs, eval_inside=False)
    points = []
    for p in probs:
        cfg.failure_probability = float(p)
        engine.reset_state()
        timers = T.PhaseTimers()
        engine.run_span(0, epochs, eval_inside=False, timers=timers)
        vl, va = engine._eval_fn(
            engine.params, engine.test_images, engine.test_labels,
            engine.test_weights,
        )
        lives = [h.n_live for h in engine.history]
        points.append({
            "failure_probability": float(p),
            "val_acc": round(float(va), 2),
            "val_loss": round(float(vl), 4),
            "train_s": round(
                timers.get(T.TRAINING) + timers.get(T.COMMUNICATION), 3),
            "epochs_degraded": sum(1 for v in lives if v < n),
            "min_live_devices": min(lives),
            "mean_live_frac": round(sum(lives) / (len(lives) * n), 3),
        })
    # baseline = the actual p=0 control. A custom sweep without one gets
    # wall_vs_p0=None plus wall_vs_first (ratio to its first point) - the
    # field name promises p=0 and must not silently mean something else
    t0 = next((c["train_s"] for c in points
               if c["failure_probability"] == 0.0), None)
    for c in points:
        c["wall_vs_p0"] = (None if t0 is None
                           else round(c["train_s"] / max(t0, 1e-9), 3))
        if t0 is None:
            c["wall_vs_first"] = round(
                c["train_s"] / max(points[0]["train_s"], 1e-9), 3)

    # the reference's ACTUAL failure semantics, priced: --failure-duration
    # sleeps the epoch (straggler_sleep; one sleep per degraded epoch,
    # like the reference's overlapping worker sleeps behind the blocking
    # recv). Same seed and p, per-epoch path, duration 0 vs d: identical
    # masks and compute, so the wall delta IS the stall - compared to the
    # predicted epochs_degraded * duration.
    straggler = None
    if straggler_duration > 0 and max(probs) > 0:
        import contextlib
        import io

        cfg.failure_probability = float(max(probs))
        walls = {}
        first = True
        for dur in (0.0, float(straggler_duration)):
            cfg.failure_duration = dur
            engine.reset_state()
            if first:  # compile the per-epoch path outside the timing
                engine.run_epoch(0, timers=T.PhaseTimers(), do_eval=False)
                engine.reset_state()
                first = False
            # stdout redirected SYMMETRICALLY on both sides: the dur>0
            # run prints two fail/wake lines per failed device per epoch
            # (parallel/fault.py straggler_sleep) and that I/O must not
            # bias the delta; eval is skipped - the stall is the quantity
            t_w = time.perf_counter()
            with contextlib.redirect_stdout(io.StringIO()):
                for e in range(epochs):
                    engine.run_epoch(e, timers=T.PhaseTimers(),
                                     do_eval=False)
            walls[dur] = time.perf_counter() - t_w
        degraded = sum(1 for h in engine.history if h.n_live < n)
        cfg.failure_duration = 0.0
        straggler = {
            "failure_probability": float(max(probs)),
            "duration_s": float(straggler_duration),
            "epochs_degraded": degraded,
            "predicted_stall_s": round(degraded * straggler_duration, 3),
            "measured_stall_s": round(
                walls[float(straggler_duration)] - walls[0.0], 3),
        }
    return {
        "devices": n,
        "platform": jax.default_backend(),
        "epochs": epochs, "batch_size": batch_size,
        "synthetic_size": synthetic_size, "seed": seed,
        "points": points,
        "straggler": straggler,
        "note": (
            "fixed seed: p=0 is the exact control. wall_vs_p0 ~ 1.0 is "
            "the drop-and-continue claim (no one waits for dead "
            "devices); the reference's straggler-sleep design stalls "
            "every epoch behind its blocking recv instead, and its "
            "report ran no fault experiment at all (section 6.2)."
        ),
    }


def measure_ep_scaling(
    *,
    eps=(1, 2, 4, 8),
    d_model: int = 128,
    n_layers: int = 2,
    n_heads: int = 8,
    d_ff: int = 256,
    vocab: int = 2048,
    seq_len: int = 256,
    batch: int = 8,
    steps: int = 3,
    n_experts: int = 8,
    top_k: int = 2,
) -> dict:
    """Expert-parallel scaling shape on the virtual CPU mesh - the EP
    analog of `measure_sp_scaling`, completing the measured-artifact set
    for every parallelism axis the framework carries (dp / sp / pp / ep).

    Fixed GLOBAL batch and data, expert axis swept: experts shard over
    the data axis (`train/lm.py`: ep rides dp), so at ep=1 one device
    holds all experts and no dispatch collective runs; at ep>1 each
    device holds n_experts/ep experts and every MoE layer pays one
    all_to_all each way (`parallel/moe.py`). Total model FLOPs are
    identical at every ep on the shared host core, so ideal wall is flat
    and overhead_vs_ep1 is the measured expert-parallel dispatch cost.

    `moe_capacity_factor` is pinned to n_experts/top_k, which makes
    per-expert capacity equal the device's token count - the no-drop
    regime, where routing is load-independent and every ep computes the
    same model step (the loss column is the semantics check; it agrees
    to blockwise-reduction tolerance - the psum association varies with
    ep. With a smaller factor, capacity is per-device and drop patterns
    would legitimately vary with ep).
    """
    from ..models import transformer as tfm
    from . import lm as lmtrain

    cfg = tfm.TransformerConfig(
        vocab_size=vocab, d_model=d_model, n_heads=n_heads,
        n_layers=n_layers, d_ff=d_ff, n_experts=n_experts,
        moe_top_k=top_k, moe_capacity_factor=n_experts / top_k,
    )
    points = _lm_axis_sweep(
        eps, cfg=cfg, make_mesh=lambda ep: lmtrain.create_lm_mesh(ep, 1, 1),
        axis_key="ep", batch=batch, seq_len=seq_len, vocab=vocab,
        steps=steps,
        point_extras=lambda ep: {"experts_per_device": n_experts // ep},
    )
    return {
        "devices": jax.device_count(),
        "platform": jax.default_backend(),
        "d_model": d_model, "n_layers": n_layers, "seq_len": seq_len,
        "batch": batch, "steps": steps,
        "n_experts": n_experts, "top_k": top_k,
        "host_cores": os.cpu_count(),
        "points": points,
        "overhead_vs_ep1_max": max(p["overhead_vs_ep1"] for p in points),
        "note": (
            "fixed global batch and data on one shared host core: ideal "
            "wall is flat in ep; overhead_vs_ep1 is the measured "
            "expert-parallel dispatch cost (one all_to_all each way per "
            "MoE layer at ep>1, none at ep=1). capacity_factor = "
            "E/top_k pins the no-drop regime, so the loss column agrees "
            "at every ep to blockwise-reduction tolerance - the "
            "semantics check."
        ),
    }


def measure_native_batcher(
    *,
    n_rows: int = 20000,
    batch: int = 4096,
    reps: int = 5,
) -> dict:
    """Host-side input-pipeline kernels: the C++ batcher (`native/`) vs
    its own pure-numpy fallback, per kernel, best-of-`reps` wall.

    The native layer exists for the runtime *around* the XLA compute
    path (SURVEY.md section 2: the reference's native layer is external
    libmpi + ATen; here it is XLA plus these host kernels). This row
    prices that choice on the actual host: fused single-pass C++
    (decode+transpose+normalize; gather+normalize) against the multi-
    pass numpy chain the wrappers fall back to - the exact same
    functions (`native.fallback_*`), so the baseline cannot drift from
    the shipped fallback. Parity of outputs is pinned by
    tests/test_native.py; this measures only speed. Purely host CPU:
    no jax, no chip claim.
    """
    import numpy as np

    from .. import native

    rng = np.random.default_rng(0)
    rows = rng.integers(0, 256, (n_rows, 3072), dtype=np.uint8)
    idx = rng.integers(0, n_rows, batch).astype(np.int64)

    def best(f):
        f()  # warm-up (first native call builds/loads the library)
        b = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            f()
            b = min(b, time.perf_counter() - t0)
        return b

    kernels = {
        "cifar_decode_normalize": (
            lambda: native.cifar_decode_normalize(rows, 0.5, 0.5),
            lambda: native.fallback_cifar_decode_normalize(rows, 0.5, 0.5),
            n_rows,
        ),
        "gather_normalize_u8": (
            lambda: native.gather_normalize_u8(rows, idx, 0.5, 0.5),
            lambda: native.fallback_gather_normalize_u8(
                rows, idx, 0.5, 0.5),
            batch,
        ),
    }
    out = {}
    for name, (nat, fb, images) in kernels.items():
        tn, tf = best(nat), best(fb)
        out[name] = {
            "native_ms": round(tn * 1e3, 2),
            "fallback_ms": round(tf * 1e3, 2),
            "speedup_x": round(tf / max(tn, 1e-9), 2),
            "native_images_per_s": round(images / max(tn, 1e-9)),
        }
    return {
        "native_available": native.available(),
        "host_cores": os.cpu_count(),
        "n_rows": n_rows, "batch": batch, "reps": reps,
        "kernels": out,
        "note": (
            "best-of-reps wall per kernel, native C++ vs the SAME "
            "pure-numpy fallback the wrappers ship (native.fallback_*); "
            "host-only, no chip claim. Speedup on one core is pure "
            "fusion (single pass, no float32 intermediate churn); "
            "multi-core hosts add the pthread fan-out on top."
        ),
    }


def measure_serving(
    *,
    d_model: int = 512,
    n_layers: int = 8,
    n_heads: int = 8,
    d_ff: int = 2048,
    vocab: int = 256,
    dtype: str = "bfloat16",
    rate: float = 4.0,
    requests: int = 24,
    prompt_lens=(16, 64, 128),
    max_new: int = 32,
    max_batch: int = 8,
    num_blocks: int = 129,
    block_size: int = 16,
    max_seq_len: int = 256,
    prefill_chunk: int = 16,
    seed: int = 0,
    kv_dtype: str = "bf16",
    weight_dtype: str = "bf16",
    spec_decode: int = 0,
    spec_draft_layers: int = 0,
    min_capacity_ratio: float = 1.8,
    min_top1_agreement: float = 0.99,
    min_accepted_per_step: float = 1.5,
) -> dict:
    """The serving row: sustained requests/s + TTFT / inter-token
    latency under the open-loop load generator (tools/loadgen.py)
    against a real in-process server (serve/ stack end to end: HTTP,
    SSE streaming, admission, continuous batching, paged KV).

    Open loop means offered load never slows to match the server -
    queueing shows up in TTFT, which is the number a capacity plan
    needs. The serving goodput ledger's breakdown (decode = goodput,
    prefill, queue_wait, batch_formation_idle, kv_alloc_stall) rides
    along, so the row says not just how fast but WHERE the wall-clock
    went (docs/SERVING.md).

    ``kv_dtype="int8"`` runs the same workload on the quantized KV pool
    and GATES the two claims that make quantization honest
    (docs/MEASUREMENT.md "Low-precision parity gates"):

    - capacity: the concurrent-sequence capacity of an int8 pool sized
      to the SAME HBM byte budget as the bf16 pool, MEASURED by
      admitting max-length sequences into both allocators until
      OutOfBlocks, must be >= ``min_capacity_ratio`` x bf16's;
    - accuracy: per-token top-1 agreement of every completed stream vs
      the offline bf16 ``generate()`` oracle must be >=
      ``min_top1_agreement``.

    ``weight_dtype="int8"`` serves with int8-quantized weights and
    applies the same per-token top-1 agreement gate (the capacity claim
    is the pool's, so only the accuracy half applies).

    ``spec_decode=k`` runs speculative decoding (early-exit drafter +
    one k+1-position verify per tick) and gates the two claims that
    make the mode worth shipping:

    - accepted tokens per speculative slot-step (the guaranteed token
      plus accepted drafts) must be > ``min_accepted_per_step``;
    - end-to-end tokens/s must be STRICTLY greater than a paired
      non-spec run at the same offered load (measured here, same
      engine geometry, spec off).

    Greedy spec streams are token-exact vs ``generate()``, so the
    oracle gate composes rather than weakening.
    """
    import sys as _sys

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models.transformer import TransformerConfig, init_params
    from ..serve import (
        EngineConfig,
        SchedulerConfig,
        ServeEngine,
        ServeScheduler,
    )
    from ..serve.http import ServeServer
    from ..utils.obs import MetricsRegistry

    tools_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))), "tools",
    )
    if tools_dir not in _sys.path:
        _sys.path.insert(0, tools_dir)
    import loadgen

    cfg = TransformerConfig(
        vocab_size=vocab, d_model=d_model, n_heads=n_heads,
        n_layers=n_layers, d_ff=d_ff,
        dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32,
    )
    params = init_params(jax.random.key(seed), cfg)

    def _run(spec_k: int):
        """One end-to-end serving run (engine -> scheduler -> HTTP ->
        loadgen) at the shared geometry and offered load."""
        eng = ServeEngine(params, cfg, EngineConfig(
            max_batch=max_batch, num_blocks=num_blocks,
            block_size=block_size, max_seq_len=max_seq_len,
            prefill_chunk=prefill_chunk, kv_dtype=kv_dtype,
            weight_dtype=weight_dtype, spec_decode=spec_k,
            spec_draft_layers=spec_draft_layers,
        ))
        # pre-compile the bucket grid: a bench row measures serving,
        # not first-request XLA compiles (production pays these at
        # deploy time)
        n = eng.warmup()
        reg = MetricsRegistry()
        sched = ServeScheduler(
            eng, SchedulerConfig(max_queue=max(requests, 8)),
            registry=reg,
        ).start()
        srv = ServeServer(sched, reg, port=0)
        try:
            summ = loadgen.run_load(
                srv.url, rate=rate, n_requests=requests, duration=None,
                prompt_lens=list(prompt_lens), max_new=max_new,
                vocab=vocab, seed=seed, api_keys=["bench"],
                temperature=0.0, burst=0, cancel_one=False,
                timeout=600.0, poisson=False,
            )
        finally:
            rec = sched.close()
            srv.close()
        return eng, summ, rec, n

    spec = {}
    if spec_decode:
        # paired baseline first: the SAME workload at the same offered
        # load, spec off - the throughput gate compares against it
        _, base_summary, _, _ = _run(0)
        spec["baseline_tokens_per_s"] = base_summary["tokens_per_s"]
    engine, summary, record, n_compiled = _run(spec_decode)
    if spec_decode:
        slot_steps = max(
            engine.spec_proposed_tokens // max(spec_decode, 1), 1
        )
        accepted_per_step = (
            engine.spec_accepted_tokens + slot_steps
        ) / slot_steps
        spec.update({
            "k": spec_decode,
            "draft_layers": engine.draft_layers,
            "proposed_tokens": engine.spec_proposed_tokens,
            "accepted_tokens": engine.spec_accepted_tokens,
            "acceptance_rate": round(
                engine.spec_accepted_tokens
                / max(engine.spec_proposed_tokens, 1), 4
            ),
            # emitted tokens per speculative slot-step: the guaranteed
            # token + accepted drafts (1.0 == plain decode's ceiling)
            "accepted_tokens_per_step": round(accepted_per_step, 4),
            "tokens_per_s": summary["tokens_per_s"],
        })
        assert accepted_per_step > min_accepted_per_step, (
            f"spec-decode acceptance gate: {accepted_per_step:.3f} "
            f"emitted tokens per slot-step <= {min_accepted_per_step} "
            f"(k={spec_decode}, acceptance "
            f"{spec['acceptance_rate']:.1%}) - the drafter is not "
            "beating the one-token-per-slot ceiling"
        )
        assert summary["tokens_per_s"] > spec["baseline_tokens_per_s"], (
            f"spec-decode throughput gate: {summary['tokens_per_s']} "
            f"tokens/s with k={spec_decode} is not strictly greater "
            f"than the paired non-spec run's "
            f"{spec['baseline_tokens_per_s']} at the same offered load"
        )
    total = float(record.get("wall_s") or 0.0)
    bad = record.get("badput_s") or {}
    dev = jax.devices()[0]

    quant = {}
    if kv_dtype == "int8" or weight_dtype == "int8":
        # --- accuracy gate (int8 KV pool and/or int8 weights): every
        # completed stream vs the offline full-precision oracle (the
        # seeded-model contract), per-token top-1 agreement
        from ..models.transformer import generate

        agree = tot_toks = 0
        for r in summary["results"]:
            if r.status != "completed" or not r.tokens:
                continue
            oracle = np.asarray(generate(
                params, jnp.asarray([r.prompt], jnp.int32), cfg,
                max_new_tokens=len(r.tokens),
            ))[0, len(r.prompt):]
            agree += int(sum(
                int(a) == int(b) for a, b in zip(r.tokens, oracle)
            ))
            tot_toks += len(r.tokens)
        agreement = agree / max(tot_toks, 1)
        quant = {
            "oracle_top1_agreement": round(agreement, 6),
            "oracle_tokens_compared": tot_toks,
        }
        assert agreement >= min_top1_agreement, (
            f"low-precision accuracy gate (kv {kv_dtype}, weights "
            f"{weight_dtype}): per-token top-1 agreement "
            f"{agreement:.4f} < {min_top1_agreement} vs the "
            f"full-precision oracle over {tot_toks} tokens"
        )
    if kv_dtype == "int8":
        # --- capacity gate: equal-HBM-budget pools, MEASURED by
        # admitting max-length sequences into the real allocator
        from ..analysis.cost import kv_block_bytes

        bf16_name = "bf16" if dtype == "bfloat16" else "f32"
        bb_bf16 = kv_block_bytes(
            n_layers, n_heads, cfg.head_dim, block_size, bf16_name
        )
        bb_int8 = kv_block_bytes(
            n_layers, n_heads, cfg.head_dim, block_size, "int8"
        )
        budget = (num_blocks - 1) * bb_bf16  # the bf16 pool's bytes
        int8_blocks = budget // bb_int8 + 1  # + scratch
        cap_bf16 = measure_kv_capacity(
            num_blocks, block_size, max_seq_len
        )
        cap_int8 = measure_kv_capacity(
            int8_blocks, block_size, max_seq_len
        )
        ratio = cap_int8 / max(cap_bf16, 1)
        quant["kv_capacity"] = {
            "hbm_budget_bytes": int(budget),
            "bf16": {"blocks": num_blocks - 1,
                     "bytes_per_block": bb_bf16,
                     "max_seq_sequences": cap_bf16},
            "int8": {"blocks": int(int8_blocks - 1),
                     "bytes_per_block": bb_int8,
                     "max_seq_sequences": cap_int8},
            "measured_capacity_ratio": round(ratio, 4),
        }
        assert ratio >= min_capacity_ratio, (
            f"int8-KV capacity gate: measured concurrent-sequence "
            f"capacity ratio {ratio:.3f} < {min_capacity_ratio} at equal "
            f"HBM budget ({cap_int8} vs {cap_bf16} max-len sequences)"
        )

    # the servelint cost model's figure for THIS engine, next to the
    # measured one, so static-vs-measured drift is tracked per bench
    # run (tools/servelint.py --validate gates the same pair within the
    # documented tolerance - analysis/serve_trace.py)
    from ..analysis.serve_trace import static_decode_tokens_per_s

    static_pred = static_decode_tokens_per_s(engine, "cpu-host")

    return {
        "devices": f"1x {dev.device_kind}",
        "model": f"d{d_model}/L{n_layers}/H{n_heads} vocab {vocab} {dtype}",
        "kv_dtype": kv_dtype,
        "weight_dtype": weight_dtype,
        **({"spec_decode": spec} if spec else {}),
        **quant,
        "offered_rps": summary["offered_rps"],
        "sustained_rps": summary["achieved_rps"],
        "requests_completed": summary["by_status"].get("completed", 0),
        "requests_total": summary["requests"],
        "tokens_per_s": summary["tokens_per_s"],
        "static_predicted_tokens_per_s": round(
            static_pred["tokens_per_s"], 2
        ),
        "static_prediction": {
            "bucket": static_pred["bucket"],
            "hw": static_pred["hw"],
            "bound": static_pred["bound"],
            "tick_s": static_pred["tick_s"],
        },
        "ttft_p50_s": summary["ttft_p50_s"],
        "ttft_p99_s": summary["ttft_p99_s"],
        "intertoken_p50_s": summary["intertoken_p50_s"],
        "intertoken_p99_s": summary["intertoken_p99_s"],
        "engine": {
            "max_batch": max_batch, "block_size": block_size,
            "num_blocks": num_blocks, "prefill_chunk": prefill_chunk,
            "warmup_programs": n_compiled,
        },
        "serve_goodput_ratio": record.get("goodput_ratio"),
        "serve_breakdown_share": {
            c: round(v / total, 4) for c, v in bad.items() if total > 0
        },
        "note": (
            "open-loop load (tools/loadgen.py) against the in-process "
            "serve/ stack over real HTTP+SSE; sustained_rps counts "
            "COMPLETED requests over the whole window, TTFT includes "
            "queue wait (docs/SERVING.md)"
        ),
    }


def measure_fleet_serving(
    *,
    d_model: int = 256,
    n_layers: int = 4,
    n_heads: int = 8,
    d_ff: int = 1024,
    vocab: int = 256,
    dtype: str = "bfloat16",
    rate: float = 3.0,
    requests: int = 12,
    prompt_lens=(16, 64),
    max_new: int = 24,
    max_batch: int = 8,
    num_blocks: int = 129,
    block_size: int = 16,
    max_seq_len: int = 256,
    prefill_chunk: int = 16,
    seed: int = 0,
    kill_after_s: float = 1.5,
    min_scaling_ratio: float = 0.9,
) -> dict:
    """The serving-fleet row (serve/fleet.py, docs/SERVING.md "Serving
    fleet"): two replicas behind the failover router, three legs, all
    gates ASSERTED in the row.

    1. single-replica baseline at offered rate r (the denominator);
    2. healthy 2-replica fleet at 2r: sustained rps must be >=
       ``min_scaling_ratio`` x 2 x the single-replica sustained rps -
       the router's least-loaded dispatch must actually deliver the
       second replica's capacity, not just its existence;
    3. chaos failover at 2r: one replica is killed abruptly mid-run
       (scheduler torn down under live streams, then the listener -
       in-flight SSE streams break, new dispatches get connection
       refused). Every request must still COMPLETE, at least one must
       arrive via failover re-dispatch, and every retried stream must
       be per-token identical to the offline ``generate()`` oracle -
       the deterministic-replay contract, measured, not assumed.

    Per-replica serving goodput records from both fleet legs fold
    through `serve.fleet.aggregate_serve_records`, which asserts
    goodput + badput == wall conservation per replica AND on the
    aggregate (including the killed replica's partial record)."""
    import sys as _sys
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models.transformer import (
        TransformerConfig,
        generate,
        init_params,
    )
    from ..serve import (
        EngineConfig,
        SchedulerConfig,
        ServeEngine,
        ServeScheduler,
    )
    from ..serve.fleet import FleetRouter, aggregate_serve_records
    from ..serve.http import ServeServer
    from ..utils.obs import MetricsRegistry

    tools_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))), "tools",
    )
    if tools_dir not in _sys.path:
        _sys.path.insert(0, tools_dir)
    import loadgen

    cfg = TransformerConfig(
        vocab_size=vocab, d_model=d_model, n_heads=n_heads,
        n_layers=n_layers, d_ff=d_ff,
        dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32,
    )
    params = init_params(jax.random.key(seed), cfg)

    def _replica(rid: str):
        eng = ServeEngine(params, cfg, EngineConfig(
            max_batch=max_batch, num_blocks=num_blocks,
            block_size=block_size, max_seq_len=max_seq_len,
            prefill_chunk=prefill_chunk,
        ))
        eng.warmup()
        reg = MetricsRegistry()
        sched = ServeScheduler(
            eng, SchedulerConfig(max_queue=max(4 * requests, 8)),
            registry=reg,
        ).start()
        srv = ServeServer(sched, reg, port=0, replica_id=rid)
        return sched, srv

    def _load(url: str, offered: float, n: int):
        return loadgen.run_load(
            url, rate=offered, n_requests=n, duration=None,
            prompt_lens=list(prompt_lens), max_new=max_new,
            vocab=vocab, seed=seed, api_keys=["bench"],
            temperature=0.0, burst=0, cancel_one=False,
            timeout=600.0, poisson=False,
        )

    # --- leg 1: single-replica baseline at offered rate r
    sched, srv = _replica("solo")
    try:
        base = _load(srv.url, rate, requests)
    finally:
        sched.close()
        srv.close()
    single_rps = base["achieved_rps"]

    def _fleet_leg(chaos: bool):
        s0, v0 = _replica("rank0")
        s1, v1 = _replica("rank1")
        reg = MetricsRegistry()
        router = FleetRouter(reg, replicas=[
            ("rank0", v0.url), ("rank1", v1.url),
        ])
        recs: dict = {}
        killer = None
        if chaos:
            def _kill():
                # abrupt replica death under live streams: in-flight
                # requests get torn down (SSE error frames / broken
                # pipes), then the listener goes away so re-dispatch
                # sees connection refused - the router must fail both
                # over to rank1 with streams intact
                recs["rank0"] = s0.close()
                v0.close()

            killer = threading.Timer(kill_after_s, _kill)
            killer.start()
        try:
            summ = _load(router.url, 2 * rate, 2 * requests)
        finally:
            if killer is not None:
                killer.join()
            if "rank0" not in recs:
                recs["rank0"] = s0.close()
                v0.close()
            recs["rank1"] = s1.close()
            v1.close()
            failures = int(
                reg.counter("fleet_replica_failures_total").value
            )
            router.close()
        return summ, [recs["rank0"], recs["rank1"]], failures

    # --- leg 2: healthy 2-replica fleet at 2r - the scaling gate
    healthy, healthy_recs, _ = _fleet_leg(chaos=False)
    fleet_rps = healthy["achieved_rps"]
    assert fleet_rps >= min_scaling_ratio * 2.0 * single_rps, (
        f"fleet scaling gate: 2-replica sustained {fleet_rps:.3f} rps "
        f"< {min_scaling_ratio} x 2 x single-replica "
        f"{single_rps:.3f} rps - the router is not delivering the "
        "second replica's capacity"
    )
    healthy_agg = aggregate_serve_records(healthy_recs)

    # --- leg 3: chaos failover at 2r - the robustness gates
    chaos, chaos_recs, failures = _fleet_leg(chaos=True)
    completed = chaos["by_status"].get("completed", 0)
    assert completed == chaos["requests"], (
        f"fleet failover gate: {completed}/{chaos['requests']} "
        "requests completed - a replica SIGKILL must be invisible to "
        f"clients (statuses: {chaos['by_status']})"
    )
    assert chaos["requests_retried"] >= 1, (
        "fleet failover gate: killing a replica mid-run produced zero "
        "failover re-dispatches - the chaos leg did not exercise the "
        "failover path"
    )
    assert failures >= 1, (
        "fleet failover gate: router observed no replica failure "
        "(fleet_replica_failures_total == 0) after the kill"
    )
    # deterministic-replay oracle: every RETRIED stream (prompt replayed
    # with streamed tokens suppressed on a survivor) must match the
    # offline greedy oracle token for token
    checked = mismatched = 0
    for r in chaos["results"]:
        if r.status != "completed" or not r.router_retries:
            continue
        oracle = np.asarray(generate(
            params, jnp.asarray([r.prompt], jnp.int32), cfg,
            max_new_tokens=len(r.tokens),
        ))[0, len(r.prompt):]
        checked += 1
        if list(map(int, r.tokens)) != [int(t) for t in oracle]:
            mismatched += 1
    assert checked >= 1 and mismatched == 0, (
        f"fleet failover oracle gate: {mismatched}/{checked} retried "
        "streams diverged from the offline generate() oracle - "
        "deterministic replay is broken"
    )
    chaos_agg = aggregate_serve_records(chaos_recs)

    dev = jax.devices()[0]
    return {
        "devices": f"1x {dev.device_kind}",
        "model": f"d{d_model}/L{n_layers}/H{n_heads} vocab {vocab} {dtype}",
        "replicas": 2,
        "single_replica_sustained_rps": single_rps,
        "offered_rps": healthy["offered_rps"],
        "sustained_rps": fleet_rps,
        "scaling_ratio_vs_2x_single": round(
            fleet_rps / max(2.0 * single_rps, 1e-9), 4
        ),
        "ttft_p50_s": healthy["ttft_p50_s"],
        "ttft_p99_s": healthy["ttft_p99_s"],
        "by_replica": healthy.get("by_replica"),
        "failover": {
            "kill_after_s": kill_after_s,
            "requests_completed": completed,
            "requests_total": chaos["requests"],
            "requests_retried": chaos["requests_retried"],
            "retry_episodes": chaos["router_retry_episodes"],
            "replica_failures_observed": failures,
            "oracle_checked_streams": checked,
            "oracle_mismatched_streams": mismatched,
            "sustained_rps": chaos["achieved_rps"],
        },
        "fleet_goodput_ratio": healthy_agg["goodput_ratio"],
        "fleet_goodput_ratio_under_failure": chaos_agg["goodput_ratio"],
        "note": (
            "2 in-process replicas behind serve/fleet.py FleetRouter "
            "over real HTTP+SSE; scaling gate >= "
            f"{min_scaling_ratio} x 2 x single-replica sustained rps, "
            "chaos leg kills a replica under live streams and gates "
            "zero client-visible failures + per-token oracle equality "
            "of every failed-over stream (docs/SERVING.md)"
        ),
    }


def measure_kv_capacity(num_blocks: int, block_size: int,
                        max_seq_len: int) -> int:
    """MEASURED concurrent-sequence capacity of a paged-KV pool: admit
    max-length sequences into the real allocator (serve/kv_cache.py)
    until `OutOfBlocks`. The capacity half of the int8-KV gate runs on
    this, not on arithmetic - if the allocator's scratch-block reserve,
    ceil-div block math, or scale bookkeeping changed, the measured
    ratio moves with it."""
    from ..serve.kv_cache import KVCacheConfig, OutOfBlocks, PagedKVCache

    pool = PagedKVCache(KVCacheConfig(
        num_blocks=int(num_blocks), block_size=int(block_size),
        max_seq_len=int(max_seq_len),
    ))
    n = 0
    while True:
        try:
            pool.ensure_range(n, max_seq_len - 1)
        except OutOfBlocks:
            return n
        n += 1


# documented accuracy contract of the quantized training forward
# (docs/MEASUREMENT.md "Low-precision parity gates"): per-row symmetric
# int8 carries ~2^-7 relative error per operand, fp8-e4m3 ~2^-3; the
# bounds below are the end-to-end budget those translate to at the
# parity row's shapes, with headroom against seed/backend jitter. A
# kernel change that breaks numerics blows through them by orders of
# magnitude - a softmax-scale bug shows up as MAE ~ O(1), not O(0.1).
QUANT_PARITY_TOLERANCES = {
    #        (final-loss delta, logit MAE)
    "int8": (0.05, 0.05),
    "fp8": (0.10, 0.25),
}


def measure_quant_parity(
    *,
    d_model: int = 64,
    n_layers: int = 2,
    n_heads: int = 4,
    d_ff: int = 128,
    vocab: int = 64,
    seq_len: int = 32,
    batch: int = 8,
    steps: int = 40,
    lr: float = 0.05,
    seed: int = 0,
    formats: tuple = ("int8", "fp8"),
    tolerances: dict | None = None,
) -> dict:
    """The training parity row: quantized-vs-bf16 loss/logit drift,
    GATED (ROADMAP item 3's honesty rail).

    Trains the same tiny LM three times from identical init/data -
    full precision, ``attn_quant="int8"``, ``attn_quant="fp8"``
    (ops/quant.py: real low-precision QK^T/PV dots, straight-through
    backward) - and asserts the documented tolerances on

    - ``loss_delta``: |final quantized loss - final full-precision loss|
      (did quantization change what was learned), and
    - ``logit_mae``: mean |logit difference| on a held-out batch at the
      final parameters (how far individual predictions moved).

    Single-device on purpose: the quantized forward is sharding-
    agnostic (per-token scales are local math), so parity here is
    parity everywhere the spec lint lets it run; single-device also
    keeps the gate executable on any jax generation the serving CI
    runs (the mesh step needs modern shard_map).
    """
    import jax.numpy as jnp
    import numpy as np

    from ..models import transformer as tfm

    tol = dict(QUANT_PARITY_TOLERANCES)
    tol.update(tolerances or {})

    def build(fmt: str):
        return tfm.TransformerConfig(
            vocab_size=vocab, d_model=d_model, n_heads=n_heads,
            n_layers=n_layers, d_ff=d_ff, attn_quant=fmt,
        )

    # fixed synthetic next-token workload: every variant sees byte-
    # identical batches (seeded PRNG, regenerated per variant)
    def batches(n):
        key = jax.random.key(seed + 1)
        for _ in range(n):
            key, k = jax.random.split(key)
            yield jax.random.randint(k, (batch, seq_len), 0, vocab)

    def train(fmt: str):
        cfg = build(fmt)
        params = tfm.init_params(jax.random.key(seed), cfg)

        def loss_fn(p, toks):
            logits, _ = tfm.apply_with_aux(p, toks, cfg)
            tgt = toks[:, 1:]
            lp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32))
            nll = -jnp.take_along_axis(
                lp, tgt[..., None], axis=-1
            )[..., 0]
            return nll.mean()

        @jax.jit
        def step(p, toks):
            loss, g = jax.value_and_grad(loss_fn)(p, toks)
            p = jax.tree.map(lambda w, gw: w - lr * gw, p, g)
            return p, loss

        loss = None
        for toks in batches(steps):
            params, loss = step(params, toks)
        eval_toks = jax.random.randint(
            jax.random.key(seed + 2), (batch, seq_len), 0, vocab
        )
        logits, _ = tfm.apply_with_aux(params, eval_toks, cfg)
        return float(loss), np.asarray(logits, np.float32)

    base_loss, base_logits = train("")
    rows = {}
    for fmt in formats:
        q_loss, q_logits = train(fmt)
        loss_delta = abs(q_loss - base_loss)
        logit_mae = float(np.mean(np.abs(q_logits - base_logits)))
        d_tol, m_tol = tol[fmt]
        rows[fmt] = {
            "final_loss": round(q_loss, 6),
            "loss_delta": round(loss_delta, 6),
            "loss_delta_tol": d_tol,
            "logit_mae": round(logit_mae, 6),
            "logit_mae_tol": m_tol,
        }
        assert loss_delta <= d_tol, (
            f"quant parity gate [{fmt}]: final-loss delta "
            f"{loss_delta:.4f} > {d_tol} vs full precision "
            f"(base {base_loss:.4f}, quantized {q_loss:.4f})"
        )
        assert logit_mae <= m_tol, (
            f"quant parity gate [{fmt}]: logit MAE {logit_mae:.4f} > "
            f"{m_tol} vs full precision on the held-out batch"
        )
    dev = jax.devices()[0]
    return {
        "devices": f"1x {dev.device_kind}",
        "model": f"d{d_model}/L{n_layers}/H{n_heads} vocab {vocab}",
        "steps": steps,
        "baseline_final_loss": round(base_loss, 6),
        "formats": rows,
        "note": (
            "same init + byte-identical batches per variant; quantized "
            "attention forward (ops/quant.py), straight-through "
            "backward; gates assert the documented tolerances "
            "(docs/MEASUREMENT.md)"
        ),
    }
