"""Shared benchmark harness for bench.py and report.py.

One implementation of "train the data-parallel CIFAR workload and time the
train+sync phases" so the two entry points cannot drift: split loading,
warm-up policy, the fused-span fast path with its outside-the-timer final
eval (mirroring the reference's child train-time metric, which excludes the
parent's eval - SURVEY.md section 6), and the phase accounting.
"""

from __future__ import annotations

import jax

from ..data.cifar10 import load_split
from ..utils import timers as T
from .engine import Engine, TrainConfig


def measure_dp_training(
    *,
    nb_proc: int | None = None,
    batch_size: int = 16,
    epochs: int = 25,
    data: str = "auto",
    synthetic_size: int | None = None,
    sync_mode: str = "epoch",
    compute_dtype: str = "float32",
    kernels: str = "xla",
    fused: bool = True,
) -> dict:
    """Run the data-parallel regime and return measured results.

    Returns {devices, batch_size, epochs, val_acc, val_loss, train_s,
    source}. train_s = training + parameter-sync wall-clock (compile time
    excluded via AOT warm-up; eval outside), the reference-comparable
    metric.
    """
    # requested size passes through; the engine rejects infeasible counts
    # with a clear error rather than silently measuring a smaller mesh
    n = nb_proc if nb_proc else jax.device_count()
    train_split = load_split(True, source=data, synthetic_size=synthetic_size)
    test_split = load_split(
        False, source=data,
        synthetic_size=max(1, synthetic_size // 5) if synthetic_size else None,
    )
    cfg = TrainConfig(
        batch_size=batch_size, epochs=epochs, nb_proc=n,
        regime="data_parallel", sync_mode=sync_mode,
        compute_dtype=compute_dtype, kernels=kernels,
    )
    timers = T.PhaseTimers()
    engine = Engine(cfg, train_split, test_split)
    if fused:
        # one dispatch for the whole run; AOT compile, then measure
        engine.compile_span(epochs, eval_inside=False)
        engine.run_span(0, epochs, eval_inside=False, timers=timers)
        vl, va = engine._eval_fn(
            engine.params, engine.test_images, engine.test_labels,
            engine.test_weights,
        )
        final = engine.history[-1]
        final.val_loss, final.val_acc = float(vl), float(va)
    else:
        # per-epoch dispatch: warm up one epoch, rewind, measure
        engine.run_epoch(0, timers=T.PhaseTimers())
        engine.reset_state()
        for epoch in range(epochs):
            engine.run_epoch(epoch, timers=timers)
        final = engine.history[-1]
    return {
        "devices": n,
        "batch_size": batch_size,
        "epochs": epochs,
        "val_acc": final.val_acc,
        "val_loss": final.val_loss,
        "train_s": timers.get(T.TRAINING) + timers.get(T.COMMUNICATION),
        "source": train_split.source,
    }
