"""Self-healing training: in-step health signals, guard policies, rollback,
and preemption-safe exact resume.

The reference's only robustness story is the epoch-granularity fault
simulator (`data_parallelism_train.py:41-46`), upgraded by this repo to
seeded drop-and-continue masking (`parallel/fault.py`) plus epoch-boundary
checkpointing (`utils/checkpoint.py`) - but nothing there detects or
survives a failure *inside* a step: a NaN'd gradient or a diverging loss
silently corrupts the run, and a SIGTERM mid-epoch loses it. Production TPU
training (pjit-at-scale, arxiv 2204.06514) treats step-level health and
exact resume as table stakes; this module is that layer.

Three pieces, host-side (the in-jit halves live next to the code they
guard):

- **Health signals** (`ops/schedule.py health_bundle`): every guarded train
  step returns a tiny replicated bundle - loss, global grad-norm (reused
  from `clip_by_global_norm` when clipping is on), and an all-finite flag
  derived from those two scalars (a NaN/Inf anywhere in the gradient tree
  makes the global norm non-finite, so the flag costs O(1), not a second
  pass over the parameters). `HealthPipe` consumes the bundle one step
  late, so observation never fences the dispatch pipeline.
- **Policy loop** (`TrainingGuard`): an EMA loss-spike detector plus
  non-finite detection, mapped through a policy -
  ``warn`` (count + log), ``skip`` (non-finite updates are dropped INSIDE
  the compiled step via `ops/schedule.py tree_where` - the step stays
  compiled, params/momentum simply pass through), ``rollback`` (restore
  the rolling in-memory snapshot - or the newest on-disk checkpoint - and
  retry with LR backoff under a bounded budget), ``abort`` (raise
  `GuardAbort` with an actionable message). Anomaly counters flow into
  `utils/tracing.py StepStats` and ``guard`` instant events into the
  Chrome trace.
- **Preemption** (`PreemptionGuard`): SIGTERM/SIGINT set a cooperative
  flag checked at step boundaries; the training loop then writes an
  emergency checkpoint whose versioned meta carries the exact data cursor
  (step, seed - every PRNG/shuffle stream in this repo is a pure function
  of those), so resume replays from the exact batch, bit-identical.

Used by `lm_train.py` (per-step granularity) and `train/engine.py` /
`train/cli.py` (per-epoch granularity - one engine dispatch IS one step
there). Fault injectors that exercise every policy path live in
`parallel/fault.py` (`StepFaultPlan`, `ChaosMonkey`).
"""

from __future__ import annotations

import math
import signal
import threading
from dataclasses import dataclass

POLICIES = ("off", "warn", "skip", "rollback", "abort")

# bump when the checkpoint meta/cursor schema changes shape; resume rejects
# newer-versioned metas with a clear message instead of misreading them
GUARD_META_VERSION = 1


class GuardAbort(RuntimeError):
    """Training aborted by the guard policy; the message says why and what
    to do (inspect the trace's guard events, resume from the newest
    checkpoint with a lower LR, or rerun with --guard warn to observe)."""


@dataclass
class GuardConfig:
    """Knobs for `TrainingGuard`; CLI surface maps 1:1 (--guard,
    --guard-spike-zscore, --snapshot-every, --max-retries)."""

    policy: str = "warn"
    # a loss more than this many EMA standard deviations above the EMA mean
    # is a spike; non-finite loss/grad-norm is always an anomaly
    spike_zscore: float = 6.0
    # EMA decay for the spike detector's running mean/variance
    ema_decay: float = 0.9
    # observations before the spike detector arms (the first steps of a run
    # legitimately move fast); non-finite detection is active from step 0
    warmup_steps: int = 10
    # rollback retry budget; exhausted -> GuardAbort. The budget refills
    # after `warmup_steps` consecutive healthy observations, so isolated
    # incidents hours apart don't share one budget
    max_retries: int = 3
    # LR multiplier applied on each rollback (cumulative: scale *= backoff)
    lr_backoff: float = 0.5
    # steps between rolling in-memory snapshots (host copies); a rollback
    # rewinds at most this many steps
    snapshot_every: int = 50

    def __post_init__(self):
        if self.policy not in POLICIES:
            raise ValueError(
                f"guard policy must be one of {POLICIES}, got {self.policy!r}"
            )
        if self.spike_zscore <= 0:
            raise ValueError(
                f"spike_zscore must be > 0, got {self.spike_zscore}"
            )
        if not 0.0 < self.ema_decay < 1.0:
            raise ValueError(f"ema_decay must be in (0,1), got {self.ema_decay}")
        if not 0.0 < self.lr_backoff <= 1.0:
            raise ValueError(
                f"lr_backoff must be in (0,1], got {self.lr_backoff}"
            )
        if self.max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got {self.max_retries}")
        if self.snapshot_every < 1:
            raise ValueError(
                f"snapshot_every must be >= 1, got {self.snapshot_every}"
            )


@dataclass
class Verdict:
    """One observation's outcome. `action` is what the caller must do:
    'ok' / 'warn' (continue), 'skip' (the in-jit guard already dropped the
    update; bookkeeping only), 'rollback' (call `TrainingGuard.rollback()`
    and restore), 'abort' (raise - `observe` already raised GuardAbort for
    the abort policy; this action only appears via rollback exhaustion)."""

    action: str
    step: int
    reason: str | None = None
    zscore: float | None = None


class SpikeDetector:
    """EMA mean/variance loss-spike detector.

    `check(loss)` returns the z-score of the observation against the
    running EMA (None while warming up); `accept(loss)` folds a HEALTHY
    observation into the EMA - anomalous losses are never folded in, so a
    spike cannot poison the baseline it is judged against. `reset()`
    re-warms after a rollback (the restored trajectory's loss level differs
    from the post-anomaly EMA, which would otherwise re-trigger)."""

    def __init__(self, *, decay: float = 0.9, warmup: int = 10):
        self.decay = decay
        self.warmup = warmup
        self.reset()

    def reset(self) -> None:
        self.mean = 0.0
        self.var = 0.0
        self.count = 0

    def check(self, loss: float) -> float | None:
        if self.count < self.warmup:
            return None
        sd = math.sqrt(max(self.var, 1e-12))
        return (loss - self.mean) / sd

    def accept(self, loss: float) -> None:
        if self.count == 0:
            self.mean = loss
            self.var = 0.0
        else:
            d = self.decay
            delta = loss - self.mean
            self.mean = d * self.mean + (1.0 - d) * loss
            self.var = d * (self.var + (1.0 - d) * delta * delta)
        self.count += 1


class TrainingGuard:
    """Host-side guard policy: consumes per-step health, keeps the rolling
    snapshot, and decides warn/skip/rollback/abort. Thread-compatible with
    the single-threaded training loops here (no internal locking needed -
    observation and rollback happen on the loop thread)."""

    def __init__(
        self,
        config: GuardConfig | None = None,
        *,
        tracer=None,
        step_stats=None,
        registry=None,
        log=print,
        provenance=None,
    ):
        self.cfg = config if config is not None else GuardConfig()
        self.tracer = tracer
        self.step_stats = step_stats
        # non-finite provenance (train/dynamics.py DynamicsSink.bad_layer):
        # a `step -> layer-path-or-None` lookup naming the first layer
        # whose gradients went non-finite at that step. Consulted only on
        # the nonfinite anomaly path; the layer lands in the verdict
        # reason, the guard trace instant, and the flight event (whence
        # the supervisor's postmortem.json picks it up).
        self.provenance = provenance
        # live-metrics registry (utils/obs.py; None/NULL_REGISTRY = off):
        # anomaly/rollback counters surface on /metrics while the run is
        # alive, not only in the post-hoc trace/StepStats
        if registry is None:
            from ..utils.obs import NULL_REGISTRY

            registry = NULL_REGISTRY
        self._anomaly_counter = registry.counter(
            "guard_anomalies_total",
            "Guard anomalies observed, by kind (train/guard.py)",
        )
        self._rollback_counter = registry.counter(
            "guard_rollbacks_total", "Guard rollback restores"
        )
        self._lr_scale_gauge = registry.gauge(
            "guard_lr_scale", "Cumulative guard LR-backoff factor"
        )
        self._lr_scale_gauge.set(1.0)
        # headroom BEFORE a trip: the z-score of every healthy observation
        # against the EMA baseline (0 while the detector warms up), next
        # to the --guard-spike-zscore threshold it is judged against
        self._zscore_gauge = registry.gauge(
            "guard_spike_zscore",
            "Last observed loss z-score vs the spike detector's EMA "
            "(0 during warmup)",
        )
        self._zscore_gauge.set(0.0)
        self.log = log
        self.detector = SpikeDetector(
            decay=self.cfg.ema_decay, warmup=self.cfg.warmup_steps
        )
        self.counters = {
            "nonfinite": 0, "spikes": 0, "skipped": 0,
            "rollbacks": 0, "warnings": 0,
        }
        self.retries_used = 0
        self.lr_scale = 1.0
        self._healthy_streak = 0
        self._snapshot = None  # (step, host_state_tree)

    # ---------------------------------------------------------- snapshots

    @property
    def has_snapshot(self) -> bool:
        return self._snapshot is not None

    @property
    def snapshot_step(self) -> int | None:
        return self._snapshot[0] if self._snapshot else None

    def snapshot(self, step: int, state) -> None:
        """Store a host copy of `state` (any pytree of arrays) as the
        last-good rollback point. One device_get per call - size the
        cadence (`snapshot_every`) to what the host link affords."""
        import jax
        import numpy as np

        self._snapshot = (
            int(step),
            jax.tree.map(lambda x: np.asarray(jax.device_get(x)), state),
        )

    def maybe_snapshot(self, step: int, state, *, first_step: int = 0) -> bool:
        """Snapshot at the configured cadence (always on the first call)."""
        if self._snapshot is not None and (
            (step - first_step) % self.cfg.snapshot_every
        ):
            return False
        self.snapshot(step, state)
        return True

    def peek_snapshot(self):
        """(step, host_state) of the rolling snapshot, or None - the
        no-budget accessor (epoch-level 'skip' restores without consuming
        a retry)."""
        return self._snapshot

    def drop_snapshot(self) -> None:
        """Invalidate the rolling snapshot (the elastic shrink path: a
        snapshot taken under the pre-shrink mesh/optimizer layout must
        never be rolled back into the resharded run; the next cadence
        retakes one in the new layout)."""
        self._snapshot = None

    # -------------------------------------------------------- observation

    def observe(
        self,
        step: int,
        loss: float,
        *,
        grad_norm: float | None = None,
        all_finite: bool | None = None,
    ) -> Verdict:
        """Judge one step's health; returns the policy's Verdict.

        Raises GuardAbort directly under the 'abort' policy so the failure
        cannot be ignored by a caller that drops the verdict."""
        loss = float(loss)
        finite = math.isfinite(loss)
        if grad_norm is not None:
            finite = finite and math.isfinite(float(grad_norm))
        if all_finite is not None:
            finite = finite and bool(all_finite)

        if not finite:
            layer = (
                self.provenance(step) if self.provenance is not None
                else None
            )
            reason = (
                f"non-finite step (loss={loss}, grad_norm={grad_norm}, "
                f"all_finite={all_finite})"
            )
            if layer is not None:
                reason += f"; first non-finite grads in layer {layer!r}"
            return self._anomaly(step, "nonfinite", reason, None, layer=layer)
        z = self.detector.check(loss)
        self._zscore_gauge.set(z if z is not None else 0.0)
        if z is not None and z > self.cfg.spike_zscore:
            return self._anomaly(
                step, "spikes",
                f"loss spike: {loss:.6g} is {z:.1f} EMA sigma above the "
                f"running mean {self.detector.mean:.6g} "
                f"(threshold {self.cfg.spike_zscore})",
                z,
            )
        self.detector.accept(loss)
        self._healthy_streak += 1
        if self.retries_used and self._healthy_streak >= self.cfg.warmup_steps:
            self.retries_used = 0  # incident closed: refill the budget
        return Verdict(action="ok", step=step)

    def _anomaly(self, step, kind, reason, zscore, *, layer=None) -> Verdict:
        self.counters[kind] += 1
        self._anomaly_counter.labels(kind=kind).inc()
        self._healthy_streak = 0
        policy = self.cfg.policy
        action = {
            "warn": "warn", "skip": "skip",
            "rollback": "rollback", "abort": "abort",
        }.get(policy, "warn")
        if action == "skip" and kind == "spikes":
            # the in-jit skip gates on the finite flag only; a finite spike
            # has no compiled drop path, so the skip policy warns on it
            action = "warn"
        if action == "skip":
            self.counters["skipped"] += 1
        elif action == "warn":
            self.counters["warnings"] += 1
        extra = {} if layer is None else {"layer": layer}
        if self.tracer is not None:
            self.tracer.instant(
                "guard", track="guard", step=int(step), action=action,
                kind=kind, zscore=zscore, **extra,
            )
        from ..utils.obs import flight_event

        flight_event(
            "guard_anomaly", step=int(step), action=action, anomaly=kind,
            zscore=zscore, **extra,
        )
        if self.step_stats is not None:
            self.step_stats.count_anomaly(kind)
        self.log(f"(guard: step {step} {kind} -> {action}: {reason})")
        if action == "abort":
            raise GuardAbort(
                f"guard policy 'abort': {reason} at step {step}. "
                "Inspect the run's guard trace events "
                "(tools/trace_summary.py), resume from the newest "
                "checkpoint with a lower LR, or rerun with --guard warn "
                "to observe without stopping."
            )
        return Verdict(action=action, step=step, reason=reason, zscore=zscore)

    # ----------------------------------------------------------- rollback

    def rollback(self, at_step: int | None = None):
        """Consume one retry and return (step, host_state) of the rolling
        snapshot - or None when no snapshot exists yet (the caller then
        falls back to the newest on-disk checkpoint). Applies the LR
        backoff (`lr_scale *= lr_backoff`) and emits a `guard` rollback
        event. Raises GuardAbort when the retry budget is exhausted.

        ``at_step`` (the step the training loop had reached) sizes the
        goodput ledger's recompute window: the ``at_step - snapshot_step``
        replayed steps are lost progress being re-earned, so their wall
        time is attributed to ``rollback_recompute`` instead of goodput
        (utils/goodput.py)."""
        self.retries_used += 1
        if self.retries_used > self.cfg.max_retries:
            raise GuardAbort(
                f"guard retry budget exhausted ({self.cfg.max_retries} "
                f"rollback(s) without {self.cfg.warmup_steps} consecutive "
                "healthy steps between incidents). The anomaly recurs "
                "after restore + LR backoff - likely a data or numerics "
                "problem, not a transient: check the input batch at the "
                "failing step, lower the base LR, or enable gradient "
                "clipping (--clip-norm)."
            )
        self.counters["rollbacks"] += 1
        self._rollback_counter.inc()
        from ..utils.obs import flight_event

        flight_event(
            "guard_rollback", lr_scale=self.lr_scale * self.cfg.lr_backoff,
            retries_used=self.retries_used,
        )
        self.lr_scale *= self.cfg.lr_backoff
        self._lr_scale_gauge.set(self.lr_scale)
        self.detector.reset()  # re-warm against the restored trajectory
        if self.step_stats is not None:
            self.step_stats.count_anomaly("rollbacks")
        if self._snapshot is None:
            return None
        step, state = self._snapshot
        if at_step is not None and at_step > step:
            from ..utils.goodput import LEDGER

            LEDGER.mark_recompute(at_step - step)
        if self.tracer is not None:
            self.tracer.instant(
                "guard", track="guard", step=step, action="restore",
                kind="rollback", lr_scale=self.lr_scale,
                retries_used=self.retries_used,
            )
        self.log(
            f"(guard: rolling back to snapshot at step {step}, "
            f"lr_scale={self.lr_scale:g}, "
            f"retry {self.retries_used}/{self.cfg.max_retries})"
        )
        return step, state

    def summary(self) -> dict:
        return {
            "policy": self.cfg.policy,
            "lr_scale": self.lr_scale,
            "retries_used": self.retries_used,
            **{k: int(v) for k, v in self.counters.items()},
        }


class HealthPipe:
    """One-step-lagged health consumption.

    Fetching the health bundle synchronously would fence every dispatch -
    the exact overhead the guard must not add. The pipe holds step i's
    on-device bundle while step i+1 dispatches and only then blocks on it
    (by which time it has long been computed), so steady-state overhead is
    one tiny host transfer per step off the critical path. The price is
    that warn/rollback act one step late - the rolling snapshot cadence
    already absorbs that; the non-finite 'skip' drop is in-jit and never
    waits for the host at all.

    `perturb(step, loss, grad_norm, all_finite) -> (loss, grad_norm,
    all_finite)` hooks host-side fault injection (parallel/fault.py
    ChaosMonkey) into the observation path.
    """

    def __init__(self, guard: TrainingGuard, *, perturb=None):
        self.guard = guard
        self.perturb = perturb
        self._pending = None

    def push(self, step: int, health) -> Verdict | None:
        """Stash step's on-device bundle; returns the PREVIOUS step's
        verdict (None on the first call)."""
        v = self.flush()
        self._pending = (int(step), health)
        return v

    def flush(self) -> Verdict | None:
        """Observe the pending bundle now (blocks on its device values)."""
        if self._pending is None:
            return None
        import jax

        step, health = self._pending
        self._pending = None
        vals = jax.device_get(health)
        loss = float(vals["loss"])
        gn = float(vals["grad_norm"])
        ok = bool(vals["all_finite"])
        if self.perturb is not None:
            loss, gn, ok = self.perturb(step, loss, gn, ok)
        return self.guard.observe(step, loss, grad_norm=gn, all_finite=ok)

    def clear(self) -> None:
        """Drop the pending bundle (after a rollback the in-flight step's
        health belongs to the abandoned trajectory)."""
        self._pending = None


# ------------------------------------------------------------- preemption


class PreemptionGuard:
    """Cooperative SIGTERM/SIGINT handling for step-boundary emergency
    checkpoints.

    `install()` replaces the handlers with a flag-setter; the training loop
    checks `requested` at each step boundary, writes an emergency
    checkpoint, and exits cleanly - so a preempted run resumes from the
    exact step instead of losing the partial epoch. A second signal
    restores the previous handler and re-delivers (the escape hatch when
    the loop is wedged). Use as a context manager; handlers are restored
    on exit. Signal handlers can only be installed on the main thread -
    `install()` is a no-op elsewhere (requested stays False)."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT), *, log=print):
        self.signals = tuple(signals)
        self.log = log
        self.requested = False
        self.signame: str | None = None
        self._prev = {}

    def _handler(self, signum, frame):
        if self.requested:
            # second delivery: restore + re-raise via the original handler
            self.uninstall()
            signal.raise_signal(signum)
            return
        self.requested = True
        self.signame = signal.Signals(signum).name
        from ..utils.obs import flight_event

        flight_event("preempt", signal=self.signame)
        self.log(
            f"({self.signame} received: finishing the current step, then "
            "writing an emergency checkpoint and exiting; send again to "
            "force)"
        )

    def request(self, reason: str = "REQUEST") -> None:
        """Programmatic preemption (no signal involved): the watchdog's
        stall escalation (`train/monitor.py`) raises the same cooperative
        flag a SIGTERM would, so the training loop writes its emergency
        checkpoint at the next step boundary and exits cleanly. Idempotent;
        works from any thread (unlike signal delivery)."""
        if self.requested:
            return
        self.requested = True
        self.signame = reason
        from ..utils.obs import flight_event

        flight_event("preempt", signal=reason)
        self.log(
            f"({reason} preemption requested: finishing the current step, "
            "then writing an emergency checkpoint and exiting)"
        )

    def install(self) -> "PreemptionGuard":
        if threading.current_thread() is not threading.main_thread():
            return self
        for s in self.signals:
            self._prev[s] = signal.signal(s, self._handler)
        return self

    def uninstall(self) -> None:
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev = {}

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False


# ------------------------------------------------------- resume exactness


def resume_cursor(*, step: int, seed: int, **extra) -> dict:
    """The versioned checkpoint-meta block that makes resume EXACT.

    Every data/PRNG stream in this repo is a pure function of (seed, step)
    - `data/tokens.py sample_batch(step=...)`, the engine's
    fold_in(fold_in(key(seed), epoch), device) shuffle keys, the fault
    masks' `epoch_key(seed, epoch)` - so recording the two integers pins
    the exact batch sequence and PRNG stream the continuation must replay.
    """
    return {
        "meta_version": GUARD_META_VERSION,
        "cursor": {"step": int(step), "seed": int(seed), **extra},
    }


def check_cursor(meta: dict, *, seed: int, what: str = "run") -> None:
    """Validate a restored meta's cursor against this run's settings.

    Old checkpoints without a cursor pass (they predate exact-resume and
    carry no claim); a seed mismatch raises - resuming a seeded run under
    a different seed silently changes the data order mid-trajectory, which
    is exactly the corruption exact resume exists to prevent."""
    ver = meta.get("meta_version")
    if ver is not None and ver > GUARD_META_VERSION:
        raise ValueError(
            f"checkpoint meta_version {ver} is newer than this build's "
            f"{GUARD_META_VERSION} - resume with the build that wrote it"
        )
    cur = meta.get("cursor")
    if not isinstance(cur, dict):
        return
    if "seed" in cur and int(cur["seed"]) != int(seed):
        raise ValueError(
            f"checkpoint was written with seed={cur['seed']}, this {what} "
            f"has seed={seed} - the data order and PRNG streams would "
            "diverge from the recorded trajectory; resume with the "
            "original seed"
        )
