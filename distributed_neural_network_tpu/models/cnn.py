"""LeNet-style CIFAR-10 CNN, TPU-native (Flax linen, NHWC).

Capability parity with the reference `models/model.py:9-27` (`Network`):
conv(3->6, k5, valid) -> maxpool2 -> conv(6->16, k5, valid) -> maxpool2
-> flatten(400) -> fc 120 -> fc 84 -> fc 10, ReLU between.

TPU-first deltas from the reference (documented per SURVEY.md section 7 step 1):

- **Layout**: NHWC instead of torch's NCHW. On TPU, XLA's convolution
  tiling wants the channel dimension minor; NHWC is the native layout and
  avoids a transpose on every batch fed from the host pipeline.
- **Flatten order**: flattening a (N, 5, 5, 16) activation gives the 400
  features in H,W,C order, vs torch's C,H,W (reference `models/model.py:24`).
  This is a fixed permutation of fc1's input columns - training dynamics and
  accuracy are unaffected; only raw weight tensors are not bit-comparable.
- **Init**: `torch_uniform` reproduces torch's default
  `kaiming_uniform_(a=sqrt(5))` for weights and `U(-1/sqrt(fan_in),
  +1/sqrt(fan_in))` for biases, so the *training dynamics* match the
  reference's observable behaviour (SURVEY.md section 7 "Numerical parity").
  Both reduce to U(-1/sqrt(fan_in), +1/sqrt(fan_in)).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax.nn.initializers import variance_scaling

# torch's default kaiming_uniform_(a=sqrt(5)) == uniform with bound
# gain*sqrt(3/fan_in), gain = sqrt(2/(1+5)) = sqrt(1/3)  =>  bound = sqrt(1/fan_in).
# variance_scaling draws U(+-sqrt(3*scale/fan_in)); scale=1/3 gives that bound.
torch_uniform_kernel = variance_scaling(1.0 / 3.0, "fan_in", "uniform")


def torch_uniform_bias(fan_in: int):
    """torch-style bias init: U(-1/sqrt(fan_in), +1/sqrt(fan_in)).

    Flax bias initializers don't receive fan_in, so each layer closes over its
    own (conv: k*k*in_channels, dense: in_features).
    """
    bound = 1.0 / np.sqrt(fan_in)

    def init(key, shape, dtype=jnp.float32):
        import jax

        return jax.random.uniform(key, shape, dtype, -bound, bound)

    return init


class _DenseParams(nn.Module):
    """Declares a Dense layer's kernel/bias with nn.Dense's exact param tree
    (kernel (in, out), bias (out,)) without computing the layer - the fused
    Pallas head (ops/pallas_kernels.py) consumes the raw arrays, and
    checkpoints/state trees stay interchangeable between head impls."""

    features: int
    fan_in: int

    @nn.compact
    def __call__(self):
        kernel = self.param(
            "kernel", torch_uniform_kernel, (self.fan_in, self.features)
        )
        bias = self.param(
            "bias", torch_uniform_bias(self.fan_in), (self.features,)
        )
        return kernel, bias


class Network(nn.Module):
    """The reference's 62K-param CIFAR-10 classifier, re-expressed for TPU.

    Input:  (batch, 32, 32, 3) float32 (or bf16), normalized to [-1, 1].
    Output: (batch, 10) logits.

    `compute_dtype` lets the matmul/conv path run in bfloat16 on the MXU while
    params stay float32 (mixed precision); default float32 for strict parity.

    `use_pallas_head=True` runs fc1..fc3 as ONE fused Pallas kernel (weights
    VMEM-resident, h1/h2 intermediates never touch HBM; see
    ops/pallas_kernels.py). The param tree is identical either way, so
    checkpoints and sync collectives are oblivious to the choice. The fused
    head computes in float32 regardless of compute_dtype.
    """

    num_classes: int = 10
    compute_dtype: jnp.dtype = jnp.float32
    use_pallas_head: bool = False

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.compute_dtype)
        x = nn.Conv(
            6,
            (5, 5),
            padding="VALID",
            kernel_init=torch_uniform_kernel,
            bias_init=torch_uniform_bias(5 * 5 * 3),
            dtype=self.compute_dtype,
            name="conv1",
        )(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(
            16,
            (5, 5),
            padding="VALID",
            kernel_init=torch_uniform_kernel,
            bias_init=torch_uniform_bias(5 * 5 * 6),
            dtype=self.compute_dtype,
            name="conv2",
        )(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))  # (N, 5*5*16=400), H,W,C order
        if self.use_pallas_head:
            from ..ops.pallas_kernels import fused_mlp3

            w1, b1 = _DenseParams(120, 400, name="fc1")()
            w2, b2 = _DenseParams(84, 120, name="fc2")()
            w3, b3 = _DenseParams(self.num_classes, 84, name="fc3")()
            return fused_mlp3(x, w1, b1, w2, b2, w3, b3)
        x = nn.Dense(
            120,
            kernel_init=torch_uniform_kernel,
            bias_init=torch_uniform_bias(400),
            dtype=self.compute_dtype,
            name="fc1",
        )(x)
        x = nn.relu(x)
        x = nn.Dense(
            84,
            kernel_init=torch_uniform_kernel,
            bias_init=torch_uniform_bias(120),
            dtype=self.compute_dtype,
            name="fc2",
        )(x)
        x = nn.relu(x)
        x = nn.Dense(
            self.num_classes,
            kernel_init=torch_uniform_kernel,
            bias_init=torch_uniform_bias(84),
            dtype=self.compute_dtype,
            name="fc3",
        )(x)
        return x.astype(jnp.float32)  # logits/loss in f32 for stable CE


def param_count(params) -> int:
    """Total parameter count (reference Network: 62,006)."""
    import jax

    return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))


def flops_per_image(num_classes: int = 10) -> float:
    """Analytic forward-pass FLOPs for one 32x32x3 image (2*MACs of the
    convs + dense head; bias/relu/pool are noise at this scale).

    The MFU fallback when the backend's `cost_analysis()` reports no FLOPs
    (utils/tracing.py compiled_flops): training FLOPs ~ 3x this (fwd +
    2x bwd, the PaLM-appendix convention used by
    train/measure.py model_flops_per_token).
    """
    conv1 = 2 * 28 * 28 * 6 * (5 * 5 * 3)
    conv2 = 2 * 10 * 10 * 16 * (5 * 5 * 6)
    dense = 2 * (400 * 120 + 120 * 84 + 84 * num_classes)
    return float(conv1 + conv2 + dense)
