"""Decoder-only transformer LM with composable DP x SP x TP shardings.

The reference framework has exactly one model family - the LeNet CNN
(`/root/reference/models/model.py:9-27`) - and scales only the batch axis.
This module is the framework's second model family and its long-context /
multi-axis-parallel showcase: a GPT-style causal LM whose forward pass runs
unchanged on a single device or inside `jax.shard_map` over any combination
of

- a **data** axis (batch-sharded tokens),
- a **seq** axis (sequence/context parallelism: activations sharded along
  the sequence, attention via `parallel/ring.py`'s ring or Ulysses
  primitives, positions computed from the global offset),
- a **model** axis (Megatron-style tensor parallelism: attention heads and
  the MLP hidden dim column-sharded, row-sharded second projections
  followed by a single psum per block),
- an **expert** dimension (`cfg.n_experts > 0`): the dense FFN becomes a
  mixture-of-experts (`parallel/moe.py`), experts sharded over the data
  axis GShard-style with one all_to_all each way (`ep_axis`).

Design choices, TPU-first:
- Pure-JAX parameter pytree (no Module class): inside shard_map every leaf
  is the *local* shard, and the same `apply` code path serves all layouts -
  the sharding lives entirely in `param_specs()` + the mesh, XLA inserts
  the collectives.
- Matmul-heavy, static shapes, `lax` control-flow free: everything tiles
  onto the MXU; bf16-friendly (`cfg.dtype`).
- Sinusoidal positions computed on the fly from global offsets, so sequence
  shards need no position table and arbitrary context lengths cost nothing.
- Grad synchronization falls out of shard_map's autodiff typing: replicated
  (invariant) params get their gradient psum over data/seq automatically;
  tensor-sharded params keep local gradients. No hand-written allreduce.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..ops.decode_pallas import decode_cache_attention, decode_kernel_ok
from ..parallel.moe import expert_capacity, moe_ffn
from ..parallel.ring import (
    attention,
    ring_attention,
    ulysses_attention,
    zigzag_positions,
    zigzag_ring_attention,
)

# "zigzag" = load-balanced causal ring attention; tokens must be fed in
# zigzag shard order (parallel/ring.py zigzag_order) - ~2x the causal
# throughput of "ring" at scale. "flash" = Pallas TPU flash kernel for the
# LOCAL (seq_axis=None) case - long contexts on one chip (ops/flash.py).
ATTN_IMPLS = ("full", "ring", "ulysses", "zigzag", "flash")


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 256
    d_model: int = 128
    n_heads: int = 8
    n_layers: int = 2
    d_ff: int = 512
    dtype: jnp.dtype = jnp.float32
    # rematerialize each block in the backward pass (jax.checkpoint): trades
    # ~1/3 more FLOPs for O(layers * seq^2) less activation memory - the
    # standard long-context/deep-stack memory lever on TPU
    remat: bool = False
    # jax.checkpoint policy NAME (jax.checkpoint_policies.*) applied with
    # remat=True; "" = save nothing (full recompute). "dots_saveable"
    # stores every matmul output and recomputes only the elementwise ops
    # (LN/gelu/residual) in backward - a few percent FLOP tax instead of
    # full remat's ~1/3, while still dropping the non-dot intermediates
    # that OOM the 16 GB chip at d1024/b8 no-remat (measured r5:
    # AllocateBuffer on 512 MB stacked-scan temps). The canonical TPU
    # memory/FLOP trade between "none" and "full".
    remat_policy: str = ""
    # rematerialize ONLY the attention inner call (scores/softmax/values):
    # the (B, H, S, S) score tensor - the piece that actually OOMs at long
    # seq - is recomputed in backward while every matmul residual
    # ((B, S, d)-sized, cheap) stays stored. Costs ~4*S*d extra
    # FLOPs/token/layer (the attention einsums only) instead of block
    # remat's full ~1/3, and needs no Pallas kernel. Ignored when
    # remat=True (block remat already covers the scores).
    remat_attn: bool = False
    # Mixture-of-experts FFN (0 = dense). Experts replace the MLP in every
    # block; capacity_factor sizes the static per-expert slot count.
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0
    # dispatch: "sort" (scatter/gather coordinates, O(T*k + E*C*d) memory,
    # real-scale default) or "dense" ((T, E, C) one-hot einsums, the
    # small-shape oracle) - identical numerics (parallel/moe.py)
    moe_dispatch: str = "sort"
    # low-precision attention forward ("" = off): "int8" / "fp8" run the
    # QK^T and PV matmuls in the quantized dtype with per-token scales
    # and wide accumulation (ops/quant.py; the Pallas quant kernel under
    # attn_impl='flash' on TPU, the XLA reference elsewhere). Training
    # backward stays full precision (straight-through); the bench parity
    # gate bounds the loss/logit effect (docs/MEASUREMENT.md). Local
    # attention only - a sequence axis (ring/ulysses/zigzag) rejects it.
    attn_quant: str = ""
    # router z-loss weight RELATIVE to the load-balance aux: the training
    # loss adds aux_weight * (switch_aux + moe_z_weight * mean(lse^2)), so
    # the default 0.1 with lm_loss's aux_weight=0.01 gives the standard
    # 1e-3 z-loss coefficient (ST-MoE)
    moe_z_weight: float = 0.1

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads


def init_params(key: jax.Array, cfg: TransformerConfig):
    """Replicated-layout parameter pytree (shard with `param_specs`)."""
    d, f, v = cfg.d_model, cfg.d_ff, cfg.vocab_size
    k_embed, k_layers, k_out = jax.random.split(key, 3)
    scale = 1.0 / np.sqrt(d)

    def dense(k, shape, s):
        return (jax.random.normal(k, shape, jnp.float32) * s).astype(jnp.float32)

    e = cfg.n_experts
    layers = []
    for lk in jax.random.split(k_layers, cfg.n_layers):
        ks = jax.random.split(lk, 7)
        layer = {
            "ln1_scale": jnp.ones((d,), jnp.float32),
            "ln1_bias": jnp.zeros((d,), jnp.float32),
            "wq": dense(ks[0], (d, d), scale),
            "wk": dense(ks[1], (d, d), scale),
            "wv": dense(ks[2], (d, d), scale),
            "wo": dense(ks[3], (d, d), scale / np.sqrt(2 * cfg.n_layers)),
            "ln2_scale": jnp.ones((d,), jnp.float32),
            "ln2_bias": jnp.zeros((d,), jnp.float32),
        }
        w2_scale = 1.0 / np.sqrt(f) / np.sqrt(2 * cfg.n_layers)
        if e:
            layer.update(
                {
                    "wr": dense(ks[6], (d, e), scale),
                    "w1": dense(ks[4], (e, d, f), scale),
                    "b1": jnp.zeros((e, f), jnp.float32),
                    "w2": dense(ks[5], (e, f, d), w2_scale),
                    "b2": jnp.zeros((e, d), jnp.float32),
                }
            )
        else:
            layer.update(
                {
                    "w1": dense(ks[4], (d, f), scale),
                    "b1": jnp.zeros((f,), jnp.float32),
                    "w2": dense(ks[5], (f, d), w2_scale),
                    "b2": jnp.zeros((d,), jnp.float32),
                }
            )
        layers.append(layer)
    return {
        "embed": dense(k_embed, (v, d), 1.0),
        "lnf_scale": jnp.ones((d,), jnp.float32),
        "lnf_bias": jnp.zeros((d,), jnp.float32),
        "head": dense(k_out, (d, v), scale),
        "layers": _stack_layers(layers),
    }


def _stack_layers(layers):
    """Stack per-layer dicts on a leading layer axis (scanned in apply)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *layers)


def param_skeleton(cfg: TransformerConfig):
    """The param tree's STRUCTURE (same keys as `init_params`, placeholder
    leaves) - what the partition-rule matcher walks when no real params
    exist yet. Kept next to `init_params` so the two can never drift."""
    layer_keys = [
        "ln1_scale", "ln1_bias", "wq", "wk", "wv", "wo",
        "ln2_scale", "ln2_bias",
    ]
    if cfg.n_experts:
        layer_keys += ["wr", "w1", "b1", "w2", "b2"]
    else:
        layer_keys += ["w1", "b1", "w2", "b2"]
    return {
        "embed": 0,
        "lnf_scale": 0,
        "lnf_bias": 0,
        "head": 0,
        "layers": {k: 0 for k in layer_keys},
    }


def param_specs(
    cfg: TransformerConfig,
    tp_axis: str | None = None,
    ep_axis: str | None = None,
    rules=None,
):
    """PartitionSpec pytree for the param tree, derived from the
    declarative rule table (`parallel/rules.py lm_partition_rules`).

    With `tp_axis`: wq/wk/wv and w1 column-sharded (heads / ff-hidden split),
    wo and w2 row-sharded (psum after), b1 sharded with its columns;
    everything else replicated. Without: fully replicated. With
    `cfg.n_experts > 0` and `ep_axis`: expert tensors additionally sharded
    over the expert dimension (router replicated).

    ``rules`` overrides the built-in table with a custom ordered
    ``(regex, PartitionSpec)`` list (the ``--sharding rules:<file>``
    path); every leaf must match or derivation fails with the path named.
    """
    from ..parallel.rules import lm_partition_rules, match_partition_rules

    if rules is None:
        rules = lm_partition_rules(
            tp_axis=tp_axis, ep_axis=ep_axis, n_experts=cfg.n_experts
        )
    return match_partition_rules(
        rules, param_skeleton(cfg), skip_scalars=False
    )


def _layer_norm(x, scale, bias, eps=1e-5):
    m = x.mean(-1, keepdims=True)
    v = ((x - m) ** 2).mean(-1, keepdims=True)
    return (x - m) * jax.lax.rsqrt(v + eps) * scale + bias


def _positions(s_local: int, seq_axis: str | None, attn_impl: str = "ring"):
    if seq_axis is None:
        return jnp.arange(s_local)
    if attn_impl == "zigzag":
        return zigzag_positions(s_local, seq_axis)
    return jax.lax.axis_index(seq_axis) * s_local + jnp.arange(s_local)


def _sinusoid_pe(pos, d_model, dtype):
    half = d_model // 2
    freqs = jnp.exp(-np.log(10000.0) * jnp.arange(half) / half)
    ang = pos[:, None].astype(jnp.float32) * freqs[None, :]
    return jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1).astype(dtype)


def _attend(q, k, v, *, impl, seq_axis, s_local, quant: str = ""):
    if seq_axis is None:
        if impl == "flash":
            from ..ops.flash import flash_local_attention

            return flash_local_attention(q, k, v, causal=True,
                                         quant=quant or None)
        if quant:
            from ..ops.quant import quantized_attention

            return quantized_attention(q, k, v, causal=True, fmt=quant)
        return attention(q, k, v, causal=True)
    if quant:
        raise ValueError(
            f"attn_quant={quant!r} is the local quantized path; a "
            "sequence axis (ring/ulysses/zigzag) has no quantized "
            "attention - drop the seq axis or attn_quant"
        )
    if impl == "flash":
        raise ValueError(
            "attn impl 'flash' is the local kernel (no sequence axis); use "
            "'ring'/'ulysses'/'zigzag' for sequence parallelism"
        )
    if impl == "ring":
        return ring_attention(q, k, v, seq_axis, causal=True)
    if impl == "ulysses":
        return ulysses_attention(q, k, v, seq_axis, causal=True)
    if impl == "zigzag":
        return zigzag_ring_attention(q, k, v, seq_axis)
    raise ValueError(
        f"with a sequence axis, attn impl must be 'ring', 'ulysses' or "
        f"'zigzag', got {impl!r}"
    )


def transformer_block(x, lp, cfg: TransformerConfig, *, attend, tp_axis=None,
                      ep_axis=None, capacity=None):
    """One pre-norm block on x (B, S_local, d) with layer params lp.

    `attend`: (q, k, v) -> output, each (B, S_local, H_local, head_dim) -
    the caller chooses full/ring/Ulysses and the causal offset convention.
    Returns (x, aux) where aux is the MoE load-balancing loss (0.0 dense).
    Shared by `apply_with_aux` (flat or dp/sp/tp-sharded execution) and the
    pipeline schedule (`parallel/pipeline.py`), so the block math lives in
    exactly one place.
    """
    dt = cfg.dtype
    b, s_local = x.shape[:2]
    d_local_heads = lp["wq"].shape[-1] // cfg.head_dim
    h = _layer_norm(x, lp["ln1_scale"], lp["ln1_bias"]).astype(dt)
    q = (h @ lp["wq"].astype(dt)).reshape(b, s_local, d_local_heads, cfg.head_dim)
    k = (h @ lp["wk"].astype(dt)).reshape(b, s_local, d_local_heads, cfg.head_dim)
    v = (h @ lp["wv"].astype(dt)).reshape(b, s_local, d_local_heads, cfg.head_dim)
    o = attend(q, k, v)
    o = o.reshape(b, s_local, -1) @ lp["wo"].astype(dt)
    if tp_axis is not None:
        o = jax.lax.psum(o, tp_axis)
    x = x + o

    h = _layer_norm(x, lp["ln2_scale"], lp["ln2_bias"]).astype(dt)
    if cfg.n_experts:
        y, aux = moe_ffn(
            h.reshape(b * s_local, cfg.d_model),
            lp["wr"],
            lp["w1"],
            lp["b1"],
            lp["w2"],
            lp["b2"],
            top_k=cfg.moe_top_k,
            capacity=capacity,
            ep_axis=ep_axis,
            tp_axis=tp_axis,
            dispatch_impl=cfg.moe_dispatch,
            z_loss_weight=cfg.moe_z_weight,
        )
        x = x + y.reshape(b, s_local, cfg.d_model)
    else:
        h = jax.nn.gelu(h @ lp["w1"].astype(dt) + lp["b1"].astype(dt))
        h = h @ lp["w2"].astype(dt)
        if tp_axis is not None:
            h = jax.lax.psum(h, tp_axis)
        x = x + h + lp["b2"].astype(dt)
        aux = jnp.float32(0.0)
    return x, aux


def apply_hidden(
    params,
    tokens,
    cfg: TransformerConfig,
    *,
    seq_axis: str | None = None,
    tp_axis: str | None = None,
    ep_axis: str | None = None,
    attn_impl: str = "ring",
):
    """tokens (B, S_local) int32 -> (hidden (B, S_local, d_model), aux).

    The pre-head forward: embedding + blocks + final layer norm, WITHOUT the
    vocab projection. Loss paths that chunk the cross-entropy (train/lm.py)
    consume this directly so the (B, S, vocab) logits tensor is never
    materialized whole - at vocab 32k/seq 2048 that tensor is GBs of HBM
    traffic and the single biggest single-chip LM cost.
    """
    dt = cfg.dtype
    b, s_local = tokens.shape
    x = params["embed"][tokens].astype(dt)
    x = x + _sinusoid_pe(
        _positions(s_local, seq_axis, attn_impl), cfg.d_model, dt
    )[None]
    cap = expert_capacity(
        b * s_local, cfg.n_experts, cfg.moe_top_k, cfg.moe_capacity_factor
    ) if cfg.n_experts else None

    def attend(q, k, v):
        return _attend(
            q, k, v, impl=attn_impl, seq_axis=seq_axis, s_local=s_local,
            quant=cfg.attn_quant,
        )

    if cfg.remat_attn and not cfg.remat:
        attend = jax.checkpoint(attend)

    def block(x, lp):
        return transformer_block(
            x,
            lp,
            cfg,
            attend=attend,
            tp_axis=tp_axis,
            ep_axis=ep_axis,
            capacity=cap,
        )

    if cfg.remat:
        policy = (getattr(jax.checkpoint_policies, cfg.remat_policy)
                  if cfg.remat_policy else None)
        block = jax.checkpoint(block, policy=policy)
    x, aux = jax.lax.scan(block, x, params["layers"])
    x = _layer_norm(x, params["lnf_scale"], params["lnf_bias"]).astype(dt)
    return x, aux.mean()


def apply_with_aux(
    params,
    tokens,
    cfg: TransformerConfig,
    *,
    seq_axis: str | None = None,
    tp_axis: str | None = None,
    ep_axis: str | None = None,
    attn_impl: str = "ring",
):
    """tokens (B, S_local) int32 -> (logits (B, S_local, vocab) f32, aux).

    Call directly for single-device, or inside shard_map with tokens sharded
    (data/seq axes) and params placed per `param_specs`. With tp_axis, each
    device holds H/tp heads and d_ff/tp hidden columns; one psum per
    attention-out and MLP-out projection restores the full residual. With
    cfg.n_experts, the MLP is a mixture-of-experts (experts sharded over
    `ep_axis` when given) and `aux` is the mean Switch load-balancing loss
    over layers (0.0 for dense).
    """
    x, aux = apply_hidden(
        params,
        tokens,
        cfg,
        seq_axis=seq_axis,
        tp_axis=tp_axis,
        ep_axis=ep_axis,
        attn_impl=attn_impl,
    )
    logits = (x @ params["head"].astype(cfg.dtype)).astype(jnp.float32)
    return logits, aux


def apply(params, tokens, cfg: TransformerConfig, **kw):
    """Logits-only wrapper over `apply_with_aux` (same signature)."""
    return apply_with_aux(params, tokens, cfg, **kw)[0]


def early_exit_params(params, n_layers: int):
    """The same param tree truncated to its FIRST ``n_layers`` blocks
    (leading stacked-layer axis sliced; embed / final LN / head shared
    with the full model). This IS the serving drafter's model
    (docs/SERVING.md "Speculative decoding"): `ServeEngine` slices once
    at init and runs k cheap greedy steps through it per speculative
    round, so the draft distribution is pinned against
    ``apply(early_exit_params(p, E), ...)`` - no second set of weights,
    no train-time change."""
    total = next(iter(jax.tree.leaves(params["layers"]))).shape[0]
    if not 1 <= n_layers <= total:
        raise ValueError(
            f"early-exit depth must be in [1, {total}], got {n_layers}"
        )
    return {
        **params,
        "layers": jax.tree.map(lambda p: p[:n_layers], params["layers"]),
    }


def early_exit_logits(params, tokens, cfg: TransformerConfig,
                      n_layers: int):
    """Teacher-forced logits of the early-exit drafter: the first
    ``n_layers`` blocks + the shared final LN/head, (B, S) -> (B, S,
    vocab) f32. The offline oracle tests pin the engine's jitted
    drafter against (greedy argmax over these logits == the drafted
    tokens)."""
    return apply(early_exit_params(params, n_layers), tokens, cfg,
                 attn_impl="full")


def param_count(params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


def generate_sharded(
    params,
    prompt,
    cfg: TransformerConfig,
    mesh,
    *,
    data_axis: str = "data",
    **kw,
):
    """`generate` with the batch sharded over `data_axis` of `mesh`.

    Fleet-style decode: params replicate, each device decodes its slice of
    the prompt batch - the KV caches and every per-token intermediate
    carry the batch dimension, so XLA's SPMD partitioner runs the whole
    scan with zero cross-device traffic after the initial placement
    (verified identical to single-device `generate` by
    tests/test_generate.py). Batch must divide the axis size.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    b = prompt.shape[0]
    n = mesh.shape[data_axis]
    if b % n:
        raise ValueError(
            f"prompt batch ({b}) must divide by the {data_axis!r} axis "
            f"size ({n})"
        )
    repl = NamedSharding(mesh, PartitionSpec())
    params = jax.tree.map(lambda p: jax.device_put(p, repl), params)
    prompt = jax.device_put(
        prompt, NamedSharding(mesh, PartitionSpec(data_axis))
    )
    if kw.get("prompt_lens") is not None:
        kw = dict(kw)
        kw["prompt_lens"] = jax.device_put(
            jnp.asarray(kw["prompt_lens"], jnp.int32),
            NamedSharding(mesh, PartitionSpec(data_axis)),
        )
    return generate(params, prompt, cfg, **kw)


# ------------------------------------------------------------- inference


def generate(
    params,
    prompt,
    cfg: TransformerConfig,
    *,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 0.0,
    key: jax.Array | None = None,
    prompt_lens=None,
):
    """Autoregressive decoding with per-layer KV caches.

    prompt: (B, S_p) int32. Returns (B, S_p + max_new_tokens) int32 - the
    prompt followed by generated tokens. temperature 0 = greedy argmax;
    > 0 samples from softmax(logits / temperature) (requires `key`);
    top_k > 0 restricts sampling to the k most likely tokens first;
    top_p in (0, 1) further restricts it to the nucleus - the smallest
    set of tokens whose cumulative probability (at this temperature,
    after any top-k cut) reaches top_p. Both filters always keep the
    most likely token, so sampling never degenerates.

    ``prompt_lens`` (B,) int32 makes the batch LEFT-PADDED mixed-length:
    sequence b's real tokens occupy the LAST ``prompt_lens[b]`` columns
    (columns 0..S_p-len-1 are pad and fully ignored - their cache
    entries are masked out of every attention and their position ids
    never exist). Left padding aligns every sequence's last prompt token
    at column S_p-1, so generation is the uniform region [S_p, total) -
    exactly the batch shape a continuous-batching server feeds
    (serve/engine.py). Per-sequence positions are 0..len-1 (position
    embeddings offset by the pad width), so each row decodes exactly as
    its unpadded single-sequence `generate` would (pinned by
    tests/test_generate.py against the per-sequence oracle). Not
    supported with the fused Pallas decode kernel (a scalar-pos kernel;
    per-sequence masks need the XLA path) - explicitly rejected.

    TPU-shaped: one `lax.scan` over time steps (static total length
    S_p + max_new_tokens), an inner scan over the stacked layers, KV
    caches updated in place with `dynamic_update_slice` - no growing
    shapes, one compile. The prompt is consumed through the same cached
    step as generation (its logits are discarded), so there is a single
    code path whose cache math is pinned against the teacher-forced
    forward by tests/test_generate.py. Training-side parallelism
    (`apply`'s seq/tp/ep axes) is out of scope here: decode is the
    single-device inference path; shard the batch outside for fleet
    serving. MoE models route through the dense dispatch with capacity
    sized so decode never drops a token; the training forward, by
    contrast, is capacity-limited (moe_capacity_factor) and can drop
    under router imbalance - parity with the teacher-forced forward
    therefore holds exactly in the no-drop regime and diverges on
    whatever tokens training would have dropped.
    """
    if temperature > 0.0 and key is None:
        raise ValueError("temperature > 0 sampling requires `key`")
    if not 0.0 <= top_p <= 1.0:
        raise ValueError(f"top_p must be in [0, 1], got {top_p}")
    dt = cfg.dtype
    b, s_p = prompt.shape
    offsets = None
    if prompt_lens is not None:
        prompt_lens = jnp.asarray(prompt_lens, jnp.int32)
        if prompt_lens.shape != (b,):
            raise ValueError(
                f"prompt_lens must be shape ({b},) to match the prompt "
                f"batch, got {prompt_lens.shape}"
            )
        lens = np.asarray(prompt_lens)
        if (lens < 1).any() or (lens > s_p).any():
            raise ValueError(
                f"prompt_lens must be in [1, {s_p}] (the padded prompt "
                f"width), got {lens.tolist()}"
            )
        offsets = s_p - prompt_lens  # pad width per sequence
    total = s_p + max_new_tokens
    L, H, Dh = cfg.n_layers, cfg.n_heads, cfg.head_dim
    prompt_pad = jnp.pad(prompt, ((0, 0), (0, max_new_tokens)))
    # caches are (L, B, H, total, Dh): collapsing (B, H) for the decode
    # kernel is then a free reshape. DNN_TPU_DECODE_IMPL selects the
    # per-step attention: "auto"/"xla" (the XLA chain - measured FASTER
    # than the fused kernel at d512/cache<=640: 2.59 vs 3.69 ms/step at
    # b16/hd64, r5; XLA lowers the whole step as one well-tiled batched
    # einsum and a per-layer pallas_call costs more than it fuses),
    # "pallas" (the ops/decode_pallas.py kernel - kept selectable for
    # larger caches where dead-block skipping should eventually win),
    # "pallas-interpret" (CPU-testable kernel path).
    impl = os.environ.get("DNN_TPU_DECODE_IMPL", "auto")
    if impl not in ("auto", "xla", "pallas", "pallas-interpret"):
        raise ValueError(f"unknown decode impl {impl!r} "
                         "(DNN_TPU_DECODE_IMPL)")
    use_kernel = impl in ("pallas", "pallas-interpret")
    if use_kernel and offsets is not None:
        raise ValueError(
            "decode impl {!r} does not support left-padded batches "
            "(prompt_lens): the fused kernel masks on a scalar position; "
            "use impl=auto/xla for mixed-length prompts".format(impl)
        )
    if use_kernel and not decode_kernel_ok(total):
        # an explicitly requested kernel must not silently measure XLA
        raise ValueError(
            f"decode impl {impl!r} requested but cache size {total} "
            "admits no sublane-legal k block (decode_kernel_ok: the "
            "largest divisor of the total at or under the k block size "
            "must be a multiple of 16) - choose prompt+max_new_tokens "
            "with such a divisor (any multiple of 128 works) or use "
            "impl=auto"
        )
    cache_k = jnp.zeros((L, b, H, total, Dh), dt)
    cache_v = jnp.zeros((L, b, H, total, Dh), dt)
    pe_all = _sinusoid_pe(jnp.arange(total), cfg.d_model, dt)
    neg = jnp.asarray(-1e30, jnp.float32)

    def layer_step(xp, lcaches):
        (x, pos) = xp
        lp, ck, cv = lcaches
        h = _layer_norm(x, lp["ln1_scale"], lp["ln1_bias"]).astype(dt)
        q = (h @ lp["wq"].astype(dt)).reshape(b, 1, H, Dh)
        k = (h @ lp["wk"].astype(dt)).reshape(b, H, 1, Dh)
        v = (h @ lp["wv"].astype(dt)).reshape(b, H, 1, Dh)
        ck = jax.lax.dynamic_update_slice_in_dim(ck, k, pos, axis=2)
        cv = jax.lax.dynamic_update_slice_in_dim(cv, v, pos, axis=2)
        if use_kernel:
            # fused single-query kernel: one pallas_call instead of the
            # einsum/softmax/einsum chain, dead cache blocks skipped
            # (ops/decode_pallas.py)
            o = decode_cache_attention(
                q.reshape(b, H, Dh), ck, cv, pos,
                interpret=impl == "pallas-interpret",
            ).reshape(b, 1, H * Dh)
        else:
            # scores over the full static cache, future slots masked out
            scores = jnp.einsum(
                "bqhd,bhsd->bhqs", q, ck
            ).astype(jnp.float32)
            scores = scores / np.sqrt(Dh)
            live = (jnp.arange(total) <= pos)[None, :]
            if offsets is not None:
                # left-padded batch: pad columns (before each row's
                # offset) never existed - mask their cache entries out
                live = live & (
                    jnp.arange(total)[None, :] >= offsets[:, None]
                )
            live = live[:, None, None, :]
            probs = jax.nn.softmax(jnp.where(live, scores, neg), axis=-1)
            o = jnp.einsum("bhqs,bhsd->bqhd", probs.astype(dt), cv)
            o = o.reshape(b, 1, H * Dh)
        x = x + o @ lp["wo"].astype(dt)
        h2 = _layer_norm(x, lp["ln2_scale"], lp["ln2_bias"]).astype(dt)
        if cfg.n_experts:
            # dense dispatch at decode shapes (B tokens/step): capacity =
            # B guarantees zero drops. Parity caveat: the training
            # forward uses moe_capacity_factor and CAN drop tokens under
            # router imbalance, so cached decode matches the
            # teacher-forced forward exactly only in the no-drop regime
            # (dropped training tokens pass through the residual with no
            # expert output; decode never drops)
            y, _ = moe_ffn(
                h2.reshape(b, cfg.d_model),
                lp["wr"], lp["w1"], lp["b1"], lp["w2"], lp["b2"],
                top_k=cfg.moe_top_k, capacity=b, dispatch_impl="dense",
            )
            x = x + y.reshape(b, 1, cfg.d_model)
        else:
            h2 = jax.nn.gelu(h2 @ lp["w1"].astype(dt) + lp["b1"].astype(dt))
            x = x + h2 @ lp["w2"].astype(dt) + lp["b2"].astype(dt)
        return (x, pos), (ck, cv)

    def time_step(carry, pos):
        ck, cv, prev, k_rng = carry
        tok = jnp.where(
            pos < s_p,
            jax.lax.dynamic_index_in_dim(prompt_pad, pos, axis=1,
                                         keepdims=False),
            prev,
        )
        if offsets is None:
            pe = pe_all[pos][None, None]
        else:
            # per-sequence positions: global slot pos maps to local
            # position pos - offset (clipped: pad slots get position 0,
            # masked out of every attention anyway)
            pe = pe_all[jnp.clip(pos - offsets, 0)][:, None, :]
        x = params["embed"][tok].astype(dt)[:, None, :] + pe
        (x, _), (ck, cv) = jax.lax.scan(
            layer_step, (x, pos), (params["layers"], ck, cv),
            # unrolling the (short) layer scan lets XLA overlap across
            # layers inside one decode step - measured r5: 1.19 -> 0.82
            # ms/step at cache 256, 2.59 -> 2.41 at cache 640 (b16/hd64).
            # Chunked so deep stacks don't blow up compile time.
            unroll=min(L, 8),
        )
        h = _layer_norm(x, params["lnf_scale"], params["lnf_bias"]).astype(dt)
        logits = (h[:, 0] @ params["head"].astype(dt)).astype(jnp.float32)
        if temperature > 0.0:
            if top_k > 0:
                kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
                logits = jnp.where(logits < kth, -jnp.inf, logits)
            if 0.0 < top_p < 1.0:
                # nucleus cut on the temperature-scaled distribution
                # (ordering is temperature-invariant; the cumulative
                # mass is not): keep tokens whose cumulative probability
                # of STRICTLY more likely tokens is < top_p - the top-1
                # always survives, and -inf (top-k-cut) entries sort
                # last with zero mass
                srt = jnp.sort(logits, axis=-1)[:, ::-1]
                p_srt = jax.nn.softmax(srt / temperature, axis=-1)
                keep = (jnp.cumsum(p_srt, axis=-1) - p_srt) < top_p
                cutoff = jnp.min(
                    jnp.where(keep, srt, jnp.inf), axis=-1, keepdims=True
                )
                logits = jnp.where(logits < cutoff, -jnp.inf, logits)
            k_rng, k_tok = jax.random.split(k_rng)
            nxt = jax.random.categorical(k_tok, logits / temperature, axis=-1)
        else:
            nxt = jnp.argmax(logits, axis=-1)
        nxt = nxt.astype(jnp.int32)
        return (ck, cv, nxt, k_rng), nxt

    k0 = key if key is not None else jax.random.key(0)
    (_, _, _, _), nexts = jax.lax.scan(
        time_step,
        (cache_k, cache_v, jnp.zeros((b,), jnp.int32), k0),
        jnp.arange(total),
    )
    # nexts[t] = token predicted AFTER consuming position t; generation
    # starts from the prediction at the last prompt position
    gen = nexts.swapaxes(0, 1)[:, s_p - 1: total - 1]
    return jnp.concatenate([prompt, gen], axis=1)
