"""Subpackage: models."""
