// Native host-side data kernels for the TPU framework's input pipeline.
//
// The reference delegates its host data path to torchvision + torch
// DataLoader (C++ under ATen: `data_parallelism_train.py:69-79`). This
// framework batches on-device (data/pipeline.py), so the host hot spots
// that remain are the one-time dataset decode (CIFAR plane-major uint8 ->
// normalized NHWC float32 - a 4-pass numpy chain of reshape / transpose /
// astype / affine) and row-gather for host-side streaming. Each is fused
// here into a single cache-friendly pass, parallelized across rows with
// std::thread. Built at import time by distributed_neural_network_tpu/
// native/__init__.py (g++ -O3 -shared), called through ctypes; numpy is
// the documented fallback when no compiler is available.
//
// All functions write `out = a * x + b` per element, which expresses any
// mean/std normalization: a = 1/(255*std), b = -mean/std.

#include <algorithm>
#include <cstdint>
#include <thread>
#include <vector>

namespace {

constexpr int64_t kH = 32, kW = 32, kC = 3;
constexpr int64_t kRow = kH * kW * kC;  // 3072

int resolve_threads(int32_t nthreads, int64_t rows) {
  int hw = static_cast<int>(std::thread::hardware_concurrency());
  if (hw <= 0) hw = 1;
  int t = nthreads > 0 ? nthreads : std::min(hw, 8);
  return static_cast<int>(std::min<int64_t>(t, std::max<int64_t>(rows, 1)));
}

template <typename Fn>
void parallel_rows(int64_t rows, int32_t nthreads, Fn fn) {
  int t = resolve_threads(nthreads, rows);
  if (t <= 1) {
    fn(0, rows);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(t);
  int64_t chunk = (rows + t - 1) / t;
  for (int i = 0; i < t; ++i) {
    int64_t lo = i * chunk;
    int64_t hi = std::min(rows, lo + chunk);
    if (lo >= hi) break;
    pool.emplace_back([=] { fn(lo, hi); });
  }
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// CIFAR python-batch layout: each row is 3072 bytes, plane-major
// (R[32][32], G[32][32], B[32][32]). Emit NHWC float32, out = a*x + b.
void cifar_decode_chw_to_nhwc(const uint8_t* src, int64_t n, float a, float b,
                              float* dst, int32_t nthreads) {
  parallel_rows(n, nthreads, [=](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      const uint8_t* in = src + r * kRow;
      float* out = dst + r * kRow;
      for (int64_t hw = 0; hw < kH * kW; ++hw) {
        float* px = out + hw * kC;
        px[0] = a * in[hw] + b;
        px[1] = a * in[kH * kW + hw] + b;
        px[2] = a * in[2 * kH * kW + hw] + b;
      }
    }
  });
}

// Elementwise affine uint8 -> float32 over an arbitrary contiguous buffer
// (layout-preserving; used for NHWC arrays that are already interleaved).
void affine_u8_to_f32(const uint8_t* src, int64_t size, float a, float b,
                      float* dst, int32_t nthreads) {
  // treat as pseudo-rows for threading granularity
  constexpr int64_t kBlock = 1 << 16;
  int64_t blocks = (size + kBlock - 1) / kBlock;
  parallel_rows(blocks, nthreads, [=](int64_t lo, int64_t hi) {
    int64_t start = lo * kBlock;
    int64_t end = std::min(size, hi * kBlock);
    for (int64_t i = start; i < end; ++i) dst[i] = a * src[i] + b;
  });
}

// Row gather + affine: dst[j] = a * src[idx[j]] + b for row_elems-wide rows.
// The host-streaming batch assembly (gather/convert/normalize in one pass).
void gather_affine_u8(const uint8_t* src, const int64_t* idx, int64_t nidx,
                      int64_t row_elems, float a, float b, float* dst,
                      int32_t nthreads) {
  parallel_rows(nidx, nthreads, [=](int64_t lo, int64_t hi) {
    for (int64_t j = lo; j < hi; ++j) {
      const uint8_t* in = src + idx[j] * row_elems;
      float* out = dst + j * row_elems;
      for (int64_t i = 0; i < row_elems; ++i) out[i] = a * in[i] + b;
    }
  });
}

}  // extern "C"
