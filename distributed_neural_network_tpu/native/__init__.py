"""Native (C++) host-side data kernels: build-on-import + ctypes bindings.

The TPU compute path is JAX/XLA/Pallas; this package is the native layer of
the *runtime around it* - the host input pipeline (see batcher.cpp for what
and why). `batcher.cpp` is compiled once per source change with g++ into a
shared library cached under `_cache/`, loaded via ctypes (no pybind11
dependency), and exposed as numpy-typed wrappers. Every entry point has a
pure-numpy fallback selected automatically when no C++ toolchain is
available, so the framework never *requires* the native layer - it only
gets faster with it. `DNN_TPU_NO_NATIVE=1` forces the fallback (used by the
parity tests).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys
import threading

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "batcher.cpp")
_CACHE = os.path.join(os.path.dirname(__file__), "_cache")
_lock = threading.Lock()
_lib = None
_tried = False


def _disabled() -> bool:
    return os.environ.get("DNN_TPU_NO_NATIVE", "") not in ("", "0")


def _build() -> str | None:
    with open(_SRC, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:16]
    so = os.path.join(_CACHE, f"batcher-{tag}.so")
    if os.path.exists(so):
        return so
    os.makedirs(_CACHE, exist_ok=True)
    # per-process tmp name: concurrent first builds (e.g. pytest-xdist) must
    # not interleave compiler output into one file; os.replace is atomic
    tmp = f"{so}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        _SRC, "-o", tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)
    except (OSError, subprocess.SubprocessError) as e:
        print(f"[native] build failed, using numpy fallback: {e}", file=sys.stderr)
        return None
    return so


def _load():
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if _disabled():
            return None
        so = _build()
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError as e:  # corrupted/incompatible cached .so
            print(f"[native] load failed, using numpy fallback: {e}",
                  file=sys.stderr)
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        f32p = ctypes.POINTER(ctypes.c_float)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.cifar_decode_chw_to_nhwc.argtypes = [
            u8p, ctypes.c_int64, ctypes.c_float, ctypes.c_float, f32p,
            ctypes.c_int32,
        ]
        lib.affine_u8_to_f32.argtypes = [
            u8p, ctypes.c_int64, ctypes.c_float, ctypes.c_float, f32p,
            ctypes.c_int32,
        ]
        lib.gather_affine_u8.argtypes = [
            u8p, i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_float,
            ctypes.c_float, f32p, ctypes.c_int32,
        ]
        _lib = lib
        return _lib


def available() -> bool:
    """True when the compiled native library is loadable."""
    return _load() is not None


def _affine_coeffs(mean: float, std: float) -> tuple[float, float]:
    # out = (x/255 - mean)/std = x * 1/(255*std) - mean/std
    return 1.0 / (255.0 * std), -mean / std


def _as_u8(a) -> np.ndarray:
    a = np.asarray(a)
    if a.dtype != np.uint8:
        raise TypeError(
            f"native data kernels take uint8 input, got {a.dtype}; "
            "normalize non-uint8 arrays with plain numpy math"
        )
    return np.ascontiguousarray(a)


def _u8ptr(a):  # contiguous views for ctypes
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _f32ptr(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


# Pure-numpy reference implementations: what the wrappers run with no
# native lib, what the parity tests compare against, and the baseline
# the bench row times the C++ kernels over (one source of truth).


def fallback_cifar_decode_normalize(rows_u8, mean, std) -> np.ndarray:
    x = rows_u8.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return (x.astype(np.float32) / 255.0 - mean) / std


def fallback_normalize_u8(images_u8, mean, std) -> np.ndarray:
    return (images_u8.astype(np.float32) / 255.0 - mean) / std


def fallback_gather_normalize_u8(images_u8, idx, mean, std) -> np.ndarray:
    return (images_u8[idx].astype(np.float32) / 255.0 - mean) / std


def cifar_decode_normalize(
    rows_u8: np.ndarray, mean: float, std: float, *, nthreads: int = 0
) -> np.ndarray:
    """(N, 3072) plane-major uint8 -> (N, 32, 32, 3) normalized float32.

    One fused pass (native) or the equivalent numpy chain (fallback).
    """
    rows_u8 = _as_u8(rows_u8)
    n = rows_u8.shape[0]
    assert rows_u8.ndim == 2 and rows_u8.shape[1] == 3072, rows_u8.shape
    a, b = _affine_coeffs(mean, std)
    lib = _load()
    if lib is None:
        return fallback_cifar_decode_normalize(rows_u8, mean, std)
    out = np.empty((n, 32, 32, 3), np.float32)
    lib.cifar_decode_chw_to_nhwc(
        _u8ptr(rows_u8), n, a, b, _f32ptr(out), nthreads
    )
    return out


def normalize_u8(
    images_u8: np.ndarray, mean: float, std: float, *, nthreads: int = 0
) -> np.ndarray:
    """Layout-preserving uint8 -> normalized float32 (any shape)."""
    images_u8 = _as_u8(images_u8)
    a, b = _affine_coeffs(mean, std)
    lib = _load()
    if lib is None:
        return fallback_normalize_u8(images_u8, mean, std)
    out = np.empty(images_u8.shape, np.float32)
    lib.affine_u8_to_f32(
        _u8ptr(images_u8), images_u8.size, a, b, _f32ptr(out), nthreads
    )
    return out


def gather_normalize_u8(
    images_u8: np.ndarray,
    indices: np.ndarray,
    mean: float,
    std: float,
    *,
    nthreads: int = 0,
) -> np.ndarray:
    """Batch assembly: images_u8[indices] normalized, in one fused pass.

    images_u8: (N, ...) uint8; indices: (B,) integer. Returns (B, ...)
    float32. The host-streaming path's gather+convert+normalize.
    """
    images_u8 = _as_u8(images_u8)
    idx = np.ascontiguousarray(indices, dtype=np.int64)
    if idx.size and (idx.min() < 0 or idx.max() >= images_u8.shape[0]):
        raise IndexError(
            f"indices out of range [0, {images_u8.shape[0]}): "
            f"[{idx.min()}, {idx.max()}]"
        )
    a, b = _affine_coeffs(mean, std)
    lib = _load()
    if lib is None:
        return fallback_gather_normalize_u8(images_u8, idx, mean, std)
    row = int(np.prod(images_u8.shape[1:], dtype=np.int64))
    out = np.empty((idx.shape[0], *images_u8.shape[1:]), np.float32)
    lib.gather_affine_u8(
        _u8ptr(images_u8),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        idx.shape[0], row, a, b, _f32ptr(out), nthreads,
    )
    return out
