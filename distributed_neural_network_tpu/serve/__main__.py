"""`python -m distributed_neural_network_tpu.serve` -> serve/http.py."""

import sys

from .http import main

if __name__ == "__main__":
    sys.exit(main())
