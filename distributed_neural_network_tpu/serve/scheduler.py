"""Request scheduling: admission control, per-tenant fairness, the
serve loop, and the serving goodput ledger.

The scheduler is the single writer of the engine: one daemon loop
thread admits requests, drives `ServeEngine.step`, and streams tokens
back through per-request queues. Everything user-facing rides three
policies:

- **Admission control**: a bounded global queue - overflow is an
  `AdmissionError` the HTTP layer turns into 429 (the load-balancer
  backoff signal), never an unbounded memory ramp. Requests that could
  never run (prompt + max_new > max_seq_len) are rejected up front
  (400), not admitted to die later.
- **Per-tenant fairness**: each API key gets its own FIFO and a token
  bucket (``tenant_rate`` requests/s, ``tenant_burst`` size - 429 when
  empty); admission drains the per-key FIFOs round-robin, so one
  chatty tenant queues behind itself, not in front of everyone else.
- **KV backpressure**: a request is only admitted when the paged pool
  has blocks for its prompt (plus ``block_headroom``); mid-flight
  exhaustion parks sequences and may preempt the youngest
  (`engine.py`) - preempted sequences re-enter at the FRONT of the
  admission order (they hold streamed state a client is watching).

**Serving ledger** (`utils/goodput.py` taxonomy "serve"): every
wall-clock second of the loop lands in exactly one bucket -

- ``decode``  (goodput)       - step time apportioned to generated
                                tokens;
- ``prefill``                 - step + chunked-prefill time apportioned
                                to prompt tokens;
- ``kv_alloc_stall``          - ticks where block exhaustion blocked
                                every runnable sequence (incl.
                                preemption work);
- ``batch_formation_idle``    - loop time spent assembling batches /
                                admitting while work existed;
- ``queue_wait``              - each request's arrival->admission
                                window, low-priority in the sweep so it
                                claims only otherwise-idle seconds
                                (capacity pressure, not double-counted
                                compute);
- ``idle_other``              - the residual (an empty server).

Conservation is asserted at `close()` (ledger.finalize), the record is
written through to ``run_record`` when configured, and
``goodput_ratio`` / ``badput_seconds_total{cause}`` export live on the
metrics registry next to the QPS/TTFT/KV-occupancy series.
"""

from __future__ import annotations

import itertools
import queue as queue_mod
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from ..utils.goodput import GoodputLedger
from ..utils.obs import NULL_REGISTRY
from .engine import ServeEngine, Sequence, export_descriptor
from .reqtrace import RequestTraceRecorder

# histogram buckets for TTFT / inter-token latency: 1 ms .. 60 s
LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class AdmissionError(Exception):
    """Rejection with an HTTP status: 429 (queue full / rate limited)
    or 400 (a request that could never run)."""

    def __init__(self, status: int, reason: str, message: str):
        self.status = status
        self.reason = reason
        super().__init__(message)


@dataclass
class ServeRequest:
    """One client request + its streaming channel. The HTTP layer (or a
    test) reads ``events`` - a queue of ``("token", id)``,
    ``("done", summary)``, ``("error", message)`` tuples - and sets
    ``cancelled`` on client disconnect."""

    prompt: list
    max_new_tokens: int
    api_key: str = "anonymous"
    temperature: float = 0.0
    seed: int = 0
    # fleet-router failover provenance (X-Router-Retries headers):
    # re-dispatch episode count + client-visible seconds lost before
    # this replica saw the request (serve/reqtrace.py router_retry)
    router_retries: int = 0
    router_retry_s: float = 0.0
    req_id: int = 0
    t_arrival: float = 0.0
    t_admitted: float | None = None
    t_first_token: float | None = None
    t_done: float | None = None
    status: str = "new"
    tokens: list = field(default_factory=list)
    events: object = None       # queue.Queue, created by submit()
    cancelled: threading.Event = field(default_factory=threading.Event)
    # True when a streaming channel (the HTTP layer) owns the tail of
    # the request's lifecycle: the per-request trace record then stays
    # open in ``stream_write`` until `finish_stream` acks the flush
    stream_owner: bool = False
    _seq: object = None
    _t_arrival_ledger: float = 0.0
    _t_prev_token: float | None = None

    def summary(self) -> dict:
        return {
            "req_id": self.req_id,
            "status": self.status,
            "prompt_len": len(self.prompt),
            "tokens": list(self.tokens),
            "n_tokens": len(self.tokens),
            "ttft_s": (
                round(self.t_first_token - self.t_arrival, 6)
                if self.t_first_token is not None else None
            ),
            "total_s": (
                round(self.t_done - self.t_arrival, 6)
                if self.t_done is not None else None
            ),
        }


@dataclass(frozen=True)
class SchedulerConfig:
    max_queue: int = 64          # global bound -> 429 on overflow
    tenant_rate: float = 0.0     # requests/s per API key (0 = unlimited)
    tenant_burst: int = 8        # token-bucket size per API key
    block_headroom: int = 0      # extra free blocks required to admit
    idle_poll_s: float = 0.02    # loop wakeup when completely idle
    run_record: str | None = None  # serving goodput record path
    request_ring: int = 256      # finalized per-request records kept


class _TokenBucket:
    """Per-tenant request-rate limiter (refill-on-read)."""

    def __init__(self, rate: float, burst: int):
        self.rate = float(rate)
        self.burst = max(int(burst), 1)
        self.level = float(self.burst)
        self.t_last = time.monotonic()

    def try_take(self) -> bool:
        now = time.monotonic()
        self.level = min(
            self.burst, self.level + (now - self.t_last) * self.rate
        )
        self.t_last = now
        if self.level >= 1.0:
            self.level -= 1.0
            return True
        return False


class ServeScheduler:
    """Owns the engine + queues; `start()` spawns the loop thread."""

    def __init__(
        self,
        engine: ServeEngine,
        cfg: SchedulerConfig | None = None,
        *,
        registry=NULL_REGISTRY,
        clock=time.monotonic,
        tracer=None,
    ):
        self.engine = engine
        self.cfg = cfg or SchedulerConfig()
        self.registry = registry
        self._clock = clock
        self.tracer = tracer
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._tenants: dict[str, deque] = {}
        self._tenant_order: deque = deque()
        self._buckets: dict[str, _TokenBucket] = {}
        self._queued = 0
        self._by_seq: dict[int, ServeRequest] = {}
        self._ids = itertools.count(1)
        self._running = False
        self._thread: threading.Thread | None = None
        # graceful drain (serve/fleet.py): once set, admission 503s and
        # the loop migrates every live sequence out as deterministic
        # replay descriptors (engine.export_descriptor)
        self._draining = False
        self._drained = threading.Event()
        self._drain_out: list = []
        self.ledger = GoodputLedger(taxonomy="serve", clock=clock)
        self.ledger.start()
        # per-request lifecycle records on the ledger's clock, so the
        # two accountings reconcile (tools/request_trace.py --ledger)
        self.reqtrace = RequestTraceRecorder(
            ring=self.cfg.request_ring, clock=self.ledger.now,
            tracer=tracer,
        )
        if self.cfg.run_record:
            self.ledger.arm(self.cfg.run_record)
        self.ledger.describe(
            config={
                "engine": {
                    "max_batch": engine.ecfg.max_batch,
                    "num_blocks": engine.ecfg.num_blocks,
                    "block_size": engine.ecfg.block_size,
                    "max_seq_len": engine.ecfg.max_seq_len,
                    "prefill_chunk": engine.ecfg.prefill_chunk,
                    "kv_dtype": engine.ecfg.kv_dtype,
                    "weight_dtype": engine.ecfg.weight_dtype,
                    "spec_decode": engine.ecfg.spec_decode,
                    "spec_draft_layers": (
                        engine.draft_layers if engine.spec_k else 0
                    ),
                },
                "scheduler": {
                    "max_queue": self.cfg.max_queue,
                    "tenant_rate": self.cfg.tenant_rate,
                    "tenant_burst": self.cfg.tenant_burst,
                },
            },
        )
        # ---- metrics (resolved once; the publish path is lock-free)
        r = registry
        self._m_requests = r.counter(
            "serve_requests_total",
            "Requests by terminal status (serve/scheduler.py)",
        )
        self._m_rejected = r.counter(
            "serve_rejected_total", "Admission rejections by reason"
        )
        self._m_tokens = r.counter(
            "serve_tokens_total", "Tokens processed, by kind"
        )
        self._m_queue = r.gauge("serve_queue_depth", "Queued requests")
        self._m_draining = r.gauge(
            "serve_draining", "1 while the replica is draining"
        )
        self._m_active = r.gauge(
            "serve_active_sequences", "Sequences in the decode batch"
        )
        self._m_kv_used = r.gauge(
            "serve_kv_blocks_in_use", "Paged-KV blocks allocated"
        )
        self._m_kv_total = r.gauge(
            "serve_kv_blocks_total", "Paged-KV usable block count"
        )
        self._m_kv_total.set(engine.kv.cfg.usable_blocks)
        # occupancy in the bytes the pool ACTUALLY allocates (int8 KV
        # halves them; analysis/cost.py kv_block_bytes incl. scales) +
        # the effective concurrent-sequence capacity at max_seq_len -
        # the number an operator can compare across kv dtypes, unlike a
        # raw block count whose byte value silently changed
        from ..analysis.cost import kv_capacity_sequences

        self._kv_block_bytes = engine.kv_block_bytes()
        self._m_kv_dtype = r.gauge(
            "serve_kv_dtype",
            "KV-pool storage dtype (1 at the active label)",
        )
        self._m_kv_dtype.labels(dtype=engine.kv_dtype_name()).set(1)
        self._m_kv_bytes_used = r.gauge(
            "serve_kv_bytes_in_use",
            "Allocated paged-KV bytes at the pool dtype (incl. scales)",
        )
        self._m_kv_bytes_total = r.gauge(
            "serve_kv_bytes_total",
            "Usable paged-KV pool bytes at the pool dtype (incl. scales)",
        )
        self._m_kv_bytes_total.set(
            engine.kv.cfg.usable_blocks * self._kv_block_bytes
        )
        self._m_kv_capacity = r.gauge(
            "serve_kv_capacity_sequences",
            "Concurrent max_seq_len sequences the pool holds",
        )
        self._m_kv_capacity.set(kv_capacity_sequences(
            engine.kv.cfg.usable_blocks, engine.ecfg.block_size,
            engine.ecfg.max_seq_len,
        ))
        self._m_ttft = r.histogram(
            "serve_ttft_seconds", "Time to first token",
            buckets=LATENCY_BUCKETS,
        )
        self._m_intertoken = r.histogram(
            "serve_intertoken_seconds", "Gap between streamed tokens",
            buckets=LATENCY_BUCKETS,
        )
        self._m_preempt = r.counter(
            "serve_preemptions_total", "Sequences preempted on KV pressure"
        )
        self._m_steps = r.counter(
            "serve_engine_steps_total", "Engine decode steps executed"
        )
        # speculative decoding: proposed/accepted draft tokens plus a
        # per-slot-step acceptance histogram (integer buckets 0..k -
        # "how many of this step's k drafts survived verification")
        self._m_spec_proposed = r.counter(
            "serve_spec_proposed_tokens_total",
            "Draft tokens proposed by the speculative drafter",
        )
        self._m_spec_accepted = r.counter(
            "serve_spec_accepted_tokens_total",
            "Draft tokens accepted by target-model verification",
        )
        spec_k = max(int(getattr(engine, "spec_k", 0)), 1)
        self._m_spec_accept_hist = r.histogram(
            "serve_spec_accepted_per_step",
            "Accepted draft tokens per speculative slot-step",
            buckets=tuple(float(i) for i in range(spec_k)),
        )
        if r is not NULL_REGISTRY:
            self.ledger.publish(r)

    # --------------------------------------------------------- admission

    def submit(self, req: ServeRequest) -> ServeRequest:
        """Admit a request to the queue (any thread). Raises
        `AdmissionError` (429/400/503); on success the request will
        stream through ``req.events``."""
        if self._draining:
            self._m_rejected.labels(reason="draining").inc()
            self.reqtrace.note_rejected("draining")
            raise AdmissionError(
                503, "draining",
                "replica is draining; retry on another replica",
            )
        ecfg = self.engine.ecfg
        if not req.prompt:
            raise AdmissionError(400, "empty_prompt", "empty prompt")
        total = len(req.prompt) + req.max_new_tokens
        if req.max_new_tokens < 1:
            raise AdmissionError(
                400, "bad_max_new_tokens",
                f"max_new_tokens must be >= 1, got {req.max_new_tokens}",
            )
        if total > ecfg.max_seq_len:
            raise AdmissionError(
                400, "too_long",
                f"prompt {len(req.prompt)} + max_new_tokens "
                f"{req.max_new_tokens} = {total} exceeds max_seq_len "
                f"{ecfg.max_seq_len}",
            )
        vmax = self.engine.cfg.vocab_size
        if any(not (0 <= int(t) < vmax) for t in req.prompt):
            raise AdmissionError(
                400, "bad_token",
                f"prompt token out of range [0, {vmax})",
            )
        if self.cfg.tenant_rate > 0:
            with self._lock:
                bucket = self._buckets.get(req.api_key)
                if bucket is None:
                    bucket = self._buckets[req.api_key] = _TokenBucket(
                        self.cfg.tenant_rate, self.cfg.tenant_burst
                    )
            if not bucket.try_take():
                self._m_rejected.labels(reason="rate_limited").inc()
                self.reqtrace.note_rejected("rate_limited")
                raise AdmissionError(
                    429, "rate_limited",
                    f"tenant {req.api_key!r} over "
                    f"{self.cfg.tenant_rate:g} req/s "
                    f"(burst {self.cfg.tenant_burst})",
                )
        with self._work:
            if self._queued >= self.cfg.max_queue:
                self._m_rejected.labels(reason="queue_full").inc()
                self.reqtrace.note_rejected("queue_full")
                raise AdmissionError(
                    429, "queue_full",
                    f"admission queue full ({self.cfg.max_queue})",
                )
            req.req_id = next(self._ids)
            req.t_arrival = time.monotonic()
            req._t_arrival_ledger = self.ledger.now()
            req.events = queue_mod.Queue()
            req.status = "queued"
            self.reqtrace.arrive(
                req.req_id, req.api_key, len(req.prompt),
                req.max_new_tokens,
            )
            if req.router_retries:
                self.reqtrace.note_router_retry(
                    req.req_id, req.router_retries, req.router_retry_s
                )
            fifo = self._tenants.get(req.api_key)
            if fifo is None:
                fifo = self._tenants[req.api_key] = deque()
                self._tenant_order.append(req.api_key)
            fifo.append(req)
            self._queued += 1
            self._m_queue.set(self._queued)
            self._m_requests.labels(status="accepted").inc()
            self._work.notify()
        return req

    def cancel(self, req: ServeRequest) -> None:
        """Client-side cancel (disconnect): flagged here, enacted by the
        loop at the next step boundary."""
        req.cancelled.set()
        with self._work:
            self._work.notify()

    def finish_stream(self, req: ServeRequest) -> None:
        """Streaming-channel ack (any thread): the owner finished
        writing the request's tail, so its trace record's
        ``stream_write`` span closes and the record seals. Only acts on
        a request already at a terminal status - a mid-flight stream
        error stays with the loop (cancel / shutdown paths)."""
        if req.req_id and req.status in (
            "done", "cancelled", "error", "migrated"
        ):
            self.reqtrace.finalize(
                req.req_id, req.status  # idempotent vs the loop's seal
            )

    # ------------------------------------------------------------- loop

    def start(self) -> "ServeScheduler":
        if self._thread is not None:
            return self
        self._running = True
        self._thread = threading.Thread(
            target=self._loop, name="serve-scheduler", daemon=True
        )
        self._thread.start()
        return self

    def close(self, *, finalize: bool = True) -> dict | None:
        """Stop the loop, fail queued/active requests, finalize the
        serving ledger (conservation asserted) and return the record."""
        self._running = False
        with self._work:
            self._work.notify()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        # drain every remaining request with a shutdown error
        with self._work:
            pending = [r for f in self._tenants.values() for r in f]
            for f in self._tenants.values():
                f.clear()
            self._queued = 0
            self._m_queue.set(0)
        for req in pending + list(self._by_seq.values()):
            if req.status not in ("done", "cancelled", "error", "migrated"):
                req.status = "error"
                if req.events is not None:
                    req.events.put(("error", "server shutting down"))
        self.reqtrace.finalize_all()
        if finalize:
            return self.ledger.finalize()
        return None

    # ------------------------------------------------------------ drain

    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self, timeout: float = 30.0) -> dict:
        """Stop admission and migrate every live sequence out as a
        deterministic replay descriptor (any thread). Returns
        ``{"draining", "completed", "migrated"}`` where ``migrated`` is
        the descriptor list a peer replica (or the fleet router) can
        resubmit via `engine.resume_request` for a byte-identical
        continuation. Idempotent; an empty replica completes
        immediately."""
        with self._work:
            first = not self._draining
            self._draining = True
            self._m_draining.set(1)
            self._work.notify()
        if self._thread is None:
            # no loop thread (tests / synchronous drivers): sweep inline
            self._drain_sweep()
        ok = self._drained.wait(timeout=timeout)
        with self._work:
            descs = list(self._drain_out)
            if first:
                self._drain_out = []
        return {"draining": True, "completed": ok, "migrated": descs}

    def _migrate_one(self, req: ServeRequest) -> None:
        """Seal one request as migrated and emit its replay descriptor
        (loop thread). Queued requests (no engine sequence yet) migrate
        with an empty emitted list — a plain re-dispatch."""
        if req._seq is not None:
            desc = export_descriptor(req._seq)
        else:
            desc = {
                "seq_id": int(req.req_id),
                "prompt": [int(t) for t in req.prompt],
                "emitted": [],
                "max_new_tokens": int(req.max_new_tokens),
                "remaining_tokens": int(req.max_new_tokens),
                "temperature": float(req.temperature),
                "seed": int(req.seed),
                "preemptions": 0,
            }
        desc["api_key"] = req.api_key
        req.status = "migrated"
        req.t_done = time.monotonic()
        self._m_requests.labels(status="migrated").inc()
        self.reqtrace.finalize(req.req_id, "migrated")
        if req.events is not None:
            req.events.put(("migrate", desc))
        self._drain_out.append(desc)

    def _drain_sweep(self) -> None:
        """Evict every live request as a migration descriptor (loop
        thread, or inline when the loop never started). Cancels are
        enacted FIRST so a client cancel racing the drain wins — its
        request ends cancelled, not migrated."""
        self._enact_cancels()
        # active (running AND parked-on-kv) sequences: both live in
        # engine.active; cancel() frees their blocks
        for sid, req in list(self._by_seq.items()):
            self.engine.cancel(sid)
            self._by_seq.pop(sid, None)
            self._migrate_one(req)
        # preempted sequences' requests were in _by_seq too (their
        # blocks are already freed); clear the replay deque
        self.engine.preempted.clear()
        with self._work:
            pending = [r for f in self._tenants.values() for r in f]
            for f in self._tenants.values():
                f.clear()
            self._queued = 0
            self._m_queue.set(0)
        for req in pending:
            if req.cancelled.is_set():
                req.status = "cancelled"
                req.t_done = time.monotonic()
                self._m_requests.labels(status="cancelled").inc()
                self.reqtrace.finalize(req.req_id, "cancelled")
                if req.events is not None:
                    req.events.put(("done", req.summary()))
            else:
                self._migrate_one(req)
        self._m_active.set(len(self.engine.active))
        self._m_kv_used.set(self.engine.kv.blocks_in_use)
        self._m_kv_bytes_used.set(
            self.engine.kv.blocks_in_use * self._kv_block_bytes
        )
        self._drained.set()

    def _next_request(self):
        """Round-robin over tenant FIFOs (caller holds the lock)."""
        for _ in range(len(self._tenant_order)):
            key = self._tenant_order[0]
            self._tenant_order.rotate(-1)
            fifo = self._tenants.get(key)
            if fifo:
                self._queued -= 1
                return fifo.popleft()
        return None

    def _admit_one(self, req: ServeRequest) -> None:
        """Wire a queued request into the engine (loop thread)."""
        if req.cancelled.is_set():
            req.status = "cancelled"
            req.t_done = time.monotonic()
            self._m_requests.labels(status="cancelled").inc()
            self.reqtrace.finalize(req.req_id, "cancelled")
            if req.events is not None:
                req.events.put(("done", req.summary()))
            return
        self.reqtrace.mark(req.req_id, "admission")
        seq = Sequence(
            seq_id=req.req_id,
            prompt=[int(t) for t in req.prompt],
            max_new_tokens=int(req.max_new_tokens),
            temperature=float(req.temperature),
            seed=int(req.seed),
            on_token=self._on_token,
        )
        req._seq = seq
        self._by_seq[seq.seq_id] = req
        self.engine.add(seq)
        req.t_admitted = time.monotonic()
        req.status = "active"
        self.reqtrace.mark(req.req_id, "prefill")
        # the request's whole queued window, attributed once the sweep
        # resolves overlaps (it only claims otherwise-idle seconds)
        self.ledger.add(
            "queue_wait", req._t_arrival_ledger, self.ledger.now()
        )

    def _on_token(self, seq: Sequence, tok: int, done: bool) -> None:
        """Engine callback (loop thread): stream + latency metrics."""
        req = self._by_seq.get(seq.seq_id)
        if req is None:
            return
        now = time.monotonic()
        req.tokens.append(int(tok))
        self.reqtrace.note_token(seq.seq_id)
        if req.t_first_token is None:
            req.t_first_token = now
            self._m_ttft.observe(now - req.t_arrival)
        elif req._t_prev_token is not None:
            self._m_intertoken.observe(now - req._t_prev_token)
        req._t_prev_token = now
        if req.events is not None:
            req.events.put(("token", int(tok)))
        if done:
            req.status = "done"
            req.t_done = now
            self._m_requests.labels(status="completed").inc()
            self._by_seq.pop(seq.seq_id, None)
            # the stream_write window opens BEFORE the done event is
            # visible to the streaming thread; with no stream owner the
            # record seals immediately (zero-length flush)
            self.reqtrace.mark(seq.seq_id, "stream_write")
            if not req.stream_owner:
                self.reqtrace.finalize(seq.seq_id, "done")
            if req.events is not None:
                req.events.put(("done", req.summary()))

    def _enact_cancels(self) -> None:
        for sid, req in list(self._by_seq.items()):
            if req.cancelled.is_set() and req.status == "active":
                self.engine.cancel(sid)
                self._by_seq.pop(sid, None)
                req.status = "cancelled"
                req.t_done = time.monotonic()
                self._m_requests.labels(status="cancelled").inc()
                self.reqtrace.finalize(sid, "cancelled")
                if req.events is not None:
                    req.events.put(("done", req.summary()))
        # preempted sequences whose request was cancelled while parked
        self.engine.preempted = deque(
            s for s in self.engine.preempted
            if self._by_seq.get(s.seq_id) is not None
        )

    def _loop(self) -> None:
        eng = self.engine
        kv = eng.kv
        cfg = self.cfg
        while self._running:
            if self._draining:
                self._drain_sweep()
                with self._work:
                    self._work.wait(timeout=cfg.idle_poll_s)
                continue
            with self._work:
                have_queued = self._queued > 0
            if not have_queued and not eng.has_work() and not eng.preempted:
                with self._work:
                    self._work.wait(timeout=cfg.idle_poll_s)
                continue

            t_form0 = self.ledger.now()
            self._enact_cancels()
            # re-admit preempted sequences first (streamed state)
            while eng.preempted and len(eng.active) < eng.ecfg.max_batch:
                s = eng.preempted[0]
                if not kv.can_fit(s.prompt_len + 1):
                    break
                eng.preempted.popleft()
                eng.add(s)
                # replay starts at pos 0: back to prefill until the
                # engine re-derives the held tokens
                self.reqtrace.mark(s.seq_id, "prefill")
            # admit new requests round-robin while capacity lasts
            while len(eng.active) < eng.ecfg.max_batch:
                with self._work:
                    nxt = self._next_request() if self._queued > 0 else None
                    if nxt is not None:
                        self._m_queue.set(self._queued)
                if nxt is None:
                    break
                need = kv.cfg.blocks_for_tokens(len(nxt.prompt) + 1)
                if need + cfg.block_headroom > kv.free_blocks:
                    # no room for this prompt yet: back to the head of
                    # its tenant FIFO (it keeps its place; 429 pressure
                    # builds behind the queue bound), stop admitting
                    with self._work:
                        self._tenants[nxt.api_key].appendleft(nxt)
                        self._queued += 1
                        self._m_queue.set(self._queued)
                    break
                self._admit_one(nxt)
            t_form1 = self.ledger.now()
            if t_form1 > t_form0:
                self.ledger.add("batch_formation_idle", t_form0, t_form1)

            if not eng.has_work():
                continue
            preempted_before = len(eng.preempted)
            t0 = self.ledger.now()
            stats = eng.step()
            t1 = self.ledger.now()
            self._m_steps.inc()
            self.reqtrace.observe_step(stats, t0, t1)
            if len(eng.preempted) > preempted_before:
                self._m_preempt.inc(len(eng.preempted) - preempted_before)
            spec = stats.get("spec")
            if spec:
                if spec["proposed"]:
                    self._m_spec_proposed.inc(spec["proposed"])
                if spec["accepted"]:
                    self._m_spec_accepted.inc(spec["accepted"])
                for a in spec.get("per_slot", ()):
                    self._m_spec_accept_hist.observe(float(a))
            dec, pre = stats["decode_tokens"], stats["prefill_tokens"]
            span = t1 - t0
            if dec + pre > 0 and span > 0:
                # one fenced step span, apportioned to the two phases by
                # token counts - prefill and decode genuinely share the
                # batch (token-level continuous batching), so the split
                # is the honest per-phase cost
                t_split = t0 + span * (pre / (dec + pre))
                if pre > 0:
                    self.ledger.add("prefill", t0, t_split)
                if dec > 0:
                    self.ledger.add("decode", t_split, t1)
                self._m_tokens.labels(kind="prefill").inc(pre)
                self._m_tokens.labels(kind="decode").inc(dec)
                self.ledger.note_steps(1, tokens=float(dec))
            elif span > 0:
                # a tick that moved nothing: block exhaustion (possibly
                # including preemption work)
                self.ledger.add("kv_alloc_stall", t0, t1)
            self._m_active.set(len(eng.active))
            self._m_kv_used.set(kv.blocks_in_use)
            self._m_kv_bytes_used.set(
                kv.blocks_in_use * self._kv_block_bytes
            )
            self.ledger.maybe_publish()
            self.ledger.maybe_write()
            self.registry.beat(eng.ticks)
            if not self.registry.ready and eng.ticks > 0:
                self.registry.mark_ready()
