"""Per-request lifecycle tracing: latency attribution for every
request the serving stack touches.

The serving ledger (`scheduler.py` + `utils/goodput.py` taxonomy
"serve") partitions the SERVER's wall clock - it can say "the fleet
spent 12% of this hour stalled on KV blocks" but not "THIS request's
p99 TTFT was 62% queue_wait". This module is the per-request dual: an
event-sourced recorder that walks every request through a CLOSED
taxonomy mirroring the serve goodput causes -

- ``queue_wait``      - arrival -> the admission loop picks it up;
- ``admission``       - wiring into the engine (sequence build + add);
- ``prefill``         - consuming prompt tokens (incl. chunked prefill
                        and post-preemption replay);
- ``decode``          - generating tokens (the goodput phase). With
                        speculative decoding on, ``draft_s``/``verify_s``
                        sub-attribute the device seconds INSIDE this
                        cause (counters on the record, not new taxonomy
                        members) along with proposed/accepted token
                        counts;
- ``kv_alloc_stall``  - parked: block exhaustion blocked this sequence
                        this tick;
- ``preempted_wait``  - evicted (blocks freed, pos reset), waiting for
                        re-admission at the front of the queue;
- ``stream_write``    - engine-side done -> the streaming channel
                        finished writing (the SSE flush window).

**Conservation rule** (same discipline as `utils/goodput.py`): a
request's spans PARTITION its ``arrival -> terminal`` wall-clock -
contiguous, non-overlapping, summing to the request's total lifetime
within ``max(1e-6 * max(total, 1), 1e-9)`` seconds. `finalize()`
asserts it; a request whose seconds leak is a bug, not a metric.

The recorder is the single source for three export surfaces:

- ``GET /v1/requests`` (serve/http.py) - in-flight summaries plus a
  bounded ring of finalized records (``?id=N`` for one request's full
  span sequence, ``?full=1`` for every ringed record with spans);
- Chrome trace lanes - with a `utils/tracing.py` Tracer attached
  (``--trace-out``), each request's spans land on a per-slot lane
  (``slot0..slotN``) with preemption instants, so
  `tools/trace_merge.py` / Perfetto render serving timelines next to
  training shards;
- `tools/request_trace.py` - decomposes TTFT/E2E percentiles by cause,
  prints slow-request exemplars, gates SLOs, and joins client-observed
  latency (tools/loadgen.py ``--out-requests``) against these records.

Two accountings ride each record:

- ``spans``    - the request's OWN wall-clock partition (conservation
                 asserted). Concurrent requests overlap freely here: a
                 tick that decodes a batch of 8 puts "decode" time on
                 all 8 records at once.
- ``engine_s`` - engine step seconds APPORTIONED per request exactly
                 the way the serve ledger splits them (by token counts
                 within each tick; equal split of stalled ticks across
                 parked sequences). Summed over all records these
                 reconcile with the ledger's prefill / decode /
                 kv_alloc_stall buckets to float precision when no
                 record has been evicted from the ring
                 (`tools/request_trace.py --ledger` gates it).

Thread-safety: one lock; writers are the scheduler loop (marks, ticks),
`submit()` callers (arrive), and the HTTP threads (stream completion) -
same seams the scheduler already serializes. Stdlib-only; importable
without jax.
"""

from __future__ import annotations

import heapq
import threading
import time
from collections import deque

# The CLOSED per-request taxonomy. Order is presentation order in
# /v1/requests and tools/request_trace.py.
REQUEST_CAUSES = (
    "queue_wait",
    "admission",
    "prefill",
    "decode",
    "kv_alloc_stall",
    "preempted_wait",
    "stream_write",
)

# the subset of causes that reconcile against the serve goodput ledger
# buckets (the apportioned engine seconds; see module docstring)
ENGINE_CAUSES = ("prefill", "decode", "kv_alloc_stall")

# "migrated" = drained off this replica mid-flight (serve/fleet.py):
# terminal HERE - the request's remaining lifetime continues as a fresh
# record on the peer replica the router re-dispatched it to
TERMINAL_STATES = ("done", "cancelled", "error", "migrated")


def _tolerance(total: float) -> float:
    """The conservation tolerance, same rule as GoodputLedger.finalize."""
    return max(1e-6 * max(total, 1.0), 1e-9)


class RequestRecord:
    """One request's lifecycle: open-span state machine + counters."""

    __slots__ = (
        "req_id", "tenant", "prompt_len", "max_new_tokens",
        "t_arrival", "t_first_token", "t_terminal", "state",
        "spans", "_open_cause", "_open_t0", "_last_t",
        "tokens_emitted", "decode_ticks", "prefill_tokens",
        "replayed_ticks", "preemptions", "episodes", "engine_s", "lane",
        "draft_s", "verify_s", "proposed_tokens", "accepted_tokens",
        "router_retries", "router_retry_s",
    )

    def __init__(self, req_id, tenant, prompt_len, max_new_tokens, t, lane):
        self.req_id = int(req_id)
        self.tenant = str(tenant)
        self.prompt_len = int(prompt_len)
        self.max_new_tokens = int(max_new_tokens)
        self.t_arrival = float(t)
        self.t_first_token: float | None = None
        self.t_terminal: float | None = None
        self.state = "queue_wait"          # open cause; terminal later
        self.spans: list[tuple[str, float, float]] = []
        self._open_cause = "queue_wait"
        self._open_t0 = float(t)
        self._last_t = float(t)
        self.tokens_emitted = 0
        self.decode_ticks = 0
        self.prefill_tokens = 0
        self.replayed_ticks = 0
        self.preemptions = 0
        self.episodes: list[dict] = []
        self.engine_s = {c: 0.0 for c in ENGINE_CAUSES}
        self.lane = lane
        # speculative-decoding sub-attribution: draft_s + verify_s live
        # INSIDE the decode cause (they are device seconds of the decode
        # spans, not new taxonomy members - conservation is untouched)
        self.draft_s = 0.0
        self.verify_s = 0.0
        self.proposed_tokens = 0
        self.accepted_tokens = 0
        # router failover provenance (serve/fleet.py): how many times
        # the fleet router re-dispatched this request before it reached
        # this replica, and the seconds those episodes cost the client.
        # Record-level counters like the preemption ``episodes`` - NOT
        # spans, so per-request conservation (this replica's own
        # arrival -> terminal partition) is untouched
        self.router_retries = 0
        self.router_retry_s = 0.0

    # ------------------------------------------------------------- views

    @property
    def open(self) -> bool:
        return self.t_terminal is None

    def causes(self) -> dict:
        """Closed-span seconds by cause (the open span excluded)."""
        out = {c: 0.0 for c in REQUEST_CAUSES}
        for cause, t0, t1 in self.spans:
            out[cause] += t1 - t0
        return {c: v for c, v in out.items() if v > 0}

    def dominant_cause(self, now: float | None = None) -> str:
        """Largest-seconds cause; an open record counts its live span."""
        acc = {c: 0.0 for c in REQUEST_CAUSES}
        for cause, t0, t1 in self.spans:
            acc[cause] += t1 - t0
        if self.open and now is not None and now > self._open_t0:
            acc[self._open_cause] += now - self._open_t0
        best = max(acc.items(), key=lambda kv: kv[1])
        return best[0] if best[1] > 0 else self._open_cause

    def ttft_s(self) -> float | None:
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.t_arrival

    def e2e_s(self) -> float | None:
        if self.t_terminal is None:
            return None
        return self.t_terminal - self.t_arrival

    def acceptance_rate(self) -> float | None:
        """accepted / proposed draft tokens; None if the request never
        took a speculative step."""
        if not self.proposed_tokens:
            return None
        return self.accepted_tokens / self.proposed_tokens

    def summary(self, now: float | None = None) -> dict:
        doc = {
            "req_id": self.req_id,
            "tenant": self.tenant,
            "state": self.state,
            "tokens_emitted": self.tokens_emitted,
            "preemptions": self.preemptions,
            "dominant_cause": self.dominant_cause(now),
        }
        if self.open:
            doc["age_s"] = (
                round(now - self.t_arrival, 6) if now is not None else None
            )
        else:
            ttft, e2e = self.ttft_s(), self.e2e_s()
            doc["ttft_s"] = round(ttft, 6) if ttft is not None else None
            doc["e2e_s"] = round(e2e, 6) if e2e is not None else None
        return doc

    def detail(self, now: float | None = None) -> dict:
        """The full record: spans relative to arrival, both accountings,
        preemption episodes with replay provenance."""
        doc = self.summary(now)
        doc.update(
            prompt_len=self.prompt_len,
            max_new_tokens=self.max_new_tokens,
            decode_ticks=self.decode_ticks,
            prefill_tokens=self.prefill_tokens,
            replayed_ticks=self.replayed_ticks,
            t_first_token_rel=(
                round(self.t_first_token - self.t_arrival, 9)
                if self.t_first_token is not None else None
            ),
            spans=[
                [c, round(t0 - self.t_arrival, 9),
                 round(t1 - self.t_arrival, 9)]
                for c, t0, t1 in self.spans
            ],
            causes={c: round(v, 9) for c, v in self.causes().items()},
            engine_s={
                c: round(v, 9) for c, v in self.engine_s.items() if v > 0
            },
            episodes=list(self.episodes),
        )
        if self.proposed_tokens:
            doc.update(
                proposed_tokens=self.proposed_tokens,
                accepted_tokens=self.accepted_tokens,
                acceptance_rate=round(self.acceptance_rate(), 6),
                draft_s=round(self.draft_s, 9),
                verify_s=round(self.verify_s, 9),
            )
        if self.router_retries:
            doc["router_retry"] = {
                "episodes": self.router_retries,
                "seconds": round(self.router_retry_s, 9),
            }
        return doc


class RequestTraceRecorder:
    """Event-sources request lifecycles; bounded ring of finalized
    records; optional Chrome-trace lane emission."""

    def __init__(self, *, ring: int = 256, clock=time.monotonic,
                 tracer=None):
        self._clock = clock
        self._lock = threading.Lock()
        self._open: dict[int, RequestRecord] = {}
        self._ring: deque[RequestRecord] = deque()
        self._ring_max = max(int(ring), 1)
        self._by_id: dict[int, RequestRecord] = {}
        self._rejected: dict[str, int] = {}
        self._by_state: dict[str, int] = {}
        self.finalized_total = 0
        self.evicted_total = 0
        self._tracer = tracer if (
            tracer is not None and getattr(tracer, "enabled", False)
        ) else None
        # recorder-clock -> tracer-clock offset (both monotonic; the
        # delta is fixed at construction)
        self._trace_off = (
            self._tracer.now_s() - clock() if self._tracer else 0.0
        )
        # per-request trace lane: lowest free slot index, freed on
        # finalize - requests stack onto slot lanes like engine slots
        self._free_lanes: list[int] = []
        self._next_lane = 0

    def now(self) -> float:
        return self._clock()

    # --------------------------------------------------------- lifecycle

    def arrive(self, req_id: int, tenant: str, prompt_len: int,
               max_new_tokens: int) -> None:
        """Open a record; the queue_wait span starts now."""
        with self._lock:
            if self._free_lanes:
                lane = heapq.heappop(self._free_lanes)
            else:
                lane = self._next_lane
                self._next_lane += 1
            rec = RequestRecord(
                req_id, tenant, prompt_len, max_new_tokens,
                self._clock(), lane,
            )
            self._open[rec.req_id] = rec
            self._by_id[rec.req_id] = rec

    def note_rejected(self, reason: str) -> None:
        """An admission rejection (429) - counted, no lifecycle."""
        with self._lock:
            self._rejected[reason] = self._rejected.get(reason, 0) + 1

    def note_router_retry(self, req_id: int, episodes: int,
                          seconds: float) -> None:
        """Failover provenance from the fleet router (X-Router-Retries
        headers): this request was re-dispatched ``episodes`` times
        before arriving here, losing ``seconds`` of client-visible
        time on dead/drained replicas. Carried as record-level
        counters (like preemption episodes), never as spans - the
        lost seconds happened BEFORE this replica's arrival clock
        started, so span conservation stays exact."""
        with self._lock:
            rec = self._open.get(req_id)
            if rec is not None:
                rec.router_retries = max(int(episodes), 0)
                rec.router_retry_s = max(float(seconds), 0.0)

    def mark(self, req_id: int, cause: str) -> None:
        """Transition a request to ``cause`` now: closes the open span,
        opens the next. No-op for unknown/finalized ids and for repeated
        marks of the current cause."""
        if cause not in REQUEST_CAUSES:
            raise ValueError(
                f"unknown request cause {cause!r} "
                f"(taxonomy: {REQUEST_CAUSES})"
            )
        with self._lock:
            rec = self._open.get(req_id)
            if rec is not None:
                self._mark_locked(rec, cause)

    def note_token(self, req_id: int) -> None:
        """One NEW token streamed to the client (replay re-derivations
        never reach here - the engine drops them before emitting)."""
        with self._lock:
            rec = self._open.get(req_id)
            if rec is None:
                return
            rec.tokens_emitted += 1
            if rec.t_first_token is None:
                rec.t_first_token = self._now_locked(rec)

    def observe_step(self, stats: dict, t0: float, t1: float) -> None:
        """Digest one engine tick: per-sequence state transitions,
        tick counters, apportioned engine seconds, preempt episodes.

        ``stats`` is `ServeEngine.step`'s dict (``per_seq`` +
        ``preempted``); ``t0``/``t1`` bound the tick on the recorder's
        clock (the scheduler measures them, same as for the ledger).
        The apportioning mirrors the ledger exactly: the tick span
        splits across sequences by token counts; an all-parked tick
        splits equally across the parked sequences - so per-cause sums
        over every record equal the ledger buckets.
        """
        per = stats.get("per_seq") or {}
        if not per:
            return
        span = max(float(t1) - float(t0), 0.0)
        total_tokens = (
            stats.get("decode_tokens", 0) + stats.get("prefill_tokens", 0)
        )
        parked_n = sum(1 for d in per.values() if d.get("parked"))
        with self._lock:
            for sid, d in per.items():
                rec = self._by_id.get(sid)
                if rec is None:
                    continue
                rec.decode_ticks += d.get("decode", 0)
                rec.prefill_tokens += d.get("prefill", 0)
                rec.replayed_ticks += d.get("replayed", 0)
                rec.proposed_tokens += d.get("proposed", 0)
                rec.accepted_tokens += d.get("accepted", 0)
                rec.draft_s += d.get("draft_s", 0.0)
                rec.verify_s += d.get("verify_s", 0.0)
                if span > 0:
                    if total_tokens > 0:
                        if d.get("prefill"):
                            rec.engine_s["prefill"] += (
                                span * d["prefill"] / total_tokens
                            )
                        if d.get("decode"):
                            rec.engine_s["decode"] += (
                                span * d["decode"] / total_tokens
                            )
                    elif parked_n and d.get("parked"):
                        rec.engine_s["kv_alloc_stall"] += span / parked_n
                # state transition - but never past the engine-side
                # finish: a request already in stream_write (done mid-
                # tick via the token callback) keeps that state
                if rec.open and rec._open_cause != "stream_write":
                    if d.get("parked"):
                        self._mark_locked(rec, "kv_alloc_stall")
                    elif d.get("decode"):
                        self._mark_locked(rec, "decode")
                    elif d.get("prefill"):
                        self._mark_locked(rec, "prefill")
            for info in stats.get("preempted") or ():
                rec = self._open.get(info.get("seq_id"))
                if rec is None:
                    continue
                rec.preemptions += 1
                rec.episodes.append({
                    "t_rel": round(
                        self._now_locked(rec) - rec.t_arrival, 9
                    ),
                    "tokens_held": int(info.get("tokens_held", 0)),
                    "wait_s": None,   # filled when re-admitted
                })
                self._mark_locked(rec, "preempted_wait")

    def finalize(self, req_id: int, state: str) -> dict | None:
        """Seal a record with a terminal state; asserts conservation
        (spans partition arrival->terminal), moves it to the ring,
        emits its trace lane. Idempotent - a second finalize (e.g. the
        HTTP ack racing a cancel sweep) is a no-op returning None."""
        if state not in TERMINAL_STATES:
            raise ValueError(
                f"terminal state must be one of {TERMINAL_STATES}, "
                f"got {state!r}"
            )
        with self._lock:
            rec = self._open.pop(req_id, None)
            if rec is None:
                return None
            t = self._now_locked(rec)
            if t > rec._open_t0:
                rec.spans.append((rec._open_cause, rec._open_t0, t))
            rec.t_terminal = t
            rec.state = state
            self._assert_conserved(rec)
            self._by_state[state] = self._by_state.get(state, 0) + 1
            self.finalized_total += 1
            self._ring.append(rec)
            if len(self._ring) > self._ring_max:
                old = self._ring.popleft()
                self._by_id.pop(old.req_id, None)
                self.evicted_total += 1
            heapq.heappush(self._free_lanes, rec.lane)
            self._emit_trace(rec)
            return rec.detail()

    def finalize_all(self) -> int:
        """Shutdown sweep: seal every still-open record. A request the
        engine finished but the stream never acked counts ``done``
        (the work happened); everything else is an ``error`` (the
        server went away under it). Returns how many were sealed."""
        with self._lock:
            ids = [
                (rid, "done" if rec._open_cause == "stream_write"
                 else "error")
                for rid, rec in self._open.items()
            ]
        n = 0
        for rid, state in ids:
            if self.finalize(rid, state) is not None:
                n += 1
        return n

    # --------------------------------------------------------- queries

    def get(self, req_id: int) -> dict | None:
        """Full detail for one request (open or ringed), else None."""
        with self._lock:
            rec = self._by_id.get(req_id)
            if rec is None:
                return None
            return rec.detail(self._clock())

    def in_flight(self) -> list[dict]:
        """Open-request summaries, oldest first (the /v1/status and
        live_top 'slowest in-flight' source)."""
        with self._lock:
            now = self._clock()
            recs = sorted(self._open.values(), key=lambda r: r.t_arrival)
            return [r.summary(now) for r in recs]

    def snapshot(self, *, full: bool = False) -> dict:
        """The GET /v1/requests document."""
        with self._lock:
            now = self._clock()
            recent = [
                (r.detail() if full else r.summary()) for r in self._ring
            ]
            return {
                "taxonomy": list(REQUEST_CAUSES),
                "counts": {
                    "in_flight": len(self._open),
                    "finalized": self.finalized_total,
                    "ring": len(self._ring),
                    "evicted": self.evicted_total,
                    "by_state": dict(self._by_state),
                    "rejected": dict(self._rejected),
                },
                "in_flight": [
                    r.summary(now) for r in sorted(
                        self._open.values(), key=lambda r: r.t_arrival
                    )
                ],
                "recent": recent,
            }

    # -------------------------------------------------------- internals

    def _now_locked(self, rec: RequestRecord) -> float:
        """A timestamp that never runs backwards within one record (the
        span chain must stay contiguous even if the clock is coarse)."""
        t = max(self._clock(), rec._last_t)
        rec._last_t = t
        return t

    def _mark_locked(self, rec: RequestRecord, cause: str) -> None:
        if rec._open_cause == cause:
            return
        t = self._now_locked(rec)
        if t > rec._open_t0:
            rec.spans.append((rec._open_cause, rec._open_t0, t))
        if (
            rec._open_cause == "preempted_wait"
            and rec.episodes
            and rec.episodes[-1].get("wait_s") is None
        ):
            rec.episodes[-1]["wait_s"] = round(t - rec._open_t0, 9)
        rec._open_cause = cause
        rec._open_t0 = t
        rec.state = cause

    def _assert_conserved(self, rec: RequestRecord) -> None:
        total = rec.t_terminal - rec.t_arrival
        attributed = sum(t1 - t0 for _, t0, t1 in rec.spans)
        tol = _tolerance(total)
        ok = abs(attributed - total) <= tol
        if ok and rec.spans:
            ok = abs(rec.spans[0][1] - rec.t_arrival) <= tol and abs(
                rec.spans[-1][2] - rec.t_terminal
            ) <= tol
            for (_, _, a1), (_, b0, _) in zip(rec.spans, rec.spans[1:]):
                ok = ok and abs(b0 - a1) <= tol
        if not ok:
            raise AssertionError(
                f"request span conservation violated: req {rec.req_id} "
                f"attributed {attributed:.9f}s != lifetime {total:.9f}s "
                f"(tolerance {tol:.2e}; spans {rec.spans!r})"
            )

    def _emit_trace(self, rec: RequestRecord) -> None:
        tr = self._tracer
        if tr is None:
            return
        track = f"slot{rec.lane}"
        off = self._trace_off
        for cause, t0, t1 in rec.spans:
            tr.complete(
                cause, t0 + off, t1 + off, track=track,
                req_id=rec.req_id, tenant=rec.tenant, state=rec.state,
            )
        for ep in rec.episodes:
            tr.instant_at(
                "preempt", rec.t_arrival + ep["t_rel"] + off, track=track,
                req_id=rec.req_id, tokens_held=ep["tokens_held"],
            )


# ------------------------------------- percentile decomposition (shared)
#
# The canonical TTFT/E2E percentile decomposition over finalized request
# records - ONE implementation consumed by three readers: the fleet
# SLO readout / autoscaler (serve/fleet.py slo_readout), the offline
# report + gates (tools/request_trace.py mirrors it stdlib-side, no
# package import), and the serve-mode digital twin
# (analysis/fleetsim.py), which must decompose its SIMULATED records
# with the very arithmetic the measured ones are judged by.


def percentile(xs, q: float):
    """Nearest-rank percentile over unsorted samples (None if empty)."""
    if not xs:
        return None
    import math

    s = sorted(xs)
    return s[max(0, math.ceil(q * len(s)) - 1)]


def clipped_causes(rec: dict, metric: str) -> dict:
    """Per-cause seconds of one record's spans, clipped at first-token
    time for ``metric="ttft"`` (unclipped for ``"e2e"``). Records that
    never produced a token have no TTFT decomposition ({})."""
    if metric == "ttft":
        hi = rec.get("t_first_token_rel")
        if hi is None:
            return {}
    else:
        hi = float("inf")
    out: dict = {}
    for cause, t0, t1 in rec.get("spans") or ():
        lo, up = float(t0), min(float(t1), hi)
        if up > lo:
            out[cause] = out.get(cause, 0.0) + (up - lo)
    return out


def decompose(records, metric: str, q: float):
    """Decompose one latency percentile by cause over the TAIL (records
    at or beyond the percentile value): ``{"value", "shares",
    "dominant"}`` or None when no record carries the metric."""
    vals = [
        (r, v) for r in records
        if (v := r.get("ttft_s" if metric == "ttft" else "e2e_s"))
        is not None
    ]
    if not vals:
        return None
    pv = percentile([v for _, v in vals], q)
    tail = [r for r, v in vals if v >= pv - 1e-12]
    acc: dict = {}
    for r in tail:
        for cause, s in clipped_causes(r, metric).items():
            acc[cause] = acc.get(cause, 0.0) + s
    total = sum(acc.values())
    shares = {c: acc[c] / total for c in acc} if total > 0 else {}
    dominant = max(shares, key=shares.get) if shares else None
    return {"value": pv, "shares": shares, "dominant": dominant}
