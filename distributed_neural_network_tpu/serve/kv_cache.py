"""Block/paged KV-cache allocator for the serving engine.

The training-side decode path (`models/transformer.py generate`)
allocates one contiguous ``(L, B, H, total, Dh)`` cache per batch -
every sequence pays for ``total`` slots up front, so a mixed-length
serving batch wastes HBM proportional to the spread between the longest
request and everyone else, and admission is limited by the WORST case.
This module is the serving answer (the vLLM/PagedAttention idea, cast
into this repo's static-shape jit discipline):

- one shared device pool of ``num_blocks`` fixed-size blocks per layer,
  laid out flat as ``(L, num_blocks * block_size, H, Dh)`` so a block
  table turns into plain integer gather/scatter indices - the jitted
  decode step keeps ONE static shape per (batch, table-width) bucket;
- a host-side free-list allocator: sequences take blocks one at a time
  as their position crosses a block boundary and return them all on
  retirement - internal fragmentation is bounded by ``block_size - 1``
  tokens per live sequence, external fragmentation is zero by
  construction (all blocks are interchangeable);
- ``OutOfBlocks`` is the backpressure signal, not a crash: the engine
  parks the sequence (a ``kv_alloc_stall`` ledger second), the
  scheduler stops admitting, and - if nothing at all can run - the
  youngest sequence is preempted back to the queue, its blocks freed.

Block id 0 is reserved as a scratch block: table rows are padded with
it (reads beyond a sequence's live range are masked to -inf before
softmax, so the values never matter), and inactive batch slots scatter
their dead writes into it. The allocator therefore hands out ids
``1..num_blocks-1``.

Pure host bookkeeping + index math; the device pools live on
`ServeEngine` (functionally updated by the jitted step). Stdlib+numpy
only, importable without jax.

Under ``EngineConfig.kv_dtype="int8"`` the device pools are stored
QUANTIZED - int8 codes plus one f32 scale per (block, head) per layer -
using the same block ids this allocator hands out (scale of slot ``s``
= ``scales[table[s // block_size]]``), which roughly doubles how many
concurrent sequences one HBM budget holds (`analysis/cost.py
kv_block_bytes` prices it exactly; docs/SERVING.md "int8 KV cache").
The allocator itself is dtype-blind; the engine zeroes a freed block's
scales so reuse is history-free (deterministic preemption replay).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

import numpy as np

# block id every table row is padded with and every inactive slot
# writes to; never allocated
SCRATCH_BLOCK = 0


class OutOfBlocks(Exception):
    """The pool has no free block - the admission/scheduling
    backpressure signal (never a crash in the serving path)."""

    def __init__(self, need: int, free: int, total: int):
        self.need, self.free, self.total = need, free, total
        super().__init__(
            f"KV pool exhausted: need {need} block(s), {free} free of "
            f"{total} usable - admission should back off (429) or a "
            "sequence must be preempted"
        )


@dataclass(frozen=True)
class KVCacheConfig:
    """Pool geometry. ``num_blocks`` INCLUDES the reserved scratch
    block, so ``usable_blocks = num_blocks - 1``; ``max_seq_len`` bounds
    any sequence's prompt+generation and sizes the widest block table
    (``max_blocks_per_seq``)."""

    num_blocks: int = 64
    block_size: int = 16
    max_seq_len: int = 512

    def __post_init__(self):
        if self.num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (one scratch + one usable), "
                f"got {self.num_blocks}"
            )
        if self.block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {self.block_size}")
        if self.max_seq_len < 1:
            raise ValueError(
                f"max_seq_len must be >= 1, got {self.max_seq_len}"
            )

    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1

    @property
    def max_blocks_per_seq(self) -> int:
        return -(-self.max_seq_len // self.block_size)  # ceil div

    @property
    def pool_slots(self) -> int:
        """Flat token-slot count of the device pool's second axis."""
        return self.num_blocks * self.block_size

    def blocks_for_tokens(self, n_tokens: int) -> int:
        return -(-max(n_tokens, 0) // self.block_size)


class PagedKVCache:
    """Host-side block allocator + table builder (thread-safe: the HTTP
    admission path asks `can_fit` while the engine thread allocates)."""

    def __init__(self, cfg: KVCacheConfig):
        self.cfg = cfg
        self._lock = threading.Lock()
        # LIFO free list: a just-freed (cache-hot) block is reused first
        self._free = list(range(cfg.num_blocks - 1, SCRATCH_BLOCK, -1))
        self._seq_blocks: dict[int, list[int]] = {}
        self._seq_used: dict[int, int] = {}  # tokens written (pos + 1)
        self.alloc_total = 0
        self.free_total = 0

    # ------------------------------------------------------------ queries

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def blocks_in_use(self) -> int:
        with self._lock:
            return self.cfg.usable_blocks - len(self._free)

    def utilization(self) -> float:
        return self.blocks_in_use / self.cfg.usable_blocks

    def can_fit(self, n_tokens: int) -> bool:
        """Would a fresh sequence of ``n_tokens`` find blocks right now?
        Advisory (the engine thread may race it); admission uses it as
        the cheap first gate before the queue."""
        return self.cfg.blocks_for_tokens(n_tokens) <= self.free_blocks

    def seq_block_ids(self, seq_id: int) -> list[int]:
        with self._lock:
            return list(self._seq_blocks.get(seq_id, ()))

    def waste_slots(self) -> int:
        """Allocated-but-unwritten token slots across live sequences -
        the internal fragmentation, bounded by
        ``(block_size - 1) * live_sequences`` (tested)."""
        with self._lock:
            total = 0
            for sid, blocks in self._seq_blocks.items():
                total += len(blocks) * self.cfg.block_size - self._seq_used.get(
                    sid, 0
                )
            return total

    # --------------------------------------------------------- allocation

    def ensure(self, seq_id: int, pos: int) -> None:
        """Guarantee a block exists for token position ``pos`` of
        ``seq_id`` (allocating at most one - positions advance by one
        token at a time; chunked prefill calls this per position in the
        chunk). Raises `OutOfBlocks` without mutating anything."""
        if pos >= self.cfg.max_seq_len:
            raise ValueError(
                f"position {pos} exceeds max_seq_len {self.cfg.max_seq_len}"
            )
        need_blocks = pos // self.cfg.block_size + 1
        with self._lock:
            blocks = self._seq_blocks.setdefault(seq_id, [])
            if len(blocks) < need_blocks:
                if not self._free:
                    raise OutOfBlocks(
                        1, 0, self.cfg.usable_blocks
                    )
                blocks.append(self._free.pop())
                self.alloc_total += 1
            if pos + 1 > self._seq_used.get(seq_id, 0):
                self._seq_used[seq_id] = pos + 1

    def ensure_range(self, seq_id: int, end_pos: int) -> None:
        """`ensure` every position up to ``end_pos`` inclusive (the
        chunked-prefill span). All-or-nothing: on OutOfBlocks the blocks
        already held are KEPT (they hold written history), but no
        partial allocation for the new span leaks."""
        need = end_pos // self.cfg.block_size + 1
        if end_pos >= self.cfg.max_seq_len:
            raise ValueError(
                f"position {end_pos} exceeds max_seq_len "
                f"{self.cfg.max_seq_len}"
            )
        with self._lock:
            blocks = self._seq_blocks.setdefault(seq_id, [])
            missing = need - len(blocks)
            if missing > len(self._free):
                raise OutOfBlocks(
                    missing, len(self._free), self.cfg.usable_blocks
                )
            for _ in range(max(missing, 0)):
                blocks.append(self._free.pop())
                self.alloc_total += 1
            if end_pos + 1 > self._seq_used.get(seq_id, 0):
                self._seq_used[seq_id] = end_pos + 1

    def rewind(self, seq_id: int, n_tokens: int) -> list[int]:
        """Roll ``seq_id``'s write cursor back to ``n_tokens`` tokens
        written, returning any trailing blocks past
        ``blocks_for_tokens(n_tokens)`` to the pool (newest first, so
        the LIFO free list reuses them immediately). Returns the freed
        block ids so the engine can zero their int8 scales - the same
        history-free-reuse contract `free` has.

        This is the cursor-rewind speculative decoding relies on: a
        verify step writes k+1 positions optimistically, then the host
        rewinds past the rejected suffix. It is exactly the bookkeeping
        preemption replay performs (free + re-ensure), just partial, so
        replay determinism carries over unchanged. Growing the cursor is
        not this primitive's job (``n_tokens`` above the current count
        is a ValueError, not a silent alloc)."""
        if n_tokens < 0:
            raise ValueError(f"n_tokens must be >= 0, got {n_tokens}")
        keep = self.cfg.blocks_for_tokens(n_tokens)
        with self._lock:
            blocks = self._seq_blocks.get(seq_id)
            if blocks is None:
                return []
            used = self._seq_used.get(seq_id, 0)
            if n_tokens > used:
                raise ValueError(
                    f"rewind({seq_id}, {n_tokens}) would grow the "
                    f"cursor (currently {used} tokens written)"
                )
            freed = blocks[keep:]
            del blocks[keep:]
            # newest-written first onto the LIFO list (pop() reuses the
            # cache-hot block next), mirroring free()'s ordering intent
            self._free.extend(reversed(freed))
            self.free_total += len(freed)
            if n_tokens:
                self._seq_used[seq_id] = n_tokens
            else:
                self._seq_used.pop(seq_id, None)
                if not blocks:
                    self._seq_blocks.pop(seq_id, None)
            return freed

    def free(self, seq_id: int) -> int:
        """Return all of ``seq_id``'s blocks to the pool (retirement,
        cancel, preemption); returns how many were freed. Unknown ids
        are a no-op (idempotent - cancel can race retirement)."""
        with self._lock:
            blocks = self._seq_blocks.pop(seq_id, [])
            self._seq_used.pop(seq_id, None)
            # append in allocation order so pop() (the next alloc) hands
            # back the most recently written block first (LIFO)
            self._free.extend(blocks)
            self.free_total += len(blocks)
            return len(blocks)

    # ------------------------------------------------------------- tables

    def table(self, seq_ids, width: int) -> np.ndarray:
        """``(len(seq_ids), width)`` int32 block table, rows padded with
        the scratch block. ``width`` must cover every sequence's
        allocated blocks (the engine picks the bucket)."""
        out = np.full((len(seq_ids), width), SCRATCH_BLOCK, np.int32)
        with self._lock:
            for i, sid in enumerate(seq_ids):
                blocks = self._seq_blocks.get(sid, ())
                if len(blocks) > width:
                    raise ValueError(
                        f"table width {width} < {len(blocks)} allocated "
                        f"blocks for seq {sid}"
                    )
                out[i, : len(blocks)] = blocks
        return out

    def max_blocks_live(self) -> int:
        """Widest live sequence in blocks (the width-bucket input)."""
        with self._lock:
            return max(
                (len(b) for b in self._seq_blocks.values()), default=0
            )
