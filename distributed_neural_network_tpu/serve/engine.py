"""Continuous-batching decode engine over the paged KV cache.

The execution model, in one paragraph: every engine tick runs ONE
jitted decode step in which each active slot consumes exactly one token
- a prompt token while the sequence is still prefilling (its logits
discarded, except at the last prompt position, which yields the first
generated token), a just-generated token afterwards. Because prompt and
generation tokens ride the same step, sequences JOIN the batch at any
step boundary and RETIRE without draining anyone else - continuous
(in-flight) batching is the default behavior, not a special mode. KV
state lives in the shared paged pool (`kv_cache.py`): the step
scatter-writes each slot's new K/V at ``block_table[pos // bs] * bs +
pos % bs`` and gather-reads each slot's whole table, so one compiled
program serves any mix of sequence lengths at a given (batch,
table-width) bucket.

Two static-shape bucket axes bound compile count: batch size and table
width both round up to powers of two, so a server that has seen B=4/W=2
traffic never compiles again for B<=4/W<=2.

**Prefill/decode separation** (``prefill_chunk > 1``): long prompts pay
one model call per token on the default path - correct, and bitwise
identical to `models/transformer.py generate` (the parity pin), but a
1000-token prompt would occupy 1000 ticks. The chunked prefill path
processes up to ``prefill_chunk`` prompt tokens of one sequence per
call (causal within the chunk + attention to its cached history),
bounded per tick by ``prefill_token_budget`` so a burst of long prompts
cannot starve the decode batch - decode latency stays one decode step
per tick regardless of prefill backlog. Chunked prefill changes matmul
shapes, so its logits can differ from the token-at-a-time path by float
ulps; greedy token streams are pinned equal in tests at serving shapes.

**Backpressure**: a sequence whose next position needs a block the pool
cannot give is PARKED for the tick (a ``kv_alloc_stall`` ledger
second). If nothing at all could run, the youngest parked sequence is
preempted - blocks freed, position reset - and re-admitted later;
greedy decoding (and the per-position sampling keys) make the replay
deterministic, and already-streamed tokens are not re-emitted.

**Speculative decoding** (``spec_decode = k > 0``): greedy slots break
the one-token-per-tick ceiling. A cheap drafter - the SAME model
early-exited after its first ``spec_draft_layers`` blocks
(models/transformer.py early_exit_params: shared embed/final-LN/head,
no second weight set) - proposes k tokens per slot in one jitted call
that READS the paged pool but writes nothing (in-flight draft K/V live
in a per-call buffer, so the pool - and under int8 its running scales -
never sees a draft). One target-model VERIFY step then consumes
``[t0, d1..dk]`` at positions ``pos..pos+k`` in a single call (a new
per-(batch, k+1, table-width) jitted bucket family, pre-compiled by
`warmup()`), writing all k+1 KV entries optimistically and returning
the greedy prediction at every position. The host accepts the longest
draft prefix that matches, emits ``a+1`` tokens (the all-rejected step
emits exactly 1 - the same token plain decode would), and REWINDS the
block-table write cursor past the rejected suffix
(`kv_cache.py rewind` - the same bookkeeping preemption replay
performs, so replay/cancel invariants carry over byte-identically and
greedy streams stay token-exact vs the offline `generate()` oracle).
Sampled slots (temperature > 0) take the plain decode path untouched -
their per-(seed, position) keys never see speculation. Preemption
replay feeds already-known tokens back as drafts (guaranteed
acceptance under greedy determinism), so replay advances k+1 positions
per tick instead of one.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer import (
    TransformerConfig,
    _layer_norm,
    _sinusoid_pe,
)
from ..ops.decode_pallas import decode_cache_attention, decode_kernel_ok
from ..ops.quant import prequantize_weight, quantized_matmul
from .kv_cache import KVCacheConfig, OutOfBlocks, PagedKVCache

_INT8_MAX = 127.0
_SCALE_EPS = 1e-30

# the weight matrices --precision int8-w stores quantized (per-column
# int8 codes + per-column f32 scales, ops/quant.py prequantize_weight);
# embeddings (a lookup), layer norms and biases stay f32
_QUANT_WEIGHT_KEYS = ("wq", "wk", "wv", "wo", "w1", "w2")


def _prequantize_params(params):
    """Quantize every transformer-block matmul weight of the (dense)
    param tree once at engine init: each ``w`` becomes ``{"q": int8
    (n, k), "s": f32 (n,)}`` - exactly the pair `ops/quant.py
    quantized_matmul` consumes as a prequantized right operand. Stacked
    layer weights keep their leading layer axis, so the jitted steps'
    layer scan is unchanged. The head (logit) projection stays full
    precision: it feeds the argmax directly, so quantizing it flips
    top-1 tokens far more than any block weight, for a d_model x vocab
    sliver of the weight bytes."""
    layers = dict(params["layers"])
    for key in _QUANT_WEIGHT_KEYS:
        q, s = prequantize_weight(layers[key])
        layers[key] = {"q": q, "s": s}
    out = dict(params)
    out["layers"] = layers
    return out


def _make_mm(weight_quantized: bool, dt):
    """The one matmul the jitted steps route every weight through:
    plain ``x @ w`` at the model dtype, or - under int8-w - the
    low-precision dot against the prequantized codes (activation rows
    quantized per call, int8 x int8 -> int32, f32 dequant)."""
    if not weight_quantized:
        def mm(x, w):
            return x @ w.astype(dt)
        return mm

    def mm(x, w):
        shp = x.shape
        y = quantized_matmul(x.reshape(-1, shp[-1]), (w["q"], w["s"]),
                             weight_only=True)
        return y.astype(dt).reshape(*shp[:-1], y.shape[-1])
    return mm


@dataclass(frozen=True)
class EngineConfig:
    """Serving-side knobs (model geometry lives in TransformerConfig)."""

    max_batch: int = 8          # decode-slot cap = largest batch bucket
    num_blocks: int = 64        # shared pool size (incl. scratch block)
    block_size: int = 16        # tokens per KV block
    max_seq_len: int = 512      # prompt + generation hard cap
    prefill_chunk: int = 1      # 1 = exact token-at-a-time prefill
    prefill_token_budget: int = 0   # 0 = one chunk call per tick
    eos_token: int | None = None    # retire on this token id
    # "bf16" = pool in the model dtype; "int8" = quantized pool with
    # per-(block, head) f32 scales - ~2x the concurrent-sequence
    # capacity per HBM byte (the exact multiplier:
    # analysis/cost.py kv_block_bytes), quantize-on-append +
    # dequantize-in-step, accuracy gated vs the bf16 oracle
    # (docs/SERVING.md "int8 KV cache")
    kv_dtype: str = "bf16"
    # per-step attention under the paged gather: "xla" = the einsum/
    # softmax/einsum chain (PR 12 path), "pallas" = the tuned decode
    # kernel (ops/decode_pallas.py) reading the gathered bucket with
    # per-slot positions (int8 pools stream quantized with fused
    # dequant), "auto" = pallas on TPU when the bucket's width admits a
    # sublane-legal block, xla otherwise (off-TPU the kernel only runs
    # interpreted - a test vehicle, not a fast path)
    decode_impl: str = "auto"
    # speculative decoding: k > 0 lets each GREEDY slot emit up to k+1
    # tokens per tick (draft k with the early-exit drafter, verify all
    # of them in one multi-position target step, rewind the rejected
    # suffix). 0 = off (every slot is one token per tick, the PR 12
    # contract). docs/SERVING.md "Speculative decoding"
    spec_decode: int = 0
    # early-exit depth of the drafter (first E blocks of the same
    # model); 0 = auto: max(1, n_layers // 8) - the measured
    # sweet spot where draft agreement stays useful while the drafter's
    # weight traffic stays a small fraction of the target step's
    spec_draft_layers: int = 0
    # "bf16" = params at the model dtype; "int8" = every matmul weight
    # stored int8 + per-column f32 scales (ops/quant.py
    # prequantize_weight), consumed by quantized_matmul in every jitted
    # step - the --precision int8-w path, accuracy gated >= 99% top-1
    # vs the bf16 oracle like int8-kv (composes with it)
    weight_dtype: str = "bf16"

    def __post_init__(self):
        if self.kv_dtype not in ("bf16", "int8"):
            raise ValueError(
                f"kv_dtype must be 'bf16' or 'int8', got {self.kv_dtype!r}"
            )
        if self.weight_dtype not in ("bf16", "int8"):
            raise ValueError(
                f"weight_dtype must be 'bf16' or 'int8', got "
                f"{self.weight_dtype!r}"
            )
        if self.decode_impl not in ("auto", "xla", "pallas"):
            raise ValueError(
                f"decode_impl must be auto/xla/pallas, got "
                f"{self.decode_impl!r}"
            )
        if self.spec_decode < 0:
            raise ValueError(
                f"spec_decode must be >= 0, got {self.spec_decode}"
            )
        if self.spec_draft_layers < 0:
            raise ValueError(
                f"spec_draft_layers must be >= 0 (0 = auto), got "
                f"{self.spec_draft_layers}"
            )

    def kv(self) -> KVCacheConfig:
        return KVCacheConfig(
            num_blocks=self.num_blocks,
            block_size=self.block_size,
            max_seq_len=self.max_seq_len,
        )


@dataclass
class Sequence:
    """One in-flight request's decode state (engine-internal; the
    scheduler owns queueing/streaming around it)."""

    seq_id: int
    prompt: list
    max_new_tokens: int
    temperature: float = 0.0
    seed: int = 0
    on_token: object = None  # callable(seq, token_id, done) or None

    pos: int = 0               # tokens consumed (= KV entries written)
    out: list = field(default_factory=list)
    emitted: int = 0           # tokens already streamed (preempt replay)
    finished: bool = False
    preemptions: int = 0
    t_first_token: float | None = None

    @property
    def prompt_len(self) -> int:
        return len(self.prompt)

    @property
    def in_prefill(self) -> bool:
        return self.pos < self.prompt_len

    def next_input(self) -> int:
        """The token this sequence consumes at its current position."""
        if self.pos < self.prompt_len:
            return int(self.prompt[self.pos])
        return int(self.out[self.pos - self.prompt_len])

    def total_len(self) -> int:
        return self.prompt_len + self.max_new_tokens


def export_descriptor(seq: Sequence) -> dict:
    """A live sequence as a migration descriptor: everything a PEER
    replica needs to re-derive the exact remaining stream by
    deterministic re-prefill replay (serve/fleet.py drain/failover).

    The contract is the same one preemption replay rests on: the seeded
    model is identical on every replica, greedy decode is a pure
    function of the token history, and sampled slots key on the
    ABSOLUTE position (`_sample_key` folds ``seq.pos``) - so prefilling
    ``prompt + already-emitted tokens`` on any replica reconstructs the
    byte-identical KV state and the next sampling key, and the
    continuation matches the stream a single never-failing replica
    would have produced. ``emitted`` holds only tokens the client has
    already seen (the dedup rule: they become prompt on resume, never
    re-streamed)."""
    emitted = [int(t) for t in seq.out[: seq.emitted]]
    return {
        "seq_id": int(seq.seq_id),
        "prompt": [int(t) for t in seq.prompt],
        "emitted": emitted,
        "max_new_tokens": int(seq.max_new_tokens),
        "remaining_tokens": int(seq.max_new_tokens) - len(emitted),
        "temperature": float(seq.temperature),
        "seed": int(seq.seed),
        "preemptions": int(seq.preemptions),
    }


def resume_request(desc: dict) -> dict:
    """The re-dispatch request body for a migrated descriptor: emitted
    tokens are folded into the prompt (re-prefill replay) and the token
    budget shrinks by the tokens already streamed. Raises ValueError
    when nothing remains to generate (the caller should synthesize the
    done frame itself - it already holds the full stream)."""
    emitted = [int(t) for t in desc.get("emitted") or ()]
    remaining = int(desc["max_new_tokens"]) - len(emitted)
    if remaining < 1:
        raise ValueError(
            f"descriptor for seq {desc.get('seq_id')} has no tokens "
            f"left to generate ({len(emitted)} already emitted)"
        )
    return {
        "prompt": [int(t) for t in desc["prompt"]] + emitted,
        "max_new_tokens": remaining,
        "temperature": float(desc.get("temperature", 0.0)),
        "seed": int(desc.get("seed", 0)),
    }


def resume_sequence(desc: dict, *, seq_id: int | None = None,
                    on_token=None) -> Sequence:
    """Import a migration descriptor as a fresh `Sequence` on this
    engine (the direct, HTTP-less form of `resume_request`). The
    emitted tokens ride as prompt, so the engine prefills them and the
    first token it EMITS is the first one the client has not seen."""
    body = resume_request(desc)
    return Sequence(
        seq_id=int(desc["seq_id"]) if seq_id is None else int(seq_id),
        prompt=body["prompt"],
        max_new_tokens=body["max_new_tokens"],
        temperature=body["temperature"],
        seed=body["seed"],
        on_token=on_token,
    )


def _bucket(n: int, lo: int = 1) -> int:
    """Smallest power of two >= n (>= lo)."""
    b = lo
    while b < n:
        b *= 2
    return b


class ServeEngine:
    """The model executor: owns device params + KV pools and advances
    all active sequences one tick at a time. Single-threaded by
    contract - exactly one caller (the scheduler loop) drives
    `step()`; admission/cancel mutate the active set under `lock`
    between ticks."""

    def __init__(self, params, cfg: TransformerConfig, ecfg: EngineConfig):
        if cfg.n_experts:
            raise ValueError(
                "the serving engine supports dense models; MoE decode "
                "routes through models/transformer.py generate()"
            )
        self.cfg = cfg
        self.ecfg = ecfg
        self.kv = PagedKVCache(ecfg.kv())
        self.weight_quantized = ecfg.weight_dtype == "int8"
        if self.weight_quantized:
            params = _prequantize_params(params)
        self.params = jax.device_put(params)
        self.spec_k = ecfg.spec_decode
        self.draft_layers = 0
        self.draft_params = None
        if self.spec_k:
            self.draft_layers = (
                ecfg.spec_draft_layers or max(1, cfg.n_layers // 8)
            )
            if self.draft_layers > cfg.n_layers:
                raise ValueError(
                    f"spec_draft_layers {self.draft_layers} > model "
                    f"n_layers {cfg.n_layers}"
                )
            if self.spec_k + 1 >= ecfg.max_seq_len:
                raise ValueError(
                    f"spec_decode {self.spec_k} leaves no room under "
                    f"max_seq_len {ecfg.max_seq_len}"
                )
            # the drafter IS the target model early-exited: slice the
            # stacked layer axis once (embed / final LN / head shared) -
            # works identically on prequantized int8-w trees
            self.draft_params = {
                **self.params,
                "layers": jax.tree.map(
                    lambda p: p[: self.draft_layers],
                    self.params["layers"],
                ),
            }
        dt = cfg.dtype
        L, H, Dh = cfg.n_layers, cfg.n_heads, cfg.head_dim
        slots = self.kv.cfg.pool_slots
        self.quantized = ecfg.kv_dtype == "int8"
        if self.quantized:
            # int8 pool + per-(block, head) f32 scales: the one extra
            # small array rides the SAME block-table addressing (scale
            # of slot s = scales[table[s // bs]]), so every gather/
            # scatter index the bf16 path computes is reused verbatim
            self.k_pool = jnp.zeros((L, slots, H, Dh), jnp.int8)
            self.v_pool = jnp.zeros((L, slots, H, Dh), jnp.int8)
            self.k_scale = jnp.zeros((L, ecfg.num_blocks, H), jnp.float32)
            self.v_scale = jnp.zeros((L, ecfg.num_blocks, H), jnp.float32)
        else:
            self.k_pool = jnp.zeros((L, slots, H, Dh), dt)
            self.v_pool = jnp.zeros((L, slots, H, Dh), dt)
            self.k_scale = self.v_scale = None
        self.lock = threading.Lock()
        self.active: list[Sequence] = []
        self._step_fns: dict = {}
        self._prefill_fns: dict = {}
        self._draft_fns: dict = {}
        self._verify_fns: dict = {}
        self.ticks = 0
        self.decode_tokens = 0
        self.prefill_tokens = 0
        self.stall_events = 0
        # cumulative speculative-decoding counters (the
        # serve_spec_*_tokens_total metrics + /v1/status)
        self.spec_proposed_tokens = 0
        self.spec_accepted_tokens = 0
        self.spec_steps = 0
        # drained from the FRONT by the scheduler (popleft), re-parked at
        # the back on eviction - a deque so both ends are O(1)
        self.preempted: deque[Sequence] = deque()

    # --------------------------------------------------------- lifecycle

    def add(self, seq: Sequence) -> None:
        """Join the batch at the next step boundary. Raises ValueError
        on an over-long request (an admission-time check, not a crash
        mid-flight) - block availability is the scheduler's gate."""
        if seq.total_len() > self.ecfg.max_seq_len:
            raise ValueError(
                f"request needs {seq.total_len()} positions "
                f"(prompt {seq.prompt_len} + {seq.max_new_tokens} new) "
                f"> max_seq_len {self.ecfg.max_seq_len}"
            )
        if not seq.prompt:
            raise ValueError("empty prompt")
        if len(self.active) >= self.ecfg.max_batch:
            raise ValueError(
                f"engine full ({self.ecfg.max_batch} slots) - the "
                "scheduler should hold admission"
            )
        with self.lock:
            self.active.append(seq)

    def cancel(self, seq_id: int) -> bool:
        """Drop a sequence mid-flight (client disconnect); frees its
        blocks. True when it was active."""
        with self.lock:
            for i, s in enumerate(self.active):
                if s.seq_id == seq_id:
                    self.active.pop(i)
                    self._free_seq(seq_id)
                    s.finished = True
                    return True
        return False

    def has_work(self) -> bool:
        with self.lock:
            return bool(self.active)

    # ------------------------------------------------- bytes + kv dtype

    def kv_dtype_name(self) -> str:
        """The /metrics ``serve_kv_dtype`` label value."""
        if self.quantized:
            return "int8"
        return "bf16" if self.cfg.dtype == jnp.bfloat16 else "f32"

    def weight_dtype_name(self) -> str:
        """The /metrics ``serve_weight_dtype`` label value."""
        if self.weight_quantized:
            return "int8"
        return "bf16" if self.cfg.dtype == jnp.bfloat16 else "f32"

    def kv_block_bytes(self) -> int:
        """Device bytes of one paged block at this engine's kv dtype
        (K + V + any per-(block, head) scales) - analysis/cost.py's
        table, so the serving occupancy gauges and the autoshard HBM
        gate can never disagree on a byte."""
        from ..analysis.cost import kv_block_bytes

        cfg = self.cfg
        dtype = self.kv_dtype_name()
        return kv_block_bytes(
            cfg.n_layers, cfg.n_heads, cfg.head_dim,
            self.ecfg.block_size, "f32" if dtype == "f32" else dtype,
        )

    def compiled_programs(self) -> dict:
        """Per-bucket-family compiled-program counts (plus ``total``) -
        the live figure ``GET /v1/status`` reports so a deployment can
        be reconciled against the servelint grid manifest
        (analysis/serve_trace.py enumerate_grid): after ``warmup()``
        the counts match the manifest and must never grow while
        serving (a growth is an un-warmed bucket paying its XLA
        compile on a live request)."""
        fams = {
            "decode": len(self._step_fns),
            "prefill": len(self._prefill_fns),
            "draft": len(self._draft_fns),
            "verify": len(self._verify_fns),
        }
        fams["total"] = sum(fams.values())
        return fams

    def _free_seq(self, seq_id: int) -> int:
        """Free a sequence's blocks; under int8 KV also zero the freed
        blocks' scales - a reused block must start from scale 0 or the
        previous owner's scale would leak into the new sequence's
        quantization (breaking both accuracy and the deterministic
        preemption replay)."""
        if not self.quantized:
            return self.kv.free(seq_id)
        blocks = self.kv.seq_block_ids(seq_id)
        n = self.kv.free(seq_id)
        if blocks:
            idx = jnp.asarray(blocks, jnp.int32)
            self.k_scale = self.k_scale.at[:, idx, :].set(0.0)
            self.v_scale = self.v_scale.at[:, idx, :].set(0.0)
        return n

    def _attn_route(self, W: int) -> str:
        """Per-bucket attention impl under the paged gather: the tuned
        decode kernel when routable, the XLA chain otherwise. The
        kernel needs the bucket's gathered length W * block_size to
        admit a sublane-legal k block (16-multiples for bf16, 32 for
        int8 - ops/decode_pallas.py decode_kernel_ok)."""
        impl = self.ecfg.decode_impl
        if impl == "xla":
            return "xla"
        legal = decode_kernel_ok(
            W * self.ecfg.block_size, quantized=self.quantized
        )
        if impl == "pallas":
            if not legal:
                raise ValueError(
                    f"decode_impl 'pallas' requested but bucket width "
                    f"{W} x block_size {self.ecfg.block_size} admits no "
                    f"sublane-legal k block for "
                    f"{'int8' if self.quantized else 'bf16'} - use a "
                    "block_size multiple of "
                    f"{32 if self.quantized else 16} or decode_impl "
                    "'auto'"
                )
            return "pallas"
        # auto: the kernel only pays on TPU (off-TPU it would run the
        # Pallas interpreter - a test vehicle, not a fast path)
        return (
            "pallas"
            if legal and jax.default_backend() == "tpu" else "xla"
        )

    # ------------------------------------------------------ jitted steps

    def _decode_fn(self, B: int, W: int):
        fn = self._step_fns.get((B, W))
        if fn is not None:
            return fn
        cfg, kv = self.cfg, self.kv.cfg
        dt = cfg.dtype
        L, H, Dh = cfg.n_layers, cfg.n_heads, cfg.head_dim
        bs = kv.block_size
        S = W * bs
        neg = jnp.asarray(-1e30, jnp.float32)
        quantized = self.quantized
        attn_route = self._attn_route(W)
        interpret = jax.default_backend() != "tpu"
        mm = _make_mm(self.weight_quantized, dt)

        def xla_attend(q, ks, vs, live):
            # the PR 12 chain, byte-identical for the bf16 pool
            scores = jnp.einsum(
                "bqhd,bhsd->bhqs", q, ks
            ).astype(jnp.float32)
            scores = scores / np.sqrt(Dh)
            probs = jax.nn.softmax(
                jnp.where(live, scores, neg), axis=-1
            )
            return jnp.einsum(
                "bhqs,bhsd->bqhd", probs.astype(dt), vs
            ).reshape(B, 1, H * Dh)

        def step(params, k_pool, v_pool, k_scale, v_scale,
                 tok, pos, table, temps, keys):
            # tok/pos (B,), table (B, W), temps (B,), keys (B, 2);
            # k_scale/v_scale (L, num_blocks, H) f32 (None-shaped dummies
            # never reach here: the bf16 wrapper below drops them)
            x = params["embed"][tok].astype(dt)[:, None, :]
            x = x + _sinusoid_pe(pos, cfg.d_model, dt)[:, None, :]
            blk = table[jnp.arange(B), pos // bs]
            flat = blk * bs + pos % bs
            gather_idx = (
                (table * bs)[:, :, None] + jnp.arange(bs)[None, None, :]
            ).reshape(B, S)
            live = (jnp.arange(S)[None, :] <= pos[:, None])[:, None, None, :]
            rows = blk[:, None] * bs + jnp.arange(bs)[None, :]  # (B, bs)

            def append_q8(pool, scales, val):
                # quantize-on-append with a per-(block, head) running
                # scale: a token whose amax outgrows the block's scale
                # RE-QUANTIZES the block's existing slab under the new
                # scale (one (B, bs) gather/scatter - the block is
                # already hot), so every stored code is always ``value /
                # scales[block]``. Scale growth is monotone per block
                # and both it and the re-rounding depend only on this
                # sequence's own writes - preemption replay is bitwise
                # (tested).
                a = jnp.max(jnp.abs(val.astype(jnp.float32)), -1)  # (B,H)
                s_old = scales[blk]                                # (B,H)
                s_new = jnp.maximum(s_old, a / _INT8_MAX)
                ratio = jnp.where(
                    s_new > 0.0,
                    s_old / jnp.maximum(s_new, _SCALE_EPS), 1.0
                )
                slab = pool[rows].astype(jnp.float32)   # (B, bs, H, Dh)
                slab = jnp.clip(
                    jnp.round(slab * ratio[:, None, :, None]),
                    -_INT8_MAX, _INT8_MAX,
                ).astype(jnp.int8)
                pool = pool.at[rows].set(slab)
                q8 = jnp.clip(
                    jnp.round(
                        val.astype(jnp.float32)
                        / jnp.maximum(s_new[..., None], _SCALE_EPS)
                    ),
                    -_INT8_MAX, _INT8_MAX,
                ).astype(jnp.int8)
                pool = pool.at[flat].set(q8)
                scales = scales.at[blk].set(s_new)
                return pool, scales

            def layer_step(x, lcaches):
                if quantized:
                    lp, ck, cv, ksc, vsc = lcaches
                else:
                    lp, ck, cv = lcaches
                    ksc = vsc = None
                h = _layer_norm(x, lp["ln1_scale"], lp["ln1_bias"]).astype(dt)
                q = mm(h, lp["wq"]).reshape(B, 1, H, Dh)
                k = mm(h, lp["wk"]).reshape(B, H, Dh)
                v = mm(h, lp["wv"]).reshape(B, H, Dh)
                if quantized:
                    ck, ksc = append_q8(ck, ksc, k)
                    cv, vsc = append_q8(cv, vsc, v)
                    ks_q = ck[gather_idx]          # (B, S, H, Dh) int8
                    vs_q = cv[gather_idx]
                    # per-slot scale view: same block-table addressing,
                    # one repeat per block (B, W, H) -> (B, S, H)
                    k_slot = jnp.repeat(ksc[table], bs, axis=1)
                    v_slot = jnp.repeat(vsc[table], bs, axis=1)
                    if attn_route == "pallas":
                        # the tuned decode kernel reads the int8 stream
                        # directly - dequant fused in its k-block loop
                        o = decode_cache_attention(
                            q.reshape(B, H, Dh),
                            ks_q.transpose(0, 2, 1, 3),
                            vs_q.transpose(0, 2, 1, 3),
                            pos,
                            k_scale=k_slot.transpose(0, 2, 1),
                            v_scale=v_slot.transpose(0, 2, 1),
                            interpret=interpret,
                        ).reshape(B, 1, H * Dh)
                    else:
                        ks = (
                            ks_q.astype(jnp.float32) * k_slot[..., None]
                        ).astype(dt).transpose(0, 2, 1, 3)
                        vs = (
                            vs_q.astype(jnp.float32) * v_slot[..., None]
                        ).astype(dt).transpose(0, 2, 1, 3)
                        o = xla_attend(q, ks, vs, live)
                else:
                    ck = ck.at[flat].set(k)
                    cv = cv.at[flat].set(v)
                    if attn_route == "pallas":
                        o = decode_cache_attention(
                            q.reshape(B, H, Dh),
                            ck[gather_idx].transpose(0, 2, 1, 3),
                            cv[gather_idx].transpose(0, 2, 1, 3),
                            pos, interpret=interpret,
                        ).reshape(B, 1, H * Dh)
                    else:
                        ks = ck[gather_idx].transpose(0, 2, 1, 3)
                        vs = cv[gather_idx].transpose(0, 2, 1, 3)
                        o = xla_attend(q, ks, vs, live)
                x = x + mm(o, lp["wo"])
                h2 = _layer_norm(
                    x, lp["ln2_scale"], lp["ln2_bias"]
                ).astype(dt)
                h2 = jax.nn.gelu(mm(h2, lp["w1"]) + lp["b1"].astype(dt))
                x = x + mm(h2, lp["w2"]) + lp["b2"].astype(dt)
                if quantized:
                    return x, (ck, cv, ksc, vsc)
                return x, (ck, cv)

            if quantized:
                xs = (params["layers"], k_pool, v_pool, k_scale, v_scale)
            else:
                xs = (params["layers"], k_pool, v_pool)
            x, out = jax.lax.scan(layer_step, x, xs, unroll=min(L, 8))
            if quantized:
                k_pool, v_pool, k_scale, v_scale = out
            else:
                k_pool, v_pool = out
            h = _layer_norm(
                x, params["lnf_scale"], params["lnf_bias"]
            ).astype(dt)
            logits = h[:, 0] @ params["head"].astype(dt).astype(jnp.float32)
            greedy = jnp.argmax(logits, axis=-1)
            sampled = jax.vmap(
                lambda k_, lg, t: jax.random.categorical(
                    k_, lg / jnp.maximum(t, 1e-6)
                )
            )(keys, logits, temps)
            nxt = jnp.where(temps > 0.0, sampled, greedy).astype(jnp.int32)
            return k_pool, v_pool, k_scale, v_scale, nxt, logits

        # the pools (and under int8 their scales) are donated: every
        # call site threads them through and rebinds the outputs, and
        # an un-donated pool double-buffers the engine's largest
        # allocation for the life of the step. Params are NEVER donated
        # (they are not returned - donating them would free the weights
        # after the first call). servelint audits this contract
        # per bucket (analysis/serve_trace.py).
        if quantized:
            fn = jax.jit(step, donate_argnums=(1, 2, 3, 4))
        else:
            # bf16 keeps the PR 12 signature (no scale operands)
            def step_bf16(params, k_pool, v_pool, tok, pos, table,
                          temps, keys):
                k_pool, v_pool, _, _, nxt, logits = step(
                    params, k_pool, v_pool, None, None, tok, pos, table,
                    temps, keys,
                )
                return k_pool, v_pool, nxt, logits

            fn = jax.jit(step_bf16, donate_argnums=(1, 2))
        self._step_fns[(B, W)] = fn
        return fn

    def _prefill_fn(self, C: int, W: int):
        fn = self._prefill_fns.get((C, W))
        if fn is not None:
            return fn
        cfg, kv = self.cfg, self.kv.cfg
        dt = cfg.dtype
        L, H, Dh = cfg.n_layers, cfg.n_heads, cfg.head_dim
        bs = kv.block_size
        S = W * bs
        neg = jnp.asarray(-1e30, jnp.float32)
        quantized = self.quantized
        mm = _make_mm(self.weight_quantized, dt)

        def prefill(params, k_pool, v_pool, k_scale, v_scale,
                    toks, pos0, table, n_valid):
            # toks (C,), pos0 scalar, table (W,), n_valid scalar
            pv = pos0 + jnp.arange(C)
            valid = jnp.arange(C) < n_valid
            x = params["embed"][toks].astype(dt)[None]  # (1, C, d)
            x = x + _sinusoid_pe(pv, cfg.d_model, dt)[None]
            flat = table[pv // bs] * bs + pv % bs
            flat = jnp.where(valid, flat, 0)  # dead tail -> scratch
            blkv = jnp.where(valid, table[pv // bs], 0)  # (C,) block ids
            gather_idx = (
                (table * bs)[:, None] + jnp.arange(bs)[None, :]
            ).reshape(S)
            # query at chunk offset q attends to positions <= pos0 + q
            live = (
                jnp.arange(S)[None, :] <= pv[:, None]
            )[None, None, :, :]  # (1, 1, C, S)

            def append_q8(pool, scales, val):
                # chunk form of the decode append: the chunk's per-block
                # amax arrives by scatter-max (commutative ->
                # deterministic under duplicate block ids), then the
                # whole table span is re-quantized under the grown
                # scales (it is being gathered for attention anyway)
                # and the chunk written at its final scales
                a = jnp.where(
                    valid[:, None],
                    jnp.max(jnp.abs(val.astype(jnp.float32)), -1),
                    0.0,
                )                                         # (C, H)
                new_scales = scales.at[blkv].max(a / _INT8_MAX)
                ratio = jnp.where(
                    new_scales > 0.0,
                    scales / jnp.maximum(new_scales, _SCALE_EPS), 1.0
                )                                         # (nb, H)
                ratio_slot = jnp.repeat(ratio[table], bs, axis=0)
                slab = pool[gather_idx].astype(jnp.float32)  # (S, H, Dh)
                slab = jnp.clip(
                    jnp.round(slab * ratio_slot[..., None]),
                    -_INT8_MAX, _INT8_MAX,
                ).astype(jnp.int8)
                pool = pool.at[gather_idx].set(slab)
                s_tok = new_scales[blkv]                  # (C, H)
                q8 = jnp.clip(
                    jnp.round(
                        val.astype(jnp.float32)
                        / jnp.maximum(s_tok[..., None], _SCALE_EPS)
                    ),
                    -_INT8_MAX, _INT8_MAX,
                ).astype(jnp.int8)
                pool = pool.at[flat].set(q8)
                return pool, new_scales

            def layer_step(x, lcaches):
                if quantized:
                    lp, ck, cv, ksc, vsc = lcaches
                else:
                    lp, ck, cv = lcaches
                    ksc = vsc = None
                h = _layer_norm(x, lp["ln1_scale"], lp["ln1_bias"]).astype(dt)
                q = mm(h, lp["wq"]).reshape(1, C, H, Dh)
                k = mm(h, lp["wk"]).reshape(C, H, Dh)
                v = mm(h, lp["wv"]).reshape(C, H, Dh)
                if quantized:
                    ck, ksc = append_q8(ck, ksc, k)
                    cv, vsc = append_q8(cv, vsc, v)
                    k_slot = jnp.repeat(ksc[table], bs, axis=0)  # (S, H)
                    v_slot = jnp.repeat(vsc[table], bs, axis=0)
                    ks = (
                        ck[gather_idx].astype(jnp.float32)
                        * k_slot[..., None]
                    ).astype(dt)[None].transpose(0, 2, 1, 3)
                    vs = (
                        cv[gather_idx].astype(jnp.float32)
                        * v_slot[..., None]
                    ).astype(dt)[None].transpose(0, 2, 1, 3)
                else:
                    ck = ck.at[flat].set(k)
                    cv = cv.at[flat].set(v)
                    ks = ck[gather_idx][None].transpose(0, 2, 1, 3)
                    vs = cv[gather_idx][None].transpose(0, 2, 1, 3)
                scores = jnp.einsum(
                    "bqhd,bhsd->bhqs", q, ks
                ).astype(jnp.float32)
                scores = scores / np.sqrt(Dh)
                probs = jax.nn.softmax(
                    jnp.where(live, scores, neg), axis=-1
                )
                o = jnp.einsum(
                    "bhqs,bhsd->bqhd", probs.astype(dt), vs
                ).reshape(1, C, H * Dh)
                x = x + mm(o, lp["wo"])
                h2 = _layer_norm(
                    x, lp["ln2_scale"], lp["ln2_bias"]
                ).astype(dt)
                h2 = jax.nn.gelu(mm(h2, lp["w1"]) + lp["b1"].astype(dt))
                x = x + mm(h2, lp["w2"]) + lp["b2"].astype(dt)
                if quantized:
                    return x, (ck, cv, ksc, vsc)
                return x, (ck, cv)

            if quantized:
                xs = (params["layers"], k_pool, v_pool, k_scale, v_scale)
            else:
                xs = (params["layers"], k_pool, v_pool)
            x, out = jax.lax.scan(layer_step, x, xs, unroll=min(L, 8))
            if quantized:
                k_pool, v_pool, k_scale, v_scale = out
            else:
                k_pool, v_pool = out
            h = _layer_norm(
                x, params["lnf_scale"], params["lnf_bias"]
            ).astype(dt)
            logits = h[0] @ params["head"].astype(dt).astype(jnp.float32)
            return k_pool, v_pool, k_scale, v_scale, logits  # (C, vocab)

        # pool donation: same contract as _decode_fn (params never)
        if quantized:
            fn = jax.jit(prefill, donate_argnums=(1, 2, 3, 4))
        else:
            def prefill_bf16(params, k_pool, v_pool, toks, pos0, table,
                             n_valid):
                k_pool, v_pool, _, _, logits = prefill(
                    params, k_pool, v_pool, None, None, toks, pos0,
                    table, n_valid,
                )
                return k_pool, v_pool, logits

            fn = jax.jit(prefill_bf16, donate_argnums=(1, 2))
        self._prefill_fns[(C, W)] = fn
        return fn

    def _draft_fn(self, B: int, W: int):
        """k greedy early-exit steps in ONE jitted call: reads the paged
        pool (history < pos), keeps the in-flight draft K/V in a local
        per-call buffer, writes NOTHING back - the pool (and under int8
        its running scales) never sees a draft, so rejected speculation
        cannot pollute live state."""
        fn = self._draft_fns.get((B, W))
        if fn is not None:
            return fn
        cfg, kv = self.cfg, self.kv.cfg
        dt = cfg.dtype
        E, K = self.draft_layers, self.spec_k
        H, Dh = cfg.n_heads, cfg.head_dim
        bs = kv.block_size
        S = W * bs
        neg = jnp.asarray(-1e30, jnp.float32)
        quantized = self.quantized
        mm = _make_mm(self.weight_quantized, dt)

        def draft(params, k_pool, v_pool, k_scale, v_scale,
                  tok, pos, table):
            # tok/pos (B,), table (B, W) -> (B, K) greedy draft tokens.
            # Gather + (int8) dequantize the E layers of pool history
            # ONCE - it is invariant across the K draft steps.
            gather_idx = (
                (table * bs)[:, :, None] + jnp.arange(bs)[None, None, :]
            ).reshape(B, S)
            hk = k_pool[:E][:, gather_idx]     # (E, B, S, H, Dh)
            hv = v_pool[:E][:, gather_idx]
            if quantized:
                k_slot = jnp.repeat(
                    k_scale[:E][:, table], bs, axis=2
                )                               # (E, B, S, H)
                v_slot = jnp.repeat(v_scale[:E][:, table], bs, axis=2)
                hk = (hk.astype(jnp.float32) * k_slot[..., None]).astype(dt)
                hv = (hv.astype(jnp.float32) * v_slot[..., None]).astype(dt)
            hk = hk.transpose(0, 1, 3, 2, 4)   # (E, B, H, S, Dh)
            hv = hv.transpose(0, 1, 3, 2, 4)
            hist_live = (jnp.arange(S)[None, :] < pos[:, None])  # (B, S)
            bufk = jnp.zeros((E, B, H, K, Dh), dt)
            bufv = jnp.zeros((E, B, H, K, Dh), dt)
            drafts = []
            for i in range(K):
                x = params["embed"][tok].astype(dt)[:, None, :]
                x = x + _sinusoid_pe(pos + i, cfg.d_model, dt)[:, None, :]
                loc = jnp.broadcast_to(
                    (jnp.arange(K) <= i)[None, :], (B, K)
                )
                live = jnp.concatenate(
                    [hist_live, loc], axis=1
                )[:, None, None, :]             # (B, 1, 1, S + K)

                def layer_step(x, lc, i=i):
                    lp, lhk, lhv, bk, bv = lc
                    h = _layer_norm(
                        x, lp["ln1_scale"], lp["ln1_bias"]
                    ).astype(dt)
                    q = mm(h, lp["wq"]).reshape(B, 1, H, Dh)
                    kk = mm(h, lp["wk"]).reshape(B, H, 1, Dh)
                    vv = mm(h, lp["wv"]).reshape(B, H, 1, Dh)
                    bk = jax.lax.dynamic_update_slice_in_dim(
                        bk, kk, i, axis=2
                    )
                    bv = jax.lax.dynamic_update_slice_in_dim(
                        bv, vv, i, axis=2
                    )
                    ks = jnp.concatenate([lhk, bk], axis=2)
                    vs = jnp.concatenate([lhv, bv], axis=2)
                    scores = jnp.einsum(
                        "bqhd,bhsd->bhqs", q, ks
                    ).astype(jnp.float32) / np.sqrt(Dh)
                    probs = jax.nn.softmax(
                        jnp.where(live, scores, neg), axis=-1
                    )
                    o = jnp.einsum(
                        "bhqs,bhsd->bqhd", probs.astype(dt), vs
                    ).reshape(B, 1, H * Dh)
                    x = x + mm(o, lp["wo"])
                    h2 = _layer_norm(
                        x, lp["ln2_scale"], lp["ln2_bias"]
                    ).astype(dt)
                    h2 = jax.nn.gelu(
                        mm(h2, lp["w1"]) + lp["b1"].astype(dt)
                    )
                    x = x + mm(h2, lp["w2"]) + lp["b2"].astype(dt)
                    return x, (bk, bv)

                x, (bufk, bufv) = jax.lax.scan(
                    layer_step, x, (params["layers"], hk, hv, bufk, bufv),
                    unroll=min(E, 8),
                )
                h = _layer_norm(
                    x, params["lnf_scale"], params["lnf_bias"]
                ).astype(dt)
                logits = h[:, 0] @ params["head"].astype(dt).astype(jnp.float32)
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                drafts.append(tok)
            return jnp.stack(drafts, axis=1)    # (B, K)

        if quantized:
            fn = jax.jit(draft)
        else:
            def draft_bf16(params, k_pool, v_pool, tok, pos, table):
                return draft(
                    params, k_pool, v_pool, None, None, tok, pos, table
                )

            fn = jax.jit(draft_bf16)
        self._draft_fns[(B, W)] = fn
        return fn

    def _verify_fn(self, B: int, W: int):
        """One target-model step over K = spec_k + 1 positions per slot
        (inputs ``[t0, d1..dk]`` at ``pos..pos+k``): write-then-gather
        over the paged pool with the chunked-prefill causal mask
        generalized to a batch axis, greedy prediction returned at
        EVERY position - the host accepts the longest matching draft
        prefix and rewinds the rest."""
        fn = self._verify_fns.get((B, W))
        if fn is not None:
            return fn
        cfg, kv = self.cfg, self.kv.cfg
        dt = cfg.dtype
        L, H, Dh = cfg.n_layers, cfg.n_heads, cfg.head_dim
        K = self.spec_k + 1
        bs = kv.block_size
        S = W * bs
        neg = jnp.asarray(-1e30, jnp.float32)
        quantized = self.quantized
        mm = _make_mm(self.weight_quantized, dt)

        def verify(params, k_pool, v_pool, k_scale, v_scale,
                   toks, pos0, table):
            # toks (B, K), pos0 (B,), table (B, W)
            pv = pos0[:, None] + jnp.arange(K)[None, :]      # (B, K)
            x = params["embed"][toks].astype(dt)             # (B, K, d)
            x = x + _sinusoid_pe(
                pv.reshape(-1), cfg.d_model, dt
            ).reshape(B, K, cfg.d_model)
            blkv = jnp.take_along_axis(table, pv // bs, axis=1)  # (B, K)
            flat = blkv * bs + pv % bs                           # (B, K)
            gather_idx = (
                (table * bs)[:, :, None] + jnp.arange(bs)[None, None, :]
            ).reshape(B, S)
            # query (b, i) attends pool slots <= pos0[b] + i (its own
            # just-written position included - write-then-gather, the
            # chunked-prefill pattern with a batch axis)
            live = (
                jnp.arange(S)[None, None, :] <= pv[:, :, None]
            )[:, None]                                       # (B,1,K,S)

            def append_q8(pool, scales, val):
                # batch form of the chunked-prefill append: per-block
                # amax by scatter-max (commutative -> deterministic
                # under duplicate block ids), whole-table-span requant
                # under the grown scales, then the K new tokens written
                # at their final scales
                a = jnp.max(jnp.abs(val.astype(jnp.float32)), -1)  # (B,K,H)
                new_scales = scales.at[blkv].max(a / _INT8_MAX)
                ratio = jnp.where(
                    new_scales > 0.0,
                    scales / jnp.maximum(new_scales, _SCALE_EPS), 1.0
                )                                            # (nb, H)
                ratio_slot = jnp.repeat(ratio[table], bs, axis=1)
                slab = pool[gather_idx].astype(jnp.float32)  # (B,S,H,Dh)
                slab = jnp.clip(
                    jnp.round(slab * ratio_slot[..., None]),
                    -_INT8_MAX, _INT8_MAX,
                ).astype(jnp.int8)
                pool = pool.at[gather_idx].set(slab)
                s_tok = new_scales[blkv]                     # (B, K, H)
                q8 = jnp.clip(
                    jnp.round(
                        val.astype(jnp.float32)
                        / jnp.maximum(s_tok[..., None], _SCALE_EPS)
                    ),
                    -_INT8_MAX, _INT8_MAX,
                ).astype(jnp.int8)
                pool = pool.at[flat].set(q8)
                return pool, new_scales

            def layer_step(x, lcaches):
                if quantized:
                    lp, ck, cv, ksc, vsc = lcaches
                else:
                    lp, ck, cv = lcaches
                    ksc = vsc = None
                h = _layer_norm(
                    x, lp["ln1_scale"], lp["ln1_bias"]
                ).astype(dt)
                q = mm(h, lp["wq"]).reshape(B, K, H, Dh)
                k = mm(h, lp["wk"]).reshape(B, K, H, Dh)
                v = mm(h, lp["wv"]).reshape(B, K, H, Dh)
                if quantized:
                    ck, ksc = append_q8(ck, ksc, k)
                    cv, vsc = append_q8(cv, vsc, v)
                    k_slot = jnp.repeat(ksc[table], bs, axis=1)  # (B,S,H)
                    v_slot = jnp.repeat(vsc[table], bs, axis=1)
                    ks = (
                        ck[gather_idx].astype(jnp.float32)
                        * k_slot[..., None]
                    ).astype(dt).transpose(0, 2, 1, 3)
                    vs = (
                        cv[gather_idx].astype(jnp.float32)
                        * v_slot[..., None]
                    ).astype(dt).transpose(0, 2, 1, 3)
                else:
                    ck = ck.at[flat].set(k)
                    cv = cv.at[flat].set(v)
                    ks = ck[gather_idx].transpose(0, 2, 1, 3)
                    vs = cv[gather_idx].transpose(0, 2, 1, 3)
                scores = jnp.einsum(
                    "bqhd,bhsd->bhqs", q, ks
                ).astype(jnp.float32) / np.sqrt(Dh)
                probs = jax.nn.softmax(
                    jnp.where(live, scores, neg), axis=-1
                )
                o = jnp.einsum(
                    "bhqs,bhsd->bqhd", probs.astype(dt), vs
                ).reshape(B, K, H * Dh)
                x = x + mm(o, lp["wo"])
                h2 = _layer_norm(
                    x, lp["ln2_scale"], lp["ln2_bias"]
                ).astype(dt)
                h2 = jax.nn.gelu(mm(h2, lp["w1"]) + lp["b1"].astype(dt))
                x = x + mm(h2, lp["w2"]) + lp["b2"].astype(dt)
                if quantized:
                    return x, (ck, cv, ksc, vsc)
                return x, (ck, cv)

            if quantized:
                xs = (params["layers"], k_pool, v_pool, k_scale, v_scale)
            else:
                xs = (params["layers"], k_pool, v_pool)
            x, out = jax.lax.scan(layer_step, x, xs, unroll=min(L, 8))
            if quantized:
                k_pool, v_pool, k_scale, v_scale = out
            else:
                k_pool, v_pool = out
            h = _layer_norm(
                x, params["lnf_scale"], params["lnf_bias"]
            ).astype(dt)
            logits = h @ params["head"].astype(dt).astype(jnp.float32)  # (B,K,v)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return k_pool, v_pool, k_scale, v_scale, nxt

        # pool donation: same contract as _decode_fn (params never).
        # _draft_fn stays donation-free by design - it READS the pools
        # and returns only draft tokens, so there is nothing to alias.
        if quantized:
            fn = jax.jit(verify, donate_argnums=(1, 2, 3, 4))
        else:
            def verify_bf16(params, k_pool, v_pool, toks, pos0, table):
                k_pool, v_pool, _, _, nxt = verify(
                    params, k_pool, v_pool, None, None, toks, pos0, table
                )
                return k_pool, v_pool, nxt

            fn = jax.jit(verify_bf16, donate_argnums=(1, 2))
        self._verify_fns[(B, W)] = fn
        return fn

    # ----------------------------------------------------------- warmup

    def warmup(self, *, max_width_blocks: int | None = None) -> int:
        """Pre-compile the (batch, width) bucket grid with dummy calls
        (all writes land in the scratch block, so live state is
        untouched). Without warmup each new bucket pays its XLA compile
        on the first request that needs it - a TTFT spike production
        serving cannot afford. Returns the number of programs built."""
        bs = self.kv.cfg.block_size
        max_w = _bucket(max_width_blocks or self.kv.cfg.max_blocks_per_seq)
        widths = []
        w = 1
        while w <= max_w:
            widths.append(w)
            w *= 2
        batches = []
        b = 1
        while b <= self.ecfg.max_batch:
            batches.append(b)
            b *= 2
        n = 0
        for B in batches:
            for W in widths:
                fn = self._decode_fn(B, W)
                args = (
                    self.params, self.k_pool, self.v_pool,
                ) + ((self.k_scale, self.v_scale) if self.quantized
                     else ()) + (
                    jnp.zeros((B,), jnp.int32), jnp.zeros((B,), jnp.int32),
                    jnp.zeros((B, W), jnp.int32),
                    jnp.zeros((B,), jnp.float32),
                    jnp.zeros((B, 2), jnp.uint32),
                )
                if self.quantized:
                    (self.k_pool, self.v_pool, self.k_scale,
                     self.v_scale, _, _) = fn(*args)
                    # warmup writes land in the scratch block; its scale
                    # is garbage by contract, but reset anyway so a
                    # fresh engine stays bitwise clean
                    self.k_scale = self.k_scale.at[:, 0, :].set(0.0)
                    self.v_scale = self.v_scale.at[:, 0, :].set(0.0)
                else:
                    self.k_pool, self.v_pool, _, _ = fn(*args)
                n += 1
        if self.ecfg.prefill_chunk > 1:
            chunks = []
            c = 1
            while c <= self.ecfg.prefill_chunk:
                chunks.append(c)
                c *= 2
            for C in chunks:
                for W in widths:
                    if C > W * bs:
                        continue
                    fn = self._prefill_fn(C, W)
                    args = (
                        self.params, self.k_pool, self.v_pool,
                    ) + ((self.k_scale, self.v_scale) if self.quantized
                         else ()) + (
                        jnp.zeros((C,), jnp.int32), jnp.int32(0),
                        jnp.zeros((W,), jnp.int32), jnp.int32(0),
                    )
                    if self.quantized:
                        (self.k_pool, self.v_pool, self.k_scale,
                         self.v_scale, _) = fn(*args)
                        self.k_scale = self.k_scale.at[:, 0, :].set(0.0)
                        self.v_scale = self.v_scale.at[:, 0, :].set(0.0)
                    else:
                        self.k_pool, self.v_pool, _ = fn(*args)
                    n += 1
        if self.spec_k:
            # the speculative bucket families: drafter + K-position
            # verify per (batch, width). Dummy writes land in the
            # scratch block (zero tables), like every other warmup call.
            K = self.spec_k + 1
            for B in batches:
                for W in widths:
                    dfn = self._draft_fn(B, W)
                    dargs = (
                        self.draft_params, self.k_pool, self.v_pool,
                    ) + ((self.k_scale, self.v_scale) if self.quantized
                         else ()) + (
                        jnp.zeros((B,), jnp.int32),
                        jnp.zeros((B,), jnp.int32),
                        jnp.zeros((B, W), jnp.int32),
                    )
                    dfn(*dargs)  # read-only: no pool state to restore
                    n += 1
                    vfn = self._verify_fn(B, W)
                    vargs = (
                        self.params, self.k_pool, self.v_pool,
                    ) + ((self.k_scale, self.v_scale) if self.quantized
                         else ()) + (
                        jnp.zeros((B, K), jnp.int32),
                        jnp.zeros((B,), jnp.int32),
                        jnp.zeros((B, W), jnp.int32),
                    )
                    if self.quantized:
                        (self.k_pool, self.v_pool, self.k_scale,
                         self.v_scale, _) = vfn(*vargs)
                        self.k_scale = self.k_scale.at[:, 0, :].set(0.0)
                        self.v_scale = self.v_scale.at[:, 0, :].set(0.0)
                    else:
                        self.k_pool, self.v_pool, _ = vfn(*vargs)
                    n += 1
        return n

    # ------------------------------------------------------------ the tick

    def _sample_key(self, seq: Sequence) -> np.ndarray:
        """Per-(sequence, position) sampling key: deterministic across
        preemption replay."""
        k = jax.random.PRNGKey(seq.seed)
        return np.asarray(jax.random.fold_in(k, seq.pos), np.uint32)

    def _emit(self, seq: Sequence, tok: int) -> None:
        """One NEW generated token: record, maybe retire, stream."""
        seq.out.append(tok)
        done = (
            len(seq.out) >= seq.max_new_tokens
            or (self.ecfg.eos_token is not None
                and tok == self.ecfg.eos_token)
        )
        if done:
            seq.finished = True
        seq.emitted = len(seq.out)
        if seq.on_token is not None:
            seq.on_token(seq, tok, done)

    def _retire_finished(self) -> list:
        done = [s for s in self.active if s.finished]
        if done:
            with self.lock:
                self.active = [s for s in self.active if not s.finished]
            for s in done:
                self._free_seq(s.seq_id)
        return done

    def _preempt_youngest(self, parked: list) -> Sequence:
        """Nothing could run: evict the youngest parked sequence so the
        others' next allocation can succeed. Blocks freed, position
        reset; generated tokens are kept for replay dedup (greedy /
        per-position keys make the regeneration identical). Returns the
        victim so the caller can record provenance."""
        victim = parked[-1]
        with self.lock:
            self.active = [
                s for s in self.active if s.seq_id != victim.seq_id
            ]
        self._free_seq(victim.seq_id)
        victim.pos = 0
        victim.preemptions += 1
        self.preempted.append(victim)
        self.stall_events += 1
        return victim

    def _spec_eligible(self, s: Sequence) -> bool:
        """Slots speculation applies to: GREEDY (sampled slots keep the
        plain path so their per-(seed, position) keys never change),
        past prefill (positions pos+1..pos+k must all be generation
        positions, i.e. pos >= prompt_len - 1), and with room for k+1
        optimistic writes under max_seq_len."""
        return (
            s.temperature == 0.0
            and s.pos >= s.prompt_len - 1
            and s.pos + self.spec_k + 1 <= self.ecfg.max_seq_len
        )

    def _rewind_seq(self, seq_id: int, n_tokens: int) -> None:
        """Rewind the KV write cursor past a rejected speculative
        suffix; freed blocks get their int8 scales zeroed (the same
        history-free-reuse contract `_free_seq` keeps)."""
        freed = self.kv.rewind(seq_id, n_tokens)
        if freed and self.quantized:
            idx = jnp.asarray(freed, jnp.int32)
            self.k_scale = self.k_scale.at[:, idx, :].set(0.0)
            self.v_scale = self.v_scale.at[:, idx, :].set(0.0)

    def _spec_step(self, batch: list, stats: dict, seqstat) -> None:
        """The speculative phase of one tick: draft k tokens per slot
        (skipped for slots whose future is already known from
        preemption replay - their own `out` tokens are the drafts,
        guaranteed acceptance under greedy determinism), verify all
        k+1 positions in one target step, accept the longest matching
        prefix, emit, rewind the rest."""
        k = self.spec_k
        K = k + 1
        bs = self.kv.cfg.block_size
        n = len(batch)
        W = _bucket(max((s.pos + k) // bs + 1 for s in batch))
        drafts = np.zeros((n, k), np.int32)
        need_draft = []
        for idx, s in enumerate(batch):
            j0 = s.pos + 1 - s.prompt_len
            if 0 <= j0 and j0 + k <= len(s.out):
                drafts[idx] = s.out[j0: j0 + k]   # replay: known future
            else:
                need_draft.append(idx)
        draft_s = 0.0
        if need_draft:
            Bd = _bucket(len(need_draft))
            if Bd > self.ecfg.max_batch:
                Bd = self.ecfg.max_batch
            dtok = np.zeros((Bd,), np.int32)
            dpos = np.zeros((Bd,), np.int32)
            for row, idx in enumerate(need_draft):
                dtok[row] = batch[idx].next_input()
                dpos[row] = batch[idx].pos
            dtable = self.kv.table(
                [batch[i].seq_id for i in need_draft]
                + [-1] * (Bd - len(need_draft)), W,
            )
            fn = self._draft_fn(Bd, W)
            t0 = time.perf_counter()
            args = (
                self.draft_params, self.k_pool, self.v_pool,
            ) + ((self.k_scale, self.v_scale) if self.quantized
                 else ()) + (
                jnp.asarray(dtok), jnp.asarray(dpos), jnp.asarray(dtable),
            )
            out_d = np.asarray(fn(*args))  # asarray = device sync
            draft_s = time.perf_counter() - t0
            for row, idx in enumerate(need_draft):
                drafts[idx] = out_d[row]

        B = _bucket(n)
        if B > self.ecfg.max_batch:
            B = self.ecfg.max_batch
        toks = np.zeros((B, K), np.int32)
        pos0 = np.zeros((B,), np.int32)
        for i, s in enumerate(batch):
            toks[i, 0] = s.next_input()
            toks[i, 1:] = drafts[i]
            pos0[i] = s.pos
        table = self.kv.table(
            [s.seq_id for s in batch] + [-1] * (B - n), W
        )
        fn = self._verify_fn(B, W)
        tail = (jnp.asarray(toks), jnp.asarray(pos0), jnp.asarray(table))
        t0 = time.perf_counter()
        if self.quantized:
            (self.k_pool, self.v_pool, self.k_scale, self.v_scale,
             nxt) = fn(
                self.params, self.k_pool, self.v_pool,
                self.k_scale, self.v_scale, *tail,
            )
        else:
            self.k_pool, self.v_pool, nxt = fn(
                self.params, self.k_pool, self.v_pool, *tail,
            )
        nxt = np.asarray(nxt)
        verify_s = time.perf_counter() - t0

        sp = stats["spec"] = {
            "proposed": 0, "accepted": 0, "steps": 1,
            "draft_s": draft_s, "verify_s": verify_s, "per_slot": [],
        }
        self.spec_steps += 1
        for i, s in enumerate(batch):
            tgt = nxt[i]          # greedy prediction at pos..pos+k
            a = 0
            while a < k and drafts[i, a] == tgt[a]:
                a += 1
            d = seqstat(s)
            d["proposed"] += k
            d["accepted"] += a
            d["verify_s"] += verify_s / n
            if i in need_draft:
                d["draft_s"] += draft_s / len(need_draft)
            sp["proposed"] += k
            sp["accepted"] += a
            sp["per_slot"].append(a)
            self.spec_proposed_tokens += k
            self.spec_accepted_tokens += a
            # emit tgt[0..a] (a+1 tokens; the all-rejected step emits
            # exactly 1 - the token plain decode would have) through the
            # SAME per-consumed-position accounting as the plain path,
            # so decode_ticks == tokens_emitted + replayed_ticks holds
            # by construction
            start = s.pos
            for t in range(a + 1):
                consumed_at = start + t
                s.pos = consumed_at + 1
                j = consumed_at + 1 - s.prompt_len
                if j == len(s.out):
                    self._emit(s, int(tgt[t]))
                else:
                    d["replayed"] += 1
                self.decode_tokens += 1
                stats["decode_tokens"] += 1
                d["decode"] += 1
                if s.finished:
                    break
            # the verify step wrote K entries optimistically; keep only
            # the consumed prefix (retirement frees everything anyway)
            if not s.finished:
                self._rewind_seq(s.seq_id, s.pos)

    def step(self) -> dict:
        """One engine tick. Returns per-tick stats for the scheduler's
        ledger/metrics: ``{"decode_tokens", "prefill_tokens",
        "finished", "parked", "batch", "prefill_s", "decode_s"}``
        (span seconds measured by the caller via the returned work
        counts - the engine itself is clock-free for testability).

        For per-request attribution (serve/reqtrace.py) the dict also
        carries ``per_seq`` - ``{seq_id: {"prefill", "decode",
        "replayed", "parked", "proposed", "accepted", "draft_s",
        "verify_s"}}``, this tick's token counts and park flag
        for every sequence the tick touched - and ``preempted``, the
        provenance of evictions performed this tick (``seq_id``,
        ``tokens_held`` for replay accounting, cumulative
        ``preemptions``). Ticks with a speculative phase additionally
        carry ``spec`` - ``{"proposed", "accepted", "steps",
        "draft_s", "verify_s", "per_slot"}`` (``per_slot`` = accepted
        drafts per slot, the acceptance-histogram input)."""
        ecfg = self.ecfg
        bs = self.kv.cfg.block_size
        with self.lock:
            todo = list(self.active)
        parked: list[Sequence] = []
        stats = {"decode_tokens": 0, "prefill_tokens": 0, "finished": 0,
                 "parked": 0, "batch": 0, "per_seq": {}, "preempted": []}

        def seqstat(s: Sequence) -> dict:
            d = stats["per_seq"].get(s.seq_id)
            if d is None:
                d = stats["per_seq"][s.seq_id] = {
                    "prefill": 0, "decode": 0, "replayed": 0,
                    "parked": False,
                    # speculative sub-attribution (zero when spec off)
                    "proposed": 0, "accepted": 0,
                    "draft_s": 0.0, "verify_s": 0.0,
                }
            return d

        # ---- chunked prefill phase (prefill_chunk > 1 only)
        if ecfg.prefill_chunk > 1:
            budget = ecfg.prefill_token_budget or ecfg.prefill_chunk
            for seq in todo:
                if budget <= 0:
                    break
                if not seq.in_prefill or seq.finished:
                    continue
                # leave the LAST prompt token to the decode batch: its
                # logits produce the first generated token there, so
                # first-token sampling/argmax runs on the same path for
                # every sequence
                remaining = seq.prompt_len - 1 - seq.pos
                if remaining <= 0:
                    continue
                n = min(remaining, ecfg.prefill_chunk, budget)
                try:
                    self.kv.ensure_range(seq.seq_id, seq.pos + n - 1)
                except OutOfBlocks:
                    parked.append(seq)
                    seqstat(seq)["parked"] = True
                    continue
                C = _bucket(n)
                W = _bucket(
                    (seq.pos + n - 1) // bs + 1
                )
                toks = np.zeros((C,), np.int32)
                toks[:n] = seq.prompt[seq.pos: seq.pos + n]
                table = self.kv.table([seq.seq_id], W)[0]
                fn = self._prefill_fn(C, W)
                tail = (
                    jnp.asarray(toks), jnp.int32(seq.pos),
                    jnp.asarray(table), jnp.int32(n),
                )
                if self.quantized:
                    (self.k_pool, self.v_pool, self.k_scale,
                     self.v_scale, _) = fn(
                        self.params, self.k_pool, self.v_pool,
                        self.k_scale, self.v_scale, *tail,
                    )
                else:
                    self.k_pool, self.v_pool, _ = fn(
                        self.params, self.k_pool, self.v_pool, *tail,
                    )
                seq.pos += n
                budget -= n
                self.prefill_tokens += n
                stats["prefill_tokens"] += n
                seqstat(seq)["prefill"] += n

        # ---- decode batch: plain slots (one token each) + speculative
        # slots (k drafts verified in one multi-position step)
        batch: list[Sequence] = []
        spec_batch: list[Sequence] = []
        for seq in todo:
            if seq.finished or seq in parked:
                continue
            if ecfg.prefill_chunk > 1 and seq.in_prefill and (
                seq.pos < seq.prompt_len - 1
            ):
                continue  # still mid-chunked-prefill; next tick
            if self.spec_k and self._spec_eligible(seq):
                try:
                    self.kv.ensure_range(
                        seq.seq_id, seq.pos + self.spec_k
                    )
                    spec_batch.append(seq)
                    continue
                except OutOfBlocks:
                    pass  # degrade to the one-block plain path
            try:
                self.kv.ensure(seq.seq_id, seq.pos)
            except OutOfBlocks:
                parked.append(seq)
                seqstat(seq)["parked"] = True
                continue
            batch.append(seq)

        stats["parked"] = len(parked)
        if parked:
            self.stall_events += 1
        if not batch and not spec_batch:
            if parked:
                # every active sequence is parked on blocks: preempt the
                # youngest so the others' next allocation can succeed
                victim = self._preempt_youngest(parked)
                stats["preempted"].append({
                    "seq_id": victim.seq_id,
                    "tokens_held": len(victim.out),
                    "preemptions": victim.preemptions,
                })
            return stats

        if batch:
            B = _bucket(len(batch))
            if B > ecfg.max_batch:
                B = ecfg.max_batch
                batch = batch[:B]
            W = _bucket(max(
                s.pos // bs + 1 for s in batch
            ))
            tok = np.zeros((B,), np.int32)
            pos = np.zeros((B,), np.int32)
            temps = np.zeros((B,), np.float32)
            keys = np.zeros((B, 2), np.uint32)
            for i, s in enumerate(batch):
                tok[i] = s.next_input()
                pos[i] = s.pos
                temps[i] = s.temperature
                keys[i] = self._sample_key(s)
            table = self.kv.table(
                [s.seq_id for s in batch] + [-1] * (B - len(batch)), W
            )
            fn = self._decode_fn(B, W)
            tail = (
                jnp.asarray(tok), jnp.asarray(pos), jnp.asarray(table),
                jnp.asarray(temps), jnp.asarray(keys),
            )
            if self.quantized:
                (self.k_pool, self.v_pool, self.k_scale, self.v_scale,
                 nxt, _) = fn(
                    self.params, self.k_pool, self.v_pool,
                    self.k_scale, self.v_scale, *tail,
                )
            else:
                self.k_pool, self.v_pool, nxt, _ = fn(
                    self.params, self.k_pool, self.v_pool, *tail,
                )
            nxt = np.asarray(nxt)
            for i, s in enumerate(batch):
                consumed_at = s.pos
                s.pos += 1
                if consumed_at >= s.prompt_len - 1:
                    # prediction for generated-token index j; after a
                    # preemption the replay re-derives tokens the
                    # sequence already holds (j < len(out)) -
                    # deterministic by construction (greedy, or the
                    # per-position sampling key), so they are dropped,
                    # not re-appended/re-streamed
                    j = consumed_at + 1 - s.prompt_len
                    if j == len(s.out):
                        self._emit(s, int(nxt[i]))
                    else:
                        seqstat(s)["replayed"] += 1
                    self.decode_tokens += 1
                    stats["decode_tokens"] += 1
                    seqstat(s)["decode"] += 1
                else:
                    self.prefill_tokens += 1
                    stats["prefill_tokens"] += 1
                    seqstat(s)["prefill"] += 1
        if spec_batch:
            self._spec_step(spec_batch, stats, seqstat)
        self.ticks += 1
        stats["batch"] = len(batch) + len(spec_batch)
        stats["finished"] = len(self._retire_finished())
        return stats
