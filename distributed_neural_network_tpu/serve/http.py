"""HTTP face of the serving stack + the `python -m
distributed_neural_network_tpu.serve` CLI.

One `utils/obs.py ObsServer` carries everything: the observability
endpoints every load balancer / scraper already knows (``/metrics``
Prometheus text with the full serve_* series, ``/healthz`` liveness ->
status-code mapping) plus the serving routes mounted through the
pluggable route table:

- ``POST /v1/generate`` - body ``{"prompt": [int, ...] | "text": str,
  "max_new_tokens": N, "temperature": t, "seed": s, "stream": bool,
  "api_key": k}`` (the key may also ride the ``X-API-Key`` header).
  With ``stream`` (default true) the response is server-sent events:
  one ``data: {"token": id}`` frame per generated token as it leaves
  the decode step, then ``data: {"done": true, ...summary}``. A client
  disconnect mid-stream cancels the request at the next step boundary
  (blocks freed - a closed tab never holds KV memory). Without
  ``stream``, one JSON body after completion. Admission rejections map
  to HTTP status: 429 (queue full / tenant over rate, with
  ``Retry-After``) and 400 (malformed / over-length), so standard
  client backoff just works.
- ``GET /v1/status`` - one JSON snapshot (active/queued/KV occupancy,
  in-flight request summaries).
- ``GET /v1/requests`` - the per-request lifecycle records
  (serve/reqtrace.py): in-flight summaries + the bounded ring of
  finalized records. ``?full=1`` includes every ringed record's span
  sequence (the `tools/request_trace.py` input); ``?id=N`` returns one
  request's full detail (404 when it fell off the ring).

``"text"`` prompts are byte-tokenized (the `data/tokens.py` .txt
convention; needs vocab >= 256); responses for text prompts include the
decoded completion.

The CLI builds a seeded-random model (the same ``init_params(key(seed),
cfg)`` any offline process can rebuild - `tools/loadgen.py
--check-oracle` exploits exactly this to verify streamed completions
bitwise against `models/transformer.py generate`), prints the bound URL
for port-0 discovery, and on SIGTERM/SIGINT finalizes the serving
goodput ledger (conservation asserted) before printing a
``SERVE_SUMMARY`` JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
from urllib.parse import parse_qs, urlsplit

from ..utils.obs import MetricsRegistry, ObsServer
from .engine import EngineConfig, ServeEngine
from .scheduler import (
    AdmissionError,
    SchedulerConfig,
    ServeRequest,
    ServeScheduler,
)

# how long a streaming reader waits on the next token before declaring
# the stream wedged (a generous multiple of any sane step time)
STREAM_TIMEOUT_S = 300.0


def _json_response(handler, code: int, doc: dict,
                   extra_headers=()) -> None:
    body = (json.dumps(doc) + "\n").encode()
    handler.send_response(code)
    handler.send_header("Content-Type", "application/json")
    for k, v in extra_headers:
        handler.send_header(k, v)
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


class ServeServer:
    """The scheduler behind an ObsServer with /v1/* routes mounted."""

    def __init__(
        self,
        scheduler: ServeScheduler,
        registry: MetricsRegistry,
        *,
        port: int = 0,
        host: str = "127.0.0.1",
        replica_id: str | None = None,
    ):
        self.scheduler = scheduler
        self.registry = registry
        self.replica_id = replica_id
        self.obs = ObsServer(
            registry,
            port=port,
            host=host,
            routes={
                ("POST", "/v1/generate"): self._generate,
                ("GET", "/v1/status"): self._status,
                ("GET", "/v1/requests"): self._requests,
                ("POST", "/v1/drain"): self._drain_route,
            },
        )
        self.port = self.obs.port
        self.url = self.obs.url

    def close(self) -> None:
        self.obs.close()

    # ------------------------------------------------------------ routes

    def _status(self, handler) -> None:
        eng = self.scheduler.engine
        blk_bytes = eng.kv_block_bytes()
        _json_response(handler, 200, {
            "replica": self.replica_id,
            "draining": self.scheduler.draining,
            "active_sequences": len(eng.active),
            "queued": self.scheduler._queued,
            "kv_blocks_in_use": eng.kv.blocks_in_use,
            "kv_blocks_total": eng.kv.cfg.usable_blocks,
            "kv_utilization": round(eng.kv.utilization(), 4),
            "kv_dtype": eng.kv_dtype_name(),
            "kv_bytes_in_use": eng.kv.blocks_in_use * blk_bytes,
            "kv_bytes_total": eng.kv.cfg.usable_blocks * blk_bytes,
            "engine_ticks": eng.ticks,
            "decode_tokens": eng.decode_tokens,
            "prefill_tokens": eng.prefill_tokens,
            # per-bucket-family compiled-program counts: reconcile a
            # live deployment against its servelint grid manifest
            # (after warmup() the counts match the manifest and must
            # never grow - analysis/serve_trace.py)
            "compiled_programs": eng.compiled_programs(),
            "weight_dtype": eng.weight_dtype_name(),
            "spec_decode": eng.spec_k,
            "spec_draft_layers": eng.draft_layers if eng.spec_k else 0,
            "spec_proposed_tokens": eng.spec_proposed_tokens,
            "spec_accepted_tokens": eng.spec_accepted_tokens,
            "spec_steps": eng.spec_steps,
            "spec_acceptance_rate": (
                round(eng.spec_accepted_tokens
                      / eng.spec_proposed_tokens, 4)
                if eng.spec_proposed_tokens else None
            ),
            "requests": self.scheduler.reqtrace.in_flight(),
            "requests_finalized":
                self.scheduler.reqtrace.finalized_total,
        })

    def _requests(self, handler) -> None:
        # the route table keys on the query-stripped path; the raw
        # request line still carries ?id= / ?full=
        qs = parse_qs(urlsplit(handler.path).query)
        rid = qs.get("id", [None])[0]
        if rid is not None:
            try:
                rid = int(rid)
            except ValueError:
                _json_response(
                    handler, 400, {"error": "id must be an integer"}
                )
                return
            doc = self.scheduler.reqtrace.get(rid)
            if doc is None:
                _json_response(handler, 404, {
                    "error": f"request {rid} not found "
                    "(never seen, or evicted from the ring)",
                })
            else:
                _json_response(handler, 200, {"request": doc})
            return
        full = qs.get("full", ["0"])[0] not in ("0", "", "false")
        _json_response(
            handler, 200, self.scheduler.reqtrace.snapshot(full=full)
        )

    def _drain_route(self, handler) -> None:
        """Graceful drain: stop admission, migrate live sequences out as
        deterministic replay descriptors (engine.export_descriptor), and
        report them so the fleet router can re-dispatch to peers. The
        process itself is released by the caller (SIGTERM after drain -
        the CLI exits 0)."""
        out = self.scheduler.drain()
        _json_response(handler, 200, {
            "replica": self.replica_id,
            "draining": True,
            "completed": bool(out.get("completed")),
            "migrated": out.get("migrated", []),
        })

    def _parse_request(self, handler):
        try:
            n = int(handler.headers.get("Content-Length") or 0)
        except ValueError:
            n = 0
        try:
            body = json.loads(handler.rfile.read(n) or b"{}")
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
        except (ValueError, UnicodeDecodeError) as e:
            raise AdmissionError(400, "bad_json", f"invalid JSON body: {e}")
        is_text = False
        prompt = body.get("prompt")
        if prompt is None and isinstance(body.get("text"), str):
            vocab = self.scheduler.engine.cfg.vocab_size
            if vocab < 256:
                raise AdmissionError(
                    400, "no_text_tokens",
                    f"text prompts are byte-tokenized and need "
                    f"vocab_size >= 256 (model has {vocab}); send "
                    "integer 'prompt' tokens instead",
                )
            prompt = list(body["text"].encode())
            is_text = True
        if not isinstance(prompt, list) or not all(
            isinstance(t, int) for t in prompt
        ):
            raise AdmissionError(
                400, "bad_prompt",
                "body needs 'prompt': [int token ids] or 'text': str",
            )
        api_key = (
            handler.headers.get("X-API-Key")
            or body.get("api_key")
            or "anonymous"
        )
        # fleet-router failover provenance (serve/fleet.py re-dispatch)
        try:
            retries = int(handler.headers.get("X-Router-Retries") or 0)
            retry_s = float(
                handler.headers.get("X-Router-Retry-Seconds") or 0.0
            )
        except ValueError:
            retries, retry_s = 0, 0.0
        req = ServeRequest(
            prompt=prompt,
            max_new_tokens=int(body.get("max_new_tokens", 16)),
            temperature=float(body.get("temperature", 0.0)),
            seed=int(body.get("seed", 0)),
            api_key=str(api_key),
            router_retries=retries,
            router_retry_s=retry_s,
            stream_owner=True,  # this handler acks the stream tail
        )
        return req, bool(body.get("stream", True)), is_text

    def _generate(self, handler) -> None:
        try:
            req, stream, is_text = self._parse_request(handler)
            self.scheduler.submit(req)
        except AdmissionError as e:
            extra = (
                (("Retry-After", "1"),) if e.status == 429 else ()
            )
            _json_response(handler, e.status, {
                "error": str(e), "reason": e.reason,
            }, extra)
            return
        if stream:
            self._stream_response(handler, req, is_text)
        else:
            self._block_response(handler, req, is_text)

    def _drain(self, req):
        """Yield events until done/error/timeout (generator)."""
        import queue as queue_mod

        while True:
            try:
                kind, payload = req.events.get(timeout=STREAM_TIMEOUT_S)
            except queue_mod.Empty:
                yield "error", "stream timeout"
                return
            yield kind, payload
            if kind in ("done", "error", "migrate"):
                return

    def _summary_doc(self, req, is_text) -> dict:
        doc = req.summary()
        if self.replica_id is not None:
            doc["replica"] = self.replica_id
        if is_text:
            doc["text"] = bytes(
                t for t in req.tokens if 0 <= t < 256
            ).decode("utf-8", "replace")
        return doc

    def _stream_response(self, handler, req, is_text) -> None:
        handler.send_response(200)
        handler.send_header("Content-Type", "text/event-stream")
        handler.send_header("Cache-Control", "no-store")
        handler.send_header("Connection", "close")
        handler.end_headers()
        try:
            for kind, payload in self._drain(req):
                if kind == "token":
                    frame = {"token": payload}
                elif kind == "done":
                    frame = dict(self._summary_doc(req, is_text))
                    frame["done"] = True
                elif kind == "migrate":
                    # drain migration: the fleet router re-dispatches
                    # with already-streamed tokens as prompt suffix
                    frame = {
                        "migrated": True,
                        "req_id": req.req_id,
                        "n_tokens": len(req.tokens),
                        "replica": self.replica_id,
                    }
                else:
                    frame = {"error": payload}
                handler.wfile.write(
                    f"data: {json.dumps(frame)}\n\n".encode()
                )
                handler.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            # client went away mid-stream: free its slot + KV blocks
            self.scheduler.cancel(req)
        finally:
            # seals the trace record's stream_write span (no-op unless
            # the request already reached a terminal status - a wedged
            # stream stays with the loop's cancel/shutdown paths)
            self.scheduler.finish_stream(req)

    def _block_response(self, handler, req, is_text) -> None:
        last_err = None
        for kind, payload in self._drain(req):
            if kind == "error":
                last_err = payload
        try:
            if last_err is not None and req.status != "done":
                _json_response(handler, 500, {"error": last_err})
                return
            _json_response(handler, 200, self._summary_doc(req, is_text))
        finally:
            self.scheduler.finish_stream(req)


# ----------------------------------------------------------------- CLI


def build_model(args):
    """Seeded-random model from CLI geometry (rebuildable offline for
    the oracle check)."""
    import jax
    import jax.numpy as jnp

    from ..models.transformer import TransformerConfig, init_params

    cfg = TransformerConfig(
        vocab_size=args.vocab,
        d_model=args.d_model,
        n_heads=args.n_heads,
        n_layers=args.n_layers,
        d_ff=args.d_ff,
        dtype=jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32,
    )
    params = init_params(jax.random.key(args.seed), cfg)
    return params, cfg


def add_model_args(p: argparse.ArgumentParser) -> None:
    """The model-geometry flags, shared verbatim by `tools/loadgen.py
    --check-oracle` so both sides always rebuild the same model."""
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--n-heads", type=int, default=4)
    p.add_argument("--n-layers", type=int, default=2)
    p.add_argument("--d-ff", type=int, default=128)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--dtype", choices=("float32", "bfloat16"),
                   default="float32")
    p.add_argument("--seed", type=int, default=0,
                   help="init_params seed (the oracle contract)")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributed_neural_network_tpu.serve",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--port", type=int, default=8000,
                   help="0 = ephemeral (the bound URL is printed)")
    p.add_argument("--host", default="127.0.0.1")
    add_model_args(p)
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--num-blocks", type=int, default=128)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--max-seq-len", type=int, default=512)
    p.add_argument("--prefill-chunk", type=int, default=1,
                   help="prompt tokens per chunked-prefill call (1 = "
                   "exact token-at-a-time prefill)")
    p.add_argument("--precision", default="bf16",
                   help="comma-separated set from {bf16, int8-kv, "
                   "int8-w}. 'int8-kv' stores the paged KV pool "
                   "quantized (int8 + per-(block, head) f32 scales): "
                   "~2x the concurrent-sequence capacity per HBM byte; "
                   "'int8-w' stores the weights quantized (int8 codes "
                   "+ per-output-column f32 scales) and routes every "
                   "weight matmul through the int8 dot path; they "
                   "compose ('int8-kv,int8-w'). Per-token top-1 "
                   "agreement vs the bf16 oracle gated >= 99%% in the "
                   "bench/CI parity rows (docs/SERVING.md). "
                   "'bf16' = neither quantization")
    p.add_argument("--spec-decode", type=int, default=0, metavar="K",
                   help="speculative decoding: an early-exit drafter "
                   "(the first --spec-draft-layers layers of the same "
                   "model) proposes K tokens per greedy slot each tick "
                   "and ONE verify step checks all K+1 positions at "
                   "once; rejected suffixes rewind the block-table "
                   "write cursor. Greedy streams stay token-exact vs "
                   "offline generate(). 0 = off")
    p.add_argument("--spec-draft-layers", type=int, default=0,
                   metavar="E",
                   help="drafter depth (early-exit layer count); "
                   "0 = auto (max(1, n_layers // 8))")
    p.add_argument("--decode-impl", choices=("auto", "xla", "pallas"),
                   default="auto",
                   help="attention under the paged gather: the tuned "
                   "Pallas decode kernel ('pallas'; int8 pools stream "
                   "with fused dequant) vs the XLA chain ('xla'); "
                   "'auto' routes to the kernel on TPU when the bucket "
                   "width admits a legal block, XLA otherwise")
    p.add_argument("--eos-token", type=int, default=None)
    p.add_argument("--max-queue", type=int, default=64)
    p.add_argument("--tenant-rate", type=float, default=0.0,
                   help="per-API-key token-bucket rate (req/s; 0 = off)")
    p.add_argument("--tenant-burst", type=int, default=8)
    p.add_argument("--run-record",
                   default=os.environ.get("DNN_TPU_RUN_RECORD"),
                   help="write the serving goodput record here "
                   "(utils/goodput.py taxonomy 'serve'; default "
                   "$DNN_TPU_RUN_RECORD - the fleet supervisor sets it "
                   "so serve/fleet.py aggregate_serve_records can fold "
                   "per-replica records into the fleet view)")
    p.add_argument("--trace-out", default=None,
                   help="export a Chrome trace of per-request lifecycle "
                   "lanes (one slot lane per concurrent request, spans "
                   "by cause + preempt instants) at shutdown - merges "
                   "with training shards via tools/trace_merge.py")
    p.add_argument("--request-ring", type=int, default=256,
                   help="finalized per-request records kept for "
                   "GET /v1/requests / tools/request_trace.py")
    p.add_argument("--warmup", action="store_true",
                   help="pre-compile the (batch, width) bucket grid "
                   "before binding the port (no first-request compile "
                   "TTFT spike)")
    p.add_argument("--replica-id",
                   default=os.environ.get("DNN_TPU_REPLICA_ID"),
                   help="fleet replica identity (stamped on summaries "
                   "and /v1/status; default $DNN_TPU_REPLICA_ID)")
    p.add_argument("--heartbeat-file",
                   default=os.environ.get("DNN_TPU_HEARTBEAT_FILE"),
                   help="write a liveness heartbeat JSON here "
                   "(advertises the /metrics URL for serve/fleet.py "
                   "router discovery; default $DNN_TPU_HEARTBEAT_FILE)")
    args = p.parse_args(argv)

    precision = {s.strip() for s in args.precision.split(",") if s.strip()}
    bad = precision - {"bf16", "int8-kv", "int8-w"}
    if bad:
        p.error(f"--precision: unknown mode(s) {sorted(bad)} "
                "(choose from bf16, int8-kv, int8-w)")

    params, cfg = build_model(args)
    engine = ServeEngine(params, cfg, EngineConfig(
        max_batch=args.max_batch,
        num_blocks=args.num_blocks,
        block_size=args.block_size,
        max_seq_len=args.max_seq_len,
        prefill_chunk=args.prefill_chunk,
        eos_token=args.eos_token,
        kv_dtype="int8" if "int8-kv" in precision else "bf16",
        weight_dtype="int8" if "int8-w" in precision else "bf16",
        decode_impl=args.decode_impl,
        spec_decode=args.spec_decode,
        spec_draft_layers=args.spec_draft_layers,
    ))
    if args.warmup:
        n = engine.warmup()
        print(f"(warmup: {n} bucket programs compiled)", flush=True)
    registry = MetricsRegistry()
    tracer = None
    if args.trace_out:
        import socket

        from ..utils.tracing import Tracer

        tracer = Tracer().set_process(
            hostname=socket.gethostname(),
            label=f"serve:{args.port}",
        )
    scheduler = ServeScheduler(
        engine,
        SchedulerConfig(
            max_queue=args.max_queue,
            tenant_rate=args.tenant_rate,
            tenant_burst=args.tenant_burst,
            run_record=args.run_record,
            request_ring=args.request_ring,
        ),
        registry=registry,
        tracer=tracer,
    ).start()
    server = ServeServer(
        scheduler, registry, port=args.port, host=args.host,
        replica_id=args.replica_id,
    )
    heartbeat = None
    if args.heartbeat_file:
        from ..utils.obs import HeartbeatFileWriter

        heartbeat = HeartbeatFileWriter(
            registry, args.heartbeat_file,
            metrics_url=server.url, role="serve",
        )
    print(
        f"serving on {server.url} "
        f"(model d{args.d_model}/L{args.n_layers}/H{args.n_heads} "
        f"vocab {args.vocab} seed {args.seed}; "
        f"{engine.kv.cfg.usable_blocks} KV blocks x "
        f"{args.block_size} tokens [{engine.kv_dtype_name()}, "
        f"{engine.kv_block_bytes():,} B/block]; "
        f"weights {engine.weight_dtype_name()}; "
        + (f"spec-decode k={engine.spec_k} "
           f"E={engine.draft_layers}; " if engine.spec_k else "")
        + "endpoints: "
        "POST /v1/generate, GET /v1/status, GET /v1/requests, "
        "/metrics, /healthz)",
        flush=True,
    )

    stop = threading.Event()

    def _stop(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    while not stop.wait(0.2):
        pass
    if heartbeat is not None:
        heartbeat.close()
    record = scheduler.close()
    server.close()
    if tracer is not None:
        tracer.export(args.trace_out, goodput=record)
        print(f"(request trace lanes -> {args.trace_out})", flush=True)
    print("SERVE_SUMMARY " + json.dumps({
        "requests_completed": int(
            registry.counter("serve_requests_total")
            .labels(status="completed").value
        ),
        "decode_tokens": engine.decode_tokens,
        "prefill_tokens": engine.prefill_tokens,
        "spec_proposed_tokens": engine.spec_proposed_tokens,
        "spec_accepted_tokens": engine.spec_accepted_tokens,
        "spec_steps": engine.spec_steps,
        "goodput_ratio": record.get("goodput_ratio") if record else None,
        "run_record": args.run_record,
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
