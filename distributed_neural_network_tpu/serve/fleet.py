"""Serving fleet: the failover router, drain orchestration, and the
SLO-driven autoscaling decision function.

The single-replica stack (serve/http.py) dies with its process; this
module makes replica death a non-event for clients by composing four
things the repo already has:

- **Discovery the federation way** (train/supervisor.py): each replica
  advertises its ``/metrics`` URL through its heartbeat file
  (``role: "serve"``); the router watches a heartbeat directory, so
  supervisor restarts (new PID, new ephemeral port) re-register
  automatically and a stale heartbeat marks the replica DOWN.
- **Least-loaded dispatch**: the router scrapes each replica's
  ``serve_queue_depth`` / ``serve_active_sequences`` / KV occupancy and
  routes ``POST /v1/generate`` to the least-loaded UP replica,
  corrected by router-side in-flight counts between scrapes.
- **Failover by deterministic replay** (the PR 12 seeded-replay
  contract): generation is a pure function of (model seed, prompt,
  request seed, temperature) and sampling keys are per absolute
  position, so when a replica dies mid-stream the router re-dispatches
  to a survivor with ``prompt' = prompt + already_streamed`` and
  ``max_new' = max_new - n_streamed`` - the same dedup rule preemption
  replay uses - and the client stream stays byte-identical to the
  offline oracle. Bounded by ``max_retries`` episodes per request;
  re-dispatch provenance rides the ``X-Router-Retries`` /
  ``X-Router-Retry-Seconds`` headers into the replica's per-request
  trace (serve/reqtrace.py ``router_retry``).
- **Graceful drain**: ``POST /v1/drain {"replica": id}`` stops
  admission on the target (scheduler 503s), migrates its live
  sequences out as replay descriptors, and every router-proxied stream
  self-heals through the same failover path when its ``migrated``
  frame arrives - SIGTERM rolling restarts and scale-down both reuse
  this.

`autoscale_decision` is the pure policy the `tools/serve_fleet.py`
operator loop runs: scale UP on queue_wait-dominant SLO violations (or
raw queue pressure), explicitly do NOT scale on kv_alloc_stall-dominant
violations (more replicas can't fix an undersized KV pool - the readout
says "add KV capacity" instead), scale DOWN after sustained idleness.
`slo_readout` produces the dominant-cause gates from fleet-merged
``/v1/requests?full=1`` records (the PR 14 taxonomy);
`aggregate_serve_records` folds per-replica serving goodput records
into one fleet record with conservation asserted.

Stdlib + utils/obs.py only - the router must not need jax.
"""

from __future__ import annotations

import http.client
import json
import math
import os
import threading
import time
import urllib.request
from dataclasses import dataclass, field
from urllib.parse import urlsplit

from ..utils.obs import ObsServer, parse_prom_samples
from .http import STREAM_TIMEOUT_S, _json_response

# mirrors tools/request_trace.py (stdlib tool, can't be imported here)
PERCENTILES = (0.50, 0.95, 0.99)
SLO_KEYS = tuple(
    f"{m}_p{int(q * 100)}" for m in ("ttft", "e2e") for q in PERCENTILES
)


# ------------------------------------------------------------- replicas


@dataclass
class ReplicaState:
    """One replica as the router sees it: identity, liveness, and the
    scraped load signals dispatch keys on."""

    replica_id: str
    url: str                      # serving/metrics base URL
    state: str = "down"           # "up" | "draining" | "down"
    hb_path: str | None = None    # heartbeat file (None = static)
    queue_depth: int = 0
    active: int = 0
    kv_blocks_in_use: int = 0
    kv_blocks_total: int = 0
    kv_util: float = 0.0
    completed: int = 0
    ttft_p99_s: float | None = None
    dispatched: int = 0           # router dispatch episodes, lifetime
    inflight: int = 0             # router-side open episodes
    failures: int = 0             # up->down transitions observed
    last_seen: float = 0.0        # last successful scrape (monotonic)

    def load_key(self):
        """Least-loaded sort key (queue first, then KV pressure)."""
        return (
            self.queue_depth + self.active + self.inflight,
            self.kv_util,
            self.replica_id,
        )

    def doc(self) -> dict:
        return {
            "replica": self.replica_id,
            "url": self.url,
            "state": self.state,
            "queue_depth": self.queue_depth,
            "active_sequences": self.active,
            "kv_blocks_in_use": self.kv_blocks_in_use,
            "kv_blocks_total": self.kv_blocks_total,
            "kv_utilization": round(self.kv_util, 4),
            "requests_completed": self.completed,
            "ttft_p99_s": self.ttft_p99_s,
            "dispatched": self.dispatched,
            "inflight": self.inflight,
            "failures": self.failures,
        }


@dataclass(frozen=True)
class RouterConfig:
    poll_s: float = 0.5           # discovery + scrape cadence
    scrape_timeout_s: float = 2.0
    hb_stale_s: float = 5.0       # heartbeat age -> DOWN
    max_retries: int = 3          # failover episodes per request
    connect_timeout_s: float = 5.0
    drain_timeout_s: float = 60.0


def _hist_quantile(bucket_samples: dict, q: float):
    """Quantile from Prometheus cumulative ``_bucket`` samples
    ({label_key_tuple: count}); None when empty."""
    pts = []
    for key, count in bucket_samples.items():
        le = dict(key).get("le")
        if le is None:
            continue
        try:
            pts.append((float(le), count))
        except ValueError:
            continue
    pts.sort()
    if not pts or pts[-1][1] <= 0:
        return None
    total = pts[-1][1]
    rank = q * total
    for le, count in pts:
        if count >= rank:
            return None if math.isinf(le) else le
    return None


# --------------------------------------------------------------- router


class FleetRouter:
    """The fleet front door: same /v1/generate + /v1/status surface as
    a single replica, plus /v1/fleet (per-replica detail) and
    /v1/drain (graceful replica drain). `close()` stops the poll
    thread and the HTTP server."""

    def __init__(
        self,
        registry,
        *,
        watch_dir: str | None = None,
        replicas=(),
        cfg: RouterConfig | None = None,
        port: int = 0,
        host: str = "127.0.0.1",
    ):
        self.registry = registry
        self.cfg = cfg or RouterConfig()
        self.watch_dir = watch_dir
        self._lock = threading.Lock()
        self._replicas: dict[str, ReplicaState] = {}
        for rid, url in replicas:
            self._replicas[str(rid)] = ReplicaState(
                replica_id=str(rid), url=str(url).rstrip("/")
            )
        self._target = len(self._replicas)
        self._closed = threading.Event()
        r = registry
        self._m_requests = r.counter(
            "fleet_router_requests_total",
            "Router requests by terminal status (serve/fleet.py)",
        )
        self._m_retries = r.counter(
            "fleet_router_retries_total",
            "Failover re-dispatch episodes across all requests",
        )
        self._m_failures = r.counter(
            "fleet_replica_failures_total",
            "Replica up->down transitions the router observed",
        )
        self._m_dispatch = r.counter(
            "fleet_dispatch_total", "Dispatch episodes by replica"
        )
        self._m_replicas = r.gauge(
            "fleet_replicas", "Replica count by state"
        )
        self._m_target = r.gauge(
            "fleet_target_replicas", "Autoscaler target replica count"
        )
        self._m_actual = r.gauge(
            "fleet_actual_replicas", "UP (dispatchable) replica count"
        )
        self._m_r_queue = r.gauge(
            "fleet_replica_queue_depth", "Scraped queue depth per replica"
        )
        self._m_r_active = r.gauge(
            "fleet_replica_active_sequences",
            "Scraped decode-batch size per replica",
        )
        self._m_r_kv = r.gauge(
            "fleet_replica_kv_utilization",
            "Scraped paged-KV occupancy per replica",
        )
        self._m_r_up = r.gauge(
            "fleet_replica_up",
            "1 up / 0.5 draining / 0 down, per replica",
        )
        self.obs = ObsServer(
            registry,
            port=port,
            host=host,
            routes={
                ("POST", "/v1/generate"): self._generate,
                ("GET", "/v1/status"): self._status,
                ("GET", "/v1/fleet"): self._fleet,
                ("POST", "/v1/drain"): self._drain,
            },
        )
        self.port = self.obs.port
        self.url = self.obs.url
        self._poll_once()
        self._thread = threading.Thread(
            target=self._poll_loop, name="fleet-router-poll", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._closed.set()
        self._thread.join(timeout=5)
        self.obs.close()

    # ------------------------------------------------- discovery + scrape

    def set_target(self, n: int) -> None:
        """Autoscaler's declared target size (display + /v1/fleet)."""
        self._target = int(n)
        self._m_target.set(self._target)

    @property
    def target(self) -> int:
        return self._target

    def replicas(self) -> list[ReplicaState]:
        with self._lock:
            return list(self._replicas.values())

    def up_count(self) -> int:
        with self._lock:
            return sum(
                1 for r in self._replicas.values() if r.state == "up"
            )

    def _poll_loop(self) -> None:
        while not self._closed.wait(self.cfg.poll_s):
            try:
                self._poll_once()
            except Exception:
                pass  # discovery must never kill the router

    def _discover(self) -> None:
        """Fold heartbeat files (role == "serve") into the replica set.
        A restarted replica rewrites its stable per-rank file with a
        fresh PID + metrics URL, so re-registration is automatic."""
        if not self.watch_dir or not os.path.isdir(self.watch_dir):
            return
        for name in sorted(os.listdir(self.watch_dir)):
            if not name.endswith(".json"):
                continue
            path = os.path.join(self.watch_dir, name)
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            if doc.get("role") != "serve" or not doc.get("metrics_url"):
                continue
            rank = doc.get("rank")
            rid = f"rank{rank}" if rank is not None else name[:-5]
            fresh = (time.time() - float(doc.get("t") or 0)
                     ) <= self.cfg.hb_stale_s
            with self._lock:
                rep = self._replicas.get(rid)
                if rep is None:
                    rep = self._replicas[rid] = ReplicaState(
                        replica_id=rid, url="", hb_path=path
                    )
                rep.url = str(doc["metrics_url"]).rstrip("/")
                rep.hb_path = path
                if not fresh:
                    self._mark_down(rep)

    def _mark_down(self, rep: ReplicaState) -> None:
        """Caller holds the lock. Counts the up->down transition once."""
        if rep.state != "down":
            rep.failures += 1
            self._m_failures.inc()
        rep.state = "down"

    def _scrape_one(self, rep: ReplicaState) -> None:
        hb_fresh = True
        if rep.hb_path is not None:
            try:
                with open(rep.hb_path) as f:
                    doc = json.load(f)
                hb_fresh = (time.time() - float(doc.get("t") or 0)
                            ) <= self.cfg.hb_stale_s
            except (OSError, ValueError):
                hb_fresh = False
        try:
            with urllib.request.urlopen(
                rep.url + "/metrics", timeout=self.cfg.scrape_timeout_s
            ) as resp:
                samples = parse_prom_samples(resp.read().decode())
        except (OSError, ValueError):
            with self._lock:
                self._mark_down(rep)
            return

        def scalar(name, default=0.0):
            return next(iter(samples.get(name, {}).values()), default)

        with self._lock:
            if not hb_fresh:
                self._mark_down(rep)
                return
            rep.queue_depth = int(scalar("serve_queue_depth"))
            rep.active = int(scalar("serve_active_sequences"))
            rep.kv_blocks_in_use = int(scalar("serve_kv_blocks_in_use"))
            rep.kv_blocks_total = int(scalar("serve_kv_blocks_total"))
            rep.kv_util = (
                rep.kv_blocks_in_use / rep.kv_blocks_total
                if rep.kv_blocks_total else 0.0
            )
            rep.completed = int(
                samples.get("serve_requests_total", {}).get(
                    (("status", "completed"),), 0
                )
            )
            rep.ttft_p99_s = _hist_quantile(
                samples.get("serve_ttft_seconds_bucket", {}), 0.99
            )
            rep.state = (
                "draining" if scalar("serve_draining") > 0 else "up"
            )
            rep.last_seen = time.monotonic()

    def _poll_once(self) -> None:
        self._discover()
        for rep in self.replicas():
            self._scrape_one(rep)
        with self._lock:
            counts = {"up": 0, "draining": 0, "down": 0}
            for rep in self._replicas.values():
                counts[rep.state] = counts.get(rep.state, 0) + 1
                self._m_r_queue.labels(replica=rep.replica_id).set(
                    rep.queue_depth
                )
                self._m_r_active.labels(replica=rep.replica_id).set(
                    rep.active
                )
                self._m_r_kv.labels(replica=rep.replica_id).set(
                    rep.kv_util
                )
                self._m_r_up.labels(replica=rep.replica_id).set(
                    {"up": 1.0, "draining": 0.5}.get(rep.state, 0.0)
                )
            for state, n in counts.items():
                self._m_replicas.labels(state=state).set(n)
            self._m_actual.set(counts["up"])
            self._m_target.set(self._target)

    # ------------------------------------------------------------ dispatch

    def pick_replica(self, exclude=()) -> ReplicaState | None:
        """Least-loaded UP replica, preferring ones not in ``exclude``
        (already failed for this request); falls back to an excluded-
        but-up replica rather than failing a request that could run."""
        with self._lock:
            up = [
                r for r in self._replicas.values() if r.state == "up"
            ]
            fresh = [r for r in up if r.replica_id not in exclude]
            pool = fresh or up
            if not pool:
                return None
            return min(pool, key=ReplicaState.load_key)

    # -------------------------------------------------------------- routes

    def _status(self, handler) -> None:
        with self._lock:
            reps = list(self._replicas.values())
        _json_response(handler, 200, {
            "fleet": True,
            "replicas_up": sum(1 for r in reps if r.state == "up"),
            "replicas_draining": sum(
                1 for r in reps if r.state == "draining"
            ),
            "replicas_down": sum(1 for r in reps if r.state == "down"),
            "target_replicas": self._target,
            "active_sequences": sum(r.active for r in reps),
            "queued": sum(r.queue_depth for r in reps),
            "kv_blocks_in_use": sum(r.kv_blocks_in_use for r in reps),
            "kv_blocks_total": sum(r.kv_blocks_total for r in reps),
            "requests_completed": sum(r.completed for r in reps),
        })

    def _fleet(self, handler) -> None:
        with self._lock:
            reps = [r.doc() for r in self._replicas.values()]
        reps.sort(key=lambda d: d["replica"])
        _json_response(handler, 200, {
            "replicas": reps,
            "target_replicas": self._target,
            "actual_replicas": sum(
                1 for d in reps if d["state"] == "up"
            ),
            "router": {
                "requests_completed": int(
                    self._m_requests.labels(status="completed").value
                ),
                "retries_total": int(self._m_retries.value),
                "replica_failures": int(self._m_failures.value),
            },
        })

    def _drain(self, handler) -> None:
        """Orchestrate a graceful replica drain: proxy /v1/drain to the
        target (admission stops, live sequences emit migrate frames on
        their router-proxied streams and fail over automatically)."""
        try:
            n = int(handler.headers.get("Content-Length") or 0)
            body = json.loads(handler.rfile.read(n) or b"{}")
            rid = str(body.get("replica") or "")
        except (ValueError, UnicodeDecodeError):
            _json_response(handler, 400, {"error": "invalid JSON body"})
            return
        with self._lock:
            rep = self._replicas.get(rid)
        if rep is None:
            _json_response(handler, 404, {
                "error": f"unknown replica {rid!r}",
                "replicas": sorted(self._replicas),
            })
            return
        try:
            req = urllib.request.Request(
                rep.url + "/v1/drain", data=b"{}", method="POST"
            )
            with urllib.request.urlopen(
                req, timeout=self.cfg.drain_timeout_s
            ) as resp:
                doc = json.loads(resp.read())
        except (OSError, ValueError) as e:
            _json_response(handler, 502, {
                "error": f"drain of {rid} failed: {e}",
            })
            return
        with self._lock:
            rep.state = "draining"
        _json_response(handler, 200, doc)

    def drain_replica(self, rid: str) -> dict:
        """Programmatic drain (tools/serve_fleet.py scale-down path)."""
        with self._lock:
            rep = self._replicas.get(str(rid))
        if rep is None:
            raise KeyError(f"unknown replica {rid!r}")
        req = urllib.request.Request(
            rep.url + "/v1/drain", data=b"{}", method="POST"
        )
        with urllib.request.urlopen(
            req, timeout=self.cfg.drain_timeout_s
        ) as resp:
            doc = json.loads(resp.read())
        with self._lock:
            rep.state = "draining"
        return doc

    # --------------------------------------------------- generate (proxy)

    def _parse_client(self, handler):
        try:
            n = int(handler.headers.get("Content-Length") or 0)
        except ValueError:
            n = 0
        try:
            body = json.loads(handler.rfile.read(n) or b"{}")
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
        except (ValueError, UnicodeDecodeError) as e:
            raise ValueError(f"invalid JSON body: {e}")
        prompt = body.get("prompt")
        is_text = False
        if prompt is None and isinstance(body.get("text"), str):
            # byte-tokenize here so the replay prompt is always integer
            # tokens (the replica enforces vocab >= 256 and 400s for us)
            prompt = list(body["text"].encode())
            is_text = True
        if not isinstance(prompt, list) or not all(
            isinstance(t, int) for t in prompt
        ):
            raise ValueError(
                "body needs 'prompt': [int token ids] or 'text': str"
            )
        api_key = handler.headers.get("X-API-Key") or body.get("api_key")
        return {
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(body.get("max_new_tokens", 16)),
            "temperature": float(body.get("temperature", 0.0)),
            "seed": int(body.get("seed", 0)),
            "api_key": api_key,
            "stream": bool(body.get("stream", True)),
            "is_text": is_text,
        }

    def _open_episode(self, rep: ReplicaState, spec: dict,
                      streamed: list, retries: int, retry_s: float):
        """One upstream dispatch: POST /v1/generate with the replay
        body (original prompt + streamed tokens suppressed into the
        prompt; the remaining budget as max_new_tokens)."""
        body = {
            "prompt": spec["prompt"] + streamed,
            "max_new_tokens": spec["max_new_tokens"] - len(streamed),
            "temperature": spec["temperature"],
            "seed": spec["seed"],
            "stream": True,
        }
        if spec["api_key"] is not None:
            body["api_key"] = str(spec["api_key"])
        u = urlsplit(rep.url)
        conn = http.client.HTTPConnection(
            u.hostname, u.port, timeout=STREAM_TIMEOUT_S
        )
        headers = {
            "Content-Type": "application/json",
            "X-Router-Retries": str(retries),
            "X-Router-Retry-Seconds": f"{retry_s:.6f}",
        }
        if spec["api_key"] is not None:
            headers["X-API-Key"] = str(spec["api_key"])
        conn.request(
            "POST", "/v1/generate", body=json.dumps(body).encode(),
            headers=headers,
        )
        return conn, conn.getresponse()

    def _send_frame(self, handler, frame: dict) -> None:
        handler.wfile.write(f"data: {json.dumps(frame)}\n\n".encode())
        handler.wfile.flush()

    def _finish(self, handler, spec, frame, *, headers_sent) -> None:
        """Deliver the rewritten done frame (stream) or the single JSON
        body (non-stream)."""
        if spec["stream"]:
            if not headers_sent:
                self._send_stream_headers(handler)
            self._send_frame(handler, frame)
        else:
            _json_response(handler, 200, frame)

    def _send_stream_headers(self, handler) -> None:
        handler.send_response(200)
        handler.send_header("Content-Type", "text/event-stream")
        handler.send_header("Cache-Control", "no-store")
        handler.send_header("Connection", "close")
        handler.end_headers()

    def _done_frame(self, spec, streamed, upstream_done, rep,
                    retries, t_start) -> dict:
        """The client-facing summary: tokens are the FULL accumulated
        stream (failover suppressed duplicates upstream, so upstream's
        summary only covers the final episode's suffix)."""
        frame = dict(upstream_done or {})
        frame.update({
            "status": "done",
            "done": True,
            "prompt_len": len(spec["prompt"]),
            "tokens": list(streamed),
            "n_tokens": len(streamed),
            "total_s": round(time.monotonic() - t_start, 6),
            "replica": rep.replica_id if rep is not None else None,
            "router_retries": retries,
        })
        if spec["is_text"]:
            frame["text"] = bytes(
                t for t in streamed if 0 <= t < 256
            ).decode("utf-8", "replace")
        return frame

    def _generate(self, handler) -> None:
        try:
            spec = self._parse_client(handler)
        except ValueError as e:
            self._m_requests.labels(status="rejected").inc()
            _json_response(handler, 400, {
                "error": str(e), "reason": "bad_request",
            })
            return
        streamed: list[int] = []
        retries = 0          # completed failover episodes
        retry_s = 0.0        # wall seconds burned in failed episodes
        tried: set[str] = set()
        headers_sent = False
        t_start = time.monotonic()
        last_reject = None   # (status, doc) from a 4xx/503 upstream
        while True:
            rep = self.pick_replica(exclude=tried)
            if rep is None:
                break
            with self._lock:
                rep.dispatched += 1
                rep.inflight += 1
            self._m_dispatch.labels(replica=rep.replica_id).inc()
            t_ep = time.monotonic()
            conn = None
            failed = False
            migrated_ep = False
            upstream_done = None
            last_reject = None
            try:
                conn, resp = self._open_episode(
                    rep, spec, streamed, retries, retry_s
                )
                if resp.status == 400:
                    # malformed for ANY replica: forward, don't retry
                    doc = json.loads(resp.read() or b"{}")
                    self._m_requests.labels(status="rejected").inc()
                    if not headers_sent:
                        _json_response(handler, 400, doc)
                    return
                if resp.status != 200:
                    # 429 / 503 (draining): try the other replicas
                    last_reject = (
                        resp.status, json.loads(resp.read() or b"{}")
                    )
                    failed = True
                else:
                    for frame in self._read_frames(resp):
                        if "token" in frame:
                            streamed.append(int(frame["token"]))
                            if spec["stream"]:
                                if not headers_sent:
                                    self._send_stream_headers(handler)
                                    headers_sent = True
                                self._send_frame(handler, frame)
                        elif frame.get("done"):
                            upstream_done = frame
                            break
                        elif frame.get("migrated") or "error" in frame:
                            # drain migration or replica-side failure:
                            # both re-dispatch with streamed suppressed
                            failed = True
                            migrated_ep = bool(frame.get("migrated"))
                            break
                    else:
                        failed = True  # EOF without a terminal frame
            except (OSError, http.client.HTTPException, ValueError):
                failed = True
            finally:
                if conn is not None:
                    try:
                        conn.close()
                    except OSError:
                        pass
                with self._lock:
                    rep.inflight = max(rep.inflight - 1, 0)
            if not failed:
                frame = self._done_frame(
                    spec, streamed, upstream_done, rep, retries, t_start
                )
                try:
                    self._finish(
                        handler, spec, frame, headers_sent=headers_sent
                    )
                    self._m_requests.labels(status="completed").inc()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    self._m_requests.labels(status="client_gone").inc()
                return
            # episode failed -> bounded re-dispatch
            retry_s += time.monotonic() - t_ep
            tried.add(rep.replica_id)
            if last_reject is None and not migrated_ep:
                # a connection/stream failure (not a polite 429/503 or
                # a drain migration): distrust the replica until the
                # next scrape clears it
                with self._lock:
                    self._mark_down(rep)
            if len(streamed) >= spec["max_new_tokens"]:
                # died/migrated between the last token and the done
                # frame: the stream is already complete - synthesize
                frame = self._done_frame(
                    spec, streamed, {}, rep, retries, t_start
                )
                try:
                    self._finish(
                        handler, spec, frame, headers_sent=headers_sent
                    )
                    self._m_requests.labels(status="completed").inc()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    self._m_requests.labels(status="client_gone").inc()
                return
            if retries >= self.cfg.max_retries:
                break
            retries += 1
            self._m_retries.inc()
        # no replica completed the request
        self._m_requests.labels(status="error").inc()
        if headers_sent:
            try:
                self._send_frame(handler, {
                    "error": "no replica could complete the request "
                    f"(retries {retries}, streamed {len(streamed)})",
                })
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass
            return
        if last_reject is not None:
            status, doc = last_reject
            extra = (("Retry-After", "1"),) if status == 429 else ()
            _json_response(handler, status, doc, extra)
            return
        _json_response(handler, 503, {
            "error": "no replicas available",
            "reason": "no_replicas",
        })

    def _read_frames(self, resp):
        """SSE frames from an upstream response (generator); raises
        OSError family on transport failure, StopIteration semantics
        on EOF."""
        while True:
            line = resp.readline()
            if not line:
                return
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            try:
                yield json.loads(line[len(b"data: "):])
            except ValueError:
                return


# -------------------------------------------- SLO readout + autoscaling
#
# The percentile decomposition moved to serve/reqtrace.py (`decompose`)
# so the serve-mode digital twin (analysis/fleetsim.py) judges its
# simulated records with the same arithmetic; the old underscore names
# stay as aliases for in-repo callers and tests.

from .reqtrace import clipped_causes as _clipped_causes  # noqa: E402
from .reqtrace import decompose as _decompose  # noqa: E402
from .reqtrace import percentile as _percentile  # noqa: E402


def slo_readout(records: list, slo: dict) -> dict:
    """Dominant-cause SLO gates over fleet-merged per-request records
    (the ``recent`` lists of each replica's ``/v1/requests?full=1``).
    ``slo`` maps keys like ``ttft_p99`` to limit seconds; each gate in
    the result carries value/limit/violated/dominant/shares - the
    autoscaler's input (mirrors tools/request_trace.py decompose)."""
    out = {}
    for key, limit in slo.items():
        if key not in SLO_KEYS:
            raise ValueError(
                f"unknown SLO key {key!r} (choose from {SLO_KEYS})"
            )
        metric, _, pq = key.partition("_p")
        d = _decompose(records, metric, int(pq) / 100.0)
        if d is None:
            out[key] = {
                "value": None, "limit": float(limit),
                "violated": False, "dominant": None, "shares": {},
            }
            continue
        out[key] = {
            "value": d["value"],
            "limit": float(limit),
            "violated": d["value"] > float(limit),
            "dominant": d["dominant"],
            "shares": d["shares"],
        }
    return out


def autoscale_decision(
    *,
    actual: int,
    min_replicas: int,
    max_replicas: int,
    queue_depth: int = 0,
    queue_high: int = 8,
    gates: dict | None = None,
    idle_s: float = 0.0,
    scale_down_idle_s: float = 60.0,
) -> dict:
    """The pure autoscaling policy (tools/serve_fleet.py runs it on a
    timer; tests pin it directly). Returns ``{"action": "scale_up" |
    "scale_down" | "hold", "target": n, "reason": str}``.

    The PR 14 dominant-cause taxonomy does the triage: a queue_wait-
    dominant SLO violation means requests are waiting for a SLOT -
    another replica fixes that; a kv_alloc_stall-dominant violation
    means sequences stall on KV BLOCKS - another replica leaves the
    per-replica pool just as undersized, so the decision is HOLD with
    add-KV-capacity advice, never a futile scale-up."""
    gates = gates or {}
    violated = {
        k: g for k, g in gates.items() if g.get("violated")
    }
    queue_dom = [
        k for k, g in violated.items()
        if g.get("dominant") == "queue_wait"
    ]
    kv_dom = [
        k for k, g in violated.items()
        if g.get("dominant") == "kv_alloc_stall"
    ]
    if queue_dom:
        if actual < max_replicas:
            return {
                "action": "scale_up", "target": actual + 1,
                "reason": "queue_wait-dominant SLO violation "
                f"({', '.join(sorted(queue_dom))})",
            }
        return {
            "action": "hold", "target": actual,
            "reason": "queue_wait-dominant SLO violation but already "
            f"at max_replicas={max_replicas}",
        }
    if kv_dom:
        return {
            "action": "hold", "target": actual,
            "reason": "kv_alloc_stall-dominant SLO violation "
            f"({', '.join(sorted(kv_dom))}): add KV capacity "
            "(--num-blocks / int8-kv), replicas won't help",
        }
    if queue_depth >= queue_high:
        if actual < max_replicas:
            return {
                "action": "scale_up", "target": actual + 1,
                "reason": f"queue depth {queue_depth} >= {queue_high}",
            }
        return {
            "action": "hold", "target": actual,
            "reason": f"queue depth {queue_depth} but already at "
            f"max_replicas={max_replicas}",
        }
    if idle_s >= scale_down_idle_s and actual > min_replicas:
        return {
            "action": "scale_down", "target": actual - 1,
            "reason": f"idle {idle_s:.0f}s >= {scale_down_idle_s:.0f}s",
        }
    return {"action": "hold", "target": actual, "reason": "steady"}


# ------------------------------------------------- fleet serve records


def collect_records(replica_urls) -> list:
    """Fleet-merged finalized per-request records: each replica's
    ``/v1/requests?full=1`` ``recent`` list, concatenated (unreachable
    replicas are skipped - dead replicas can't report)."""
    out: list = []
    for url in replica_urls:
        try:
            with urllib.request.urlopen(
                str(url).rstrip("/") + "/v1/requests?full=1", timeout=10
            ) as resp:
                doc = json.loads(resp.read())
        except (OSError, ValueError):
            continue
        out.extend(
            r for r in (doc.get("recent") or [])
            if isinstance(r.get("spans"), list)
        )
    return out


def aggregate_serve_records(records: list) -> dict:
    """Fold per-replica serving goodput records (`utils/goodput.py`
    taxonomy "serve") into one fleet record. Conservation is asserted
    per input AND on the aggregate: goodput + badput buckets must sum
    to wall-clock within tolerance - the bench gate's honesty rail."""
    if not records:
        raise ValueError("no serve records to aggregate")
    wall = good = 0.0
    bad: dict = {}
    for rec in records:
        if rec.get("taxonomy") != "serve":
            raise ValueError(
                f"record taxonomy {rec.get('taxonomy')!r} != 'serve'"
            )
        w = float(rec.get("wall_s") or 0.0)
        g = float(rec.get("goodput_s") or 0.0)
        b = {
            k: float(v) for k, v in (rec.get("badput_s") or {}).items()
        }
        attributed = g + sum(b.values())
        if abs(attributed - w) > max(1e-3 * max(w, 1.0), 1e-6):
            raise AssertionError(
                "serve record conservation violated: "
                f"{attributed:.6f}s attributed over {w:.6f}s wall "
                f"(rank={rec.get('rank')}, pid={rec.get('pid')})"
            )
        wall += w
        good += g
        for k, v in b.items():
            bad[k] = bad.get(k, 0.0) + v
    return {
        "taxonomy": "serve",
        "kind": "fleet",
        "replicas": len(records),
        "wall_s": round(wall, 6),
        "goodput_s": round(good, 6),
        "goodput_ratio": round(good / wall, 6) if wall > 0 else None,
        "badput_s": {k: round(v, 6) for k, v in sorted(bad.items())},
    }
