"""Production inference service: continuous batching over a paged KV
cache, streamed over HTTP (ROADMAP item 1, the "millions of users"
pillar).

Layers, bottom up:

- `kv_cache.py`  - the block/paged KV-cache allocator: fixed-size blocks
  out of one shared device pool, a block table per sequence, so
  thousands of concurrent mixed-length sequences share device memory
  without per-request max-seq allocation.
- `engine.py`    - the model-executing engine: one jitted decode step
  per (batch, table-width) bucket that consumes exactly one token per
  active slot - continuous (in-flight) batching falls out, sequences
  join at any step boundary and retire without draining - plus a
  chunked-prefill fast path so long prompts cannot starve decode.
- `scheduler.py` - admission control (bounded queue -> 429), per-tenant
  token-bucket fairness, the serve loop, and the serving goodput ledger
  (queue_wait / prefill / decode / batch_formation_idle /
  kv_alloc_stall - `utils/goodput.py` taxonomy "serve").
- `reqtrace.py`  - per-request lifecycle tracing: every request
  event-sourced through a closed cause taxonomy (queue_wait /
  admission / prefill / decode / kv_alloc_stall / preempted_wait /
  stream_write) with span conservation asserted; exported via
  `GET /v1/requests`, Chrome trace lanes, and
  `tools/request_trace.py` (tail attribution + SLO gates).
- `http.py`      - the HTTP face: `POST /v1/generate` with
  server-sent-event token streaming on the ObsServer route surface
  (`/metrics` + `/healthz` come with it), `GET /v1/status` /
  `GET /v1/requests`, and the `python -m
  distributed_neural_network_tpu.serve` CLI.

docs/SERVING.md covers architecture, batching semantics, the KV-block
math, the ledger taxonomy, per-request tracing, and the load-generator
workflow (tools/loadgen.py).
"""

from .engine import EngineConfig, ServeEngine, Sequence  # noqa: F401
from .kv_cache import KVCacheConfig, OutOfBlocks, PagedKVCache  # noqa: F401
from .reqtrace import (  # noqa: F401
    REQUEST_CAUSES,
    RequestRecord,
    RequestTraceRecorder,
)
from .scheduler import (  # noqa: F401
    AdmissionError,
    SchedulerConfig,
    ServeRequest,
    ServeScheduler,
)
