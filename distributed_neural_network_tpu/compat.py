"""Version shims for the jax APIs the compiled paths target.

The framework's shard_map programs are written against the modern API
surface (``jax.shard_map`` with vma-typed autodiff, ``jax.lax.axis_size``).
Older jax builds (pre-``jax.shard_map``; seen in CI containers at 0.4.x)
carry the experimental predecessor, whose *execution* semantics differ in a
way that matters here: without vma typing there is no typed-autodiff
gradient psum and no ``pcast``, so the grad-sync schedules would run with
silently different numerics. Running training on such a build is therefore
refused, exactly as before this module existed (an ``AttributeError``
naming ``jax.shard_map``).

What IS supported everywhere is *abstract tracing*: the static analyzer
(``distributed_neural_network_tpu.analysis``, tools/shardlint.py) only
needs ``jax.make_jaxpr`` of the step program, never an executed step. Under
``trace_compat()`` the builders fall back to
``jax.experimental.shard_map.shard_map(check_rep=False)`` so the program
can be traced and its collectives/donation audited on any jax. Manifests
record which mode produced them (``trace_mode``), because the traced
program differs across jax generations (pre-vma traces carry no implicit
typed-autodiff psums - see docs/STATIC_ANALYSIS.md).
"""

from __future__ import annotations

import contextlib
import os
import threading

import jax

HAS_NATIVE_SHARD_MAP = hasattr(jax, "shard_map")

_tls = threading.local()


def trace_compat_enabled() -> bool:
    """True when the experimental-shard_map trace fallback may be used
    (inside a ``trace_compat()`` block, or DNN_TPU_SHARDMAP_COMPAT=1)."""
    if getattr(_tls, "trace_compat", False):
        return True
    return os.environ.get("DNN_TPU_SHARDMAP_COMPAT", "") == "1"


@contextlib.contextmanager
def trace_compat():
    """Allow step BUILDERS to fall back to the experimental shard_map.

    For ``jax.make_jaxpr``-style abstract analysis only - never wrap an
    executed training step in this (on pre-vma jax the fallback's autodiff
    inserts no typed gradient psums, so executing it would train with
    different numerics than the modern program)."""
    prev = getattr(_tls, "trace_compat", False)
    _tls.trace_compat = True
    try:
        yield
    finally:
        _tls.trace_compat = prev


def trace_mode() -> str:
    """'native' when jax.shard_map exists, else 'compat' (the experimental
    fallback without vma typing) - recorded in shardlint manifests."""
    return "native" if HAS_NATIVE_SHARD_MAP else "compat"


def shard_map(fn, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` on modern jax; the experimental predecessor only
    under ``trace_compat()`` (abstract tracing), else the same
    ``AttributeError`` a direct ``jax.shard_map`` access would raise."""
    if HAS_NATIVE_SHARD_MAP:
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    if trace_compat_enabled():
        from jax.experimental.shard_map import shard_map as _shard_map

        # check_rep=False: the old replication checker cannot infer the
        # replication the vma-typed program relies on (no typed-autodiff
        # psum exists to prove it), so checking is off for trace-compat
        return _shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )
    raise AttributeError(
        "module 'jax' has no attribute 'shard_map': this jax build "
        f"({jax.__version__}) predates the vma-typed shard_map the "
        "compiled training paths require. Static analysis still works - "
        "build the step inside "
        "distributed_neural_network_tpu.compat.trace_compat() (what "
        "tools/shardlint.py does) - but executing a step needs a modern jax."
    )


def axis_size(axis_name) -> int:
    """Static mesh-axis size inside shard_map: ``jax.lax.axis_size`` where
    it exists, else the classic ``psum(1, axis)`` constant-fold."""
    lax_axis_size = getattr(jax.lax, "axis_size", None)
    if lax_axis_size is not None:
        return lax_axis_size(axis_name)
    return jax.lax.psum(1, axis_name)
