"""Live runtime observability: in-process metrics registry + HTTP endpoint.

Everything observability-grade before this module was post-hoc: Chrome
traces (`utils/tracing.py`), StepStats summaries, and metrics JSONL are
only inspectable after the run exits. Production training fleets are
monitored LIVE - per-host health endpoints, scrapeable metrics, stall
detection (the pjit-at-scale training infrastructure, arxiv 2204.06514,
treats fleet health monitoring and fast fault localization as
load-bearing). This module is that layer:

- ``MetricsRegistry`` - counters, gauges, and histograms with labels,
  rendered as Prometheus text exposition (format 0.0.4). The fast path is
  lock-free by construction: callers resolve a metric child ONCE at wiring
  time (``registry.counter(...).labels(...)`` cached in a closure/attr)
  and each publish is then a single float add/store - no dict lookup, no
  lock. Locks exist only around child creation and ``render()``.
- ``NULL_REGISTRY`` - the no-op default every instrumented path carries
  (mirroring ``tracing.NULL_TRACER``): with no ``--metrics-port`` the
  whole layer costs one attribute call per publish site.
- ``ObsServer`` - a daemon-thread HTTP server exposing ``/metrics``
  (Prometheus text), ``/healthz`` (JSON liveness/readiness: liveness =
  heartbeat age under a threshold, readiness = the first step - i.e. XLA
  compilation - has completed), and ``/profile?steps=N`` (on-demand
  `jax.profiler` capture via `train/monitor.py ProfileController`).
  Port 0 binds an ephemeral port; ``.port`` reports what the OS chose.
- heartbeat plumbing - ``registry.beat(step)`` records (time, step) and
  the recent beat-interval window the stall watchdog
  (`train/monitor.py`) sizes its detection threshold from;
  ``begin_step(step)`` marks step STARTS, the fleet federation's
  wedge-attribution signal (`train/supervisor.py`).
- ``FlightRecorder`` / ``flight_event()`` - the crash flight recorder:
  a bounded ring of structured anomaly/lifecycle events with an atomic
  write-through dump that survives SIGKILL, bundled per rank into the
  supervisor's ``postmortem.json`` (docs/OBSERVABILITY.md "Fleet
  observability"). Guard anomalies carry the training-dynamics
  provenance (`layer=` - the first layer whose gradients went
  non-finite, train/dynamics.py), and watchdog stall events carry the
  last model-health gauges, so a postmortem answers "was the model sick
  when it died" without the JSONL stream.

The training-dynamics observatory (train/dynamics.py) publishes its
model-health gauges here too: ``dynamics_grad_norm`` /
``dynamics_param_norm`` / ``dynamics_upd_ratio_max``, per-layer
``dynamics_layer_{grad_norm,upd_ratio}{layer=...}``, the noise-scale
pair ``dynamics_gns_noise_scale`` / ``dynamics_crit_batch_size``, the
engine's ``dynamics_replica_div_{mean,max}``, and the guard's
``guard_spike_zscore`` headroom gauge (docs/OBSERVABILITY.md "Training
dynamics").

Stdlib-only (no jax import), so the registry and server work on any host
- including the dashboard/test side (`tools/live_top.py`).
"""

from __future__ import annotations

import http.server
import json
import math
import os
import socket
import threading
import time
import urllib.parse
from collections import deque

# env var naming the per-worker flight-recorder dump file; the elastic
# supervisor (train/supervisor.py) exports it next to the heartbeat file
# so every supervised worker's last-seconds event ring survives even a
# SIGKILL (write-through) and lands in the postmortem bundle
FLIGHT_ENV = "DNN_TPU_FLIGHT_FILE"
# env var naming the per-worker goodput run record (the third supervisor-
# exported write-through channel; `utils/goodput.py` owns the value -
# re-exported here so the env-var surface reads in one place)
RUN_RECORD_ENV = "DNN_TPU_RUN_RECORD"

# default histogram bucket bounds (seconds) for step-time histograms:
# spans 1 ms compiled CPU smoke steps to multi-minute fused spans
DEFAULT_TIME_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)

_NAME_OK = set("abcdefghijklmnopqrstuvwxyz"
               "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:")


def _check_name(name: str) -> str:
    if not name or name[0].isdigit() or not set(name) <= _NAME_OK:
        raise ValueError(
            f"invalid Prometheus metric/label name {name!r} "
            "(use [a-zA-Z_:][a-zA-Z0-9_:]*)"
        )
    return name


def _fmt_value(v: float) -> str:
    """Prometheus sample value: integers render bare, floats via repr,
    non-finite as +Inf/-Inf/NaN (legal in the exposition format)."""
    if isinstance(v, float):
        if math.isnan(v):
            return "NaN"
        if math.isinf(v):
            return "+Inf" if v > 0 else "-Inf"
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return repr(v)
    return str(v)


def _fmt_labels(labels: tuple) -> str:
    if not labels:
        return ""
    esc = lambda s: str(s).replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n"
    )
    return "{" + ",".join(f'{k}="{esc(v)}"' for k, v in labels) + "}"


class _Child:
    """One (metric, label-set) sample. Publishing is a plain float
    attribute update - resolve the child once, then every ``inc``/``set``
    is lock-free (CPython attribute stores are atomic; a lost increment
    under a torn race would be a sub-sample error in a monitoring counter,
    which the render-side lock does not need to prevent)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount

    def set(self, value: float) -> None:
        self.value = float(value)

    def set_max(self, value: float) -> None:
        """Monotonic set: only moves forward (republishing accumulated
        totals - e.g. phase_seconds_total - can never regress a counter)."""
        v = float(value)
        if v > self.value:
            self.value = v


class _HistChild:
    """Histogram sample: fixed bucket bounds, cumulative counts on render."""

    __slots__ = ("bounds", "counts", "sum", "count", "_lock")

    def __init__(self, bounds):
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # last = +Inf overflow
        self.sum = 0.0
        self.count = 0
        # observe() mutates three fields; a tiny lock keeps render()'s
        # cumulative math consistent (observe is not the per-step hot
        # path's inner loop - one call per step)
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        i = len(self.bounds)
        for j, b in enumerate(self.bounds):
            if v <= b:
                i = j
                break
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1

    def quantile(self, q: float) -> float | None:
        """Approximate quantile from bucket counts (upper bound of the
        bucket containing the q-th observation); None when empty. Used by
        the watchdog and dashboard, not by Prometheus (which computes
        histogram_quantile server-side)."""
        with self._lock:
            counts = list(self.counts)
            total = self.count
        if not total:
            return None
        target = q * total
        acc = 0
        for j, c in enumerate(counts):
            acc += c
            if acc >= target:
                return (
                    self.bounds[j] if j < len(self.bounds)
                    else self.bounds[-1]
                )
        return self.bounds[-1]


class _Metric:
    def __init__(self, name, help_, kind, buckets=None):
        self.name = _check_name(name)
        self.help = help_
        self.kind = kind
        self.buckets = buckets
        self._children: dict[tuple, object] = {}
        self._lock = threading.Lock()

    def labels(self, **labels):
        for k in labels:
            _check_name(k)
        key = tuple(sorted(labels.items()))
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = (
                        _HistChild(self.buckets)
                        if self.kind == "histogram" else _Child()
                    )
                    self._children[key] = child
        return child

    # label-less convenience: metric.inc()/set()/observe() act on the
    # empty-label child (resolved once, cached on the instance)
    def _default(self):
        d = self.__dict__.get("_default_child")
        if d is None:
            d = self.__dict__["_default_child"] = self.labels()
        return d

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def set(self, value: float) -> None:
        self._default().set(value)

    def set_max(self, value: float) -> None:
        self._default().set_max(value)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    def quantile(self, q: float):
        return self._default().quantile(q)

    @property
    def value(self) -> float:
        return self._default().value

    def render(self) -> list[str]:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.kind}",
        ]
        with self._lock:
            children = list(self._children.items())
        for key, child in sorted(children):
            if self.kind == "histogram":
                with child._lock:
                    counts = list(child.counts)
                    s, n = child.sum, child.count
                acc = 0
                for j, b in enumerate(child.bounds):
                    acc += counts[j]
                    lines.append(
                        f"{self.name}_bucket"
                        f"{_fmt_labels(key + (('le', _fmt_value(float(b))),))}"
                        f" {acc}"
                    )
                lines.append(
                    f"{self.name}_bucket"
                    f"{_fmt_labels(key + (('le', '+Inf'),))} {n}"
                )
                lines.append(
                    f"{self.name}_sum{_fmt_labels(key)} {_fmt_value(s)}"
                )
                lines.append(f"{self.name}_count{_fmt_labels(key)} {n}")
            else:
                lines.append(
                    f"{self.name}{_fmt_labels(key)} "
                    f"{_fmt_value(child.value)}"
                )
        return lines


class MetricsRegistry:
    """Metric factory + heartbeat state + Prometheus text renderer.

    ``counter``/``gauge``/``histogram`` are idempotent by name (the same
    metric object comes back, so independent modules can wire the same
    series without coordination); a kind mismatch on an existing name
    raises - two subsystems silently sharing a name with different types
    is exactly the bug a registry exists to catch.
    """

    def __init__(self, *, beat_window: int = 64):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()
        self.started_unix = time.time()
        # heartbeat state (read by /healthz and the watchdog)
        self._beat_lock = threading.Lock()
        self._last_beat: float | None = None
        self._last_step: int | None = None
        self._last_begin: int | None = None
        self._intervals: deque[float] = deque(maxlen=beat_window)
        self.ready = False
        self._ready_unix: float | None = None
        # optional per-beat callback (step) - the step-boundary hook both
        # training loops already drive via beat(); the on-demand profiler
        # (train/monitor.py ProfileController) rides it so no step-loop
        # signature changes are needed. Exceptions are swallowed: a hook
        # bug must never kill a training step.
        self.beat_hook = None

    # ------------------------------------------------------------ metrics

    def _get(self, name, help_, kind, buckets=None) -> _Metric:
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    m = _Metric(name, help_, kind, buckets)
                    self._metrics[name] = m
        if m.kind != kind:
            raise ValueError(
                f"metric {name!r} already registered as {m.kind}, "
                f"requested {kind}"
            )
        return m

    def counter(self, name: str, help: str = "") -> _Metric:
        return self._get(name, help, "counter")

    def gauge(self, name: str, help: str = "") -> _Metric:
        return self._get(name, help, "gauge")

    def histogram(
        self, name: str, help: str = "",
        buckets=DEFAULT_TIME_BUCKETS,
    ) -> _Metric:
        return self._get(name, help, "histogram", tuple(buckets))

    def get(self, name: str) -> _Metric | None:
        return self._metrics.get(name)

    # ---------------------------------------------------------- heartbeat

    def beat(self, step: int | None = None) -> None:
        """One liveness heartbeat (call at each step boundary). Records
        the interval since the previous beat - the window the watchdog
        derives its stall threshold (N x steady p95) from."""
        now = time.time()
        with self._beat_lock:
            if self._last_beat is not None:
                self._intervals.append(now - self._last_beat)
            self._last_beat = now
            if step is not None:
                self._last_step = int(step)
        hook = self.beat_hook
        if hook is not None:
            try:
                hook(step)
            except Exception:
                pass

    def begin_step(self, step: int) -> None:
        """Mark step ``step`` as STARTED (called before the dispatch,
        where ``beat`` marks completion). The begin/beat pair is the
        fleet straggler-attribution channel for synchronized SPMD
        groups: a rank wedged host-side never begins step S+1 while its
        peers (blocked in the collective, steps already dispatched)
        have - so begin-step divergence names the guilty rank even
        though every rank's COMPLETION is delayed equally
        (`train/supervisor.py FleetFederation`)."""
        with self._beat_lock:
            self._last_begin = int(step)

    def last_begin_step(self) -> int | None:
        with self._beat_lock:
            return self._last_begin

    def mark_ready(self) -> None:
        """Flip readiness (first compiled step completed). /healthz
        reports ready=false until then, so a scraper can tell 'still
        compiling' from 'serving but stalled'."""
        if not self.ready:
            self.ready = True
            self._ready_unix = time.time()

    def heartbeat_age(self) -> float | None:
        with self._beat_lock:
            if self._last_beat is None:
                return None
            return time.time() - self._last_beat

    def last_step(self) -> int | None:
        with self._beat_lock:
            return self._last_step

    def beat_intervals(self) -> list[float]:
        with self._beat_lock:
            return list(self._intervals)

    def health(self, *, stall_after_s: float = 300.0) -> dict:
        """The /healthz JSON body. ``alive`` = a heartbeat arrived within
        ``stall_after_s`` (or none expected yet - a run still compiling
        step 0 is alive, just not ready)."""
        age = self.heartbeat_age()
        return {
            "alive": age is None or age < stall_after_s,
            "ready": self.ready,
            "heartbeat_age_s": round(age, 3) if age is not None else None,
            "step": self.last_step(),
            "uptime_s": round(time.time() - self.started_unix, 3),
            "ready_unix": self._ready_unix,
        }

    # -------------------------------------------------------------- render

    def render(self) -> str:
        """Prometheus text exposition (0.0.4) of every registered metric
        plus the heartbeat/readiness gauges."""
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in sorted(metrics, key=lambda m: m.name):
            lines.extend(m.render())
        with self._beat_lock:
            beat, step = self._last_beat, self._last_step
        lines.append("# HELP process_start_time_seconds Unix start time")
        lines.append("# TYPE process_start_time_seconds gauge")
        lines.append(
            f"process_start_time_seconds {_fmt_value(self.started_unix)}"
        )
        lines.append("# HELP train_ready 1 once the first step compiled")
        lines.append("# TYPE train_ready gauge")
        lines.append(f"train_ready {1 if self.ready else 0}")
        if beat is not None:
            lines.append(
                "# HELP train_heartbeat_timestamp_seconds Unix time of "
                "the last step heartbeat"
            )
            lines.append("# TYPE train_heartbeat_timestamp_seconds gauge")
            lines.append(
                f"train_heartbeat_timestamp_seconds {_fmt_value(beat)}"
            )
        if step is not None:
            lines.append("# HELP train_heartbeat_step Last heartbeat step")
            lines.append("# TYPE train_heartbeat_step gauge")
            lines.append(f"train_heartbeat_step {step}")
        return "\n".join(lines) + "\n"


class _NullMetric:
    """No-op metric/child: every method swallows its arguments."""

    __slots__ = ()
    value = 0.0

    def labels(self, **labels):
        return self

    def inc(self, amount: float = 1.0) -> None: ...

    def set(self, value: float) -> None: ...

    def set_max(self, value: float) -> None: ...

    def observe(self, value: float) -> None: ...

    def quantile(self, q: float):
        return None

    def render(self):
        return []


_NULL_METRIC = _NullMetric()


class NullRegistry:
    """The disabled registry (mirrors tracing.NULL_TRACER): one shared
    no-op metric for every name, no state, nothing rendered."""

    ready = False

    def counter(self, name: str, help: str = "") -> _NullMetric:
        return _NULL_METRIC

    def gauge(self, name: str, help: str = "") -> _NullMetric:
        return _NULL_METRIC

    def histogram(self, name: str, help: str = "", buckets=()) -> _NullMetric:
        return _NULL_METRIC

    def get(self, name: str):
        return None

    def beat(self, step: int | None = None) -> None: ...

    def begin_step(self, step: int) -> None: ...

    def last_begin_step(self):
        return None

    def mark_ready(self) -> None: ...

    def heartbeat_age(self):
        return None

    def last_step(self):
        return None

    def beat_intervals(self):
        return []

    def health(self, *, stall_after_s: float = 300.0) -> dict:
        return {"alive": True, "ready": False, "heartbeat_age_s": None,
                "step": None, "uptime_s": 0.0, "ready_unix": None}

    def render(self) -> str:
        return ""


NULL_REGISTRY = NullRegistry()


class HeartbeatFileWriter:
    """Daemon thread mirroring a registry's heartbeat state into a small
    JSON file - the per-worker liveness channel the elastic supervisor
    (`train/supervisor.py`) monitors across the process boundary.

    Schema (all the supervisor's failure detection and chaos step
    triggers need): ``{"t": <writer wall time>, "beat_unix": <last
    training-step heartbeat or null while compiling>, "step": <last
    heartbeat step or null>, "pid": ..., "rank": <process rank or null>,
    "hostname": ..., "metrics_url": <this worker's /metrics base URL or
    null>}``. ``rank``/``hostname`` make attribution survive file
    relocation (the supervisor used to infer rank from the file PATH
    alone); ``metrics_url`` is the federation handshake - the
    supervisor's scraper (`train/supervisor.py FleetFederation`) learns
    each worker's endpoint from here instead of any port convention.
    Old files without the new keys stay parseable (readers ``.get``).
    Written atomically (tmp + rename) every ``interval_s`` so a reader
    never sees a torn file; the file's very existence doubles as the
    worker's "rendezvous done" signal (the writer is attached after
    `parallel/distributed.py initialize()` succeeded).
    """

    def __init__(
        self, registry, path: str, *, interval_s: float = 0.5,
        rank: int | None = None, hostname: str | None = None,
        metrics_url: str | None = None, role: str | None = None,
    ):
        self.registry = registry
        self.path = os.path.abspath(path)
        self.interval_s = float(interval_s)
        # "serve" marks fleet-router discovery targets (serve/fleet.py
        # only dispatches to heartbeats advertising role == "serve")
        self.role = role
        if rank is None:
            env_rank = os.environ.get("JAX_PROCESS_ID")
            try:
                rank = int(env_rank) if env_rank is not None else None
            except ValueError:
                rank = None
        self.rank = rank
        self.hostname = hostname if hostname is not None else _hostname()
        self.metrics_url = metrics_url
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="heartbeat-file", daemon=True
        )
        self._write()  # the rendezvous-done marker, before the first tick
        self._thread.start()

    def _write(self) -> None:
        age = self.registry.heartbeat_age()
        doc = {
            "t": time.time(),
            "beat_unix": (time.time() - age) if age is not None else None,
            "step": self.registry.last_step(),
            "begin_step": self.registry.last_begin_step(),
            "pid": os.getpid(),
            "rank": self.rank,
            "hostname": self.hostname,
            "metrics_url": self.metrics_url,
            "role": self.role,
        }
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, self.path)
        except OSError:
            pass  # a full disk must never kill the training loop

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._write()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
        self._write()  # final state (the supervisor sees the last step)


def publish_phase_timers(registry, timers) -> None:
    """Export `utils/timers.py PhaseTimers` totals as
    ``phase_seconds_total{phase=...}`` - the reference's five epoch-phase
    accumulators, visible on /metrics instead of only in log/*.txt.
    Monotonic (`set_max`): totals only accumulate, so republishing after
    each epoch can never regress the counter."""
    c = registry.counter(
        "phase_seconds_total",
        "Accumulated wall-clock per phase (utils/timers.py)",
    )
    for phase, seconds in timers.summary().items():
        c.labels(phase=phase).set_max(seconds)


# --------------------------------------------------------- flight recorder


def _hostname() -> str:
    try:
        return socket.gethostname()
    except OSError:  # pragma: no cover - defensive
        return "unknown"


def _json_safe(x):
    """Sanitize a flight event for strict JSON: non-finite floats become
    None, anything non-serializable becomes its repr."""
    if isinstance(x, float):
        return x if math.isfinite(x) else None
    if isinstance(x, dict):
        return {str(k): _json_safe(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_json_safe(v) for v in x]
    if isinstance(x, (str, int, bool)) or x is None:
        return x
    return repr(x)


class FlightRecorder:
    """Crash flight recorder: a bounded in-memory ring of structured
    events (guard anomalies, watchdog flags, chaos/elastic events,
    checkpoint saves, recompiles, preemptions) with an atomic
    write-through dump.

    The design constraint is the SIGKILL case: a hard-killed worker gets
    no exit path, so the last-seconds record must already be on disk.
    Events are therefore LOW-RATE by contract (step-boundary anomalies
    and lifecycle transitions, never per-step hot-path publishes), which
    makes write-through affordable: every ``record()`` on a configured
    recorder rewrites the dump file atomically (tmp + rename, same idiom
    as `HeartbeatFileWriter`), so the file on disk is always the complete
    current ring. The elastic supervisor points each worker at a dump
    path via ``DNN_TPU_FLIGHT_FILE`` (`FLIGHT_ENV`) and bundles the
    per-rank dumps plus exit causes into ``postmortem.json`` on any
    failure restart or SUPERVISOR ABORT (`train/supervisor.py`).

    Unconfigured (no path - the default), the ring still records in
    memory: one deque append per event, dumpable on demand. The
    module-level ``FLIGHT`` singleton is the process's recorder; call
    sites use ``flight_event(kind, step=..., **fields)``.
    """

    def __init__(self, capacity: int = 256):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._events: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.dropped = 0
        self.path: str | None = None
        self.rank: int | None = None
        self.hostname = _hostname()
        self.started_unix = time.time()

    def configure(
        self, path: str, *, rank: int | None = None,
        hostname: str | None = None,
    ) -> None:
        """Arm write-through dumping to ``path`` (created on first event;
        an immediate dump marks the recorder live)."""
        self.path = os.path.abspath(path)
        if rank is not None:
            self.rank = int(rank)
        elif self.rank is None:
            env_rank = os.environ.get("JAX_PROCESS_ID")
            try:
                self.rank = int(env_rank) if env_rank is not None else None
            except ValueError:
                self.rank = None
        if hostname is not None:
            self.hostname = hostname
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self.dump()

    def record(self, kind: str, /, *, step: int | None = None,
               **fields) -> dict:
        """Append one structured event (and write through when armed).
        ``kind`` is positional-only so a field may also be named kind;
        the reserved keys (t/kind) shadow rather than being shadowed."""
        ev = {"t": round(time.time(), 3), "kind": str(kind)}
        if step is not None:
            ev["step"] = int(step)
        for k, v in fields.items():
            k = str(k)
            if k in ("t", "kind"):
                k = f"arg_{k}"
            ev[k] = _json_safe(v)
        with self._lock:
            if len(self._events) == self.capacity:
                self.dropped += 1
            self._events.append(ev)
        if self.path is not None:
            self.dump()
        return ev

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def snapshot(self, *, cause: str | None = None) -> dict:
        """The dump document (schema: docs/OBSERVABILITY.md)."""
        with self._lock:
            events = list(self._events)
            dropped = self.dropped
        return {
            "version": 1,
            "pid": os.getpid(),
            "rank": self.rank,
            "hostname": self.hostname,
            "started_unix": self.started_unix,
            "written_unix": time.time(),
            "cause": cause,
            "capacity": self.capacity,
            "dropped": dropped,
            "events": events,
        }

    def dump(self, *, cause: str | None = None, path: str | None = None):
        """Atomically write the ring to ``path`` (default the configured
        one); returns the path, or None when there is nowhere to write.
        Never raises - a full disk must not kill the run being recorded."""
        p = path or self.path
        if p is None:
            return None
        doc = self.snapshot(cause=cause)
        tmp = f"{p}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, allow_nan=False)
            os.replace(tmp, p)
        except (OSError, ValueError):
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        return p

    def reset(self) -> None:
        """Clear ring + config (test hygiene for the shared singleton)."""
        with self._lock:
            self._events.clear()
            self.dropped = 0
        self.path = None
        self.rank = None


FLIGHT = FlightRecorder()


def flight_event(kind: str, /, *, step: int | None = None,
                 **fields) -> dict:
    """Record one event on the process flight recorder (`FLIGHT`).

    Always cheap (a deque append; plus one small atomic file write when a
    dump path is armed - see FlightRecorder's low-rate contract). This is
    the one-line hook every anomaly/lifecycle site uses
    (train/guard.py, train/monitor.py, utils/checkpoint.py,
    parallel/fault.py, train/elastic.py)."""
    return FLIGHT.record(kind, step=step, **fields)


def read_flight_dump(path: str) -> dict | None:
    """Parse one flight-recorder dump; None when absent/torn (the writer
    publishes atomically, but the worker may have died pre-configure)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


# ------------------------------------------------- Prometheus text parsing


def parse_prom_samples(text: str) -> dict:
    """{metric_name: {((label, value), ...): float}} from Prometheus text
    exposition - the supervisor-side parser the federation scraper uses
    (`train/supervisor.py`). Histogram series keep their _bucket/_sum/
    _count suffixes as distinct names; malformed lines are skipped.
    `tools/live_top.py` carries its own equivalent copy by design: the
    dashboard must stay free of repo imports.
    """
    out: dict[str, dict[tuple, float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            if "{" in line:
                name, rest = line.split("{", 1)
                labels_s, value_s = rest.rsplit("}", 1)
                labels = []
                for part in _split_label_pairs(labels_s):
                    k, v = part.split("=", 1)
                    labels.append((k, _prom_unescape(v.strip('"'))))
                key = tuple(sorted(labels))
            else:
                name, value_s = line.rsplit(None, 1)
                key = ()
            v = value_s.strip()
            value = float("inf") if v == "+Inf" else (
                float("-inf") if v == "-Inf" else float(v)
            )
        except ValueError:
            continue
        out.setdefault(name.strip(), {})[key] = value
    return out


def _prom_unescape(s: str) -> str:
    return (
        s.replace("\\\\", "\0")
        .replace('\\"', '"')
        .replace("\\n", "\n")
        .replace("\0", "\\")
    )


def _split_label_pairs(s: str):
    """Split 'a="x",b="y,z"' on commas outside quotes."""
    parts, buf, in_q, esc = [], [], False, False
    for ch in s:
        if esc:
            buf.append(ch)
            esc = False
            continue
        if ch == "\\":
            buf.append(ch)
            esc = True
            continue
        if ch == '"':
            in_q = not in_q
            buf.append(ch)
            continue
        if ch == "," and not in_q:
            parts.append("".join(buf))
            buf = []
            continue
        buf.append(ch)
    if buf:
        parts.append("".join(buf))
    return [p for p in (p.strip() for p in parts) if p]


# ------------------------------------------------------------- HTTP server


class _ObsHandler(http.server.BaseHTTPRequestHandler):
    # the registry rides on the server instance (set by ObsServer)

    def _dispatch_route(self, method: str) -> bool:
        """Pluggable route table (``ObsServer(routes=...)``): the
        serving layer (`serve/http.py`) mounts its endpoints - incl.
        long-lived SSE streams - on the same server as /metrics and
        /healthz. A route handler owns the whole response; a client
        disconnect mid-stream must be handled inside it (the serving
        handler turns it into a request cancel)."""
        routes = getattr(self.server, "routes", None)
        if not routes:
            return False
        fn = routes.get((method, self.path.split("?", 1)[0]))
        if fn is None:
            return False
        fn(self)
        return True

    def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler API
        if self._dispatch_route("POST"):
            return
        body = b"not found\n"
        self.send_response(404)
        self.send_header("Content-Type", "text/plain")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
        if self._dispatch_route("GET"):
            return
        reg = self.server.registry  # type: ignore[attr-defined]
        parts = self.path.split("?", 1)
        path = parts[0]
        query = parts[1] if len(parts) > 1 else ""
        if path == "/profile":
            self._do_profile(query)
            return
        if path == "/metrics":
            body = reg.render().encode()
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
        elif path in ("/healthz", "/health"):
            h = reg.health(
                stall_after_s=self.server.stall_after_s  # type: ignore
            )
            body = (json.dumps(h) + "\n").encode()
            # liveness maps onto the status code so `curl -f` and k8s
            # httpGet probes work without parsing the body
            self.send_response(200 if h["alive"] else 503)
            self.send_header("Content-Type", "application/json")
        elif path == "/":
            text = (
                "distributed_neural_network_tpu run\n"
                "endpoints: /metrics (Prometheus), /healthz (JSON), "
                "/profile?steps=N (on-demand jax.profiler capture)\n"
            )
            # mounted route-table endpoints (the serving layer's /v1/*)
            # listed dynamically so the index never goes stale
            mounted = getattr(self.server, "routes", None) or {}
            if mounted:
                text += "routes: " + ", ".join(
                    f"{m} {p}" for m, p in sorted(mounted)
                ) + "\n"
            body = text.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
        else:
            body = b"not found\n"
            self.send_response(404)
            self.send_header("Content-Type", "text/plain")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _do_profile(self, query: str) -> None:
        """GET /profile?steps=N -> arm an on-demand profiler capture for
        the next N steps (train/monitor.py ProfileController; 501 when
        the run was started without a profile directory)."""
        prof = getattr(self.server, "profiler", None)
        if prof is None:
            doc, code = {
                "ok": False,
                "error": "profiling not wired: start the run with "
                "--metrics-port and a profile directory (--profile-dir, "
                "or --trace-out whose directory is reused)",
            }, 501
        else:
            qs = urllib.parse.parse_qs(query)
            try:
                steps = int(qs.get("steps", ["10"])[0])
            except ValueError:
                steps = -1
            if steps < 1:
                doc, code = {
                    "ok": False,
                    "error": "steps must be a positive integer "
                    "(/profile?steps=N)",
                }, 400
            else:
                doc = prof.request(steps)
                code = 200 if doc.get("ok") else 409
        body = (json.dumps(doc) + "\n").encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass


class _ObsHTTPServer(http.server.ThreadingHTTPServer):
    # socketserver's default listen backlog is 5; a serving burst (the
    # 429 overflow probe fires dozens of connections at once) would get
    # kernel connection resets before admission control ever saw them
    request_queue_size = 128


class ObsServer:
    """Background-thread HTTP server for one training process.

    ``port=0`` binds an ephemeral port (CI/tests); the bound port is on
    ``.port`` and the full scrape URL on ``.url``. The serving thread is
    a daemon - a hung scrape can never hold the training process open -
    and ``close()`` shuts it down deterministically (both CLIs call it
    in their exit path).
    """

    def __init__(
        self,
        registry: MetricsRegistry,
        *,
        port: int = 0,
        host: str = "127.0.0.1",
        stall_after_s: float = 300.0,
        profiler=None,
        routes: dict | None = None,
    ):
        self.registry = registry
        self._httpd = _ObsHTTPServer((host, port), _ObsHandler)
        self._httpd.daemon_threads = True
        self._httpd.registry = registry  # type: ignore[attr-defined]
        self._httpd.stall_after_s = stall_after_s  # type: ignore
        # /profile target (train/monitor.py ProfileController; None =
        # the endpoint answers 501 with the wiring hint)
        self._httpd.profiler = profiler  # type: ignore[attr-defined]
        # extra {(method, path): fn(handler)} routes (serve/http.py)
        self._httpd.routes = dict(routes or {})  # type: ignore
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self.url = f"http://{host}:{self.port}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.25},
            name="obs-server",
            daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
