"""Step-level telemetry: structured span tracing + StepStats aggregation.

The reference's only observability is five epoch-granularity wall-clock
accumulators (`data_parallelism_train.py:33-37`, reproduced in
`utils/timers.py`) plus Neptune series. A production-scale system cannot be
tuned at epoch granularity: compile time, steady-state step time, collective
bytes, and device memory are invisible there. This module is the native
per-step layer (docs/OBSERVABILITY.md):

- ``Tracer`` - a span-based structured tracer: ``with tracer.span("x",
  step=i): ...`` records a Chrome trace-event "complete" event. Spans nest
  (a per-thread stack records each span's parent), are thread-safe (one
  lock around the event list), and cost near nothing when disabled
  (``span()`` returns a shared no-op singleton). ``export()`` writes
  Chrome trace-event JSON loadable in Perfetto / ``chrome://tracing``,
  one named track per phase (train/sync/eval/host), strictly valid JSON
  (``allow_nan=False`` - the schema is pinned by tests/test_tracing.py).
- ``StepStats`` - per-step wall-time aggregation separating the compile
  step (step 0, or any record flagged ``is_compile``) from steady state;
  throughput (images/s, tokens/s); device memory via
  ``device.memory_stats()`` where the backend reports it; collective
  payload bytes derived from the param pytree and mesh size
  (``collective_bytes_per_sync``); and MFU from
  ``lowered.compile().cost_analysis()`` FLOPs (``compiled_flops``) with
  graceful fallback to an analytic estimate on backends that don't
  report FLOPs. Per-step records stream into a MetricsRun sink under
  ``step/*`` series as they are recorded.

Timing honesty: the tracer records host wall-clock between span enter and
exit. Callers own the fencing - the engine closes each span after the
`hard_block` fence inside `PhaseTimers.phase` (utils/timers.py), so device
time is attributed to the right span; unfenced spans (stream-mode per-batch
dispatches, LM steps traced with ``fence=False``) carry ``fenced: false``
in their args so a trace reader can tell dispatch time from device time.

jax is imported lazily (only by the helpers that need a backend), so the
tracer, the exporter, and tools/trace_summary.py work on any host.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from dataclasses import dataclass, field

# span names the engine/CLI emit; tools/trace_summary.py groups by these
TRAIN_STEP = "train_step"
TRAIN_SPAN = "train_span"
SYNC = "sync"
EVAL = "eval"
DATA_LOADING = "data_loading"
# instant events from the guard layer (train/guard.py: one per anomaly /
# restore) and the fault simulator's straggler stall span (parallel/fault.py)
GUARD = "guard"
STRAGGLER = "straggler"
# elastic resume/shrink events (train/elastic.py): the reshard span wraps
# one whole checkpoint->new-mesh redistribution on the "elastic" track
RESHARD = "reshard"
# model-health counter tracks (train/dynamics.py DynamicsSink: per-layer
# grad norms, update-to-weight ratios, gradient-noise scale) and the
# engine's replica-divergence samples before each averaging sync
DYNAMICS = "dynamics"


class _NullSpan:
    """Shared no-op span: the disabled tracer's entire overhead is one
    attribute check and returning this singleton."""

    __slots__ = ()
    dur_s = 0.0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "name", "track", "args", "_t0", "dur_s")

    def __init__(self, tracer, name, track, args):
        self._tracer = tracer
        self.name = name
        self.track = track
        self.args = args
        self.dur_s = 0.0

    def __enter__(self):
        tr = self._tracer
        stack = tr._stack()
        if stack:
            self.args.setdefault("parent", stack[-1])
        stack.append(self.name)
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns()
        tr = self._tracer
        stack = tr._stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        self.dur_s = (t1 - self._t0) / 1e9
        tr._record(
            self.name,
            "X",
            (self._t0 - tr._epoch_ns) / 1e3,
            track=self.track,
            dur_us=(t1 - self._t0) / 1e3,
            args=self.args,
        )
        return False


@dataclass
class TraceEvent:
    """One recorded event, Chrome trace-event-shaped (ts/dur in µs)."""

    name: str
    ph: str
    ts: float
    tid: int
    dur: float | None = None
    args: dict = field(default_factory=dict)


class Tracer:
    """Span-based structured tracer with Chrome trace-event JSON export.

    ``enabled=False`` (the default for the module-level ``NULL_TRACER``)
    makes every recording call a near-zero no-op, so instrumented hot
    paths cost nothing when tracing is off.
    """

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._events: list[TraceEvent] = []
        self._tracks: dict[str, int] = {}
        self._tls = threading.local()
        self._epoch_ns = time.perf_counter_ns()
        self.epoch_unix = time.time()
        # fleet identity (set_process): rank-stamped process metadata so
        # per-rank trace shards merge into one readable timeline
        # (tools/trace_merge.py) that stays stable across supervisor
        # relaunches - pids change per (re)launch, ranks do not
        self.rank: int | None = None
        self.hostname: str | None = None
        self.label: str | None = None

    def set_process(
        self, *, rank: int | None = None, hostname: str | None = None,
        label: str | None = None,
    ) -> "Tracer":
        """Stamp this tracer's process identity. With a rank set, the
        exported Chrome document's ``process_name`` metadata becomes
        ``rank{N}`` (not the pid-keyed default) and ``otherData`` carries
        ``rank``/``hostname`` - the keys `tools/trace_merge.py` aligns
        and labels shards by. ``label`` overrides the process name for
        non-rank processes (the serve stack exports ``serve:{port}``
        lanes this way; the merge preserves such labels verbatim)."""
        self.rank = int(rank) if rank is not None else None
        self.hostname = hostname
        if label is not None:
            self.label = str(label)
        return self

    # ------------------------------------------------------------ recording

    def span(self, name: str, *, track: str | None = None, **args):
        """Context manager timing a block as one complete ("X") event.

        ``track`` names the trace track (tid) the span lands on; default is
        the recording thread's name. Extra kwargs become the event's
        ``args`` (step index, epoch, fenced flag, ...). The yielded handle
        exposes ``dur_s`` after exit.
        """
        if not self.enabled:
            return NULL_SPAN
        return _Span(self, name, track, args)

    def instant(self, name: str, *, track: str | None = None, **args) -> None:
        """A zero-duration marker event (ph "i")."""
        if not self.enabled:
            return
        self._record(
            name, "i", (time.perf_counter_ns() - self._epoch_ns) / 1e3,
            track=track, args=args,
        )

    def counter(self, name: str, values: dict, *, track: str | None = None) -> None:
        """A counter sample (ph "C") - e.g. per-device memory bytes."""
        if not self.enabled:
            return
        self._record(
            name, "C", (time.perf_counter_ns() - self._epoch_ns) / 1e3,
            track=track, args=dict(values),
        )

    # Explicit-timestamp recording: callers that already measured an
    # interval on this tracer's clock (``now_s()``) can land it after
    # the fact - serve/reqtrace.py emits whole request lifecycles this
    # way when a record finalizes.

    def now_s(self) -> float:
        """Seconds on this tracer's span clock (the ``ts`` basis)."""
        return (time.perf_counter_ns() - self._epoch_ns) / 1e9

    def complete(self, name: str, t0_s: float, t1_s: float, *,
                 track: str | None = None, **args) -> None:
        """Record an already-measured complete ("X") event with explicit
        endpoints in ``now_s()`` seconds."""
        if not self.enabled:
            return
        self._record(
            name, "X", t0_s * 1e6, track=track,
            dur_us=max(t1_s - t0_s, 0.0) * 1e6, args=args,
        )

    def instant_at(self, name: str, t_s: float, *,
                   track: str | None = None, **args) -> None:
        """A marker event (ph "i") at an explicit ``now_s()`` time."""
        if not self.enabled:
            return
        self._record(name, "i", t_s * 1e6, track=track, args=args)

    # ------------------------------------------------------------ internals

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _tid(self, track: str | None) -> int:
        label = track if track is not None else (
            threading.current_thread().name
        )
        tid = self._tracks.get(label)
        if tid is None:
            tid = self._tracks[label] = len(self._tracks)
        return tid

    def _record(self, name, ph, ts_us, *, track, dur_us=None, args=None):
        with self._lock:
            self._events.append(
                TraceEvent(
                    name=name, ph=ph, ts=ts_us, tid=self._tid(track),
                    dur=dur_us, args=dict(args or {}),
                )
            )

    # -------------------------------------------------------------- export

    def events(self) -> list[TraceEvent]:
        with self._lock:
            return list(self._events)

    def to_chrome(self, *, step_stats: "StepStats | None" = None,
                  goodput: dict | None = None) -> dict:
        """The Chrome trace-event document as a dict (sorted by ts).

        Perfetto/chrome://tracing load the ``traceEvents`` list; the
        ``stepStats`` key (ignored by viewers) embeds the StepStats summary
        so tools/trace_summary.py can report throughput/MFU from the trace
        file alone. ``goodput`` embeds the run's goodput record
        (utils/goodput.py) the same way - `tools/trace_summary.py
        --goodput` cross-checks its span-derived breakdown against it.
        """
        pid = os.getpid()
        if self.label is not None:
            pname = self.label
        elif self.rank is not None:
            pname = f"rank{self.rank}"
        else:
            pname = "dnn-tpu-train"
        events = [
            {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "ts": 0, "args": {"name": pname}},
        ]
        with self._lock:
            tracks = dict(self._tracks)
            recorded = list(self._events)
        for label, tid in sorted(tracks.items(), key=lambda kv: kv[1]):
            events.append(
                {"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                 "ts": 0, "args": {"name": label}}
            )
        for ev in sorted(recorded, key=lambda e: e.ts):
            out = {
                "name": ev.name, "ph": ev.ph, "ts": ev.ts,
                "pid": pid, "tid": ev.tid, "cat": "phase",
                "args": _finite_tree(ev.args),
            }
            if ev.ph == "X":
                out["dur"] = ev.dur if ev.dur is not None else 0.0
            events.append(out)
        other = {"epoch_unix": self.epoch_unix, "pid": pid}
        if self.rank is not None:
            other["rank"] = self.rank
        if self.hostname is not None:
            other["hostname"] = self.hostname
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": other,
        }
        if step_stats is not None:
            doc["stepStats"] = _finite_tree(step_stats.summary())
        if goodput is not None:
            doc["goodput"] = _finite_tree(goodput)
        return doc

    def export(self, path: str, *, step_stats: "StepStats | None" = None,
               goodput: dict | None = None) -> str:
        """Write strict Chrome trace-event JSON (never a bare NaN/Inf
        token - `allow_nan=False` with non-finite floats nulled first).

        Crash-safe: the document is written to ``<path>.tmp`` and
        atomically renamed over ``path``, so a SIGTERM (reachable
        mid-export via the watchdog's preemption escalation,
        train/monitor.py) or a serializer error can never leave a
        truncated half-JSON trace where a previous good one stood - the
        reader sees the old complete file or the new complete file,
        never a partial write."""
        doc = self.to_chrome(step_stats=step_stats, goodput=goodput)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, allow_nan=False)
                f.write("\n")
            os.replace(tmp, path)  # atomic publish
        finally:
            if os.path.exists(tmp):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
        return path


NULL_TRACER = Tracer(enabled=False)


def _finite_tree(x):
    """Replace non-finite floats with None so strict JSON never breaks."""
    if isinstance(x, float):
        return x if math.isfinite(x) else None
    if isinstance(x, dict):
        return {k: _finite_tree(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_finite_tree(v) for v in x]
    return x


# ---------------------------------------------------------------- StepStats


@dataclass
class StepRecord:
    step: int
    wall_s: float
    items: float = 0.0
    is_compile: bool = False


def percentile(xs, p: float) -> float:
    """Nearest-rank percentile of a non-empty sequence (p in [0, 100])."""
    ys = sorted(xs)
    if not ys:
        raise ValueError("percentile of empty sequence")
    k = max(0, min(len(ys) - 1, int(math.ceil(p / 100.0 * len(ys))) - 1))
    return ys[k]


class StepStats:
    """Per-step aggregator: compile vs steady-state wall time, throughput,
    device memory, collective bytes, and MFU.

    ``record()`` both accumulates and (when a MetricsRun-like ``sink`` is
    given) streams the per-step record under ``step/*`` series, so a run
    killed mid-training still has its step telemetry on disk.

    The first record is the compile step unless flagged otherwise - the
    reference (and this repo's engine) pays XLA compilation inside the
    first dispatch, so folding it into a mean would dominate every short
    run's throughput number.
    """

    def __init__(
        self,
        *,
        item_label: str = "items",
        sink=None,
        series_prefix: str = "step",
        n_devices: int = 1,
        comm_bytes_per_step: int | None = None,
        static_comm_bytes_per_step: int | None = None,
        flops_per_step: float | None = None,
        flops_source: str | None = None,
        peak_flops_per_device: float | None = None,
        grad_sync: str | None = None,
        comm_bucket_bytes: list | tuple | None = None,
        compilation_cache_dir: str | None = None,
        registry=None,
    ):
        self.item_label = item_label
        self.sink = sink
        # live-metrics registry (utils/obs.py; None = off): anomaly
        # counters and device-memory gauges surface on /metrics as they
        # are recorded. Step counting/heartbeat stays with the training
        # loops (engine / make_traced_step) - StepStats is opt-in, the
        # liveness signal is not.
        if registry is None:
            from .obs import NULL_REGISTRY

            registry = NULL_REGISTRY
        self._reg_mem = registry.gauge(
            "device_memory_bytes_in_use",
            "Peak bytes_in_use per device (device.memory_stats)",
        )
        if comm_bytes_per_step is not None:
            registry.gauge(
                "collective_bytes_per_step",
                "Estimated per-device collective payload bytes per step",
            ).set(comm_bytes_per_step)
        self.series_prefix = series_prefix
        self.n_devices = int(n_devices)
        self.comm_bytes_per_step = comm_bytes_per_step
        # the shardlint static trace's logical payload bytes per step
        # (analysis/trace.py), when the caller ran the analyzer - the
        # cross-check against the runtime ring estimate above
        self.static_comm_bytes_per_step = static_comm_bytes_per_step
        self.flops_per_step = flops_per_step
        self.flops_source = flops_source
        self.peak_flops_per_device = peak_flops_per_device
        # gradient-sync schedule attribution: which schedule produced
        # comm_bytes_per_step, and (overlap) the per-bucket payloads so a
        # trace reader can match collective cost to the bucket plan
        self.grad_sync = grad_sync
        self.comm_bucket_bytes = (
            [int(b) for b in comm_bucket_bytes]
            if comm_bucket_bytes is not None else None
        )
        # persistent-compilation-cache provenance: compile_s with a warm
        # cache is the cache-hit (deserialize) time, not a fresh compile
        self.compilation_cache_dir = compilation_cache_dir
        self.records: list[StepRecord] = []
        self.memory_peak: dict[str, int] = {}
        # guard-layer anomaly counters (train/guard.py observe/rollback):
        # kind -> count; lands in summary()/report() and the trace embed
        self.anomalies: dict[str, int] = {}
        self._lock = threading.Lock()

    # ----------------------------------------------------------- recording

    def record(
        self,
        step: int,
        wall_s: float,
        *,
        items: float = 0.0,
        is_compile: bool | None = None,
    ) -> StepRecord:
        with self._lock:
            if is_compile is None:
                is_compile = not self.records
            rec = StepRecord(
                step=int(step), wall_s=float(wall_s), items=float(items),
                is_compile=bool(is_compile),
            )
            self.records.append(rec)
        if self.sink is not None:
            p = self.series_prefix
            self.sink.append(f"{p}/wall_s", rec.wall_s)
            if rec.items and rec.wall_s > 0 and not rec.is_compile:
                self.sink.append(
                    f"{p}/{self.item_label}_per_s", rec.items / rec.wall_s
                )
        return rec

    def count_anomaly(self, kind: str, n: int = 1) -> None:
        """Bump a guard anomaly counter (and stream it when sinking).
        The /metrics counterpart (guard_anomalies_total) is published by
        the guard itself (train/guard.py) - the sole anomaly producer -
        so counts never double when both are wired to one registry."""
        with self._lock:
            self.anomalies[kind] = self.anomalies.get(kind, 0) + int(n)
        if self.sink is not None:
            self.sink.append(
                f"{self.series_prefix}/anomaly_{kind}", self.anomalies[kind]
            )

    def set_flops(self, flops_per_step: float | None, source: str | None) -> None:
        self.flops_per_step = flops_per_step
        self.flops_source = source

    def capture_memory(self, tracer: Tracer | None = None) -> dict | None:
        """Sample ``device.memory_stats()`` on every device, keep the peak
        ``bytes_in_use`` per device, and (optionally) emit a counter event.
        Backends without memory stats (CPU) return None - no crash."""
        snap = device_memory_snapshot()
        if not snap:
            return None
        for label, stats in snap.items():
            b = stats.get("bytes_in_use")
            if b is None:
                continue
            self.memory_peak[label] = max(self.memory_peak.get(label, 0), int(b))
            self._reg_mem.labels(device=label).set_max(int(b))
        if tracer is not None and self.memory_peak:
            tracer.counter(
                "device_memory_bytes_in_use",
                {k: v for k, v in self.memory_peak.items()}, track="memory",
            )
        if self.sink is not None and self.memory_peak:
            self.sink.append(
                f"{self.series_prefix}/mem_bytes_in_use_max",
                max(self.memory_peak.values()),
            )
        return snap

    # ------------------------------------------------------------- summary

    def summary(self) -> dict:
        """Aggregate dict; ``steady_includes_compile`` flags the 1-step
        fallback (a single compiled dispatch has no steady state - its one
        sample is reported rather than nothing)."""
        with self._lock:
            records = list(self.records)
        compile_recs = [r for r in records if r.is_compile]
        steady = [r for r in records if not r.is_compile]
        steady_includes_compile = False
        if not steady and records:
            steady = records
            steady_includes_compile = True
        out = {
            "steps": len(records),
            "item_label": self.item_label,
            "n_devices": self.n_devices,
            "compile_steps": len(compile_recs),
            "compile_s": round(sum(r.wall_s for r in compile_recs), 6)
            if compile_recs else None,
            "steady_steps": len(steady),
            "steady_includes_compile": steady_includes_compile,
            "comm_bytes_per_step": self.comm_bytes_per_step,
            "static_comm_bytes_per_step": self.static_comm_bytes_per_step,
            "grad_sync": self.grad_sync,
            "comm_buckets": (
                {
                    "count": len(self.comm_bucket_bytes),
                    "bytes_per_bucket": list(self.comm_bucket_bytes),
                }
                if self.comm_bucket_bytes is not None else None
            ),
            "compilation_cache_dir": self.compilation_cache_dir,
            "anomalies": dict(self.anomalies) or None,
            "flops_per_step": self.flops_per_step,
            "flops_source": self.flops_source,
            "peak_flops_per_device": self.peak_flops_per_device,
            "device_memory_peak_bytes": dict(self.memory_peak) or None,
        }
        if steady:
            walls = [r.wall_s for r in steady]
            total = sum(walls)
            items = sum(r.items for r in steady)
            out.update(
                steady_total_s=round(total, 6),
                steady_mean_s=round(total / len(walls), 6),
                steady_p50_s=round(percentile(walls, 50), 6),
                steady_p95_s=round(percentile(walls, 95), 6),
                steady_min_s=round(min(walls), 6),
                steady_max_s=round(max(walls), 6),
            )
            thr = items / total if total > 0 and items else None
            out["throughput_items_per_s"] = round(thr, 3) if thr else None
        else:
            out.update(
                steady_total_s=None, steady_mean_s=None, steady_p50_s=None,
                steady_p95_s=None, steady_min_s=None, steady_max_s=None,
                throughput_items_per_s=None,
            )
        out["mfu_pct"], out["mfu_note"] = self._mfu(out["steady_mean_s"])
        return out

    def _mfu(self, steady_mean_s) -> tuple[float | None, str | None]:
        if self.flops_per_step is None:
            return None, "unavailable: no FLOPs estimate (cost_analysis and analytic both absent)"
        if self.peak_flops_per_device is None:
            return None, "unavailable: no peak FLOP/s table entry for this device kind"
        if not steady_mean_s or steady_mean_s <= 0:
            return None, "unavailable: no timed steps"
        mfu = (
            self.flops_per_step
            / steady_mean_s
            / (self.peak_flops_per_device * max(self.n_devices, 1))
            * 100.0
        )
        return round(mfu, 3), None

    def report(self) -> str:
        """Human-readable multi-line summary (the --step-stats printout)."""
        s = self.summary()
        lines = [
            f"Step stats ({s['steps']} steps, {s['n_devices']} device(s)):",
            f"  compile: {s['compile_steps']} step(s), "
            + (f"{s['compile_s']:.4f} s" if s["compile_s"] is not None else "n/a"),
        ]
        if s["steady_mean_s"] is not None:
            extra = (
                " [single-dispatch run: includes compile]"
                if s["steady_includes_compile"] else ""
            )
            lines.append(
                f"  steady-state: {s['steady_steps']} step(s), mean "
                f"{s['steady_mean_s']:.4f} s, p50 {s['steady_p50_s']:.4f} s, "
                f"p95 {s['steady_p95_s']:.4f} s{extra}"
            )
        else:
            lines.append("  steady-state: n/a (no steps recorded)")
        thr = s["throughput_items_per_s"]
        lines.append(
            f"  throughput: "
            + (f"{thr:,.1f} {s['item_label']}/s" if thr else "n/a")
        )
        if s["comm_bytes_per_step"] is not None:
            sched = f", schedule: {s['grad_sync']}" if s["grad_sync"] else ""
            lines.append(
                f"  collective payload: {s['comm_bytes_per_step']:,} "
                f"bytes/step (ring all-reduce estimate{sched})"
            )
        if s["static_comm_bytes_per_step"] is not None:
            lines.append(
                f"  static analysis payload: "
                f"{s['static_comm_bytes_per_step']:,} bytes/step "
                "(shardlint logical payload; tools/trace_summary.py --lint)"
            )
        if s["comm_buckets"]:
            bb = s["comm_buckets"]["bytes_per_bucket"]
            lines.append(
                f"  gradient buckets: {len(bb)} per microbatch "
                f"({min(bb):,}-{max(bb):,} B each)"
            )
        if s["anomalies"]:
            lines.append(
                "  guard anomalies: "
                + ", ".join(
                    f"{k}={v}" for k, v in sorted(s["anomalies"].items())
                )
            )
        mem = s["device_memory_peak_bytes"]
        lines.append(
            "  device memory peak: "
            + (", ".join(f"{k}={v:,} B" for k, v in sorted(mem.items()))
               if mem else "unavailable (backend reports no memory_stats)")
        )
        if s["mfu_pct"] is not None:
            lines.append(
                f"  MFU: {s['mfu_pct']:.2f}% (FLOPs source: {s['flops_source']})"
            )
        else:
            lines.append(f"  MFU: {s['mfu_note']}")
        return "\n".join(lines)


# ----------------------------------------------------------------- helpers


def detect_rank() -> int | None:
    """This process's rank in a multi-process group, from the standard
    env handshake (``JAX_PROCESS_ID``, exported by `train/supervisor.py`
    and cluster launchers); None for a plain single-process run. Pure
    env read - usable before (or without) any jax import."""
    v = os.environ.get("JAX_PROCESS_ID")
    if v is None:
        return None
    try:
        return int(v)
    except ValueError:
        return None


def rank_trace_path(path: str, rank: int | None) -> str:
    """Per-rank trace-shard path: ``trace.json`` -> ``trace_rank{N}.json``.

    Supervised workers all run the same argv, so a shared ``--trace-out``
    would have every rank clobbering one file; the rank suffix gives each
    worker its own shard, which `tools/trace_merge.py` reassembles into
    one timeline. rank=None (single process) returns the path unchanged.
    """
    if rank is None:
        return path
    root, ext = os.path.splitext(path)
    return f"{root}_rank{int(rank)}{ext or '.json'}"


def param_bytes(tree) -> int:
    """Total bytes of a pytree of arrays (any leaf with size/dtype)."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(tree):
        size = getattr(leaf, "size", None)
        dtype = getattr(leaf, "dtype", None)
        if size is None or dtype is None:
            continue
        total += int(size) * int(dtype.itemsize)
    return total


def collective_bytes_per_sync(tree, n_devices: int, algorithm: str = "ring") -> int:
    """Per-device payload bytes of one parameter all-reduce over the mesh.

    ``ring`` is the bandwidth-optimal bound every backend implementation
    approaches: each device sends (and receives) 2*(n-1)/n of the tree per
    reduction (reduce-scatter + all-gather). ``naive`` is the reference's
    parent-star topology: every child ships its full tree up and the
    averaged tree back down - 2x the tree regardless of n.
    """
    if n_devices <= 1:
        return 0
    pb = param_bytes(tree)
    if algorithm == "ring":
        return int(pb * 2 * (n_devices - 1) / n_devices)
    if algorithm == "naive":
        return 2 * pb
    raise ValueError(f"unknown algorithm {algorithm!r} (ring | naive)")


def overlapped_collective_bytes(
    bucket_bytes, n_devices: int, accum_steps: int = 1,
    algorithm: str = "ring",
) -> int:
    """Per-device payload bytes of one train step under the OVERLAPPED
    gradient-sync schedule: every microbatch fires one collective per
    bucket, so the step total is accum_steps x the bucketed tree's ring
    cost. Same ring bound as `collective_bytes_per_sync` (a bucketed
    reduce-scatter + the post-scan all-gather together move the same
    2*(n-1)/n of the tree a bucketed psum does); the point of reporting
    it separately is that the trace shows it OVERLAPPED with backward
    compute instead of serialized after it."""
    if n_devices <= 1:
        return 0
    total = int(sum(bucket_bytes))
    if algorithm == "ring":
        per = int(total * 2 * (n_devices - 1) / n_devices)
    elif algorithm == "naive":
        per = 2 * total
    else:
        raise ValueError(f"unknown algorithm {algorithm!r} (ring | naive)")
    return per * max(int(accum_steps), 1)


GRAD_BUCKET = "grad_bucket"


def record_bucket_plan(
    tracer: Tracer, bucket_bytes, *, schedule: str, op: str,
    axis_size: int, accum_steps: int = 1, track: str = "collective",
) -> None:
    """Emit one `grad_bucket` instant event per bucket of the gradient-sync
    plan (payload bytes, collective op, schedule, mesh-axis size).

    The collectives themselves execute inside the compiled step where
    host-side spans cannot see them; these plan events put the schedule
    in-band in the Chrome trace, on their own track next to the fenced
    train_step spans, so a Perfetto reader (and the trace-schema tests)
    can attribute per-bucket collective bytes without device profiling.
    """
    for i, b in enumerate(bucket_bytes):
        tracer.instant(
            GRAD_BUCKET, track=track, bucket=i, bytes=int(b), op=op,
            schedule=schedule, axis_size=int(axis_size),
            per_microbatch=int(accum_steps),
        )


def device_memory_snapshot() -> dict[str, dict] | None:
    """``memory_stats()`` per device, or None when the backend has none.

    Keys are ``dev<i>`` labels; values the backend's stats dict (TPU/GPU
    report at least ``bytes_in_use``; CPU typically returns None/raises).
    """
    try:
        import jax

        devices = jax.devices()
    except Exception:
        return None
    snap = {}
    for i, d in enumerate(devices):
        fn = getattr(d, "memory_stats", None)
        if fn is None:
            continue
        try:
            stats = fn()
        except Exception:
            stats = None
        if stats:
            snap[f"dev{i}"] = dict(stats)
    return snap or None


def compiled_flops(fn, *args, **kwargs) -> float | None:
    """FLOPs of one call from ``fn.lower(...).compile().cost_analysis()``.

    Returns None (never raises) when the function can't lower, the backend
    doesn't report cost analysis, or the report carries no positive
    ``flops`` entry - callers fall back to an analytic estimate.
    cost_analysis() shape differs across jax versions (dict, or a
    one-element list of dicts); both are handled.
    """
    try:
        lowered = fn.lower(*args, **kwargs)
        analysis = lowered.compile().cost_analysis()
    except Exception:
        return None
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if not isinstance(analysis, dict):
        return None
    flops = analysis.get("flops")
    try:
        flops = float(flops)
    except (TypeError, ValueError):
        return None
    return flops if flops > 0 else None
