"""Checkpoint / resume at the sync boundary (SURVEY.md section 5.4).

The reference persists nothing: the parent's averaged state dict at an epoch
edge (`data_parallelism_train.py:244`) is only an *implicit* checkpointable
state, lost when the process exits. Here that state is explicit - after the
sync phase the engine holds the averaged parameters (replicated over the
mesh), the per-device momentum buffers, and the metric history - and this
module persists it at a configurable epoch interval with retention and
resume-from-latest.

Backends:
- ``orbax`` (default when importable): `orbax.checkpoint.CheckpointManager`
  with a Standard (pytree) item for arrays and a JSON item for metadata -
  the idiomatic JAX/TPU checkpoint stack.
- ``npz``: a dependency-free fallback writing one `.npz` of tree leaves plus
  a JSON sidecar per step, with the same retention semantics.

Arrays are materialized to host numpy before save and re-placed onto the
engine's mesh shardings on restore, so checkpoints are portable across
platforms (TPU run -> CPU-mesh resume and vice versa). The two backends'
on-disk formats are NOT cross-readable: resume with the same backend (and
directory) the run was saved with.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import shutil

import jax
import numpy as np

try:  # pragma: no cover - exercised indirectly via backend selection
    import orbax.checkpoint as ocp

    _HAVE_ORBAX = True
except Exception:  # pragma: no cover
    ocp = None
    _HAVE_ORBAX = False


def _host_tree(tree):
    return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), tree)


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed validation against the restore template (missing
    files, wrong leaf count, shape/dtype mismatch, unreadable archive).
    `restore_latest` catches this and falls back to the previous step."""

    def __init__(self, step: int, detail: str):
        super().__init__(
            f"corrupt/truncated checkpoint (step {step}): {detail}"
        )
        self.step = step


class _OrbaxBackend:
    def __init__(self, directory: str, keep: int):
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep if keep > 0 else None,
                enable_async_checkpointing=False,
            ),
        )

    def save(self, step: int, state, meta: dict) -> None:
        self._mgr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                meta=ocp.args.JsonSave(meta),
            ),
        )

    def latest_step(self):
        return self._mgr.latest_step()

    def restore(self, step: int, template=None):
        out = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(),
                meta=ocp.args.JsonRestore(),
            ),
        )
        return out["state"], out["meta"]

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()


class _NpzBackend:
    """One `step_{N}/state.npz` + `meta.json` per checkpoint, keep-last-K."""

    _STEP_RE = re.compile(r"^step_(\d+)$")

    def __init__(self, directory: str, keep: int):
        self.dir = os.path.abspath(directory)
        self.keep = keep
        os.makedirs(self.dir, exist_ok=True)
        # sweep stale step_*.tmp staging dirs: a crash between the tmp
        # write and the atomic rename leaves one behind, and nothing else
        # ever touches it again - it would leak forever (and a later save
        # of the same step would makedirs into the half-written remnant)
        for name in os.listdir(self.dir):
            if self._STEP_RE.match(name[:-4]) and name.endswith(".tmp"):
                shutil.rmtree(os.path.join(self.dir, name), ignore_errors=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.dir, f"step_{step}")

    def save(self, step: int, state, meta: dict) -> None:
        leaves = jax.tree.leaves(state)
        d = self._step_dir(step)
        tmp = d + ".tmp"
        os.makedirs(tmp, exist_ok=True)
        np.savez(
            os.path.join(tmp, "state.npz"),
            **{f"leaf_{i}": leaf for i, leaf in enumerate(leaves)},
        )
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f)
        if os.path.isdir(d):
            shutil.rmtree(d)
        os.rename(tmp, d)  # atomic publish: partial writes never look live
        if self.keep > 0:
            for old in self.all_steps()[: -self.keep]:
                shutil.rmtree(self._step_dir(old))

    def all_steps(self):
        steps = []
        for name in os.listdir(self.dir):
            m = self._STEP_RE.match(name)
            if m:
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self):
        steps = self.all_steps()
        return steps[-1] if steps else None

    def restore(self, step: int, template=None):
        """Load + validate one checkpoint. With a `template`, the leaf
        count, every shape, and every dtype are checked BEFORE unflatten,
        so a truncated archive or a layout from a different run raises a
        clear `CheckpointCorruptError` instead of a cryptic unflatten /
        device_put failure deep in the restore path."""
        d = self._step_dir(step)
        try:
            with np.load(os.path.join(d, "state.npz")) as z:
                leaves = [z[f"leaf_{i}"] for i in range(len(z.files))]
            with open(os.path.join(d, "meta.json")) as f:
                meta = json.load(f)
        except CheckpointCorruptError:
            raise
        except Exception as e:  # unreadable zip, missing file, bad json
            raise CheckpointCorruptError(
                step, f"{type(e).__name__}: {e}"
            ) from e
        if template is None:
            return leaves, meta
        want = jax.tree.leaves(template)
        if len(leaves) != len(want):
            raise CheckpointCorruptError(
                step,
                f"{len(leaves)} stored leaves, template has {len(want)} - "
                "truncated archive or a different model/optimizer layout",
            )
        for i, (got, ref) in enumerate(zip(leaves, want)):
            if tuple(got.shape) != tuple(np.shape(ref)):
                raise CheckpointCorruptError(
                    step,
                    f"leaf_{i} shape {tuple(got.shape)} != template "
                    f"{tuple(np.shape(ref))}",
                )
            ref_dt = np.dtype(getattr(ref, "dtype", np.asarray(ref).dtype))
            if np.dtype(got.dtype) != ref_dt:
                raise CheckpointCorruptError(
                    step,
                    f"leaf_{i} dtype {got.dtype} != template {ref_dt}",
                )
        state = jax.tree.unflatten(jax.tree.structure(template), leaves)
        return state, meta

    def close(self) -> None:
        pass


def _make_backend(backend: str, directory: str, keep: int):
    """Shared auto/orbax/npz backend selection for both checkpointers."""
    if backend == "auto":
        backend = "orbax" if _HAVE_ORBAX else "npz"
    if backend == "orbax" and not _HAVE_ORBAX:
        raise RuntimeError("orbax backend requested but orbax is not importable")
    cls = _OrbaxBackend if backend == "orbax" else _NpzBackend
    return backend, cls(directory, keep)


class _CkptMetrics:
    """Live-metrics publishing shared by both checkpointers (utils/obs.py;
    registry=None stays a no-op). The last-save timestamp gauge is what
    the watchdog's checkpoint-staleness detector (train/monitor.py) ages
    against; the step gauge tells a dashboard how far back a restore
    would rewind."""

    def __init__(self, registry=None):
        if registry is None:
            from .obs import NULL_REGISTRY

            registry = NULL_REGISTRY
        self.saves = registry.counter(
            "checkpoint_saves_total", "Checkpoints written this run"
        )
        self.last_save = registry.gauge(
            "checkpoint_last_save_timestamp_seconds",
            "Unix time of the newest checkpoint save",
        )
        self.last_step = registry.gauge(
            "checkpoint_last_step", "Step/epoch of the newest checkpoint"
        )

    def saved(self, step: int) -> None:
        import time

        self.saves.inc()
        self.last_save.set(time.time())
        self.last_step.set(int(step))


class TreeCheckpointer:
    """Save/restore an arbitrary pytree + metadata (same backends).

    The Engine-agnostic sibling of `Checkpointer`, used by the LM trainer
    (`lm_train.py`): state is any pytree of arrays (params/momentum under
    whatever mesh sharding), `meta` any JSON-serializable dict. On restore,
    pass `shardings` (a matching pytree of jax.sharding.Sharding, or None)
    to re-place leaves onto the run's mesh.
    """

    def __init__(self, directory: str, *, keep: int = 3, backend: str = "auto",
                 registry=None):
        self.backend_name, self._b = _make_backend(backend, directory, keep)
        self._metrics = _CkptMetrics(registry)

    def save(self, step: int, state, meta: dict | None = None) -> None:
        self._b.save(step, _host_tree(state), meta or {})
        self._metrics.saved(step)

    def latest_step(self):
        return self._b.latest_step()

    def restore_latest(self, template, shardings=None, *, log=print):
        """(state, meta, step) from the newest VALID checkpoint, or None.

        `template` supplies the tree structure (its leaf values are unused);
        `shardings` re-places each restored leaf via device_put. A newest
        checkpoint that fails validation (CheckpointCorruptError - e.g. the
        writer was killed mid-save on a filesystem without atomic rename)
        is skipped with a warning and the previous step is tried, oldest
        last; only if every retained checkpoint is corrupt does the error
        propagate.
        """
        steps = self._b.all_steps()
        if not steps:
            return None
        last_err = None
        for step in reversed(steps):
            try:
                state, meta = self._b.restore(step, template)
            except CheckpointCorruptError as e:
                log(f"(WARNING: {e}; falling back to the previous "
                    "checkpoint)")
                last_err = e
                continue
            if shardings is not None:
                state = jax.tree.map(jax.device_put, state, shardings)
            return state, meta, step
        raise last_err

    def close(self) -> None:
        self._b.close()


class Checkpointer:
    """Save/restore an Engine's sync-boundary state.

    `maybe_save(epoch, engine)` after each epoch; `restore_latest(engine)`
    before training to resume. Restore re-places arrays onto the engine's
    own mesh shardings, so the checkpoint itself is platform-agnostic.
    """

    def __init__(
        self,
        directory: str,
        *,
        every: int = 1,
        keep: int = 3,
        backend: str = "auto",
        registry=None,
    ):
        self.backend_name, self._b = _make_backend(backend, directory, keep)
        self._metrics = _CkptMetrics(registry)
        self.every = every

    # ------------------------------------------------------------------ save

    def maybe_save(self, epoch: int, engine) -> bool:
        if self.every <= 0 or (epoch + 1) % self.every != 0:
            return False
        self.save(epoch, engine)
        return True

    def save(self, epoch: int, engine) -> None:
        from ..train.guard import resume_cursor

        state = _host_tree(engine.state_tree())
        meta = {
            "epoch": epoch,
            "n_workers": engine.n_workers,
            "regime": engine.config.regime,
            "history": [dataclasses.asdict(m) for m in engine.history],
            # versioned exact-resume cursor: every shuffle/fault stream is
            # a pure function of (seed, epoch), so these two pin the
            # continuation's data order bit-exactly (train/guard.py)
            **resume_cursor(step=epoch, seed=engine.config.seed),
        }
        self._b.save(epoch, state, meta)
        self._metrics.saved(epoch)

    # --------------------------------------------------------------- restore

    def latest_epoch(self):
        return self._b.latest_step()

    def restore_latest(self, engine, *, log=print) -> int:
        """Load the newest VALID checkpoint into `engine`; returns the next
        epoch to run (0 if no checkpoint exists). A corrupt newest
        checkpoint is skipped with a warning (same fallback semantics as
        `TreeCheckpointer.restore_latest`)."""
        steps = self._b.all_steps()
        if not steps:
            return 0
        state = meta = None
        last_err = None
        for step in reversed(steps):
            try:
                state, meta = self._b.restore(step, engine.state_tree())
                break
            except CheckpointCorruptError as e:
                log(f"(WARNING: {e}; falling back to the previous "
                    "checkpoint)")
                last_err = e
        if meta is None:
            raise last_err
        if meta["n_workers"] != engine.n_workers:
            raise ValueError(
                f"checkpoint was written with n_workers={meta['n_workers']}, "
                f"engine has {engine.n_workers} - momentum buffers don't map"
            )
        if meta["regime"] != engine.config.regime:
            raise ValueError(
                f"checkpoint regime mismatch: written by a {meta['regime']!r} "
                f"run, engine is {engine.config.regime!r} - resuming would "
                "silently change the data-placement policy mid-trajectory"
            )
        from ..train.guard import check_cursor

        check_cursor(meta, seed=engine.config.seed, what="engine")
        engine.load_state_tree(state)
        from ..train.engine import EpochMetrics

        engine.history = [EpochMetrics(**m) for m in meta["history"]]
        return meta["epoch"] + 1

    def close(self) -> None:
        self._b.close()
