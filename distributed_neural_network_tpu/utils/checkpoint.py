"""Checkpoint / resume at the sync boundary (SURVEY.md section 5.4).

The reference persists nothing: the parent's averaged state dict at an epoch
edge (`data_parallelism_train.py:244`) is only an *implicit* checkpointable
state, lost when the process exits. Here that state is explicit - after the
sync phase the engine holds the averaged parameters (replicated over the
mesh), the per-device momentum buffers, and the metric history - and this
module persists it at a configurable epoch interval with retention and
resume-from-latest.

Backends:
- ``orbax`` (default when importable): `orbax.checkpoint.CheckpointManager`
  with a Standard (pytree) item for arrays and a JSON item for metadata -
  the idiomatic JAX/TPU checkpoint stack.
- ``npz``: a dependency-free fallback writing one `.npz` of tree leaves plus
  a JSON sidecar per step, with the same retention semantics.

Arrays are materialized to host numpy before save and re-placed onto the
engine's mesh shardings on restore, so checkpoints are portable across
platforms (TPU run -> CPU-mesh resume and vice versa). The two backends'
on-disk formats are NOT cross-readable: resume with the same backend (and
directory) the run was saved with.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import shutil

import jax
import numpy as np

try:  # pragma: no cover - exercised indirectly via backend selection
    import orbax.checkpoint as ocp

    _HAVE_ORBAX = True
except Exception:  # pragma: no cover
    ocp = None
    _HAVE_ORBAX = False


def _host_leaf(x):
    """One leaf to host numpy, multi-process safe.

    Single-process (and any fully-addressable array): plain device_get.
    On a multi-process mesh a replicated leaf is read from the first
    LOCAL shard (every replica holds the full value - no collective, so
    ranks at slightly different wall-clock positions cannot deadlock),
    while a cross-process-sharded leaf (ZeRO flat buffers, the engine's
    per-device momentum stack) is reassembled with
    ``multihost_utils.process_allgather`` - a collective, which is why
    `save()` runs the host conversion on EVERY rank before only rank 0
    writes the files.
    """
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        if x.sharding.is_fully_replicated:
            return np.asarray(x.addressable_shards[0].data)
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(jax.device_get(x))


def _host_tree(tree):
    return jax.tree.map(_host_leaf, tree)


def _is_writer_rank() -> bool:
    """True on the process that owns the checkpoint files (rank 0). With
    one process - the common case - always True; in a multi-process group
    every rank participates in `_host_tree`'s collectives but only this
    one touches the directory (a shared filesystem would otherwise get N
    racing writers of the same step)."""
    try:
        return jax.process_index() == 0
    except Exception:  # jax backend not initialized yet
        return True


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed validation against the restore template (missing
    files, wrong leaf count, shape/dtype mismatch, unreadable archive).
    `restore_latest` catches this and falls back to the previous step."""

    def __init__(self, step: int, detail: str):
        super().__init__(
            f"corrupt/truncated checkpoint (step {step}): {detail}"
        )
        self.step = step


class _OrbaxBackend:
    def __init__(self, directory: str, keep: int):
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep if keep > 0 else None,
                enable_async_checkpointing=False,
            ),
        )

    def save(self, step: int, state, meta: dict) -> None:
        self._mgr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                meta=ocp.args.JsonSave(meta),
            ),
        )

    def latest_step(self):
        return self._mgr.latest_step()

    def restore(self, step: int, template=None, shardings=None):
        # shardings are applied by the caller for this backend (orbax
        # already streams leaves; the npz backend is the one that would
        # otherwise materialize the whole host tree first)
        out = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(),
                meta=ocp.args.JsonRestore(),
            ),
        )
        return out["state"], out["meta"]

    def load_meta(self, step: int) -> dict:
        """Only the JSON meta of one step (no array reads when the orbax
        layout allows a partial restore)."""
        try:
            out = self._mgr.restore(
                step, args=ocp.args.Composite(meta=ocp.args.JsonRestore())
            )
            return out["meta"]
        except Exception:
            try:
                return self.restore(step)[1]
            except Exception as e:  # pragma: no cover - surface uniformly
                raise CheckpointCorruptError(
                    step, f"{type(e).__name__}: {e}"
                ) from e

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()


class _NpzBackend:
    """One `step_{N}/state.npz` + `meta.json` per checkpoint, keep-last-K."""

    _STEP_RE = re.compile(r"^step_(\d+)$")

    def __init__(self, directory: str, keep: int):
        self.dir = os.path.abspath(directory)
        self.keep = keep
        os.makedirs(self.dir, exist_ok=True)
        # sweep stale step_*.tmp staging dirs: a crash between the tmp
        # write and the atomic rename leaves one behind, and nothing else
        # ever touches it again - it would leak forever (and a later save
        # of the same step would makedirs into the half-written remnant)
        for name in os.listdir(self.dir):
            if self._STEP_RE.match(name[:-4]) and name.endswith(".tmp"):
                shutil.rmtree(os.path.join(self.dir, name), ignore_errors=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.dir, f"step_{step}")

    def save(self, step: int, state, meta: dict) -> None:
        leaves = jax.tree.leaves(state)
        d = self._step_dir(step)
        tmp = d + ".tmp"
        os.makedirs(tmp, exist_ok=True)
        np.savez(
            os.path.join(tmp, "state.npz"),
            **{f"leaf_{i}": leaf for i, leaf in enumerate(leaves)},
        )
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f)
        if os.path.isdir(d):
            shutil.rmtree(d)
        os.rename(tmp, d)  # atomic publish: partial writes never look live
        if self.keep > 0:
            for old in self.all_steps()[: -self.keep]:
                shutil.rmtree(self._step_dir(old))

    def all_steps(self):
        steps = []
        for name in os.listdir(self.dir):
            m = self._STEP_RE.match(name)
            if m:
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self):
        steps = self.all_steps()
        return steps[-1] if steps else None

    def load_meta(self, step: int) -> dict:
        """Only the JSON meta sidecar of one step (no array reads)."""
        try:
            with open(os.path.join(self._step_dir(step), "meta.json")) as f:
                return json.load(f)
        except Exception as e:
            raise CheckpointCorruptError(
                step, f"{type(e).__name__}: {e}"
            ) from e

    def restore(self, step: int, template=None, shardings=None):
        """Load + validate one checkpoint, leaf by leaf. With a `template`,
        the leaf count, every shape, and every dtype are checked as each
        leaf streams out of the archive, so a truncated archive or a
        layout from a different run raises a `CheckpointCorruptError`
        naming the offending LEAF PATH instead of a cryptic unflatten /
        device_put failure deep in the restore path.

        `shardings` (a pytree of jax.sharding.Sharding aligned with
        `template`) places each leaf on device THE MOMENT it is read -
        the host copy is dropped before the next leaf loads, so peak host
        memory is one leaf, not the whole tree (the npz archive is a zip;
        members decompress individually on access)."""
        d = self._step_dir(step)
        try:
            z = np.load(os.path.join(d, "state.npz"))
        except Exception as e:  # unreadable zip, missing file
            raise CheckpointCorruptError(
                step, f"{type(e).__name__}: {e}"
            ) from e
        with z:
            meta = self.load_meta(step)
            n_stored = len(z.files)
            if template is None:
                try:
                    leaves = [z[f"leaf_{i}"] for i in range(n_stored)]
                except Exception as e:
                    raise CheckpointCorruptError(
                        step, f"{type(e).__name__}: {e}"
                    ) from e
                return leaves, meta
            flat, treedef = jax.tree_util.tree_flatten_with_path(template)
            if n_stored != len(flat):
                raise CheckpointCorruptError(
                    step,
                    f"{n_stored} stored leaves, template has {len(flat)} - "
                    "truncated archive or a different model/optimizer "
                    "layout",
                )
            shard_leaves = (
                treedef.flatten_up_to(shardings)
                if shardings is not None else [None] * len(flat)
            )
            if shardings is not None:
                from ..parallel.reshard import put_leaf
            leaves = []
            for i, ((path, ref), shard) in enumerate(zip(flat, shard_leaves)):
                name = jax.tree_util.keystr(path) or f"leaf_{i}"
                try:
                    got = z[f"leaf_{i}"]
                except Exception as e:
                    raise CheckpointCorruptError(
                        step, f"{name}: {type(e).__name__}: {e}"
                    ) from e
                ref_shape = tuple(getattr(ref, "shape", np.shape(ref)))
                if tuple(got.shape) != ref_shape:
                    raise CheckpointCorruptError(
                        step,
                        f"{name} shape {tuple(got.shape)} != template "
                        f"{ref_shape}",
                    )
                ref_dt = np.dtype(
                    getattr(ref, "dtype", None) or np.asarray(ref).dtype
                )
                if np.dtype(got.dtype) != ref_dt:
                    raise CheckpointCorruptError(
                        step,
                        f"{name} dtype {got.dtype} != template {ref_dt}",
                    )
                if shard is not None:
                    got = put_leaf(got, shard)
                leaves.append(got)
        state = jax.tree_util.tree_unflatten(treedef, leaves)
        return state, meta

    def close(self) -> None:
        pass


def _make_backend(backend: str, directory: str, keep: int):
    """Shared auto/orbax/npz backend selection for both checkpointers."""
    if backend == "auto":
        backend = "orbax" if _HAVE_ORBAX else "npz"
    if backend == "orbax" and not _HAVE_ORBAX:
        raise RuntimeError("orbax backend requested but orbax is not importable")
    cls = _OrbaxBackend if backend == "orbax" else _NpzBackend
    return backend, cls(directory, keep)


class _CkptMetrics:
    """Live-metrics publishing shared by both checkpointers (utils/obs.py;
    registry=None stays a no-op). The last-save timestamp gauge is what
    the watchdog's checkpoint-staleness detector (train/monitor.py) ages
    against; the step gauge tells a dashboard how far back a restore
    would rewind."""

    def __init__(self, registry=None):
        if registry is None:
            from .obs import NULL_REGISTRY

            registry = NULL_REGISTRY
        self.saves = registry.counter(
            "checkpoint_saves_total", "Checkpoints written this run"
        )
        self.last_save = registry.gauge(
            "checkpoint_last_save_timestamp_seconds",
            "Unix time of the newest checkpoint save",
        )
        self.last_step = registry.gauge(
            "checkpoint_last_step", "Step/epoch of the newest checkpoint"
        )
        self.elastic_events = registry.counter(
            "elastic_events_total",
            "Elastic reshard events, by kind (train/elastic.py)",
        )

    def saved(self, step: int) -> None:
        import time

        from .obs import flight_event

        self.saves.inc()
        self.last_save.set(time.time())
        self.last_step.set(int(step))
        flight_event("checkpoint_save", step=int(step))

    def elastic(self, kind: str) -> None:
        from .obs import flight_event

        self.elastic_events.labels(kind=kind).inc()
        flight_event("elastic", what=kind)


class TreeCheckpointer:
    """Save/restore an arbitrary pytree + metadata (same backends).

    The Engine-agnostic sibling of `Checkpointer`, used by the LM trainer
    (`lm_train.py`): state is any pytree of arrays (params/momentum under
    whatever mesh sharding), `meta` any JSON-serializable dict. On restore,
    pass `shardings` (a matching pytree of jax.sharding.Sharding, or None)
    to re-place leaves onto the run's mesh.
    """

    def __init__(self, directory: str, *, keep: int = 3, backend: str = "auto",
                 registry=None):
        self.backend_name, self._b = _make_backend(backend, directory, keep)
        self._metrics = _CkptMetrics(registry)

    def save(self, step: int, state, meta: dict | None = None) -> None:
        from .goodput import ledger_interval

        # host conversion on EVERY rank (it may be collective for
        # cross-process-sharded leaves); file writes on rank 0 only.
        # The whole save (gather + write) is checkpoint_save badput on
        # the goodput ledger - it blocks the step loop.
        with ledger_interval("checkpoint_save"):
            host = _host_tree(state)
            if _is_writer_rank():
                self._b.save(step, host, meta or {})
        self._metrics.saved(step)

    def latest_step(self):
        return self._b.latest_step()

    def latest_meta(self, *, log=print):
        """(step, meta) of the newest checkpoint with READABLE meta, or
        None - the cheap peek the elastic resume path (train/elastic.py)
        uses to learn the SAVED mesh topology before deciding which
        template (and which resharding plan) the real restore needs."""
        steps = self._b.all_steps()
        for step in reversed(steps):
            try:
                return step, self._b.load_meta(step)
            except CheckpointCorruptError as e:
                log(f"(WARNING: {e}; falling back to the previous "
                    "checkpoint)")
        return None

    def restore_latest(self, template, shardings=None, *, log=print):
        """(state, meta, step) from the newest VALID checkpoint, or None.

        `template` supplies the tree structure (its leaf values are unused;
        `jax.ShapeDtypeStruct` leaves work); `shardings` places each
        restored leaf onto its target sharding. The npz backend applies
        the sharding PER LEAF at read time (one leaf of host memory at a
        peak, never the whole unsharded tree - the host-OOM hazard of
        restoring a large model); orbax restores its own way and leaves
        are placed afterwards. A newest checkpoint that fails validation
        (CheckpointCorruptError - e.g. the writer was killed mid-save on a
        filesystem without atomic rename) is skipped with a warning and
        the previous step is tried, oldest last; only if every retained
        checkpoint is corrupt does the error propagate.
        """
        steps = self._b.all_steps()
        if not steps:
            return None
        last_err = None
        for step in reversed(steps):
            try:
                state, meta = self._b.restore(step, template, shardings)
            except CheckpointCorruptError as e:
                log(f"(WARNING: {e}; falling back to the previous "
                    "checkpoint)")
                last_err = e
                continue
            if shardings is not None and self.backend_name != "npz":
                from ..parallel.reshard import place_tree

                state = place_tree(state, shardings)
            return state, meta, step
        raise last_err

    def close(self) -> None:
        self._b.close()


class Checkpointer:
    """Save/restore an Engine's sync-boundary state.

    `maybe_save(epoch, engine)` after each epoch; `restore_latest(engine)`
    before training to resume. Restore re-places arrays onto the engine's
    own mesh shardings, so the checkpoint itself is platform-agnostic.
    """

    def __init__(
        self,
        directory: str,
        *,
        every: int = 1,
        keep: int = 3,
        backend: str = "auto",
        registry=None,
    ):
        self.backend_name, self._b = _make_backend(backend, directory, keep)
        self._metrics = _CkptMetrics(registry)
        self.every = every

    # ------------------------------------------------------------------ save

    def maybe_save(self, epoch: int, engine) -> bool:
        if self.every <= 0 or (epoch + 1) % self.every != 0:
            return False
        self.save(epoch, engine)
        return True

    def save(self, epoch: int, engine) -> None:
        from ..train.guard import resume_cursor
        from .goodput import ledger_interval

        with ledger_interval("checkpoint_save"):
            state = _host_tree(engine.state_tree())
            meta = {
                "epoch": epoch,
                "n_workers": engine.n_workers,
                "regime": engine.config.regime,
                "history": [dataclasses.asdict(m) for m in engine.history],
                # save-time mesh topology so a restore into a different
                # worker count is DETECTED and (with elastic=True)
                # resharded instead of crashing on a momentum-stack shape
                # mismatch
                "mesh_meta": engine.mesh_meta(),
                # versioned exact-resume cursor: every shuffle/fault
                # stream is a pure function of (seed, epoch), so these two
                # pin the continuation's data order bit-exactly
                # (train/guard.py)
                **resume_cursor(step=epoch, seed=engine.config.seed),
            }
            if _is_writer_rank():
                self._b.save(epoch, state, meta)
        self._metrics.saved(epoch)

    # --------------------------------------------------------------- restore

    def latest_epoch(self):
        return self._b.latest_step()

    def restore_latest(self, engine, *, elastic: bool = False,
                       log=print) -> int:
        """Load the newest VALID checkpoint into `engine`; returns the next
        epoch to run (0 if no checkpoint exists). A corrupt newest
        checkpoint is skipped with a warning (same fallback semantics as
        `TreeCheckpointer.restore_latest`).

        ``elastic=True`` accepts a checkpoint written under a DIFFERENT
        worker count: the restore template is rebuilt for the saved stack
        shape (so leaf validation still applies) and the per-device
        momentum stack is resharded onto this engine's mesh
        (`parallel/reshard.py reshard_momentum_stack`: surviving workers
        keep their buffers on shrink, new workers start with zero momentum
        on grow). The replicated params re-place unchanged. Without it, a
        worker-count mismatch stays a hard error naming the fix."""
        steps = self._b.all_steps()
        if not steps:
            return 0
        state = meta = None
        last_err = None
        want = engine.state_tree()
        for step in reversed(steps):
            try:
                n_saved = int(
                    self._b.load_meta(step).get(
                        "n_workers", engine.n_workers
                    )
                )
                template = want
                if n_saved != engine.n_workers:
                    # validate against the SAVED stack shape; the elastic
                    # decision happens after the meta checks below
                    template = {
                        "params": want["params"],
                        "mom": jax.tree.map(
                            lambda m: jax.ShapeDtypeStruct(
                                (n_saved, *m.shape[1:]), m.dtype
                            ),
                            want["mom"],
                        ),
                    }
                state, meta = self._b.restore(step, template)
                break
            except CheckpointCorruptError as e:
                log(f"(WARNING: {e}; falling back to the previous "
                    "checkpoint)")
                last_err = e
        if meta is None:
            raise last_err
        if meta["n_workers"] != engine.n_workers:
            if not elastic:
                raise ValueError(
                    f"checkpoint was written with "
                    f"n_workers={meta['n_workers']}, engine has "
                    f"{engine.n_workers} - momentum buffers don't map; "
                    "pass elastic=True (CLI: --elastic) to reshard the "
                    "momentum stack onto this worker count"
                )
            from ..parallel.reshard import reshard_momentum_stack

            n_saved = int(meta["n_workers"])
            state = {
                "params": state["params"],
                "mom": reshard_momentum_stack(
                    state["mom"], engine.n_workers
                ),
            }
            self._metrics.elastic(
                "shrink" if engine.n_workers < n_saved else "grow"
            )
            log(
                f"(elastic: momentum stack resharded {n_saved} -> "
                f"{engine.n_workers} workers; "
                + ("surviving workers keep their buffers)"
                   if engine.n_workers < n_saved
                   else "new workers start with zero momentum)")
            )
        if meta["regime"] != engine.config.regime:
            raise ValueError(
                f"checkpoint regime mismatch: written by a {meta['regime']!r} "
                f"run, engine is {engine.config.regime!r} - resuming would "
                "silently change the data-placement policy mid-trajectory"
            )
        from ..train.guard import check_cursor

        check_cursor(meta, seed=engine.config.seed, what="engine")
        engine.load_state_tree(state)
        from ..train.engine import EpochMetrics

        engine.history = [EpochMetrics(**m) for m in meta["history"]]
        return meta["epoch"] + 1

    def close(self) -> None:
        self._b.close()
