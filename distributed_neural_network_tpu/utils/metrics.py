"""Experiment metrics: series tracking with the reference's channel layout.

Parity (SURVEY.md section 5.5): the reference logs three channels -
(1) Neptune series `train/loss`, `val/loss`, `val/acc` plus a `parameters`
dict (`data_parallelism_train.py:106-112,180-181,250`), (2) stdout epoch
prints, (3) phase-time files under `log/`. This module provides the same
series names over pluggable sinks: an always-on JSONL writer (local,
credential-free - the hardcoded Neptune API tokens at
`single_proc_train.py:22` are deliberately NOT reproduced), stdout, and an
optional real Neptune sink if the library + env credentials are present.
"""

from __future__ import annotations

import json
import os
import time

TRAIN_LOSS = "train/loss"
VAL_LOSS = "val/loss"
VAL_ACC = "val/acc"


class MetricsRun:
    """A metrics run: `run.append(series, value)`, `run["parameters"] = {...}`.

    Mirrors the subset of the neptune.Run API the reference uses
    (`run["train/loss"].append(...)` => `run.append("train/loss", ...)`).
    """

    def __init__(self, sinks):
        self.sinks = list(sinks)

    def __setitem__(self, key: str, value) -> None:
        for s in self.sinks:
            s.set_value(key, value)

    def append(self, series: str, value) -> None:
        for s in self.sinks:
            s.append(series, float(value))

    def stop(self) -> None:
        for s in self.sinks:
            s.stop()


class JsonlSink:
    """One JSON object per event: {"t": ..., "series": ..., "value"/"data": ...}."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a", buffering=1)
        self._step: dict[str, int] = {}

    def set_value(self, key, value):
        self._write({"t": time.time(), "series": key, "data": value})

    def append(self, series, value):
        step = self._step.get(series, 0)
        self._step[series] = step + 1
        self._write({"t": time.time(), "series": series, "step": step, "value": value})

    def _write(self, obj):
        self._f.write(json.dumps(obj) + "\n")

    def stop(self):
        self._f.close()


class NullSink:
    def set_value(self, key, value): ...

    def append(self, series, value): ...

    def stop(self): ...


class NeptuneSink:
    """Optional real Neptune sink; requires NEPTUNE_PROJECT/NEPTUNE_API_TOKEN
    env vars (never hardcoded creds - see module docstring)."""

    def __init__(self):
        import neptune  # noqa: F401 - optional dependency

        self._run = neptune.init_run()

    def set_value(self, key, value):
        self._run[key] = value

    def append(self, series, value):
        self._run[series].append(value)

    def stop(self):
        self._run.stop()


def init_run(jsonl_path: str | None = None, neptune: bool = False) -> MetricsRun:
    sinks = []
    if jsonl_path:
        sinks.append(JsonlSink(jsonl_path))
    if neptune:
        try:
            sinks.append(NeptuneSink())
        except Exception as e:  # lib missing / no creds: degrade, don't crash
            print(f"(neptune sink unavailable: {e}; continuing with local sinks)")
    if not sinks:
        sinks.append(NullSink())
    return MetricsRun(sinks)
