"""Experiment metrics: series tracking with the reference's channel layout.

Parity (SURVEY.md section 5.5): the reference logs three channels -
(1) Neptune series `train/loss`, `val/loss`, `val/acc` plus a `parameters`
dict (`data_parallelism_train.py:106-112,180-181,250`), (2) stdout epoch
prints, (3) phase-time files under `log/`. This module provides the same
series names over pluggable sinks: an always-on JSONL writer (local,
credential-free - the hardcoded Neptune API tokens at
`single_proc_train.py:22` are deliberately NOT reproduced), stdout, and an
optional real Neptune sink if the library + env credentials are present.
"""

from __future__ import annotations

import json
import math
import os
import time

TRAIN_LOSS = "train/loss"
VAL_LOSS = "val/loss"
VAL_ACC = "val/acc"


def _sanitize(value):
    """(json-safe value, invalid-repr-or-None) for one scalar.

    `json.dumps(float("nan"))` emits a bare `NaN` token that strict JSON
    parsers (and tools/plot_metrics.py / tools/trace_summary.py) reject;
    non-finite floats serialize as null with the original repr preserved
    in an "invalid" field so the event is still attributable.
    """
    if isinstance(value, float) and not math.isfinite(value):
        return None, repr(value)
    return value, None


def _sanitize_tree(x):
    """Recursively null non-finite floats inside set_value payloads."""
    if isinstance(x, float):
        return x if math.isfinite(x) else None
    if isinstance(x, dict):
        return {k: _sanitize_tree(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_sanitize_tree(v) for v in x]
    return x


class MetricsRun:
    """A metrics run: `run.append(series, value)`, `run["parameters"] = {...}`.

    Mirrors the subset of the neptune.Run API the reference uses
    (`run["train/loss"].append(...)` => `run.append("train/loss", ...)`).
    """

    def __init__(self, sinks):
        self.sinks = list(sinks)

    def __setitem__(self, key: str, value) -> None:
        for s in self.sinks:
            s.set_value(key, value)

    def append(self, series: str, value) -> None:
        for s in self.sinks:
            s.append(series, float(value))

    def flush(self) -> None:
        """Push buffered events to durable storage (crash-safety point)."""
        for s in self.sinks:
            s.flush()

    def stop(self) -> None:
        for s in self.sinks:
            s.stop()


class JsonlSink:
    """One JSON object per event: {"t": ..., "series": ..., "value"/"data": ...}."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a", buffering=1)
        self._step: dict[str, int] = {}

    def set_value(self, key, value):
        self._write({"t": time.time(), "series": key, "data": _sanitize_tree(value)})

    def append(self, series, value):
        step = self._step.get(series, 0)
        self._step[series] = step + 1
        v, invalid = _sanitize(float(value))
        obj = {"t": time.time(), "series": series, "step": step, "value": v}
        if invalid is not None:
            obj["invalid"] = invalid
        self._write(obj)

    def _write(self, obj):
        # allow_nan=False is the backstop: a non-finite float slipping past
        # sanitization raises here instead of corrupting the file
        self._f.write(json.dumps(obj, allow_nan=False) + "\n")

    def flush(self):
        if not self._f.closed:
            self._f.flush()

    def stop(self):
        self.flush()
        self._f.close()


class NullSink:
    def set_value(self, key, value): ...

    def append(self, series, value): ...

    def flush(self): ...

    def stop(self): ...


class NeptuneSink:
    """Optional real Neptune sink; requires NEPTUNE_PROJECT/NEPTUNE_API_TOKEN
    env vars (never hardcoded creds - see module docstring)."""

    def __init__(self):
        import neptune  # noqa: F401 - optional dependency

        self._run = neptune.init_run()

    def set_value(self, key, value):
        self._run[key] = value

    def append(self, series, value):
        self._run[series].append(value)

    def flush(self):
        # neptune buffers internally; sync() exists on recent clients
        sync = getattr(self._run, "sync", None)
        if sync is not None:
            sync()

    def stop(self):
        self._run.stop()


def init_run(jsonl_path: str | None = None, neptune: bool = False) -> MetricsRun:
    sinks = []
    if jsonl_path:
        sinks.append(JsonlSink(jsonl_path))
    if neptune:
        try:
            sinks.append(NeptuneSink())
        except Exception as e:  # lib missing / no creds: degrade, don't crash
            print(f"(neptune sink unavailable: {e}; continuing with local sinks)")
    if not sinks:
        sinks.append(NullSink())
    return MetricsRun(sinks)
