"""Phase-time instrumentation with proper device fencing.

Parity with the reference's five module-global wall-clock accumulators
(`data_parallelism_train.py:33-37`): data_loading, training, evaluation, and
communication (parent/children merged - there is no parent process here).
The reference's methodology flaw (report section 6.1: comm time measured
around the pickle call, not the blocking wait) is fixed by fencing every
phase with `jax.block_until_ready` on the phase's outputs before reading the
clock - asynchronous dispatch otherwise attributes device time to whichever
phase happens to block first.
"""

from __future__ import annotations

import functools
import operator
import time
from collections import defaultdict
from contextlib import contextmanager

import jax

# canonical phase names (reference globals, data_parallelism_train.py:33-37)
DATA_LOADING = "data_loading"
TRAINING = "training"
EVALUATION = "evaluation"
COMMUNICATION = "communication"

# Default report ordering: the reference's five accumulators - data loading,
# training, evaluation, parent comm, children comm - with the two comm
# accumulators merged (one mesh, no parent process), hence four names here.
CANONICAL_PHASES = (DATA_LOADING, TRAINING, EVALUATION, COMMUNICATION)

# the reference's stdout phrasing per phase (data_parallelism_train.py
# prints; utils/logfiles.py keeps the byte-compatible *file* variants)
REPORT_LABELS = {
    DATA_LOADING: "Train data loading time",
    TRAINING: "Time spent on training",
    EVALUATION: "Time spent on evaluation",
    COMMUNICATION: "Time spent on parent communication and param sync",
}


def hard_block(tree) -> None:
    """Fence that actually waits for device execution.

    `jax.block_until_ready` alone is NOT a reliable fence on every backend:
    on the tunneled `axon` TPU platform it returns before remote execution
    completes (measured round 3: 10 chained 8192^3 matmuls "ready" in
    0.3 ms while the value fetch took 1.66 s), which silently voids any
    wall-clock bracketed with it. This fences with block_until_ready (the
    cheap, correct path on local backends) PLUS one scalar device->host
    fetch whose value data-depends on every array leaf - a fetch cannot
    complete before the computation that produces it.

    Cost: a handful of one-element slices + adds (dispatched eagerly,
    executed device-side) and a single small transfer (~60-70 ms round
    trip through the tunnel, sub-ms locally). Use once per timed phase,
    not per step.
    """
    jax.block_until_ready(tree)
    leaves = [
        l for l in jax.tree.leaves(tree)
        if hasattr(l, "ravel") and getattr(l, "size", 0)
    ]
    if not leaves:
        return
    import jax.numpy as jnp

    s = functools.reduce(
        operator.add,
        (l.ravel()[:1].astype(jnp.float32) for l in leaves),
    )
    s[0].item()  # the actual fence: value fetch forces remote completion


def fence_rtt(tree) -> float:
    """Measured cost of fencing an ALREADY-READY pytree - the pure
    device->host round trip of `hard_block`'s value fetch (~60-70 ms
    through the axon tunnel, sub-ms locally). Callers that fence a timed
    loop once subtract this so the tunnel RTT is not charged to the
    steps; shared by measure_lm_training and tools/tune_flash.py so the
    two subtraction idioms cannot drift."""
    t0 = time.perf_counter()
    hard_block(tree)
    return time.perf_counter() - t0


class PhaseTimers:
    """Accumulating wall-clock timers keyed by phase name."""

    def __init__(self):
        self.totals: dict[str, float] = defaultdict(float)

    @contextmanager
    def phase(self, name: str, fence=None):
        """Time a block; `fence` (any pytree of arrays) is block_until_ready'd
        before the clock stops, so device work is charged to this phase."""
        start = time.perf_counter()
        holder = _FenceHolder()
        try:
            yield holder
        finally:
            target = holder.value if holder.value is not None else fence
            if target is not None:
                hard_block(target)
            self.totals[name] += time.perf_counter() - start

    def add(self, name: str, seconds: float) -> None:
        self.totals[name] += seconds

    def get(self, name: str) -> float:
        return self.totals.get(name, 0.0)

    def summary(self) -> dict[str, float]:
        return dict(self.totals)

    def merge(self, other: "PhaseTimers") -> "PhaseTimers":
        """Accumulate another timer set into this one (e.g. per-worker or
        per-stage timers folded into a run total); returns self."""
        for name, seconds in other.summary().items():
            self.totals[name] += seconds
        return self

    def report(self) -> str:
        """The canonical phase-summary block, one line per phase.

        Canonical phases print first in the reference's order and phrasing
        (always, so consumers can diff reports line-by-line even when a
        phase never ran); any extra phases follow alphabetically as
        ``<name>: <seconds>``. This is the ONE formatter behind the CLI /
        measure printouts - entry points must not hand-roll their own.
        """
        lines = [
            f"{REPORT_LABELS[name]}: {self.totals.get(name, 0.0)}"
            for name in CANONICAL_PHASES
        ]
        for name in sorted(set(self.totals) - set(CANONICAL_PHASES)):
            lines.append(f"{name}: {self.totals[name]}")
        return "\n".join(lines)


class _FenceHolder:
    """`with timers.phase(...) as t: t.value = outputs` registers the fence."""

    value = None
