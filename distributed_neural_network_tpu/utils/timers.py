"""Phase-time instrumentation with proper device fencing.

Parity with the reference's five module-global wall-clock accumulators
(`data_parallelism_train.py:33-37`): data_loading, training, evaluation, and
communication (parent/children merged - there is no parent process here).
The reference's methodology flaw (report section 6.1: comm time measured
around the pickle call, not the blocking wait) is fixed by fencing every
phase with `jax.block_until_ready` on the phase's outputs before reading the
clock - asynchronous dispatch otherwise attributes device time to whichever
phase happens to block first.
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager

import jax

# canonical phase names (reference globals, data_parallelism_train.py:33-37)
DATA_LOADING = "data_loading"
TRAINING = "training"
EVALUATION = "evaluation"
COMMUNICATION = "communication"


class PhaseTimers:
    """Accumulating wall-clock timers keyed by phase name."""

    def __init__(self):
        self.totals: dict[str, float] = defaultdict(float)

    @contextmanager
    def phase(self, name: str, fence=None):
        """Time a block; `fence` (any pytree of arrays) is block_until_ready'd
        before the clock stops, so device work is charged to this phase."""
        start = time.perf_counter()
        holder = _FenceHolder()
        try:
            yield holder
        finally:
            target = holder.value if holder.value is not None else fence
            if target is not None:
                jax.block_until_ready(target)
            self.totals[name] += time.perf_counter() - start

    def add(self, name: str, seconds: float) -> None:
        self.totals[name] += seconds

    def get(self, name: str) -> float:
        return self.totals.get(name, 0.0)

    def summary(self) -> dict[str, float]:
        return dict(self.totals)


class _FenceHolder:
    """`with timers.phase(...) as t: t.value = outputs` registers the fence."""

    value = None
