"""Subpackage: utils."""
