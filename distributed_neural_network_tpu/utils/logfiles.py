"""Phase-time log files with the reference's exact naming/line scheme.

Parity: the reference parent writes
``log/bs{bs}_log_epochs{epochs}_proc{nb_proc}_parent.txt`` with eval-side
phase totals (`data_parallelism_train.py:103-104,126-129`) and child rank 2
writes ``..._children.txt`` with train-side totals (`:143-152`), enabling
drop-in comparison against the reference's own logs under
`/root/reference/log/`. There are no separate parent/child processes on the
mesh, but both files are still emitted - "parent" = eval-side phases,
"children" = train-side phases - with byte-compatible line formats.
"""

from __future__ import annotations

import os

from .timers import COMMUNICATION, DATA_LOADING, EVALUATION, PhaseTimers, TRAINING


def log_basename(bs: int, epochs: int, nb_proc: int, role: str) -> str:
    return f"bs{bs}_log_epochs{epochs}_proc{nb_proc}_{role}.txt"


def write_phase_logs(
    log_dir: str,
    *,
    bs: int,
    epochs: int,
    nb_proc: int,
    timers: PhaseTimers,
    eval_data_loading: float | None = None,
) -> tuple[str, str]:
    """Write the parent+children phase-log pair; returns their paths."""
    os.makedirs(log_dir, exist_ok=True)
    parent = os.path.join(log_dir, log_basename(bs, epochs, nb_proc, "parent"))
    children = os.path.join(log_dir, log_basename(bs, epochs, nb_proc, "children"))
    eval_load = (
        eval_data_loading
        if eval_data_loading is not None
        else timers.get(DATA_LOADING)
    )
    with open(parent, "w") as f:
        f.write("Eval data loading time: {0}\n".format(eval_load))
        f.write("Time spent on evaluation: {0}\n".format(timers.get(EVALUATION)))
        f.write(
            "Time spent on parent communication and param sync: {0}\n".format(
                timers.get(COMMUNICATION)
            )
        )
    with open(children, "w") as f:
        f.write("Train data loading time: {0}\n".format(timers.get(DATA_LOADING)))
        f.write("Time spent on training: {0}\n".format(timers.get(TRAINING)))
        f.write(
            "Time spent on children communication: {0}\n".format(
                timers.get(COMMUNICATION)
            )
        )
    return parent, children
