"""Goodput ledger: wall-clock efficiency accounting over a closed badput
taxonomy, run records, and the fleet-level aggregation.

Every observability layer before this one emits RAW signals - spans
(`utils/tracing.py`), StepStats, flight-recorder events (`utils/obs.py`),
guard rollbacks (`train/guard.py`), reshard spans (`train/elastic.py`),
watchdog stall episodes (`train/monitor.py`), supervisor restarts
(`train/supervisor.py`). None of them answers the one question production
TPU fleets are run by (arXiv 2204.06514's utilization accounting, arXiv
2412.14374's bubble accounting): *what fraction of total wall-clock
produced training progress, and which failure/overhead class consumed the
rest?* This module is that synthesis layer.

**Taxonomy** (closed - every wall-clock second lands in exactly one
bucket; `CAUSES` is the schema):

- ``init``            - process start -> first step dispatch (mesh build,
                        param init, data load, rendezvous).
- ``compile``         - the compile step(s) (first dispatch pays XLA).
- ``steady_step``     - compiled steps that advanced training. THE
                        goodput bucket; everything else is badput.
- ``data_wait``       - host-side input pipeline blocking the step loop.
- ``checkpoint_save`` - writing checkpoints (periodic + emergency).
- ``reshard``         - elastic checkpoint->mesh redistribution.
- ``rollback_recompute`` - steps re-executed after a guard rollback
                        (lost steps x steady step time, attributed on the
                        replayed steps themselves so the cost is the
                        MEASURED recompute, not an estimate).
- ``stall``           - no-progress episodes flagged by the watchdog
                        (wedged collective, host sleep, dead thread).
- ``restart_gap``     - worker death -> first post-restart step, measured
                        supervisor-side across relaunches (the fleet
                        aggregation reclassifies a restart generation's
                        init+compile into this bucket - those seconds are
                        restart cost, not fresh-run startup).
- ``idle_other``      - the residual (eval, logging, host overhead);
                        computed as total - attributed, never recorded
                        directly.

**Serving taxonomy** (schema v2): the inference service
(`serve/scheduler.py`) runs the same ledger machinery over its own
closed cause set - ``queue_wait``, ``prefill``, ``decode`` (goodput),
``batch_formation_idle``, ``kv_alloc_stall``, ``idle_other`` - selected
with ``GoodputLedger(taxonomy="serve")``. Records carry a ``taxonomy``
field; v1 records (training, no field) still parse, and every reader
(`render_record`, `diff_records`, `check_record`, `tools/goodput.py`)
resolves causes through `record_taxonomy`.

**Conservation.** Intervals are attributed ONCE: overlapping recordings
are resolved by a priority sweep (instrumented intervals beat the
watchdog's coarse stall window, which beats nothing), the residual is
``idle_other``, and ``finalize()`` asserts the buckets sum to total
wall-clock to float precision. Concurrent publishers (step loop, watchdog
thread, checkpoint writer) therefore cannot double-count a second.

**Records.** Each run emits a schema-versioned ``run_record.json``
(`RECORD_VERSION`): config fingerprint, mesh topology, step/token counts,
goodput ratio, per-cause badput seconds, final metrics. While the run is
live the ledger writes the record THROUGH at a bounded cadence (atomic
tmp+rename, the `HeartbeatFileWriter`/`FlightRecorder` idiom), so a
SIGKILLed worker's accounting up to the last write is already on disk and
lands in the supervisor's fleet aggregation (`fleet_goodput_record`) and
``postmortem.json``. `tools/goodput.py` renders, diffs, and - against a
checked-in baseline with per-cause tolerances - gates regressions in CI.

Stdlib-only (no jax import): the ledger runs identically in workers, the
supervisor, `tools/goodput.py`, and tests. Live export rides the metrics
registry (``goodput_ratio`` gauge + ``badput_seconds_total{cause}``
counter, `utils/obs.py`); docs/OBSERVABILITY.md "Goodput accounting".
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import threading
import time

# bump when the run-record schema changes shape; readers accept same-or-
# older versions and refuse newer ones with a clear message.
# v1: training taxonomy only. v2: adds the `taxonomy` field ("train" |
# "serve") and the serving cause set; v1 records (no taxonomy field)
# still parse and render as training records.
RECORD_VERSION = 2

# env var naming the per-worker run-record path; the elastic supervisor
# (train/supervisor.py) exports it next to the heartbeat/flight files
RUN_RECORD_ENV = "DNN_TPU_RUN_RECORD"

# the closed TRAINING taxonomy, in report order. steady_step is goodput;
# idle_other is the computed residual (never recorded directly).
GOODPUT_CAUSE = "steady_step"
IDLE_CAUSE = "idle_other"
CAUSES = (
    "init",
    "compile",
    GOODPUT_CAUSE,
    "data_wait",
    "checkpoint_save",
    "reshard",
    "rollback_recompute",
    "stall",
    "restart_gap",
    IDLE_CAUSE,
)
BADPUT_CAUSES = tuple(c for c in CAUSES if c != GOODPUT_CAUSE)

# the closed SERVING taxonomy (serve/scheduler.py's ledger): decode -
# tokens reaching users - is the goodput bucket; prefill is real work
# but not yet user-visible tokens, queue_wait is time requests sat
# admitted-but-unserved while the engine had no free capacity,
# batch_formation_idle is scheduler overhead between having runnable
# work and dispatching the step, kv_alloc_stall is progress blocked on
# KV-block exhaustion.
SERVE_GOODPUT_CAUSE = "decode"
SERVE_CAUSES = (
    "queue_wait",
    "prefill",
    SERVE_GOODPUT_CAUSE,
    "batch_formation_idle",
    "kv_alloc_stall",
    IDLE_CAUSE,
)
SERVE_BADPUT_CAUSES = tuple(
    c for c in SERVE_CAUSES if c != SERVE_GOODPUT_CAUSE
)

# overlap-resolution priority (lower wins): precisely instrumented
# intervals (step walls, checkpoint saves, reshard spans, data waits)
# always beat the watchdog's coarse stall window, which covers the idle
# gap between heartbeats and may overhang into the next completed step.
# Fill intervals (internal: the untelemetered fast path's whole-window
# coarse attribution, and the synthesized open-init prefix) rank below
# everything, so any precisely recorded interval carves itself out of a
# fill instead of being swallowed by it.
_PRIORITY = {c: 0 for c in CAUSES}
_PRIORITY["stall"] = 1
_PRIORITY["restart_gap"] = 1
_FILL_CAUSES = {"_steady_fill": GOODPUT_CAUSE, "_init_fill": "init"}
_PRIORITY["_steady_fill"] = 2
_PRIORITY["_init_fill"] = 3

# serving overlap resolution: the engine's precisely fenced compute
# spans (prefill/decode/kv_alloc_stall/batch_formation_idle) always win;
# queue_wait is recorded per request over its whole admitted-but-queued
# window and may overlap the engine serving OTHER requests, so it only
# claims otherwise-idle seconds (the capacity-pressure signal).
_SERVE_PRIORITY = {c: 0 for c in SERVE_CAUSES}
_SERVE_PRIORITY["queue_wait"] = 1

# taxonomy registry: name -> (causes, goodput cause, priority map,
# fill-cause map). `GoodputLedger(taxonomy=...)` and every record
# reader resolve through this table.
TAXONOMIES = {
    "train": (CAUSES, GOODPUT_CAUSE, _PRIORITY, _FILL_CAUSES),
    "serve": (SERVE_CAUSES, SERVE_GOODPUT_CAUSE, _SERVE_PRIORITY, {}),
}


def record_taxonomy(rec: dict) -> tuple:
    """``(causes, goodput_cause)`` for a record: v2 records carry a
    ``taxonomy`` field, v1 records are training records. Unknown
    taxonomy names (a future build's record that still validated as
    version <= RECORD_VERSION) fall back to the record's own badput
    keys so rendering never drops a bucket."""
    name = rec.get("taxonomy") or "train"
    if name in TAXONOMIES:
        causes, goodput, _, _ = TAXONOMIES[name]
        return causes, goodput
    bad = tuple((rec.get("badput_s") or {}).keys())
    return ("goodput",) + bad, "goodput"


class _Interval:
    __slots__ = ("t0", "t1", "cause")

    def __init__(self, t0: float, t1: float, cause: str):
        self.t0 = t0
        self.t1 = t1
        self.cause = cause


class _LedgerSpan:
    """Context manager recording one interval on exit (never raises)."""

    __slots__ = ("_ledger", "cause", "_t0", "dur_s")

    def __init__(self, ledger, cause):
        self._ledger = ledger
        self.cause = cause
        self.dur_s = 0.0

    def __enter__(self):
        self._t0 = self._ledger._now()
        return self

    def __exit__(self, *exc):
        t1 = self._ledger._now()
        self.dur_s = t1 - self._t0
        self._ledger.add(self.cause, self._t0, t1)
        return False


class _NullSpan:
    __slots__ = ()
    dur_s = 0.0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def attribute_intervals(
    intervals, start: float, end: float, *, priority=None,
    causes=CAUSES, fills=None,
) -> dict:
    """Sweep-line attribution: partition ``[start, end]`` over the
    recorded intervals so every second is counted exactly once.

    Overlaps are resolved by ``(priority, start-time, sequence)`` - the
    highest-priority (lowest number), earliest interval owns the overlap;
    uncovered time is ``idle_other``. Same-cause overlapping intervals
    (the watchdog re-reporting a growing stall episode every poll)
    therefore coalesce instead of double-counting. Returns a full
    ``{cause: seconds}`` dict over `CAUSES`; the values sum to
    ``end - start`` to float precision BY CONSTRUCTION - the conservation
    rule `GoodputLedger.finalize` asserts.
    """
    import heapq

    prio = priority if priority is not None else _PRIORITY
    fill_map = fills if fills is not None else _FILL_CAUSES
    out = {c: 0.0 for c in causes}
    if end <= start:
        return out
    ivs = sorted(
        (
            (max(iv.t0, start), min(iv.t1, end), iv.cause, seq)
            for seq, iv in enumerate(intervals)
            if iv.t1 > start and iv.t0 < end and iv.t1 > iv.t0
        ),
        key=lambda x: x[0],
    )
    heap: list = []  # (priority, t0, seq, t1, cause)
    t = start
    i = 0
    n = len(ivs)
    while t < end:
        while i < n and ivs[i][0] <= t:
            t0, t1, cause, seq = ivs[i]
            if t1 > t:
                heapq.heappush(
                    heap, (prio.get(cause, 0), t0, seq, t1, cause)
                )
            i += 1
        while heap and heap[0][3] <= t:
            heapq.heappop(heap)
        next_start = ivs[i][0] if i < n else end
        if heap:
            winner_t1, winner_cause = heap[0][3], heap[0][4]
            seg_end = min(winner_t1, next_start, end)
            out[winner_cause] = out.get(winner_cause, 0.0) + (seg_end - t)
        else:
            seg_end = min(next_start, end)
            out[IDLE_CAUSE] += seg_end - t
        t = seg_end
    # fold internal fill causes into their public buckets
    for fill, public in fill_map.items():
        if fill in out:
            out[public] += out.pop(fill)
    return out


class GoodputLedger:
    """Event-sourced wall-clock accounting for one process.

    Disabled by default (every call is a cheap no-op - the `NULL_TRACER`
    / `NULL_REGISTRY` convention); ``start()`` arms it. Thread-safe: the
    step loop, the watchdog thread, and the checkpoint writer all publish
    into one ledger, and the sweep (`attribute_intervals`) guarantees
    each second is attributed once regardless of interleaving.

    Feeds (all optional, all additive):
    - ``step_span(step, dur_s)``  - one completed step's wall time
      (`train/lm.py make_traced_step`, `train/engine.py run_epoch`).
      The first span closes the implicit ``init`` interval and counts as
      ``compile`` unless told otherwise; spans inside a rollback-replay
      window count as ``rollback_recompute`` (see ``mark_recompute``).
    - ``interval(cause)``         - context manager for instrumented
      blocks (checkpoint saves, reshards, data waits).
    - ``add`` / ``add_ending_now``- retroactive attribution (the
      watchdog's stall episodes).
    - ``mark_recompute(n)``       - the next ``n`` step spans are
      rollback recompute, not goodput (`train/guard.py rollback`).

    ``taxonomy`` selects the cause set: ``"train"`` (the default - the
    original closed training taxonomy) or ``"serve"`` (the serving
    ledger: queue_wait / prefill / decode / batch_formation_idle /
    kv_alloc_stall, `serve/scheduler.py`). A serving ledger records via
    ``interval``/``add``/``add_ending_now`` + ``note_steps``; the
    training-specific feeds (``step_span``, ``fill_ending_now``,
    ``mark_recompute``) reject the serve taxonomy loudly.
    """

    def __init__(self, *, clock=time.monotonic, taxonomy: str = "train"):
        if taxonomy not in TAXONOMIES:
            raise ValueError(
                f"unknown ledger taxonomy {taxonomy!r} "
                f"(known: {', '.join(sorted(TAXONOMIES))})"
            )
        self.taxonomy = taxonomy
        (self._causes, self._goodput_cause, self._priority,
         self._fills) = TAXONOMIES[taxonomy]
        self._badput_causes = tuple(
            c for c in self._causes if c != self._goodput_cause
        )
        self._clock = clock
        self._lock = threading.Lock()
        self.enabled = False
        self.reset()

    # ------------------------------------------------------------- control

    def reset(self) -> None:
        """Back to the disarmed zero state (test hygiene for `LEDGER`)."""
        with self._lock:
            self.enabled = False
            self._intervals: list[_Interval] = []
            self._t_start: float | None = None
            self._t_init_open: float | None = None
            self.started_unix: float | None = None
            self.steps = 0
            self.goodput_steps = 0
            self.tokens = 0.0
            self._recompute_budget = 0
            self._seen_compile = False
            self.path: str | None = None
            self.write_interval_s = 5.0
            self._last_write = 0.0
            self.publish_interval_s = 2.0
            self._last_publish = 0.0
            self._registry = None
            self._m_ratio = None
            self._m_badput = None
            self.config: dict = {}
            self.config_fingerprint: str | None = None
            self.mesh: dict = {}
            self.rank: int | None = None
            self.generation: int | None = None
            self.metrics: dict = {}

    def start(self, *, rank: int | None = None) -> "GoodputLedger":
        """Arm the ledger; wall-clock zero is NOW and an ``init``
        interval opens, closed by the first ``step_span``."""
        with self._lock:
            self.enabled = True
            self._t_start = self._clock()
            # "init" and its synthesized fill exist only in the training
            # taxonomy; a serving ledger's pre-first-request prefix is
            # plain idle_other
            self._t_init_open = (
                self._t_start if self.taxonomy == "train" else None
            )
            self.started_unix = time.time()
            if rank is not None:
                self.rank = int(rank)
            elif self.rank is None:
                env = os.environ.get("JAX_PROCESS_ID")
                try:
                    self.rank = int(env) if env is not None else None
                except ValueError:
                    self.rank = None
            gen = os.environ.get("DNN_TPU_SUPERVISOR_GEN")
            try:
                self.generation = int(gen) if gen is not None else None
            except ValueError:
                self.generation = None
        return self

    def arm(self, path: str, *, write_interval_s: float = 5.0) -> None:
        """Write the (partial) run record through to ``path`` at a
        bounded cadence - the SIGKILL-survival channel (armed from
        `RUN_RECORD_ENV` by `train/monitor.py attach_monitor`)."""
        self.path = os.path.abspath(path)
        self.write_interval_s = float(write_interval_s)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self.write_record(final=False)

    def publish(self, registry) -> None:
        """Export ``goodput_ratio`` + ``badput_seconds_total{cause}`` on
        ``registry`` (utils/obs.py), refreshed at a bounded cadence from
        ``step_span`` and once on ``finalize``."""
        self._registry = registry
        self._m_ratio = registry.gauge(
            "goodput_ratio",
            "Fraction of wall-clock spent in steady training steps",
        )
        self._m_badput = registry.counter(
            "badput_seconds_total",
            "Wall-clock lost to non-goodput causes (utils/goodput.py)",
        )

    def describe(self, *, config: dict | None = None, mesh: dict | None = None,
                 metrics: dict | None = None) -> None:
        """Attach run identity to the record: ``config`` is fingerprinted
        (sha256 over sorted JSON), ``mesh`` is the topology block,
        ``metrics`` the final numbers (merged - call any time)."""
        if config is not None:
            self.config = _json_safe(config)
            self.config_fingerprint = config_fingerprint(config)
        if mesh is not None:
            self.mesh = _json_safe(mesh)
        if metrics is not None:
            self.metrics.update(_json_safe(metrics))

    # ------------------------------------------------------------ recording

    def _now(self) -> float:
        return self._clock()

    def _check_cause(self, cause: str) -> None:
        if cause not in self._causes or cause == IDLE_CAUSE:
            raise ValueError(
                f"unknown {self.taxonomy} goodput cause {cause!r} "
                f"(closed taxonomy: "
                f"{', '.join(c for c in self._causes if c != IDLE_CAUSE)}; "
                f"{IDLE_CAUSE} is the computed residual)"
            )

    def interval(self, cause: str, **_meta):
        """``with ledger.interval("checkpoint_save"): ...`` - no-op when
        disarmed."""
        if not self.enabled:
            return _NULL_SPAN
        self._check_cause(cause)
        return _LedgerSpan(self, cause)

    def add(self, cause: str, t0: float, t1: float) -> None:
        """Record one closed interval on the ledger's own clock."""
        if not self.enabled or t1 <= t0:
            return
        self._check_cause(cause)
        with self._lock:
            self._intervals.append(_Interval(t0, t1, cause))

    def add_ending_now(self, cause: str, dur_s: float) -> None:
        """Record an interval of ``dur_s`` seconds ending now - the
        retroactive form (the watchdog knows how long the heartbeat has
        been missing, not when the stall will end; re-reporting a growing
        episode every poll coalesces in the sweep)."""
        if not self.enabled or dur_s <= 0:
            return
        now = self._now()
        self.add(cause, now - dur_s, now)

    def now(self) -> float:
        """The ledger's own clock (for retroactive ``add`` timestamps)."""
        return self._now()

    def fill_ending_now(self, cause: str, dur_s: float) -> None:
        """Record a COARSE fill interval of ``dur_s`` seconds ending now:
        it ranks below every precisely recorded interval in the sweep, so
        instrumented activity inside the window (checkpoint saves, stall
        episodes) still carves out its own attribution - the
        untelemetered fast path's whole-steady-window accounting
        (`lm_train.py` without trace/metrics, where fencing each step
        just to time it would change the run)."""
        if not self.enabled or dur_s <= 0:
            return
        fill = {v: k for k, v in self._fills.items()}.get(cause)
        if fill is None:
            raise ValueError(
                f"no fill bucket for cause {cause!r} in the "
                f"{self.taxonomy} taxonomy "
                f"(fills: {sorted(self._fills.values())})"
            )
        now = self._now()
        with self._lock:
            self._intervals.append(_Interval(now - dur_s, now, fill))

    def mark_recompute(self, n_steps: int) -> None:
        """The next ``n_steps`` completed steps are rollback replay
        (lost progress being re-earned), attributed to
        ``rollback_recompute`` instead of ``steady_step``."""
        if not self.enabled or n_steps <= 0:
            return
        with self._lock:
            self._recompute_budget += int(n_steps)

    def note_steps(self, n: int, *, tokens: float = 0.0) -> None:
        """Bookkeeping-only step counting for callers that attribute
        wall-clock coarsely via ``add``/``add_ending_now`` instead of
        per-step spans (the untelemetered fast path, where fencing every
        step just to time it would change the run being accounted)."""
        if not self.enabled or n <= 0:
            return
        with self._lock:
            self.steps += int(n)
            self.goodput_steps += int(n)
            self.tokens += float(tokens)
            self._seen_compile = True

    def step_span(
        self, step: int, dur_s: float, *,
        tokens: float = 0.0, is_compile: bool | None = None,
    ) -> None:
        """One completed training step of ``dur_s`` seconds ending now.

        The first span (unless ``is_compile=False``) is the compile step;
        it also closes the implicit ``init`` interval at its own start.
        """
        if not self.enabled:
            return
        if self.taxonomy != "train":
            raise ValueError(
                "step_span is the training ledger's feed; a "
                f"{self.taxonomy!r} ledger records via interval()/add() "
                "+ note_steps()"
            )
        now = self._now()
        t0 = now - max(float(dur_s), 0.0)
        with self._lock:
            if self._t_init_open is not None:
                if t0 > self._t_init_open:
                    self._intervals.append(
                        _Interval(self._t_init_open, t0, "init")
                    )
                self._t_init_open = None
            if is_compile is None:
                is_compile = not self._seen_compile
            if is_compile:
                cause = "compile"
                self._seen_compile = True
            elif self._recompute_budget > 0:
                self._recompute_budget -= 1
                cause = "rollback_recompute"
            else:
                cause = GOODPUT_CAUSE
                self.goodput_steps += 1
                self.tokens += float(tokens)
            self.steps += 1
            self._intervals.append(_Interval(t0, now, cause))
        self.maybe_publish(at=now)
        self.maybe_write(at=now)

    def maybe_publish(self, *, at: float | None = None,
                      force: bool = False) -> None:
        """Refresh the registry export at the bounded cadence - called
        from `step_span` on the training path and from the serve loop
        (`serve/scheduler.py`), whose feed is `add`/`interval` and so
        never passes through `step_span`."""
        if self._registry is None or not self.enabled:
            return
        now = self._now() if at is None else at
        if force or now - self._last_publish >= self.publish_interval_s:
            self._last_publish = now
            self._publish_breakdown(self.breakdown(at=now))

    def maybe_write(self, *, at: float | None = None) -> None:
        """Write-through at the bounded cadence (same split as
        `maybe_publish`)."""
        if self.path is None or not self.enabled:
            return
        now = self._now() if at is None else at
        if now - self._last_write >= self.write_interval_s:
            self._last_write = now
            self.write_record(final=False)

    # ------------------------------------------------------------- summary

    def breakdown(self, at: float | None = None) -> dict:
        """``{cause: seconds}`` over the full taxonomy up to ``at`` (now
        by default); values sum to total wall-clock by construction."""
        if self._t_start is None:
            return {c: 0.0 for c in self._causes}
        end = self._now() if at is None else at
        with self._lock:
            intervals = list(self._intervals)
            if self._t_init_open is not None:
                # init never closed by a step span: synthesize the prefix
                # up to the first recorded activity (whole window when
                # nothing was recorded), as a low-priority fill so
                # retroactive adds that reach back before the first
                # activity still win their overlap
                first = min((iv.t0 for iv in intervals), default=end)
                stop = min(max(first, self._t_init_open), end)
                if stop > self._t_init_open:
                    intervals.append(
                        _Interval(self._t_init_open, stop, "_init_fill")
                    )
        return attribute_intervals(
            intervals, self._t_start, end, priority=self._priority,
            causes=self._causes, fills=self._fills,
        )

    def wall_s(self, at: float | None = None) -> float:
        if self._t_start is None:
            return 0.0
        return (self._now() if at is None else at) - self._t_start

    def _publish_breakdown(self, buckets: dict) -> None:
        total = sum(buckets.values())
        if total > 0:
            self._m_ratio.set(buckets[self._goodput_cause] / total)
        for cause in self._badput_causes:
            if buckets[cause] > 0:
                # set_max: totals only accumulate, so a re-publish (or a
                # sweep re-resolution shaving an overlap) never regresses
                # the counter
                self._m_badput.labels(cause=cause).set_max(buckets[cause])

    def finalize(self, *, metrics: dict | None = None) -> dict:
        """Close the ledger into a run record: compute the breakdown,
        ASSERT conservation (buckets sum to total wall-clock, every
        bucket non-negative), publish the final registry export, write
        the record through when armed, and return it."""
        if metrics is not None:
            self.describe(metrics=metrics)
        end = self._now()
        buckets = self.breakdown(at=end)
        total = self.wall_s(at=end)
        attributed = sum(buckets.values())
        if any(v < 0 for v in buckets.values()) or (
            abs(attributed - total) > max(1e-6 * max(total, 1.0), 1e-9)
        ):
            raise AssertionError(
                "goodput conservation violated: buckets sum to "
                f"{attributed:.9f}s over a {total:.9f}s wall clock "
                f"({json.dumps({k: round(v, 6) for k, v in buckets.items()})})"
                " - an interval was attributed twice or clocks ran "
                "backwards; this is a ledger bug, please report it"
            )
        if self._registry is not None:
            self._publish_breakdown(buckets)
        rec = self._record(buckets, total, final=True)
        if self.path is not None:
            _atomic_write_json(self.path, rec)
        try:
            from .obs import flight_event

            flight_event(
                "goodput_final",
                goodput_ratio=rec["goodput_ratio"], wall_s=rec["wall_s"],
            )
        except Exception:
            pass
        return rec

    def _event_stats(self) -> dict:
        """Per-cause duration statistics over the RAW recorded intervals
        (pre-sweep; the watchdog's re-reported stall episodes appear as
        they were reported, coarse fills are excluded). This is the
        record's ``events`` block - the empirical-distribution input the
        fleet digital twin samples from (`extract_distributions`,
        analysis/fleetsim.py): how long a checkpoint save, a reshard, or
        a steady step ACTUALLY takes on this hardware."""
        with self._lock:
            ivs = list(self._intervals)
        durs: dict = {}
        for iv in ivs:
            if iv.cause in self._fills:
                continue
            durs.setdefault(iv.cause, []).append(iv.t1 - iv.t0)
        return {c: _dist_summary(d) for c, d in sorted(durs.items())}

    def _record(self, buckets: dict, total: float, *, final: bool) -> dict:
        return {
            "version": RECORD_VERSION,
            "kind": "rank" if self.taxonomy == "train" else self.taxonomy,
            "taxonomy": self.taxonomy,
            "final": final,
            "rank": self.rank,
            "generation": self.generation,
            "hostname": _hostname(),
            "pid": os.getpid(),
            "started_unix": self.started_unix,
            "written_unix": time.time(),
            "config_fingerprint": self.config_fingerprint,
            "config": self.config,
            "mesh": self.mesh,
            "steps": self.steps,
            "goodput_steps": self.goodput_steps,
            "tokens": self.tokens,
            "wall_s": round(total, 6),
            "goodput_s": round(buckets[self._goodput_cause], 6),
            "goodput_ratio": round(
                buckets[self._goodput_cause] / total, 6
            ) if total > 0 else None,
            "badput_s": {
                c: round(buckets[c], 6) for c in self._badput_causes
            },
            # per-cause event-duration stats (additive, version-1
            # compatible): the distribution inputs for the fleet twin
            "events": self._event_stats(),
            "metrics": self.metrics,
        }

    def write_record(self, *, final: bool = False) -> str | None:
        """Atomically write the current record (partial unless ``final``)
        to the armed path; never raises (full-disk rule)."""
        if self.path is None or self._t_start is None:
            return None
        end = self._now()
        try:
            rec = self._record(self.breakdown(at=end),
                               self.wall_s(at=end), final=final)
            return _atomic_write_json(self.path, rec)
        except Exception:
            return None


LEDGER = GoodputLedger()


def ledger_interval(cause: str, **meta):
    """The one-line call-site hook (mirrors `obs.flight_event`):
    ``with ledger_interval("checkpoint_save"): ...`` on the process
    ledger - a shared no-op when the ledger is disarmed."""
    return LEDGER.interval(cause, **meta)


# ---------------------------------------------------------------- records


def config_fingerprint(config: dict) -> str:
    """Stable sha256 over the sorted JSON form of a config dict - two
    runs with the same fingerprint trained the same thing."""
    blob = json.dumps(_json_safe(config), sort_keys=True,
                      separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def read_record(path: str) -> dict:
    """Load + validate one record (rank or fleet); raises ValueError with
    an actionable message on schema problems."""
    with open(path) as f:
        doc = json.load(f)
    return validate_record(doc, what=path)


def validate_record(doc, what: str = "record") -> dict:
    if not isinstance(doc, dict):
        raise ValueError(f"{what}: not a JSON object")
    ver = doc.get("version")
    if not isinstance(ver, int):
        raise ValueError(
            f"{what}: missing integer 'version' - not a goodput run record"
        )
    if ver > RECORD_VERSION:
        raise ValueError(
            f"{what}: record version {ver} is newer than this build's "
            f"{RECORD_VERSION} - read it with the build that wrote it"
        )
    if "badput_s" not in doc or "wall_s" not in doc:
        raise ValueError(
            f"{what}: missing badput_s/wall_s - not a goodput run record"
        )
    # forward compat inside a version: unknown badput causes are carried
    # through untouched (rendered under their own name), never dropped
    return doc


def fleet_goodput_record(
    records: list, *,
    restart_gaps: list | None = None,
    restart_generations=None,
) -> dict:
    """Aggregate per-rank records (+ supervisor-side restart gaps) into
    one fleet-level record.

    - ``records``: per-rank rank records (partial ones from SIGKILLed
      workers included - their write-through accounting stands).
    - ``restart_gaps``: ``[{"seconds", "group_size", ...}]`` - the
      supervisor-measured death -> respawn windows, charged to
      ``restart_gap`` x the relaunched group size (capacity-seconds in
      which no worker existed - disjoint from every rank record).
    - ``restart_generations``: generations launched BY a failure restart;
      their ranks' ``init`` + ``compile`` seconds are reclassified into
      ``restart_gap`` (re-rendezvous and recompile are restart cost, not
      fresh-run startup) - together the bucket spans the issue-defined
      window: worker death -> first post-restart step.

    Conservation holds in capacity-seconds: fleet ``wall_s`` =
    sum(rank walls) + sum(gap x size), and the buckets partition it.
    """
    restart_gens = set(restart_generations or ())
    buckets = {c: 0.0 for c in CAUSES}
    wall = 0.0
    steps = goodput_steps = 0
    tokens = 0.0
    ranks = []
    pooled_events: dict = {}
    for rec in records:
        rec = validate_record(rec)
        for cause, info in (rec.get("events") or {}).items():
            pool = pooled_events.setdefault(
                cause, {"count": 0, "total_s": 0.0, "samples": []}
            )
            pool["count"] += int(info.get("count") or 0)
            pool["total_s"] += float(info.get("total_s") or 0.0)
            pool["samples"].extend(info.get("samples_s") or ())
        bad = dict(rec.get("badput_s") or {})
        reclassified = 0.0
        if rec.get("generation") in restart_gens:
            reclassified = float(bad.get("init", 0.0)) + float(
                bad.get("compile", 0.0)
            )
            bad["restart_gap"] = bad.get("restart_gap", 0.0) + reclassified
            bad["init"] = bad["compile"] = 0.0
        for c, v in bad.items():
            buckets[c] = buckets.get(c, 0.0) + float(v)
        buckets[GOODPUT_CAUSE] += float(rec.get("goodput_s") or 0.0)
        wall += float(rec.get("wall_s") or 0.0)
        steps += int(rec.get("steps") or 0)
        goodput_steps += int(rec.get("goodput_steps") or 0)
        tokens += float(rec.get("tokens") or 0.0)
        ranks.append({
            "rank": rec.get("rank"),
            "generation": rec.get("generation"),
            "final": rec.get("final"),
            "wall_s": rec.get("wall_s"),
            "goodput_ratio": rec.get("goodput_ratio"),
            "steps": rec.get("steps"),
            "restart_reclassified_s": round(reclassified, 6),
        })
    gap_capacity = 0.0
    for g in restart_gaps or ():
        gap_capacity += float(g.get("seconds", 0.0)) * max(
            int(g.get("group_size", 1)), 1
        )
    buckets["restart_gap"] += gap_capacity
    wall += gap_capacity
    return {
        "version": RECORD_VERSION,
        "kind": "fleet",
        "final": all(r.get("final", False) for r in ranks) if ranks else False,
        "written_unix": time.time(),
        "n_records": len(ranks),
        "restart_gaps": list(restart_gaps or ()),
        "steps": steps,
        "goodput_steps": goodput_steps,
        "tokens": tokens,
        "wall_s": round(wall, 6),
        "goodput_s": round(buckets[GOODPUT_CAUSE], 6),
        "goodput_ratio": round(buckets[GOODPUT_CAUSE] / wall, 6)
        if wall > 0 else None,
        "badput_s": {
            c: round(v, 6) for c, v in buckets.items()
            if c != GOODPUT_CAUSE
        },
        # per-cause event samples pooled across ranks (each rank's
        # summary keeps count/total exactly; the sample list is the
        # union of the ranks' quantile-preserving subsamples), so a
        # fleet record alone feeds `extract_distributions`
        "events": {
            c: _dist_summary(
                p["samples"], count=p["count"], total_s=p["total_s"]
            )
            for c, p in sorted(pooled_events.items())
        },
        "ranks": ranks,
    }


# ----------------------------------------------- distribution extraction

# distributions-document schema version (tools/goodput.py --distributions
# writes it; analysis/fleetsim.py Distributions reads it)
DISTRIBUTIONS_VERSION = 1

# events-block sample cap: sorted durations are subsampled evenly so
# quantiles survive the cap deterministically
_DIST_MAX_SAMPLES = 64


def _dist_summary(samples, *, count: int | None = None,
                  total_s: float | None = None,
                  max_samples: int = _DIST_MAX_SAMPLES) -> dict:
    """Summarize a list of durations into the events/distribution shape:
    count, total, mean, p50/p95, max, plus an evenly-subsampled SORTED
    sample list (deterministic, quantile-preserving) bounded to
    ``max_samples`` - small enough to embed in every write-through
    record, rich enough to resample from."""
    xs = sorted(float(x) for x in samples if float(x) >= 0.0)
    n = count if count is not None else len(xs)
    tot = total_s if total_s is not None else sum(xs)
    out = {
        "count": int(n),
        "total_s": round(float(tot), 6),
        "mean_s": round(tot / n, 6) if n else 0.0,
    }
    if xs:
        import math

        def rank(q):  # nearest-rank quantile over the sorted samples
            return xs[max(0, math.ceil(q * len(xs)) - 1)]

        out["p50_s"] = round(rank(0.50), 6)
        out["p95_s"] = round(rank(0.95), 6)
        out["max_s"] = round(xs[-1], 6)
        if len(xs) > max_samples:
            step = (len(xs) - 1) / (max_samples - 1)
            xs = [xs[round(i * step)] for i in range(max_samples)]
        out["samples_s"] = [round(x, 6) for x in xs]
    return out


def extract_distributions(records) -> dict:
    """Pool per-cause event-duration distributions out of run records -
    the empirical inputs the fleet digital twin (`analysis/fleetsim.py`)
    samples restart-gap / checkpoint-save / reshard / step durations
    from, instead of guessing them.

    ``records`` is an iterable of record dicts (rank, fleet, or sim).
    Three source channels, all additive:

    - each record's ``events`` block (raw recorded interval durations,
      quantile-preserving subsamples);
    - rank records WITHOUT events (the untelemetered ``note_steps`` fast
      path, or pre-events builds): their aggregate ``badput_s`` /
      ``goodput_s``-per-step values contribute single fallback samples;
    - fleet records' ``restart_gaps``: the supervisor-measured
      death -> respawn windows as ``restart_gap`` samples, NET of each
      entry's recorded ``backoff_s`` (the simulated policy re-adds its
      OWN backoff - this run's schedule must not leak into the sample).

    Pass either the rank records or their fleet aggregate, not both -
    the fleet record already pools its ranks' events.

    Returns ``{"version", "kind": "distributions", "n_records",
    "causes": {cause: {count, mean_s, p50_s, p95_s, max_s, samples_s}},
    "derived": {"step_overhead_s": ...}}`` where ``step_overhead_s`` is
    the pooled per-step host overhead (idle_other seconds per executed
    step) - the twin charges it on every simulated step so predictions
    include the host time real runs measurably spend between steps.
    """
    pooled: dict = {}
    idle_s = 0.0
    idle_steps = 0
    n_records = 0

    def pool(cause, samples, count=None, total=None):
        p = pooled.setdefault(
            cause, {"count": 0, "total_s": 0.0, "samples": []}
        )
        xs = [float(x) for x in samples if float(x) > 0.0]
        p["samples"].extend(xs)
        p["count"] += int(count if count is not None else len(xs))
        p["total_s"] += float(total if total is not None else sum(xs))

    for rec in records:
        rec = validate_record(rec)
        n_records += 1
        events = rec.get("events") or {}
        for cause, info in events.items():
            pool(cause, info.get("samples_s") or (),
                 count=info.get("count"), total=info.get("total_s"))
        if not events:
            # aggregate-only fallback: one sample per cause total, and a
            # mean step time when the record counted steps
            bad = rec.get("badput_s") or {}
            for cause in ("init", "compile", "checkpoint_save", "reshard"):
                v = float(bad.get(cause) or 0.0)
                if v > 0:
                    pool(cause, [v])
            gsteps = int(rec.get("goodput_steps") or 0)
            gs = float(rec.get("goodput_s") or 0.0)
            if gsteps > 0 and gs > 0:
                pool(GOODPUT_CAUSE, [gs / gsteps], count=gsteps, total=gs)
        for gap in rec.get("restart_gaps") or ():
            net = float(gap.get("seconds") or 0.0) - float(
                gap.get("backoff_s") or 0.0
            )
            if net > 0:
                pool("restart_gap", [net])
        idle_s += float((rec.get("badput_s") or {}).get(IDLE_CAUSE) or 0.0)
        idle_steps += int(rec.get("steps") or 0)
    return {
        "version": DISTRIBUTIONS_VERSION,
        "kind": "distributions",
        "n_records": n_records,
        "causes": {
            c: _dist_summary(
                p["samples"], count=p["count"], total_s=p["total_s"]
            )
            for c, p in sorted(pooled.items())
        },
        "derived": {
            "step_overhead_s": round(idle_s / idle_steps, 6)
            if idle_steps > 0 else 0.0,
        },
    }


def extract_serve_distributions(request_records, client_rows=None) -> dict:
    """The SERVE variant of `extract_distributions`: pool the workload
    and service-time distributions a serve-mode fleet twin
    (analysis/fleetsim.py) samples from, out of per-request trace
    records (serve/reqtrace.py ``detail()`` dicts - a ``GET
    /v1/requests?full=1`` dump's ``recent`` list qualifies) plus,
    optionally, the loadgen client's ``--out-requests`` JSONL rows.

    Pooled causes (names chosen so they cannot collide with ledger
    causes - these are workload/service pools, not wall-clock buckets):

    - ``prompt_len`` / ``output_len``: the request mix (tokens);
    - ``inter_arrival``: client send-time deltas (needs ``client_rows``);
    - ``acceptance_rate``: per-request spec-decode accepted/proposed;
    - ``decode_tick_s`` / ``prefill_token_s``: measured engine service
      times per decode tick / per prefill token, from each finalized
      request's fenced ``engine_s`` apportionment - the empirical
      pricing the twin prefers over the roofline when replaying a
      measured run (``--validate``), exactly as the training twin
      prefers measured ``steady_step`` samples.

    Returns the `extract_distributions` document shape with
    ``taxonomy: "serve"`` added."""
    pooled: dict = {}

    def pool(cause, samples):
        p = pooled.setdefault(
            cause, {"count": 0, "total_s": 0.0, "samples": []}
        )
        xs = [float(x) for x in samples if float(x) >= 0.0]
        p["samples"].extend(xs)
        p["count"] += len(xs)
        p["total_s"] += sum(xs)

    n_requests = 0
    for det in request_records or ():
        if not isinstance(det, dict) or det.get("state") != "done":
            continue
        n_requests += 1
        pool("prompt_len", [int(det.get("prompt_len") or 0)])
        pool("output_len", [int(det.get("tokens_emitted") or 0)])
        if det.get("proposed_tokens"):
            pool("acceptance_rate",
                 [float(det.get("acceptance_rate") or 0.0)])
        eng = det.get("engine_s") or {}
        ticks = int(det.get("decode_ticks") or 0)
        dec = float(eng.get("decode") or 0.0)
        if ticks > 0 and dec > 0:
            pool("decode_tick_s", [dec / ticks])
        ptoks = int(det.get("prefill_tokens") or 0)
        pre = float(eng.get("prefill") or 0.0)
        if ptoks > 0 and pre > 0:
            pool("prefill_token_s", [pre / ptoks])
    sends = sorted(
        float(row.get("t_send_unix") or 0.0)
        for row in client_rows or ()
        if row.get("t_send_unix")
    )
    pool("inter_arrival", [b - a for a, b in zip(sends, sends[1:])])
    return {
        "version": DISTRIBUTIONS_VERSION,
        "kind": "distributions",
        "taxonomy": "serve",
        "n_records": n_requests,
        "causes": {
            c: _dist_summary(
                p["samples"], count=p["count"], total_s=p["total_s"]
            )
            for c, p in sorted(pooled.items())
        },
        "derived": {},
    }


def aggregate_records_dir(path: str) -> dict:
    """Fleet-aggregate a directory of per-worker ``gen{g}_rank{r}.json``
    records ON THE FLY - the render path for a run that crashed before
    the supervisor wrote ``run_dir/run_record.json`` (its write-through
    worker records are all that survived).

    ``path`` may be the ``records/`` directory itself or a run dir
    containing one. Without the supervisor's own bookkeeping the
    death -> respawn gaps are unknowable (no process was alive to
    measure them), and which generations were FAILURE relaunches is
    approximated as every generation after the earliest seen - right
    for crashed runs, pessimistic for planned grows (noted on the
    record as ``aggregation: "directory"``)."""
    d = path
    sub = os.path.join(path, "records")
    if os.path.isdir(sub):
        d = sub
    records = []
    skipped = 0
    try:
        names = sorted(os.listdir(d))
    except OSError as e:
        raise ValueError(f"{path}: {e}")
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(d, name)) as f:
                records.append(validate_record(json.load(f), name))
        except (OSError, ValueError):
            skipped += 1  # torn write-through tail or a non-record file
    if not records:
        raise ValueError(
            f"{path}: no readable goodput run records "
            f"({skipped} file(s) skipped) - expected per-worker "
            "gen{g}_rank{r}.json records (utils/goodput.py)"
        )
    gens = [
        int(r["generation"]) for r in records
        if isinstance(r.get("generation"), int)
    ]
    restart_gens = (
        set(g for g in gens if g > min(gens)) if gens else set()
    )
    fleet = fleet_goodput_record(
        records, restart_generations=restart_gens
    )
    fleet["aggregation"] = "directory"
    fleet["skipped_files"] = skipped
    return fleet


# ------------------------------------------------------- trace derivation

# span/cause mapping for the trace-derived breakdown: the same taxonomy
# computed from a (merged) Chrome trace alone - tools/trace_summary.py
# --goodput; cross-checked against the ledger record by tests
_TRACE_SPAN_CAUSE = {
    "train_step": None,  # compile/steady split below
    "straggler": "stall",
    "reshard": "reshard",
    "data_loading": "data_wait",
    "checkpoint_save": "checkpoint_save",
}


def breakdown_from_trace(doc: dict) -> dict:
    """Derive the taxonomy breakdown from a Chrome trace document
    (single-rank or `tools/trace_merge.py` merged).

    Per pid (rank): ``train_step`` spans become compile (first span) /
    steady intervals, ``straggler`` spans stall, ``reshard``/
    ``data_loading``/``checkpoint_save`` their causes; the window is
    [0, last event end] (the tracer's clock zero is tracer creation, so
    the pre-first-step prefix is ``init``); uncovered time inside the
    window is ``idle_other``. Multi-rank docs aggregate the per-rank
    breakdowns (capacity-seconds, like the fleet record). Returns
    ``{"wall_s", "goodput_ratio", "goodput_s", "badput_s", "per_rank"}``.
    """
    per_pid: dict = {}
    for ev in doc.get("traceEvents", ()):
        if ev.get("ph") != "X":
            continue
        name = ev.get("name")
        if name not in _TRACE_SPAN_CAUSE:
            continue
        pid = ev.get("pid", 0)
        t0 = float(ev.get("ts", 0.0)) / 1e6
        t1 = t0 + float(ev.get("dur") or 0.0) / 1e6
        per_pid.setdefault(pid, []).append((t0, t1, name))
    buckets = {c: 0.0 for c in CAUSES}
    wall = 0.0
    per_rank = {}
    for pid, spans in sorted(per_pid.items()):
        spans.sort()
        intervals = []
        first_step = True
        first_step_t0 = None
        for t0, t1, name in spans:
            cause = _TRACE_SPAN_CAUSE[name]
            if cause is None:
                cause = "compile" if first_step else GOODPUT_CAUSE
                if first_step:
                    first_step_t0 = t0
                first_step = False
            intervals.append(_Interval(t0, t1, cause))
        if first_step_t0 is not None and first_step_t0 > 0:
            intervals.append(_Interval(0.0, first_step_t0, "init"))
        end = max(iv.t1 for iv in intervals)
        b = attribute_intervals(intervals, 0.0, end)
        per_rank[pid] = {
            "wall_s": round(end, 6),
            "goodput_ratio": round(b[GOODPUT_CAUSE] / end, 6)
            if end > 0 else None,
            "buckets": {c: round(v, 6) for c, v in b.items()},
        }
        for c, v in b.items():
            buckets[c] += v
        wall += end
    return {
        "kind": "trace",
        "wall_s": round(wall, 6),
        "goodput_s": round(buckets[GOODPUT_CAUSE], 6),
        "goodput_ratio": round(buckets[GOODPUT_CAUSE] / wall, 6)
        if wall > 0 else None,
        "badput_s": {
            c: round(v, 6) for c, v in buckets.items()
            if c != GOODPUT_CAUSE
        },
        "per_rank": per_rank,
    }


# ------------------------------------------------------ rendering / gate


def record_causes(rec: dict) -> dict:
    """Full ``{cause: seconds}`` view of a record (goodput + badput,
    unknown forward-compat causes preserved), keyed by the record's own
    taxonomy (`record_taxonomy`)."""
    causes, goodput = record_taxonomy(rec)
    out = {c: 0.0 for c in causes}
    out[goodput] = float(rec.get("goodput_s") or 0.0)
    for c, v in (rec.get("badput_s") or {}).items():
        out[c] = out.get(c, 0.0) + float(v)
    return out


def render_record(rec: dict, *, title: str | None = None) -> str:
    """Human-readable breakdown table of one record (rank/fleet/serve/
    trace)."""
    tax_causes, goodput_cause = record_taxonomy(rec)
    causes = record_causes(rec)
    total = float(rec.get("wall_s") or sum(causes.values()) or 0.0)
    lines = []
    head = title or f"Goodput breakdown ({rec.get('kind', 'rank')} record)"
    lines.append(head)
    ratio = rec.get("goodput_ratio")
    meta = []
    if ratio is not None:
        meta.append(f"goodput {100.0 * ratio:.2f}%")
    meta.append(f"wall {total:.2f}s")
    if rec.get("steps"):
        meta.append(f"{rec['steps']} step(s)")
    if rec.get("tokens"):
        meta.append(f"{rec['tokens']:,.0f} tokens")
    if rec.get("final") is False:
        meta.append("PARTIAL (write-through; the run did not finalize)")
    lines.append("  " + ", ".join(meta))
    lines.append(f"  {'cause':<20} {'seconds':>12} {'share':>8}")
    order = [c for c in tax_causes if c in causes] + sorted(
        c for c in causes if c not in tax_causes
    )
    for c in order:
        v = causes[c]
        if v <= 0 and c not in (goodput_cause, IDLE_CAUSE):
            continue
        share = v / total if total > 0 else 0.0
        tag = "  <- goodput" if c == goodput_cause else ""
        lines.append(f"  {c:<20} {v:>12.3f} {share:>7.2%}{tag}")
    return "\n".join(lines)


def diff_records(a: dict, b: dict, name_a: str = "A",
                 name_b: str = "B") -> str:
    """Side-by-side share comparison of two records."""
    tax_causes, _ = record_taxonomy(a)
    ca, cb = record_causes(a), record_causes(b)
    ta = float(a.get("wall_s") or sum(ca.values()) or 0.0)
    tb = float(b.get("wall_s") or sum(cb.values()) or 0.0)
    lines = [
        f"Goodput diff: {name_a} vs {name_b}",
        f"  wall: {ta:.2f}s vs {tb:.2f}s; goodput ratio: "
        f"{_fmt_ratio(a.get('goodput_ratio'))} vs "
        f"{_fmt_ratio(b.get('goodput_ratio'))}",
        f"  {'cause':<20} {name_a:>12} {name_b:>12} {'d-share':>9}",
    ]
    order = [c for c in tax_causes if c in ca or c in cb] + sorted(
        set(list(ca) + list(cb)) - set(tax_causes)
    )
    for c in order:
        va, vb = ca.get(c, 0.0), cb.get(c, 0.0)
        if va <= 0 and vb <= 0:
            continue
        sa = va / ta if ta > 0 else 0.0
        sb = vb / tb if tb > 0 else 0.0
        lines.append(
            f"  {c:<20} {va:>11.3f}s {vb:>11.3f}s {sb - sa:>+8.2%}"
        )
    return "\n".join(lines)


def _fmt_ratio(r) -> str:
    return f"{100.0 * r:.2f}%" if r is not None else "n/a"


DEFAULT_RATIO_TOL = 0.10
DEFAULT_SHARE_TOL = 0.10


def check_record(
    current: dict, baseline: dict, *,
    ratio_tol: float | None = None,
    share_tol: float | None = None,
    cause_tols: dict | None = None,
) -> list:
    """The regression gate: compare a record against a checked-in
    baseline in SHARES of wall-clock (so runs of different length and
    hardware speed compare), returning a list of violation strings
    (empty = pass).

    - ``goodput_ratio`` may not DROP more than ``ratio_tol`` (absolute).
    - each badput cause's share may not GROW more than its tolerance
      (``cause_tols[cause]``, falling back to ``share_tol``); causes the
      baseline never saw are held to the same tolerance from zero.

    Tolerances resolve CLI > baseline-embedded ``check_tolerances``
    block > defaults - so the committed baseline carries its own
    contract, shardlint-manifest style. Records are compared within one
    taxonomy; gating a serving record against a training baseline (or
    vice versa) is a usage error, named.
    """
    tax_cur = current.get("taxonomy") or "train"
    tax_base = baseline.get("taxonomy") or "train"
    if tax_cur != tax_base:
        raise ValueError(
            f"taxonomy mismatch: current record is {tax_cur!r}, baseline "
            f"is {tax_base!r} - gate serving records against a serving "
            "baseline (tools/goodput.py --baseline ...)"
        )
    causes, goodput_cause = record_taxonomy(current)
    badput_causes = tuple(c for c in causes if c != goodput_cause)
    embedded = baseline.get("check_tolerances") or {}
    if ratio_tol is None:
        ratio_tol = float(embedded.get("goodput_ratio", DEFAULT_RATIO_TOL))
    if share_tol is None:
        share_tol = float(embedded.get("share", DEFAULT_SHARE_TOL))
    tols = dict(embedded.get("causes") or {})
    tols.update(cause_tols or {})
    for c in tols:
        if c not in badput_causes:
            raise ValueError(
                f"unknown badput cause {c!r} in tolerances "
                f"(known: {', '.join(badput_causes)})"
            )
    problems = []
    r_cur = current.get("goodput_ratio")
    r_base = baseline.get("goodput_ratio")
    if r_base is not None:
        if r_cur is None:
            problems.append(
                "goodput_ratio: absent from the current record "
                f"(baseline {r_base:.4f})"
            )
        elif r_base - r_cur > ratio_tol:
            problems.append(
                f"goodput_ratio: {r_cur:.4f} dropped more than "
                f"{ratio_tol:.3f} below the baseline {r_base:.4f}"
            )
    cc, cb = record_causes(current), record_causes(baseline)
    t_cur = float(current.get("wall_s") or 0.0)
    t_base = float(baseline.get("wall_s") or 0.0)
    for c in sorted(set(list(cc) + list(cb))):
        if c == goodput_cause:
            continue
        s_cur = cc.get(c, 0.0) / t_cur if t_cur > 0 else 0.0
        s_base = cb.get(c, 0.0) / t_base if t_base > 0 else 0.0
        tol = float(tols.get(c, share_tol))
        if s_cur - s_base > tol:
            problems.append(
                f"badput '{c}': share {s_cur:.2%} grew more than "
                f"{tol:.2%} over the baseline {s_base:.2%} "
                f"({cc.get(c, 0.0):.3f}s of {t_cur:.3f}s)"
            )
    return problems


# ----------------------------------------------------------------- helpers


def _hostname() -> str:
    try:
        return socket.gethostname()
    except OSError:  # pragma: no cover - defensive
        return "unknown"


def _json_safe(x):
    import math

    if isinstance(x, float):
        return x if math.isfinite(x) else None
    if isinstance(x, dict):
        return {str(k): _json_safe(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_json_safe(v) for v in x]
    if isinstance(x, (str, int, bool)) or x is None:
        return x
    return repr(x)


def _atomic_write_json(path: str, doc: dict) -> str | None:
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f, allow_nan=False)
        os.replace(tmp, path)
    except (OSError, ValueError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    return path
