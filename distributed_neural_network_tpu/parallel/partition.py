"""Dataset partitioning across mesh devices.

Parity with the reference `partition_dataset` (`data_parallelism_train.py:49-53`):
contiguous shards of size total // n_workers, remainder rows silently dropped,
shard assignment fixed for the whole run (only intra-shard shuffle per epoch).

Topology delta (documented per SURVEY.md section 7 "Topology remap"): the
reference gives worker rank r in [1, N-1] rows [(r-1)*p, r*p) because rank 0 is
an idle parent. On the TPU mesh there is no parent - all N devices train - so
device d in [0, N) gets rows [d*p, (d+1)*p) with p = total // N. At
"--nb-proc N" the reference therefore has N-1 compute shards of size
total//(N-1); this build has N shards of size total//N. Use
``reference_compat=True`` to reproduce the reference's shard math exactly
(N-1 shards over N-1 devices) when comparing accuracy curves at equal
worker counts.
"""

from __future__ import annotations

import numpy as np


def validate_partition_spec(spec, mesh_axes, *, shape=None, name="array"):
    """Validate one PartitionSpec against a mesh's axes, failing EARLY.

    Without this, a spec naming a nonexistent mesh axis (or doubling up an
    axis) surfaces deep inside pjit/shard_map lowering as an opaque
    internal error; here it raises a ``ValueError`` that names the bad
    axis, the leaf, and the axes the mesh actually has. Reused by the
    static analyzer's spec lint (``analysis/lint.py``) and by the step
    builders (train/lm.py, parallel/pipeline.py) before any compilation.

    ``mesh_axes``: mapping of axis name -> axis size (``dict(mesh.shape)``).
    ``shape``: optional array shape; when given, additionally checks that
    the spec is not longer than the rank and that every sharded dim is
    divisible by the product of its axes' sizes. Specs SHORTER than the
    rank are valid (trailing dims unsharded - jax's None-padding rule).
    """
    entries = tuple(spec)
    available = tuple(mesh_axes)
    seen = []
    for d, entry in enumerate(entries):
        if entry is None:
            continue
        axes = (entry,) if isinstance(entry, str) else tuple(entry)
        for a in axes:
            if a not in mesh_axes:
                raise ValueError(
                    f"PartitionSpec for {name} names mesh axis {a!r} (dim "
                    f"{d} of {spec}), but the mesh only has axes "
                    f"{available} - fix the spec or build the mesh with "
                    f"that axis"
                )
            if a in seen:
                raise ValueError(
                    f"PartitionSpec for {name} uses mesh axis {a!r} twice "
                    f"({spec}): each mesh axis may shard at most one dim "
                    f"of one array"
                )
            seen.append(a)
    if shape is None:
        return
    if len(entries) > len(shape):
        raise ValueError(
            f"PartitionSpec for {name} has {len(entries)} entries ({spec}) "
            f"but the array has rank {len(shape)} (shape {tuple(shape)}); "
            f"specs may be shorter than the rank, never longer"
        )
    for d, entry in enumerate(entries):
        if entry is None:
            continue
        axes = (entry,) if isinstance(entry, str) else tuple(entry)
        n = 1
        for a in axes:
            n *= int(mesh_axes[a])
        if n > 0 and shape[d] % n:
            raise ValueError(
                f"PartitionSpec for {name} shards dim {d} (size "
                f"{shape[d]}) over {axes} (total {n} shards), which does "
                f"not divide evenly - pad the dim or change the spec"
            )


def validate_spec_tree(specs, mesh_axes, *, shapes=None, root="params"):
    """`validate_partition_spec` over a pytree of specs (leaf-aligned
    optional ``shapes`` tree of arrays/avals), naming each failing leaf by
    its tree path."""
    import jax
    from jax.sharding import PartitionSpec

    def is_spec(s):
        return isinstance(s, PartitionSpec)

    leaves, treedef = jax.tree_util.tree_flatten(specs, is_leaf=is_spec)
    paths = [
        jax.tree_util.keystr(p)
        for p, _ in jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=is_spec
        )[0]
    ]
    shape_leaves = (
        treedef.flatten_up_to(shapes) if shapes is not None
        else [None] * len(leaves)
    )

    def shapes_under(arr):
        # one spec may broadcast over a whole subtree (shard_map's pytree
        # prefix rule): validate it against every array leaf underneath
        if arr is None:
            return [None]
        if hasattr(arr, "shape"):
            return [arr.shape]
        if isinstance(arr, tuple) and all(isinstance(i, int) for i in arr):
            return [arr]
        return [
            leaf.shape
            for leaf in jax.tree_util.tree_leaves(arr)
            if hasattr(leaf, "shape")
        ] or [None]

    for spec, path, arr in zip(leaves, paths, shape_leaves):
        for shape in shapes_under(arr):
            validate_partition_spec(
                spec, mesh_axes, shape=shape, name=f"{root}{path or ''}"
            )


def shard_size(total: int, n_shards: int) -> int:
    if n_shards <= 0:
        raise ValueError(f"n_shards must be positive, got {n_shards}")
    return total // n_shards


def shard_bounds(total: int, n_shards: int) -> list[tuple[int, int]]:
    """Contiguous [start, end) row bounds per shard; remainder dropped."""
    p = shard_size(total, n_shards)
    return [(d * p, (d + 1) * p) for d in range(n_shards)]


def shard_rows(total: int, n_shards: int) -> np.ndarray:
    """(n_shards, p) row-index matrix - the sharded feed for the mesh.

    Row d is device d's contiguous shard, exactly the index set
    `range((r-1)*p, r*p)` of the reference (`data_parallelism_train.py:52`)
    with the 0-based all-devices-train convention.
    """
    p = shard_size(total, n_shards)
    return np.arange(n_shards * p, dtype=np.int32).reshape(n_shards, p)


def replicated_rows(total: int, n_shards: int) -> np.ndarray:
    """(n_shards, total) - every device sees the full dataset.

    This is the model-replication regime's feed (`model_replication_train.py:
    39-47`: every rank builds the full train loader). Regime == sharding
    policy; the trainer is identical (SURVEY.md section 7 step 3).
    """
    return np.broadcast_to(
        np.arange(total, dtype=np.int32), (n_shards, total)
    ).copy()
