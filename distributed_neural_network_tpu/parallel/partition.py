"""Dataset partitioning across mesh devices.

Parity with the reference `partition_dataset` (`data_parallelism_train.py:49-53`):
contiguous shards of size total // n_workers, remainder rows silently dropped,
shard assignment fixed for the whole run (only intra-shard shuffle per epoch).

Topology delta (documented per SURVEY.md section 7 "Topology remap"): the
reference gives worker rank r in [1, N-1] rows [(r-1)*p, r*p) because rank 0 is
an idle parent. On the TPU mesh there is no parent - all N devices train - so
device d in [0, N) gets rows [d*p, (d+1)*p) with p = total // N. At
"--nb-proc N" the reference therefore has N-1 compute shards of size
total//(N-1); this build has N shards of size total//N. Use
``reference_compat=True`` to reproduce the reference's shard math exactly
(N-1 shards over N-1 devices) when comparing accuracy curves at equal
worker counts.
"""

from __future__ import annotations

import numpy as np


def shard_size(total: int, n_shards: int) -> int:
    if n_shards <= 0:
        raise ValueError(f"n_shards must be positive, got {n_shards}")
    return total // n_shards


def shard_bounds(total: int, n_shards: int) -> list[tuple[int, int]]:
    """Contiguous [start, end) row bounds per shard; remainder dropped."""
    p = shard_size(total, n_shards)
    return [(d * p, (d + 1) * p) for d in range(n_shards)]


def shard_rows(total: int, n_shards: int) -> np.ndarray:
    """(n_shards, p) row-index matrix - the sharded feed for the mesh.

    Row d is device d's contiguous shard, exactly the index set
    `range((r-1)*p, r*p)` of the reference (`data_parallelism_train.py:52`)
    with the 0-based all-devices-train convention.
    """
    p = shard_size(total, n_shards)
    return np.arange(n_shards * p, dtype=np.int32).reshape(n_shards, p)


def replicated_rows(total: int, n_shards: int) -> np.ndarray:
    """(n_shards, total) - every device sees the full dataset.

    This is the model-replication regime's feed (`model_replication_train.py:
    39-47`: every rank builds the full train loader). Regime == sharding
    policy; the trainer is identical (SURVEY.md section 7 step 3).
    """
    return np.broadcast_to(
        np.arange(total, dtype=np.int32), (n_shards, total)
    ).copy()
