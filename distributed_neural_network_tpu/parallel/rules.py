"""Declarative partition rules: regex -> PartitionSpec over named trees.

The sharding layout of every model family used to be a hand-written
pytree-of-specs per scenario (`models/transformer.py param_specs`,
`parallel/pipeline.py pp_param_specs`); each new scenario (serving, fp8,
DrJAX sims) re-wired the same knowledge by hand. This module makes the
layout DECLARATIVE: an ordered list of ``(regex, PartitionSpec)`` rules is
matched against each leaf's ``/``-joined tree path, first match wins, and
an unmatched leaf is a hard error naming the path and the rules tried -
silence is never a layout.

- `match_partition_rules(rules, tree)` - the matcher (exemplar idiom:
  fmengine's ``match_partition_rules``), structure-preserving: returns a
  spec pytree congruent to ``tree``.
- `rules_to_spec_tree(rules, tree, mesh_axes)` - match + round-trip the
  result through `partition.validate_spec_tree`, so a rule naming a
  nonexistent mesh axis (or a non-divisible dim, when ``tree`` carries
  shapes) fails at derivation time with the leaf path named.
- `lm_partition_rules(...)` - THE rule set for the transformer family;
  `transformer.param_specs` is now a thin matcher call over these rules,
  so dp/tp/ep (and via `pipeline.pp_param_specs`, pp) all derive from one
  declarative table.
- `load_rules(path)` / `save_rules` / `rules_to_json` / `rules_from_json`
  - the ``--sharding rules:<file>`` file format (a JSON list of
  ``[pattern, spec-entries]`` pairs; spec entries use the same encoding
  as checkpoint mesh meta, `parallel/reshard.py spec_to_json`).

The static sharding search (`analysis/autoshard.py`) generates its spec
candidates from these rules: a candidate mesh factorization activates or
deactivates the tp/ep axes and the SAME table yields the layout, so the
search can never propose a layout training cannot build.
"""

from __future__ import annotations

import json
import re

from jax.sharding import PartitionSpec as P

SEP = "/"


def named_leaves(tree, *, sep: str = SEP, is_leaf=None):
    """[(path, leaf)] with dict keys / sequence indices ``sep``-joined
    ("layers/wq", "m/layers/wq", ...) - the names the rules match."""
    import jax

    def name_of(entry) -> str:
        key = getattr(entry, "key", None)
        if key is not None:
            return str(key)
        idx = getattr(entry, "idx", None)
        if idx is not None:
            return str(idx)
        name = getattr(entry, "name", None)
        if name is not None:
            return str(name)
        return str(entry)

    flat = jax.tree_util.tree_flatten_with_path(tree, is_leaf=is_leaf)[0]
    return [(sep.join(name_of(e) for e in path), leaf) for path, leaf in flat]


def match_partition_rules(
    rules, tree, *, sep: str = SEP, skip_scalars: bool = True
):
    """Spec pytree for ``tree``: each leaf gets the spec of the FIRST rule
    whose regex ``re.search``-matches its ``sep``-joined path.

    ``skip_scalars=True`` (the matcher default) maps rank-0 / size-1
    leaves to ``P()`` without consulting the rules - a scalar cannot be
    sharded, and optimizer counters ("t") should never need a rule. An
    unmatched non-scalar leaf raises ``ValueError`` naming the path and
    every pattern tried; a partial layout is never returned.
    """
    import jax
    import numpy as np

    rules = list(rules)
    for pattern, spec in rules:
        if not isinstance(spec, P):
            raise TypeError(
                f"rule {pattern!r} maps to {spec!r} "
                f"({type(spec).__name__}), not a PartitionSpec - build "
                "rules as (regex, PartitionSpec) pairs (load_rules decodes "
                "the JSON form)"
            )

    def spec_for(name, leaf):
        if skip_scalars and hasattr(leaf, "shape"):
            if len(leaf.shape) == 0 or int(np.prod(leaf.shape)) == 1:
                return P()
        for pattern, spec in rules:
            if re.search(pattern, name) is not None:
                return spec
        raise ValueError(
            f"no partition rule matches leaf {name!r} - every leaf must "
            "be matched (first-match-wins over "
            f"{[p for p, _ in rules]!r}); add a rule, or a catch-all "
            "('.*', PartitionSpec()) for replicated leftovers"
        )

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    names = [name for name, _ in named_leaves(tree, sep=sep)]
    return jax.tree_util.tree_unflatten(
        treedef, [spec_for(n, x) for n, x in zip(names, leaves)]
    )


def rules_to_spec_tree(
    rules, tree, mesh_axes, *, root: str = "params", sep: str = SEP,
    skip_scalars: bool = True,
):
    """`match_partition_rules` + `partition.validate_spec_tree`: the spec
    pytree, already validated against the mesh axes (and against the
    leaves' shapes when ``tree`` carries arrays/avals), failing with the
    leaf path named. This is the round-trip every rules file goes through
    before a step is built."""
    from .partition import validate_spec_tree

    specs = match_partition_rules(
        rules, tree, sep=sep, skip_scalars=skip_scalars
    )
    has_shapes = any(
        hasattr(leaf, "shape") for _, leaf in named_leaves(tree, sep=sep)
    )
    validate_spec_tree(
        specs, dict(mesh_axes), shapes=tree if has_shapes else None,
        root=root,
    )
    return specs


# ------------------------------------------------------ the LM rule table


def lm_partition_rules(
    *,
    tp_axis: str | None = None,
    ep_axis: str | None = None,
    n_experts: int = 0,
):
    """The transformer family's declarative layout, one table for every
    scenario: dp-only (both axes None -> everything effectively
    replicated), tensor parallel (``tp_axis``: wq/wk/wv and w1
    column-sharded, wo/w2 row-sharded, b1 with its columns), expert
    parallel (``ep_axis`` shards the expert dim of MoE leaves; the router
    stays replicated). Leaf paths are the stacked param-tree names
    ("layers/wq" etc. - leading dim is the scanned layer axis).

    `transformer.param_specs` matches these against the param skeleton,
    so the table IS the layout training, checkpointing, and the static
    analyzer all share.
    """
    t = tp_axis
    rules = [
        (r"^embed$", P()),
        (r"^head$", P()),
        # every norm leaf: ln1_*/ln2_* in layers, lnf_* at the root
        (r"(^|/)ln[0-9a-z]*_(scale|bias)$", P()),
        (r"(^|/)w[qkv]$", P(None, None, t)),
        (r"(^|/)wo$", P(None, t, None)),
    ]
    if n_experts:
        ep = ep_axis
        rules += [
            (r"(^|/)wr$", P()),
            (r"(^|/)w1$", P(None, ep, None, t)),
            (r"(^|/)b1$", P(None, ep, t)),
            (r"(^|/)w2$", P(None, ep, t, None)),
            (r"(^|/)b2$", P(None, ep, None)),
        ]
    else:
        rules += [
            (r"(^|/)w1$", P(None, None, t)),
            (r"(^|/)b1$", P(None, t)),
            (r"(^|/)w2$", P(None, t, None)),
            (r"(^|/)b2$", P()),
        ]
    return rules


# --------------------------------------------------- rules-file (de)serde


def rules_to_json(rules) -> list:
    """[[pattern, spec-entries], ...] - the ``--sharding rules:<file>``
    document (spec encoding shared with checkpoint mesh meta)."""
    from .reshard import spec_to_json

    return [[pattern, spec_to_json(spec)] for pattern, spec in rules]


def rules_from_json(doc) -> list:
    from .reshard import spec_from_json

    if not isinstance(doc, list):
        raise ValueError(
            f"a rules document is a JSON list of [pattern, spec] pairs, "
            f"got {type(doc).__name__}"
        )
    rules = []
    for i, entry in enumerate(doc):
        if (
            not isinstance(entry, (list, tuple)) or len(entry) != 2
            or not isinstance(entry[0], str)
            or not isinstance(entry[1], list)
        ):
            raise ValueError(
                f"rules entry {i} must be [pattern, [spec entries...]], "
                f"got {entry!r}"
            )
        pattern, spec = entry
        try:
            re.compile(pattern)
        except re.error as e:
            raise ValueError(
                f"rules entry {i}: pattern {pattern!r} is not a valid "
                f"regex: {e}"
            ) from None
        rules.append((pattern, spec_from_json(spec)))
    return rules


def save_rules(rules, path: str) -> str:
    with open(path, "w") as f:
        json.dump(rules_to_json(rules), f, indent=2)
        f.write("\n")
    return path


def load_rules(path: str) -> list:
    """Parse a ``--sharding rules:<file>`` JSON document into rule pairs,
    with file/parse errors naming the path."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except FileNotFoundError:
        raise FileNotFoundError(
            f"rules file {path!r} does not exist (--sharding rules:<file> "
            "expects a JSON list of [pattern, spec] pairs; write one with "
            "parallel/rules.py save_rules)"
        ) from None
    except json.JSONDecodeError as e:
        raise ValueError(f"rules file {path!r} is not valid JSON: {e}") from None
    try:
        return rules_from_json(doc)
    except ValueError as e:
        raise ValueError(f"rules file {path!r}: {e}") from None


def format_rules(rules) -> str:
    """One rule per line, for --explain output and error context."""
    width = max((len(p) for p, _ in rules), default=0)
    return "\n".join(
        f"  {pattern:<{width}}  ->  {spec}" for pattern, spec in rules
    )
