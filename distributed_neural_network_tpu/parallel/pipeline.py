"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

The reference has no pipeline parallelism (SURVEY.md section 2: explicitly
absent - its model is a 5-layer CNN trained data-parallel only). This module
is the framework's pipeline capability for the transformer family
(`models/transformer.py`), built the TPU way rather than the
point-to-point-send way:

- **Stages are a mesh axis.** The transformer's scanned layer stack
  (leaves shaped (L, ...)) is sharded over a `'pipe'` axis: each device
  holds L/P contiguous layers. No per-stage module objects, no rank
  branching - one shard_map'd program, SPMD over stages.
- **The schedule is a dense scan.** The classic GPipe timeline of
  T = M + P - 1 ticks (M microbatches through P stages) is a
  `jax.lax.scan`; each tick every stage applies its local layers to its
  current activation block and the blocks rotate one hop along the ring via
  `jax.lax.ppermute` (XLA lowers to ICI neighbor exchange). Stage 0 feeds a
  fresh microbatch each tick; the last stage applies the LM head and
  accumulates loss for ticks that carry a valid microbatch. Pipeline-bubble
  ticks compute on garbage and are masked out - the standard static-shape
  trade.
- **Autodiff does the backward pipeline.** The whole schedule is
  differentiable (scan + ppermute + where-masks), so reverse-mode AD yields
  the reverse-order backward pipeline automatically; stage-sharded layer
  params (device-varying over 'pipe') get local gradients, while embed/head
  (replicated over 'pipe') get their cross-stage gradient psum from
  shard_map's typing - no hand-written send/recv of activation grads.
- **The LM head runs once per microbatch, sharded over the stages.** Ticks
  only run blocks + ppermute - no vocab-sized work (r2 VERDICT weak #3:
  the old schedule computed the full head on every stage every tick and
  `where`-discarded it, paying the ~28%-of-FLOPs head P*(M+P-1)/M times
  over). The last stage's exit activations (one microbatch per tick once
  the pipe is full) are collected from the scan, redistributed round-robin
  over the 'pipe' axis with one all_to_all, and each stage runs final-norm
  + head + chunked CE for M/P microbatches: total head work is M passes
  (plus up to P-1 padding passes when P does not divide M), and it
  parallelizes over the stage axis instead of being wasted on it.
- Composes with a 'data' axis (batch sharded, grad pmean automatic) and the
  tensor-parallel 'model' axis (per-block psums inside each stage).

Remaining uniform-SPMD trade: every stage still performs the per-tick
embedding *gather* (vocab-independent indexing work) so stage 0 needs no
special program; only the matmul-heavy head was worth de-duplicating.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import compat
from ..models import transformer as tfm
from ..ops.sgd import sgd_step
from .collectives import vary_like

DATA_AXIS = "data"
PIPE_AXIS = "pipe"
TP_AXIS = "model"


def create_pp_mesh(dp: int, pp: int, tp: int = 1) -> Mesh:
    """(data, pipe, model) mesh; pipe/model innermost for ICI adjacency."""
    n = dp * pp * tp
    devices = jax.devices()
    if n > len(devices):
        raise ValueError(f"mesh {dp}x{pp}x{tp} needs {n} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(dp, pp, tp)
    return Mesh(arr, (DATA_AXIS, PIPE_AXIS, TP_AXIS))


def pp_param_specs(cfg: tfm.TransformerConfig, tp_axis: str | None = None,
                   ep_axis: str | None = None):
    """param_specs with every layer-stack leaf stage-sharded over 'pipe'.

    The layer dimension (leading axis of every `layers` leaf) is split
    across stages; embed/head/final-norm stay replicated over 'pipe'.
    ep_axis additionally shards the expert dimension of MoE leaves (the
    composition is orthogonal: 'pipe' splits dim 0, experts dim 1).
    """
    specs = tfm.param_specs(cfg, tp_axis=tp_axis, ep_axis=ep_axis)

    def stage_shard(spec: P) -> P:
        rest = tuple(spec)[1:]  # drop the layer-dim entry (None) if present
        return P(PIPE_AXIS, *rest)

    specs["layers"] = {k: stage_shard(s) for k, s in specs["layers"].items()}
    return specs


def pipeline_lm_loss(
    params,
    tokens,
    targets,
    cfg: tfm.TransformerConfig,
    *,
    pipe_axis: str = PIPE_AXIS,
    n_microbatches: int,
    tp_axis: str | None = None,
    ep_axis: str | None = None,
    sync_axes=(),
    loss_chunks: int = 0,
    interleave: int = 1,
    aux_weight: float = 0.01,
):
    """Mean next-token cross-entropy via the microbatch pipeline schedule.

    Call inside shard_map. tokens/targets: (B_local, S) int32; params: the
    local stage shard (layers leaves (L/P, ...), embed/head replicated).
    Returns the replicated global mean loss (psum over pipe + sync_axes).
    loss_chunks: CE sequence-chunk count (0 = auto by the 64 MB logits
    budget; must divide S).

    MoE blocks (cfg.n_experts) route through the same schedule: experts
    shard over `ep_axis` (the data axis, GShard convention - orthogonal
    to the 'pipe' split of the layer dim), per-tick capacity is sized
    from the MICROBATCH token count (mb * S; the mesh path sizes from the
    whole local batch, so drop behavior differs at equal
    capacity_factor), and the Switch load-balancing aux is accumulated
    only over VALID ticks - pipeline-bubble ticks compute on garbage and
    their aux is masked out exactly like their outputs are discarded.
    The reported aux is the mean over (layers x microbatches), pmean'd
    over sync_axes, weighted by aux_weight into the loss (lm_loss's
    convention).

    interleave = v > 1 runs the circular (virtual-stage / Megatron
    "interleaved") schedule: each device holds v round-robin layer chunks
    of L/(v*P) layers (global chunk l*P + q lives on device q - place
    params with `shard_pp_params(..., interleave=v)`), and every
    microbatch makes v laps around the ring. Microbatches run in groups
    of P kept fully in flight: work (group g, microbatch m, lap l) runs
    on device q at tick g*v*P + m + l*P + q, which tiles every device's
    timeline exactly once - total ticks v*M + P - 1 at L/(v*P) layers
    per tick, so the bubble fraction drops from (P-1)/(M+P-1) to
    (P-1)/(v*M + P - 1): the interleaved win, expressed as a dense scan
    instead of a hand-rolled 1F1B schedule (autodiff still derives the
    backward pipeline). Requires P | M (whole groups) and v*P | L.
    v=1 is exactly the GPipe schedule.
    """
    n_pipe = compat.axis_size(pipe_axis)
    stage = jax.lax.axis_index(pipe_axis)
    m = n_microbatches
    v = interleave
    b_local, s = tokens.shape
    assert b_local % m == 0, (b_local, m)
    assert v == 1 or m % n_pipe == 0, (m, n_pipe, v)
    mb = b_local // m
    dt = cfg.dtype
    tok_mb = tokens.reshape(m, mb, s)
    tgt_mb = targets.reshape(m, mb, s)
    pe = tfm._sinusoid_pe(jnp.arange(s), cfg.d_model, dt)[None]

    if cfg.n_experts:
        from .moe import expert_capacity

        cap = expert_capacity(
            mb * s, cfg.n_experts, cfg.moe_top_k, cfg.moe_capacity_factor
        )
    else:
        cap = None

    def chunk_blocks(x, lap):
        """Apply this device's layer chunk for the given lap (0 when v=1).
        Returns (x, aux_sum) - the MoE aux summed over the chunk's layers
        (0.0 dense)."""
        layers = params["layers"]
        if v > 1:
            # local leaves are (v, L/(v*P), ...) stacked lap-major
            layers = jax.tree.map(
                lambda a: a.reshape(v, a.shape[0] // v, *a.shape[1:]),
                layers,
            )
            layers = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, lap, keepdims=False
                ),
                layers,
            )

        def block(x, lp):
            x, aux = tfm.transformer_block(
                x,
                lp,
                cfg,
                attend=lambda q, k, v: tfm.attention(q, k, v, causal=True),
                tp_axis=tp_axis,
                ep_axis=ep_axis,
                capacity=cap,
            )
            return x, aux

        if cfg.remat:
            policy = (getattr(jax.checkpoint_policies, cfg.remat_policy)
                      if cfg.remat_policy else None)
            block = jax.checkpoint(block, policy=policy)
        x, auxes = jax.lax.scan(block, x, layers)
        return x, jnp.sum(auxes)

    perm = [(i, (i + 1) % n_pipe) for i in range(n_pipe)]

    def tick(x_in, t):
        # invert the schedule at this device: work (g, m_in_group, lap)
        # runs here at tick t = g*v*P + m + lap*P + stage
        u = t - stage
        vp = v * n_pipe
        g = u // vp
        r = u - g * vp
        lap = jnp.clip(r // n_pipe, 0, v - 1)
        mb_idx = jnp.clip(g * n_pipe + r, 0, m - 1)  # lap-0 feed index
        fresh = params["embed"][jax.lax.dynamic_index_in_dim(
            tok_mb, mb_idx, keepdims=False
        )].astype(dt) + pe
        # device 0 feeds fresh embeds at its lap-0 ticks (r < P); later
        # laps arrive by rotation from the last device
        x = jnp.where((stage == 0) & (r < n_pipe), fresh, x_in)
        out, aux = chunk_blocks(x, lap)
        x_out = jax.lax.ppermute(out, pipe_axis, perm)
        # bubble ticks compute on garbage: mask their aux exactly like
        # their outputs are discarded (valid work units on this device
        # are u in [0, v*m))
        aux = jnp.where((u >= 0) & (u < v * m), aux, 0.0)
        # emit the pre-rotation output: on the last stage at its lap-(v-1)
        # ticks it is the finished hidden state of a microbatch
        return x_out, (out, aux)

    def vary(x):
        # activations vary over the pipe axis (stage-dependent) and whatever
        # the tokens vary over (data), but stay invariant over 'model': the
        # per-block tp psums close every model-varying intermediate
        return vary_like(x, tokens, extra=(pipe_axis,))

    x0 = vary(jnp.zeros((mb, s, cfg.d_model), dt))
    _, (outs, aux_ticks) = jax.lax.scan(
        tick, x0, jnp.arange(v * m + n_pipe - 1)
    )

    # exit blocks: microbatch j = g*P + mm finishes its last lap on the
    # last stage at tick g*v*P + mm + v*P - 1 (garbage on other stages;
    # contiguous outs[P-1:] when v == 1). Pad M up to a multiple of P so
    # one tiled all_to_all can deal each stage an equal share; padded
    # microbatches carry zero weight.
    j = np.arange(m)
    exit_ticks = (j // n_pipe) * (v * n_pipe) + j % n_pipe + v * n_pipe - 1
    exits = jnp.take(outs, jnp.asarray(exit_ticks), axis=0)
    mp = -(-m // n_pipe) * n_pipe
    k = mp // n_pipe
    if mp > m:
        exits = jnp.concatenate(
            [exits, jnp.zeros((mp - m, mb, s, cfg.d_model), exits.dtype)], 0
        )
        tgt_mb = jnp.concatenate(
            [tgt_mb, jnp.zeros((mp - m, mb, s), tgt_mb.dtype)], 0
        )
    w_mb = (jnp.arange(mp) < m).astype(jnp.float32)

    # deal microbatches round-robin over stages: after the all_to_all,
    # rows [(P-1)*k, P*k) on stage q are the LAST stage's exits for global
    # microbatches [q*k, (q+1)*k) - the only rows holding finished hiddens
    dealt = jax.lax.all_to_all(
        exits, pipe_axis, split_axis=0, concat_axis=0, tiled=True
    )
    mine = jax.lax.slice_in_dim(dealt, (n_pipe - 1) * k, n_pipe * k, axis=0)
    my_tgt = jax.lax.dynamic_slice_in_dim(tgt_mb, stage * k, k, axis=0)
    my_w = jax.lax.dynamic_slice_in_dim(w_mb, stage * k, k, axis=0)

    # final norm + head + CE for my share, seq-chunked so the (k*mb, S,
    # vocab) logits never materialize whole (same trick as train/lm.py)
    h = tfm._layer_norm(
        mine, params["lnf_scale"], params["lnf_bias"]
    ).astype(dt)
    rows = k * mb
    x_rows = h.reshape(rows, s, cfg.d_model)
    t_rows = my_tgt.reshape(rows, s)
    w_rows = jnp.repeat(my_w, mb)
    from ..train.lm import auto_loss_chunks

    n_chunks = loss_chunks or auto_loss_chunks(rows, s, cfg.vocab_size)
    cs = s // n_chunks
    head = params["head"].astype(dt)

    @jax.checkpoint
    def chunk_ce(xc, tc):
        logits = (xc @ head).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, tc[..., None], axis=-1)[..., 0]
        return -(ll.sum(-1) * w_rows).sum()

    xs = x_rows.reshape(rows, n_chunks, cs, cfg.d_model).swapaxes(0, 1)
    ts = t_rows.reshape(rows, n_chunks, cs).swapaxes(0, 1)

    def body(acc, xt):
        return acc + chunk_ce(*xt), None

    loss_sum, _ = jax.lax.scan(body, vary(jnp.float32(0.0)), (xs, ts))

    axes = (pipe_axis,) + tuple(sync_axes)
    total = jax.lax.psum(loss_sum, axes)
    # global token count is static: every data-shard holds tokens.size tokens
    n_tokens = tokens.size
    for a in sync_axes:
        n_tokens = n_tokens * compat.axis_size(a)
    loss = total / jnp.float32(n_tokens)
    if cfg.n_experts:
        # masked per-tick aux sums -> mean over (layers x microbatches),
        # pmean over the data shards: psum over pipe collects every
        # stage/lap unit (m*v*P units of L/(v*P) layers = m*L layer
        # instances per data shard)
        aux_total = jax.lax.psum(jnp.sum(aux_ticks), axes)
        n_aux = m * cfg.n_layers
        for a in sync_axes:
            n_aux = n_aux * compat.axis_size(a)
        loss = loss + aux_weight * aux_total / jnp.float32(n_aux)
    return loss


def pp_wiring(cfg: tfm.TransformerConfig, mesh: Mesh):
    """(tp, ep, sync_axes, specs) for a pipeline mesh - the single source
    of the axis/spec derivation shared by make_pp_train_step,
    make_pp_eval_fn, and shard_pp_params (train/eval/placement must
    agree or shardings silently desynchronize)."""
    from ..train.lm import _ep_axis

    tp = TP_AXIS if mesh.shape.get(TP_AXIS, 1) > 1 else None
    ep = _ep_axis(cfg, mesh)
    sync = tuple(a for a in (DATA_AXIS,) if a in mesh.axis_names)
    specs = pp_param_specs(cfg, tp_axis=tp, ep_axis=ep)
    from .partition import validate_spec_tree

    validate_spec_tree(specs, dict(mesh.shape), root="params")
    return tp, ep, sync, specs


def pp_optimizer_state_specs(optimizer: str, specs):
    """PartitionSpec tree for the optimizer state on the pipeline mesh.

    sgd/adam mirror the param layout (elementwise state follows its leaf).
    The ZeRO-1 variants hold per-leaf FLAT buffers of the *stage-local*
    leaf, sharded over the data axis (the DeepSpeed ZeRO-1 + PP layout:
    optimizer state partitions across data-parallel ranks only, never
    across stages). A pipe-sharded layer leaf's buffer therefore carries
    both splits - stage content over 'pipe', ZeRO shard over 'data' -
    as one flat P(('pipe','data')) axis (stage-major); pipe-replicated
    leaves (embed/head/final-norm) shard P('data') exactly like the
    dp x sp x tp mesh path (train/lm.py optimizer_state_specs).
    """
    if optimizer == "sgd":
        return specs
    if optimizer == "adam":
        return {"m": specs, "v": specs, "t": P()}

    def leaf_spec(spec: P) -> P:
        if PIPE_AXIS in spec:
            return P((PIPE_AXIS, DATA_AXIS))
        return P(DATA_AXIS)

    if optimizer == "zero":
        return jax.tree.map(leaf_spec, specs)
    if optimizer == "zero-adam":
        shard = jax.tree.map(leaf_spec, specs)
        return {"m": shard, "v": shard, "t": P()}
    raise ValueError(f"unknown pipeline optimizer {optimizer!r}")


def init_pp_zero_state(params, specs, mesh: Mesh, optimizer: str):
    """ZeRO-1 optimizer state for the pipeline mesh (see
    `pp_optimizer_state_specs` for the layout).

    params: the (already pipe-sharded) global param tree; specs: its
    PartitionSpec tree from `shard_pp_params`. Each state leaf is a flat
    zeros buffer sized so every (pipe, data) device holds the padded
    1/dp shard of its *stage-local* leaf: pipe-sharded leaves get
    (pp * dp * S,) with S = ceil((size/pp)/dp) padded; replicated leaves
    (dp * S,). Zeros make content trivially layout-independent, so
    `device_put` against the spec is the whole init.
    """
    from .zero import leaf_shard_size

    dp = mesh.shape.get(DATA_AXIS, 1)
    pp = mesh.shape.get(PIPE_AXIS, 1)
    state_specs = pp_optimizer_state_specs(optimizer, specs)

    def buf(p, spec: P):
        if PIPE_AXIS in spec:
            local = p.size // pp
            return jnp.zeros((pp * dp * leaf_shard_size(local, dp),),
                             jnp.float32)
        return jnp.zeros((dp * leaf_shard_size(p.size, dp),), jnp.float32)

    if optimizer == "zero":
        state = jax.tree.map(buf, params, specs)
    elif optimizer == "zero-adam":
        state = {
            "m": jax.tree.map(buf, params, specs),
            "v": jax.tree.map(buf, params, specs),
            "t": jnp.zeros((), jnp.int32),
        }
    else:
        raise ValueError(f"not a ZeRO optimizer: {optimizer!r}")
    return jax.tree.map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
        state, state_specs,
    )


def make_pp_train_step(
    cfg: tfm.TransformerConfig,
    mesh: Mesh,
    *,
    n_microbatches: int = 2,
    lr: float = 0.1,
    momentum: float = 0.9,
    loss_chunks: int = 0,
    interleave: int = 1,
    lr_schedule=None,
    clip_norm: float = 0.0,
    weight_decay: float = 0.0,
    optimizer: str = "sgd",
    accum_steps: int = 1,
    grad_sync: str = "end",
    bucket_mb: float = 4.0,
):
    """Compiled pipeline-parallel (params, mom, tokens, targets) ->
    (params, mom, loss) over a (data, pipe, model) mesh.

    tokens/targets: (B, S) int32 with B divisible by
    dp * accum_steps * n_microbatches. Layer-stack params must be placed
    per `pp_param_specs` (use `shard_pp_params(..., interleave=interleave)`
    - the interleaved schedule needs the round-robin chunk layout).
    interleave = v > 1 cuts the pipeline bubble to (P-1)/(v*M+P-1); see
    `pipeline_lm_loss`.

    accum_steps = k > 1 runs k sequential schedule passes over B/k-row
    slices and averages the gradients (ops/schedule.accumulate_fwd_bwd).
    Raising n_microbatches instead shrinks the bubble but NOT the
    memory: the schedule is differentiated through, so its saved
    activations (and the collected exit blocks) scale with the rows in
    flight per pass - k passes cap that at B/k rows while reaching the
    k*B effective batch. Trade-off: each extra pass pays its own bubble,
    so prefer raising n_microbatches until activation memory binds, then
    accumulate.

    Loop transforms match train/lm.py's mesh path: lr_schedule makes the
    compiled fn take (params, mom, tokens, targets, step); clip_norm
    clips by the sharding-aware global norm (layer leaves psum over
    'pipe' + any tp axis, embed/head replicated); weight_decay applies
    decoupled decay after the momentum update (Adam applies it inside
    the update). optimizer: 'sgd' (state mirrors the param layout),
    'adam' ({"m","v","t"} from ops/adam.init_adam - elementwise, so
    pipe-sharded layer leaves keep their layout), or 'zero'/'zero-adam'
    (ZeRO-1: per-leaf flat state sharded over the data axis per
    stage-local leaf - init with `init_pp_zero_state`, specs from
    `pp_optimizer_state_specs`; not with tp, and not with expert
    parallelism - expert leaves vary over exactly the data axis the
    per-leaf layout shards state over).

    grad_sync="overlap" (with accum_steps >= 2) moves the data-axis
    gradient reduction inside the accumulation scan, one collective per
    size-capped leaf bucket (cap bucket_mb MiB; leaves grouped by
    PartitionSpec so pipe-sharded layer chunks never share a buffer with
    the replicated embed/head) - same schedule as train/lm.py's mesh
    path. The pipe-axis psums for stage-replicated leaves stay with
    typed autodiff (per microbatch, unchanged); only the data-axis sync
    is bucketed/overlapped. ZeRO variants reduce-scatter per bucket and
    carry the 1/dp shard. Matches "end" up to float reassociation; not
    compatible with expert parallelism.
    """
    pp = mesh.shape.get(PIPE_AXIS, 1)
    v = interleave
    if v < 1:
        raise ValueError(f"interleave must be >= 1, got {v}")
    if cfg.n_layers % (pp * v):
        raise ValueError(
            f"n_layers ({cfg.n_layers}) must be divisible by pipeline size "
            f"x interleave ({pp}x{v})"
        )
    if v > 1 and n_microbatches % pp:
        raise ValueError(
            f"the interleaved schedule runs microbatches in groups of the "
            f"pipeline size: n_microbatches ({n_microbatches}) must be a "
            f"multiple of {pp}"
        )
    if optimizer not in ("sgd", "adam", "zero", "zero-adam"):
        raise ValueError(
            f"pipeline optimizer must be one of sgd/adam/zero/zero-adam, "
            f"got {optimizer!r}"
        )
    if optimizer.startswith("zero") and mesh.shape.get(TP_AXIS, 1) > 1:
        raise ValueError(
            f"optimizer={optimizer!r} under --pp shards optimizer state "
            "over the data axis per stage-local leaf; tensor-sharded "
            "leaves (tp > 1) additionally vary over 'model', which the "
            "flat per-leaf layout does not track - use 'sgd'/'adam' with "
            "tp (matches the dp x sp x tp mesh path's rule)"
        )
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    tp, ep, sync, specs = pp_wiring(cfg, mesh)
    if optimizer.startswith("zero") and ep:
        raise ValueError(
            f"optimizer={optimizer!r} under --pp cannot combine with "
            "expert parallelism: expert-sharded leaves vary over the data "
            "axis, which is exactly the axis the per-leaf ZeRO layout "
            "shards state over (same rule as the mesh path)"
        )
    data_spec = P(DATA_AXIS)

    from ..ops.schedule import GRAD_SYNCS

    if grad_sync not in GRAD_SYNCS:
        raise ValueError(
            f"unknown grad_sync {grad_sync!r} (use one of {GRAD_SYNCS})"
        )
    if grad_sync == "overlap" and ep:
        raise ValueError(
            "grad_sync='overlap' psums every gradient bucket over the "
            "data axis, but expert-sharded leaves VARY over that axis - "
            "use grad_sync='end' with expert parallelism (same rule as "
            "the mesh path)"
        )

    def fwd_bwd_one(params, tokens, targets):
        return jax.value_and_grad(pipeline_lm_loss)(
            params, tokens, targets, cfg,
            pipe_axis=PIPE_AXIS, n_microbatches=n_microbatches,
            tp_axis=tp, ep_axis=ep, sync_axes=sync,
            loss_chunks=loss_chunks, interleave=v,
        )

    from ..ops.schedule import accumulate_fwd_bwd

    if grad_sync == "overlap" and accum_steps > 1:
        from ..ops.schedule import accumulate_fwd_bwd_overlap
        from .collectives import (
            pack_buckets,
            plan_buckets,
            unpack_buckets,
        )
        from .zero import make_overlap_grad_reducers

        bucket_bytes = max(int(bucket_mb * 2**20), 1)
        spec_keys = [
            str(s)
            for s in jax.tree.leaves(
                specs, is_leaf=lambda s: isinstance(s, P)
            )
        ]
        dp_size = mesh.shape.get(DATA_AXIS, 1)

        def fwd_bwd(params, tokens, targets):
            layout = plan_buckets(
                params, bucket_bytes=bucket_bytes, group_keys=spec_keys
            )
            # vary over the data axis only: grads w.r.t. params_v are
            # local over 'data' (the explicit bucket collective below is
            # the only data-axis sync) while the pipe-axis psums for
            # stage-replicated embed/head stay with typed autodiff
            params_v = jax.tree.map(
                lambda p: vary_like(p, extra=sync), params
            )
            if optimizer.startswith("zero"):
                reduce_fn, finalize_fn = make_overlap_grad_reducers(
                    layout, DATA_AXIS, dp_size
                )
            else:
                def reduce_fn(grads):
                    return tuple(
                        jax.lax.psum(b, sync)
                        for b in pack_buckets(layout, grads)
                    )

                def finalize_fn(bufs):
                    return unpack_buckets(layout, list(bufs))

            inner = accumulate_fwd_bwd_overlap(
                lambda _p, tok, tgt: fwd_bwd_one(params_v, tok, tgt),
                accum_steps, reduce_fn=reduce_fn, finalize_fn=finalize_fn,
            )
            return inner(params, tokens, targets)
    else:
        fwd_bwd = accumulate_fwd_bwd(fwd_bwd_one, accum_steps)

    def step(params, mom, tokens, targets, step_i=None):
        loss, grads = fwd_bwd(params, tokens, targets)
        if clip_norm > 0.0:
            from ..ops.schedule import clip_by_global_norm

            grads, _ = clip_by_global_norm(
                grads, clip_norm, specs=specs,
                axes=tuple(mesh.axis_names),
            )
        lr_t = lr if lr_schedule is None else lr_schedule(step_i)
        if optimizer == "adam":
            from ..ops.adam import adam_step

            params, mom = adam_step(
                params, mom, grads, lr_t, b1=momentum,
                weight_decay=weight_decay,
            )
        else:
            params, mom = sgd_step(params, mom, grads, lr_t, momentum)
            from ..ops.schedule import apply_decoupled_weight_decay

            params = apply_decoupled_weight_decay(params, lr_t, weight_decay)
        return params, mom, loss

    mom_spec = pp_optimizer_state_specs(optimizer, specs)
    has_step = lr_schedule is not None

    if optimizer.startswith("zero"):
        # Shared two-shard_map ZeRO-1 orchestration (zero.py
        # make_zero_split_step - same protocol as train/lm.py's zero
        # path). parallel/zero.py's per-leaf machinery needs no pipe
        # awareness: each device updates the 1/dp shard of whatever
        # leaf it holds - the full embed/head, or its own stage's
        # (L/P, ...) chunk (the DeepSpeed ZeRO-1 + PP layout). The
        # clip closure is this path's specs-aware norm: layer-leaf
        # sq-norms psum over 'pipe' (each stage holds its own chunk),
        # embed/head are replicated.
        from .zero import make_zero_split_step

        clip_fn = None
        if clip_norm > 0.0:
            from ..ops.schedule import clip_by_global_norm

            def clip_fn(grads):
                return clip_by_global_norm(
                    grads, clip_norm, specs=specs,
                    axes=tuple(mesh.axis_names),
                )[0]

        return make_zero_split_step(
            mesh=mesh, fwd_bwd=fwd_bwd, specs=specs, mom_spec=mom_spec,
            data_spec=data_spec, optimizer=optimizer, lr=lr,
            momentum=momentum, weight_decay=weight_decay,
            lr_schedule=lr_schedule, clip_fn=clip_fn, axis_name=DATA_AXIS,
        )

    if has_step:
        fn, extra = step, (P(),)
    else:
        fn, extra = (lambda p, m, a, b: step(p, m, a, b)), ()
    return jax.jit(
        compat.shard_map(
            fn,
            mesh=mesh,
            in_specs=(specs, mom_spec, data_spec, data_spec) + extra,
            out_specs=(specs, mom_spec, P()),
        ),
        donate_argnums=(0, 1),
    )


def abstract_pp_state(cfg: tfm.TransformerConfig, mesh: Mesh,
                      optimizer: str = "sgd"):
    """(params, mom) as ShapeDtypeStruct pytrees for the pipeline step -
    the analyzer's allocation-free view of the state signature (the ZeRO
    layouts come from `init_pp_zero_state`'s own math via eval_shape)."""
    params = jax.eval_shape(
        lambda k: tfm.init_params(k, cfg),
        jax.ShapeDtypeStruct((2,), jnp.uint32),
    )
    if optimizer == "sgd":
        return params, params
    if optimizer == "adam":
        return params, {
            "m": params, "v": params,
            "t": jax.ShapeDtypeStruct((), jnp.int32),
        }
    specs = pp_wiring(cfg, mesh)[3]
    mom = jax.eval_shape(
        lambda p: init_pp_zero_state(p, specs, mesh, optimizer), params
    )
    return params, mom


def pp_step_program(
    cfg: tfm.TransformerConfig,
    mesh: Mesh,
    *,
    batch: int,
    seq_len: int,
    name: str = "pp",
    optimizer: str = "sgd",
    n_microbatches: int = 2,
    **step_kwargs,
):
    """`make_pp_train_step` packaged as a traceable `StepProgram`
    (train/program.py) - the pipeline counterpart of train/lm.py
    `lm_step_program`, consumed by the static analyzer."""
    from ..train.program import StepProgram

    step = make_pp_train_step(
        cfg, mesh, optimizer=optimizer, n_microbatches=n_microbatches,
        **step_kwargs,
    )
    tp, ep, sync, specs = pp_wiring(cfg, mesh)
    mom_spec = pp_optimizer_state_specs(optimizer, specs)
    params, mom = abstract_pp_state(cfg, mesh, optimizer)
    tok = jax.ShapeDtypeStruct((batch, seq_len), jnp.int32)
    has_step = step_kwargs.get("lr_schedule") is not None
    args = (params, mom, tok, tok) + (
        (jax.ShapeDtypeStruct((), jnp.int32),) if has_step else ()
    )
    return StepProgram(
        name=name,
        fn=step,
        mesh=mesh,
        abstract_args=args,
        specs={"params": specs, "opt": mom_spec, "data": P(DATA_AXIS)},
        donate=(0, 1),
        donate_labels=("params", "optimizer state"),
        meta={
            "family": "pp",
            "optimizer": optimizer,
            "grad_sync": step_kwargs.get("grad_sync", "end"),
            "accum_steps": int(step_kwargs.get("accum_steps", 1)),
            "mesh": {k: int(v) for k, v in mesh.shape.items()},
            "dp": int(mesh.shape.get(DATA_AXIS, 1)),
            "pp": int(mesh.shape.get(PIPE_AXIS, 1)),
            "tp_axis": tp,
            "ep_axis": ep,
            "sync_axes": list(sync),
            "n_microbatches": n_microbatches,
            "batch": batch,
            "seq_len": seq_len,
        },
    )


def make_pp_eval_fn(
    cfg: tfm.TransformerConfig,
    mesh: Mesh,
    *,
    n_microbatches: int = 2,
    loss_chunks: int = 0,
    interleave: int = 1,
):
    """Compiled (params, tokens, targets) -> replicated mean loss through
    the same microbatch schedule as training, no grad - the held-out
    eval for pipeline runs. Lives here so the CLI never re-derives the
    pipeline's spec/axis wiring (it must match `make_pp_train_step`)."""
    tp, ep, sync, specs = pp_wiring(cfg, mesh)
    data_spec = P(DATA_AXIS)
    return jax.jit(
        compat.shard_map(
            lambda p, tok, tgt: pipeline_lm_loss(
                p, tok, tgt, cfg,
                n_microbatches=n_microbatches, tp_axis=tp, ep_axis=ep,
                sync_axes=sync, loss_chunks=loss_chunks,
                interleave=interleave,
            ),
            mesh=mesh,
            in_specs=(specs, data_spec, data_spec),
            out_specs=P(),
        )
    )


def interleave_layer_order(
    n_layers: int, pp: int, v: int, *, inverse: bool = False
) -> np.ndarray:
    """Layer-axis permutation for the interleaved chunk layout.

    Global chunk c (of v*P chunks, L/(v*P) layers each) must live on
    device c % P at local lap c // P, so the pipe-sharded leading axis is
    ordered device-major, lap-minor: position (q*v + l)*cl + j holds
    original layer (l*P + q)*cl + j. `inverse=True` returns the
    permutation that restores the canonical order (for checkpoint export
    or switching schedules).
    """
    if v < 1 or n_layers % (pp * v):
        raise ValueError(
            f"n_layers ({n_layers}) must be divisible by pipeline size x "
            f"interleave ({pp}x{v})"
        )
    cl = n_layers // (pp * v)
    order = np.empty(n_layers, np.int64)
    pos = 0
    for q in range(pp):
        for lap in range(v):
            c = lap * pp + q
            order[pos:pos + cl] = np.arange(c * cl, (c + 1) * cl)
            pos += cl
    if inverse:
        inv = np.empty_like(order)
        inv[order] = np.arange(n_layers)
        return inv
    return order


def shard_pp_params(params, cfg, mesh: Mesh, *, interleave: int = 1):
    """Place a replicated-layout param tree per pp_param_specs.

    interleave > 1 additionally permutes the layer axis into the
    round-robin chunk layout the interleaved schedule indexes
    (`interleave_layer_order`)."""
    specs = pp_wiring(cfg, mesh)[3]
    if interleave > 1:
        pp = mesh.shape.get(PIPE_AXIS, 1)
        order = interleave_layer_order(cfg.n_layers, pp, interleave)
        params = dict(params)
        params["layers"] = jax.tree.map(
            lambda a: a[order], params["layers"]
        )
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs
    ), specs
