"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

The reference has no pipeline parallelism (SURVEY.md section 2: explicitly
absent - its model is a 5-layer CNN trained data-parallel only). This module
is the framework's pipeline capability for the transformer family
(`models/transformer.py`), built the TPU way rather than the
point-to-point-send way:

- **Stages are a mesh axis.** The transformer's scanned layer stack
  (leaves shaped (L, ...)) is sharded over a `'pipe'` axis: each device
  holds L/P contiguous layers. No per-stage module objects, no rank
  branching - one shard_map'd program, SPMD over stages.
- **The schedule is a dense scan.** The classic GPipe timeline of
  T = M + P - 1 ticks (M microbatches through P stages) is a
  `jax.lax.scan`; each tick every stage applies its local layers to its
  current activation block and the blocks rotate one hop along the ring via
  `jax.lax.ppermute` (XLA lowers to ICI neighbor exchange). Stage 0 feeds a
  fresh microbatch each tick; the last stage applies the LM head and
  accumulates loss for ticks that carry a valid microbatch. Pipeline-bubble
  ticks compute on garbage and are masked out - the standard static-shape
  trade.
- **Autodiff does the backward pipeline.** The whole schedule is
  differentiable (scan + ppermute + where-masks), so reverse-mode AD yields
  the reverse-order backward pipeline automatically; stage-sharded layer
  params (device-varying over 'pipe') get local gradients, while embed/head
  (replicated over 'pipe') get their cross-stage gradient psum from
  shard_map's typing - no hand-written send/recv of activation grads.
- Composes with a 'data' axis (batch sharded, grad pmean automatic) and the
  tensor-parallel 'model' axis (per-block psums inside each stage).

Known simplicity trade: every stage computes the (cheap) embedding and LM
head every tick, with `where`-selection keeping only the boundary stages'
results - wasted VPU work proportional to vocab, in exchange for a fully
uniform SPMD program with zero stage branching.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import transformer as tfm
from ..ops.sgd import sgd_step

DATA_AXIS = "data"
PIPE_AXIS = "pipe"
TP_AXIS = "model"


def create_pp_mesh(dp: int, pp: int, tp: int = 1) -> Mesh:
    """(data, pipe, model) mesh; pipe/model innermost for ICI adjacency."""
    n = dp * pp * tp
    devices = jax.devices()
    if n > len(devices):
        raise ValueError(f"mesh {dp}x{pp}x{tp} needs {n} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(dp, pp, tp)
    return Mesh(arr, (DATA_AXIS, PIPE_AXIS, TP_AXIS))


def pp_param_specs(cfg: tfm.TransformerConfig, tp_axis: str | None = None):
    """param_specs with every layer-stack leaf stage-sharded over 'pipe'.

    The layer dimension (leading axis of every `layers` leaf) is split
    across stages; embed/head/final-norm stay replicated over 'pipe'.
    """
    specs = tfm.param_specs(cfg, tp_axis=tp_axis)

    def stage_shard(spec: P) -> P:
        rest = tuple(spec)[1:]  # drop the layer-dim entry (None) if present
        return P(PIPE_AXIS, *rest)

    specs["layers"] = {k: stage_shard(s) for k, s in specs["layers"].items()}
    return specs


def pipeline_lm_loss(
    params,
    tokens,
    targets,
    cfg: tfm.TransformerConfig,
    *,
    pipe_axis: str = PIPE_AXIS,
    n_microbatches: int,
    tp_axis: str | None = None,
    sync_axes=(),
):
    """Mean next-token cross-entropy via the microbatch pipeline schedule.

    Call inside shard_map. tokens/targets: (B_local, S) int32; params: the
    local stage shard (layers leaves (L/P, ...), embed/head replicated).
    Returns the replicated global mean loss (psum over pipe + sync_axes).
    """
    n_pipe = jax.lax.axis_size(pipe_axis)
    stage = jax.lax.axis_index(pipe_axis)
    m = n_microbatches
    b_local, s = tokens.shape
    assert b_local % m == 0, (b_local, m)
    mb = b_local // m
    dt = cfg.dtype
    tok_mb = tokens.reshape(m, mb, s)
    tgt_mb = targets.reshape(m, mb, s)
    pe = tfm._sinusoid_pe(jnp.arange(s), cfg.d_model, dt)[None]

    def local_blocks(x):
        def block(x, lp):
            x, _ = tfm.transformer_block(
                x,
                lp,
                cfg,
                attend=lambda q, k, v: tfm.attention(q, k, v, causal=True),
                tp_axis=tp_axis,
            )
            return x, None

        if cfg.remat:
            block = jax.checkpoint(block)
        x, _ = jax.lax.scan(block, x, params["layers"])
        return x

    perm = [(i, (i + 1) % n_pipe) for i in range(n_pipe)]
    is_last = stage == n_pipe - 1

    def tick(carry, t):
        x_in, loss_sum = carry
        t_feed = jnp.clip(t, 0, m - 1)
        fresh = params["embed"][jax.lax.dynamic_index_in_dim(
            tok_mb, t_feed, keepdims=False
        )].astype(dt) + pe
        x = jnp.where(stage == 0, fresh, x_in)
        out = local_blocks(x)

        # last stage: head + loss for microbatch t - (P-1), when valid
        h = tfm._layer_norm(out, params["lnf_scale"], params["lnf_bias"]).astype(dt)
        logits = (h @ params["head"].astype(dt)).astype(jnp.float32)
        t_out = jnp.clip(t - (n_pipe - 1), 0, m - 1)
        tgt = jax.lax.dynamic_index_in_dim(tgt_mb, t_out, keepdims=False)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
        valid = jnp.logical_and(is_last, t >= n_pipe - 1)
        loss_sum = loss_sum + jnp.where(valid, -ll.sum(), 0.0)

        x_out = jax.lax.ppermute(out, pipe_axis, perm)
        return (x_out, loss_sum), None

    def vary(x):
        # activations vary over the pipe axis (stage-dependent) and whatever
        # the tokens vary over (data), but stay invariant over 'model': the
        # per-block tp psums close every model-varying intermediate
        try:
            want = {pipe_axis} | set(jax.typeof(tokens).vma)
            missing = tuple(a for a in want if a not in jax.typeof(x).vma)
        except AttributeError:
            return x
        return jax.lax.pcast(x, missing, to="varying") if missing else x

    x0 = vary(jnp.zeros((mb, s, cfg.d_model), dt))
    loss0 = vary(jnp.float32(0.0))
    (_, loss_sum), _ = jax.lax.scan(
        tick, (x0, loss0), jnp.arange(m + n_pipe - 1)
    )
    axes = (pipe_axis,) + tuple(sync_axes)
    total = jax.lax.psum(loss_sum, axes)
    # global token count is static: every data-shard holds tokens.size tokens
    n_tokens = tokens.size
    for a in sync_axes:
        n_tokens = n_tokens * jax.lax.axis_size(a)
    return total / jnp.float32(n_tokens)


def make_pp_train_step(
    cfg: tfm.TransformerConfig,
    mesh: Mesh,
    *,
    n_microbatches: int = 2,
    lr: float = 0.1,
    momentum: float = 0.9,
):
    """Compiled pipeline-parallel (params, mom, tokens, targets) ->
    (params, mom, loss) over a (data, pipe, model) mesh.

    tokens/targets: (B, S) int32 with B divisible by dp * n_microbatches.
    Layer-stack params must be placed per `pp_param_specs` (use
    `shard_pp_params`).
    """
    pp = mesh.shape.get(PIPE_AXIS, 1)
    if cfg.n_layers % pp:
        raise ValueError(
            f"n_layers ({cfg.n_layers}) must be divisible by pipeline size ({pp})"
        )
    if cfg.n_experts:
        raise ValueError(
            "pipeline parallelism currently supports dense blocks only "
            f"(cfg.n_experts={cfg.n_experts}); use the dp/ep path in train/lm.py "
            "for MoE models"
        )
    tp = TP_AXIS if mesh.shape.get(TP_AXIS, 1) > 1 else None
    sync = tuple(a for a in (DATA_AXIS,) if a in mesh.axis_names)
    specs = pp_param_specs(cfg, tp_axis=tp)
    data_spec = P(DATA_AXIS)

    def step(params, mom, tokens, targets):
        loss, grads = jax.value_and_grad(pipeline_lm_loss)(
            params,
            tokens,
            targets,
            cfg,
            pipe_axis=PIPE_AXIS,
            n_microbatches=n_microbatches,
            tp_axis=tp,
            sync_axes=sync,
        )
        params, mom = sgd_step(params, mom, grads, lr, momentum)
        return params, mom, loss

    return jax.jit(
        jax.shard_map(
            step,
            mesh=mesh,
            in_specs=(specs, specs, data_spec, data_spec),
            out_specs=(specs, specs, P()),
        ),
        donate_argnums=(0, 1),
    )


def shard_pp_params(params, cfg, mesh: Mesh):
    """Place a replicated-layout param tree per pp_param_specs."""
    tp = TP_AXIS if mesh.shape.get(TP_AXIS, 1) > 1 else None
    specs = pp_param_specs(cfg, tp_axis=tp)
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs
    ), specs
