"""ZeRO-1: optimizer state sharded over the data axis.

The reference replicates everything everywhere - each MPI worker holds the
full model and a full private optimizer (`data_parallelism_train.py:187`
recreates `torch.optim.SGD` per epoch per rank), so optimizer memory scales
with replica count. SURVEY.md section 2 lists ZeRO/FSDP-style sharding as
absent from the reference; this module adds the capability TPU-natively.

Design (ZeRO stage 1, the optimizer-state partition):

- The param/grad pytree is flattened to ONE 1-D vector (`ravel_pytree`),
  zero-padded to a multiple of the data-axis size, and split into equal
  contiguous shards - perfect load balance regardless of leaf shapes, no
  per-leaf divisibility constraints.
- Each device owns 1/N of the momentum buffer (the O(params) optimizer
  state) and updates only its shard: update FLOPs and optimizer memory both
  drop by N.
- Gradient reduction: either `jax.lax.psum_scatter` of the raw per-device
  gradient (the canonical ZeRO reduce-scatter, same bytes as half an
  all-reduce) or - when gradients arrive already summed by shard_map's typed
  autodiff psum - a free local slice.
- Parameter reassembly: one tiled `jax.lax.all_gather` of the updated
  shards. reduce_scatter + all_gather together cost exactly one all-reduce,
  so ZeRO-1 is communication-neutral versus replicated SGD while saving the
  memory and update compute.

Pure functions for use inside `jax.shard_map` over a 1-D data axis; the
param tree must be replicated across that axis (dense models; tensor- or
expert-sharded leaves vary across other axes and are out of scope for the
flat vector - validated by the caller in train/lm.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree


def _padded(d: int, n: int) -> int:
    return (d + n - 1) // n * n


def zero_shard_size(params, n_shards: int) -> int:
    """Length of each device's momentum shard."""
    d = sum(int(jnp.size(p)) for p in jax.tree.leaves(params))
    return _padded(d, n_shards) // n_shards


def init_zero_momentum(params, n_shards: int):
    """Global flat momentum buffer (pad(D),) - shard it over the data axis
    (jit-level sharding P('data')); each device then holds (pad(D)/N,)."""
    return jnp.zeros((zero_shard_size(params, n_shards) * n_shards,), jnp.float32)


def zero_sgd_step(
    params,
    mom_shard,
    grads,
    lr,
    momentum,
    *,
    axis_name: str = "data",
    grads_presummed: bool = True,
):
    """One SGD(momentum) step with the momentum buffer sharded over
    `axis_name`. Call inside shard_map.

    params/grads: full (local) pytrees; mom_shard: this device's (pad(D)/N,)
    slice. Both gradient paths use the same convention - the update uses the
    GLOBAL gradient of a globally-normalized loss:
    `grads_presummed=True` means grads are already that global gradient,
    identical across the axis (shard_map's typed autodiff psum), and are
    just sliced; False means grads are per-device partials whose *sum* over
    the axis is the global gradient, reduced with the canonical
    psum_scatter. Returns (new_params, new_mom_shard).
    """
    n = jax.lax.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    flat_p, unravel = ravel_pytree(params)
    flat_g, _ = ravel_pytree(grads)
    d = flat_p.shape[0]
    pad = _padded(d, n) - d
    if pad:
        flat_p = jnp.concatenate([flat_p, jnp.zeros((pad,), flat_p.dtype)])
        flat_g = jnp.concatenate([flat_g, jnp.zeros((pad,), flat_g.dtype)])
    shard = flat_p.shape[0] // n

    if grads_presummed:
        g_sh = jax.lax.dynamic_slice(flat_g, (me * shard,), (shard,))
    else:
        g_sh = jax.lax.psum_scatter(flat_g, axis_name, scatter_dimension=0,
                                    tiled=True)

    mom_new = momentum * mom_shard + g_sh
    p_sh = jax.lax.dynamic_slice(flat_p, (me * shard,), (shard,)) - lr * mom_new
    # reassemble: scatter own shard into zeros and psum - all-gather
    # semantics, but typed *invariant* over the axis (each position is
    # written by exactly one device), which shard_map's vma checker needs
    # for the replicated params output. XLA lowers the one-hot psum to an
    # all-gather-class collective.
    flat_new = jax.lax.psum(
        jax.lax.dynamic_update_slice(
            jnp.zeros_like(flat_p), p_sh, (me * shard,)
        ),
        axis_name,
    )
    return unravel(flat_new[:d]), mom_new
