"""ZeRO-1: optimizer state sharded over the data axis.

The reference replicates everything everywhere - each MPI worker holds the
full model and a full private optimizer (`data_parallelism_train.py:187`
recreates `torch.optim.SGD` per epoch per rank), so optimizer memory scales
with replica count. SURVEY.md section 2 lists ZeRO/FSDP-style sharding as
absent from the reference; this module adds the capability TPU-natively.

Design (ZeRO stage 1, the optimizer-state partition):

- Each leaf is zero-padded to a multiple of the data-axis size and split
  into equal contiguous shards; each device owns 1/N of the momentum
  buffer (the O(params) optimizer state) and updates only its shard:
  update FLOPs and optimizer memory both drop by N.
- Gradient reduction: either `jax.lax.psum_scatter` of the raw per-device
  gradient (the canonical ZeRO reduce-scatter, same bytes as half an
  all-reduce) or - when gradients arrive already summed by shard_map's typed
  autodiff psum - a free local slice.
- Parameter reassembly: one tiled `jax.lax.all_gather` of the updated
  shards per leaf. reduce_scatter + all_gather together cost exactly one
  all-reduce, so ZeRO-1 is communication-neutral versus replicated SGD
  while saving the memory and update compute.

Two implementations, same math (the SGD update is elementwise, so the
partitioning cannot change any value - parity is bitwise):

- `zero_sgd_step_sharded` (the production path, round 2): per-leaf slice
  maps precomputed by structure, O(leaf) temporaries only, true
  `all_gather` reassembly. Runs inside a `check_vma=False` shard_map (the
  optimizer is outside autodiff, so vma typing buys nothing) - see
  train/lm.py.
- `zero_sgd_step` (retained as the oracle + for vma-checked contexts):
  `ravel_pytree` of the full tree per step and a one-hot psum reassembly,
  whose *invariant*-typed output satisfies shard_map's vma checker at the
  cost of O(D) temporaries and ~2x the reassembly communication.

Pure functions for use inside `jax.shard_map` over a 1-D data axis; the
param tree must be replicated across that axis (dense models; tensor- or
expert-sharded leaves vary across other axes and are out of scope -
validated by the caller in train/lm.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree


def _padded(d: int, n: int) -> int:
    return (d + n - 1) // n * n


def zero_shard_size(params, n_shards: int) -> int:
    """Length of each device's momentum shard."""
    d = sum(int(jnp.size(p)) for p in jax.tree.leaves(params))
    return _padded(d, n_shards) // n_shards


def init_zero_momentum(params, n_shards: int):
    """Global flat momentum buffer (pad(D),) - shard it over the data axis
    (jit-level sharding P('data')); each device then holds (pad(D)/N,)."""
    return jnp.zeros((zero_shard_size(params, n_shards) * n_shards,), jnp.float32)


def leaf_shard_size(d: int, n_shards: int) -> int:
    """Per-device shard length for one leaf of d elements (ceil(d/n))."""
    return _padded(d, n_shards) // n_shards


def init_zero_momentum_tree(params, n_shards: int):
    """Per-leaf flat momentum buffers, (pad(leaf)/N * N,) each - shard every
    leaf over the data axis (P('data')); a device then holds (pad(leaf)/N,)
    per leaf. Pair with `zero_sgd_step_sharded`."""
    return jax.tree.map(
        lambda p: jnp.zeros(
            (leaf_shard_size(p.size, n_shards) * n_shards,), jnp.float32
        ),
        params,
    )


def zero_sgd_step_sharded(
    params,
    mom_tree,
    grads,
    lr,
    momentum,
    *,
    axis_name: str = "data",
    grads_presummed: bool = True,
):
    """One SGD(momentum) step, momentum sharded per leaf over `axis_name`.

    The production ZeRO-1 path: no full-tree flatten, no full-size one-hot
    temporaries - each leaf is padded to N*S, this device updates its own
    (S,) slice, and one tiled `all_gather` per leaf reassembles the
    replicated parameter. Because `all_gather` outputs are device-varying
    in shard_map's vma typing (identical values, but the checker cannot
    prove it), call this inside `shard_map(..., check_vma=False)`; the
    optimizer runs outside autodiff, so the typing is not load-bearing
    (train/lm.py splits the step accordingly).

    params/grads: full (local) pytrees; mom_tree: per-leaf (S,) slices
    (init with `init_zero_momentum_tree`, sharded P(axis)). Gradient
    contract matches `zero_sgd_step`. Returns (new_params, new_mom_tree).
    """
    n = jax.lax.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)

    def leaf(p, m, g):
        d = p.size
        s = m.shape[0]
        flat_g = g.reshape(-1)
        pad = s * n - d
        if grads_presummed:
            if pad:
                flat_g = jnp.concatenate([flat_g, jnp.zeros((pad,), g.dtype)])
            g_sh = jax.lax.dynamic_slice(flat_g, (me * s,), (s,))
        else:
            if pad:
                flat_g = jnp.concatenate([flat_g, jnp.zeros((pad,), g.dtype)])
            g_sh = jax.lax.psum_scatter(
                flat_g, axis_name, scatter_dimension=0, tiled=True
            )
        m_new = momentum * m + g_sh
        flat_p = p.reshape(-1)
        if pad:
            flat_p = jnp.concatenate([flat_p, jnp.zeros((pad,), p.dtype)])
        p_sh = jax.lax.dynamic_slice(flat_p, (me * s,), (s,)) - lr * m_new
        full = jax.lax.all_gather(p_sh, axis_name, tiled=True)
        return full[:d].reshape(p.shape), m_new

    leaves_p, treedef = jax.tree_util.tree_flatten(params)
    leaves_m = treedef.flatten_up_to(mom_tree)
    leaves_g = treedef.flatten_up_to(grads)
    out = [leaf(p, m, g) for p, m, g in zip(leaves_p, leaves_m, leaves_g)]
    new_p = jax.tree_util.tree_unflatten(treedef, [o[0] for o in out])
    new_m = jax.tree_util.tree_unflatten(treedef, [o[1] for o in out])
    return new_p, new_m


def zero_sgd_step(
    params,
    mom_shard,
    grads,
    lr,
    momentum,
    *,
    axis_name: str = "data",
    grads_presummed: bool = True,
):
    """One SGD(momentum) step with the momentum buffer sharded over
    `axis_name`. Call inside shard_map.

    params/grads: full (local) pytrees; mom_shard: this device's (pad(D)/N,)
    slice. Both gradient paths use the same convention - the update uses the
    GLOBAL gradient of a globally-normalized loss:
    `grads_presummed=True` means grads are already that global gradient,
    identical across the axis (shard_map's typed autodiff psum), and are
    just sliced; False means grads are per-device partials whose *sum* over
    the axis is the global gradient, reduced with the canonical
    psum_scatter. Returns (new_params, new_mom_shard).
    """
    n = jax.lax.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    flat_p, unravel = ravel_pytree(params)
    flat_g, _ = ravel_pytree(grads)
    d = flat_p.shape[0]
    pad = _padded(d, n) - d
    if pad:
        flat_p = jnp.concatenate([flat_p, jnp.zeros((pad,), flat_p.dtype)])
        flat_g = jnp.concatenate([flat_g, jnp.zeros((pad,), flat_g.dtype)])
    shard = flat_p.shape[0] // n

    if grads_presummed:
        g_sh = jax.lax.dynamic_slice(flat_g, (me * shard,), (shard,))
    else:
        g_sh = jax.lax.psum_scatter(flat_g, axis_name, scatter_dimension=0,
                                    tiled=True)

    mom_new = momentum * mom_shard + g_sh
    p_sh = jax.lax.dynamic_slice(flat_p, (me * shard,), (shard,)) - lr * mom_new
    # reassemble: scatter own shard into zeros and psum - all-gather
    # semantics, but typed *invariant* over the axis (each position is
    # written by exactly one device), which shard_map's vma checker needs
    # for the replicated params output. XLA lowers the one-hot psum to an
    # all-gather-class collective.
    flat_new = jax.lax.psum(
        jax.lax.dynamic_update_slice(
            jnp.zeros_like(flat_p), p_sh, (me * shard,)
        ),
        axis_name,
    )
    return unravel(flat_new[:d]), mom_new
