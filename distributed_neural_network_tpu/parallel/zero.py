"""ZeRO-1: optimizer state sharded over the data axis.

The reference replicates everything everywhere - each MPI worker holds the
full model and a full private optimizer (`data_parallelism_train.py:187`
recreates `torch.optim.SGD` per epoch per rank), so optimizer memory scales
with replica count. SURVEY.md section 2 lists ZeRO/FSDP-style sharding as
absent from the reference; this module adds the capability TPU-natively.

Design (ZeRO stage 1, the optimizer-state partition):

- Each leaf is zero-padded to a multiple of the data-axis size and split
  into equal contiguous shards; each device owns 1/N of the momentum
  buffer (the O(params) optimizer state) and updates only its shard:
  update FLOPs and optimizer memory both drop by N.
- Gradient reduction: either `jax.lax.psum_scatter` of the raw per-device
  gradient (the canonical ZeRO reduce-scatter, same bytes as half an
  all-reduce) or - when gradients arrive already summed by shard_map's typed
  autodiff psum - a free local slice.
- Parameter reassembly: one tiled `jax.lax.all_gather` of the updated
  shards per leaf. reduce_scatter + all_gather together cost exactly one
  all-reduce, so ZeRO-1 is communication-neutral versus replicated SGD
  while saving the memory and update compute.

Two implementations, same math (the SGD update is elementwise, so the
partitioning cannot change any value - parity is bitwise):

- `zero_sgd_step_sharded` (the production path, round 2): per-leaf slice
  maps precomputed by structure, O(leaf) temporaries only, true
  `all_gather` reassembly. Runs inside a `check_vma=False` shard_map (the
  optimizer is outside autodiff, so vma typing buys nothing) - see
  train/lm.py.
- `zero_sgd_step` (retained as the oracle + for vma-checked contexts):
  `ravel_pytree` of the full tree per step and a one-hot psum reassembly,
  whose *invariant*-typed output satisfies shard_map's vma checker at the
  cost of O(D) temporaries and ~2x the reassembly communication.

Pure functions for use inside `jax.shard_map` over a 1-D data axis; the
param tree must be replicated across that axis (dense models; tensor- or
expert-sharded leaves vary across other axes and are out of scope -
validated by the caller in train/lm.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

from .. import compat


def _padded(d: int, n: int) -> int:
    return (d + n - 1) // n * n


def zero_shard_size(params, n_shards: int) -> int:
    """Length of each device's momentum shard."""
    d = sum(int(jnp.size(p)) for p in jax.tree.leaves(params))
    return _padded(d, n_shards) // n_shards


def init_zero_momentum(params, n_shards: int):
    """Global flat momentum buffer (pad(D),) - shard it over the data axis
    (jit-level sharding P('data')); each device then holds (pad(D)/N,)."""
    return jnp.zeros((zero_shard_size(params, n_shards) * n_shards,), jnp.float32)


def leaf_shard_size(d: int, n_shards: int) -> int:
    """Per-device shard length for one leaf of d elements (ceil(d/n))."""
    return _padded(d, n_shards) // n_shards


def init_zero_momentum_tree(params, n_shards: int):
    """Per-leaf flat momentum buffers, (pad(leaf)/N * N,) each - shard every
    leaf over the data axis (P('data')); a device then holds (pad(leaf)/N,)
    per leaf. Pair with `zero_sgd_step_sharded`."""
    return jax.tree.map(
        lambda p: jnp.zeros(
            (leaf_shard_size(p.size, n_shards) * n_shards,), jnp.float32
        ),
        params,
    )


def _sharded_leaf_step(
    params, grads, state_trees, update_fn, *, axis_name, grads_presummed
):
    """Shared ZeRO-1 per-leaf scaffolding for any elementwise optimizer.

    For each leaf: pad to N*S, reduce (slice or psum_scatter) the gradient
    to this device's (S,) shard, call `update_fn(p_sh, g_sh, *state_shs)
    -> (p_sh_new, *state_shs_new)` on the shards, then all_gather +
    truncate to reassemble the replicated parameter. state_trees is a
    tuple of per-leaf flat shard trees (one per optimizer buffer).
    Returns (new_params, tuple(new_state_trees)).
    """
    n = compat.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)

    def leaf(p, g, *states):
        d = p.size
        s = states[0].shape[0] if states else _padded(d, n) // n
        flat_g = g.reshape(-1)
        pad = s * n - d
        if pad:
            flat_g = jnp.concatenate([flat_g, jnp.zeros((pad,), g.dtype)])
        if grads_presummed:
            g_sh = jax.lax.dynamic_slice(flat_g, (me * s,), (s,))
        else:
            g_sh = jax.lax.psum_scatter(
                flat_g, axis_name, scatter_dimension=0, tiled=True
            )
        flat_p = p.reshape(-1)
        if pad:
            flat_p = jnp.concatenate([flat_p, jnp.zeros((pad,), p.dtype)])
        p_sh = jax.lax.dynamic_slice(flat_p, (me * s,), (s,))
        p_new, *st_new = update_fn(p_sh, g_sh, *states)
        full = jax.lax.all_gather(p_new, axis_name, tiled=True)
        return (full[:d].reshape(p.shape), *st_new)

    leaves_p, treedef = jax.tree_util.tree_flatten(params)
    leaves_g = treedef.flatten_up_to(grads)
    leaves_st = [treedef.flatten_up_to(t) for t in state_trees]
    out = [
        leaf(p, g, *sts)
        for p, g, *sts in zip(leaves_p, leaves_g, *leaves_st)
    ]
    new_p = jax.tree_util.tree_unflatten(treedef, [o[0] for o in out])
    new_states = tuple(
        jax.tree_util.tree_unflatten(treedef, [o[1 + i] for o in out])
        for i in range(len(state_trees))
    )
    return new_p, new_states


def zero_sgd_step_sharded(
    params,
    mom_tree,
    grads,
    lr,
    momentum,
    *,
    axis_name: str = "data",
    grads_presummed: bool = True,
):
    """One SGD(momentum) step, momentum sharded per leaf over `axis_name`.

    The production ZeRO-1 path: no full-tree flatten, no full-size one-hot
    temporaries - each leaf is padded to N*S, this device updates its own
    (S,) slice, and one tiled `all_gather` per leaf reassembles the
    replicated parameter. Because `all_gather` outputs are device-varying
    in shard_map's vma typing (identical values, but the checker cannot
    prove it), call this inside `shard_map(..., check_vma=False)`; the
    optimizer runs outside autodiff, so the typing is not load-bearing
    (train/lm.py splits the step accordingly).

    params/grads: full (local) pytrees; mom_tree: per-leaf (S,) slices
    (init with `init_zero_momentum_tree`, sharded P(axis)). Gradient
    contract matches `zero_sgd_step`. Returns (new_params, new_mom_tree).
    """

    def upd(p_sh, g_sh, m):
        m_new = momentum * m + g_sh
        return p_sh - lr * m_new, m_new

    new_p, (new_m,) = _sharded_leaf_step(
        params, grads, (mom_tree,), upd,
        axis_name=axis_name, grads_presummed=grads_presummed,
    )
    return new_p, new_m


def init_zero_adam_tree(params, n_shards: int):
    """ZeRO-1 Adam state: per-leaf flat first/second-moment buffers (shard
    each P('data') like the SGD momentum tree) + replicated step counter.
    Pair with `zero_adam_step_sharded`."""
    return {
        "m": init_zero_momentum_tree(params, n_shards),
        "v": init_zero_momentum_tree(params, n_shards),
        "t": jnp.zeros((), jnp.int32),
    }


def zero_adam_step_sharded(
    params,
    state,
    grads,
    lr,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    *,
    axis_name: str = "data",
    grads_presummed: bool = True,
):
    """One Adam/AdamW step with BOTH moment buffers sharded per leaf over
    `axis_name` - ZeRO-1 for the adaptive family, where the win doubles:
    Adam state is 2x params, so sharding saves 2*D*(N-1)/N memory.

    Same slice/update/all_gather pattern and calling contract as
    `zero_sgd_step_sharded` (call inside shard_map(check_vma=False); see
    train/lm.py) - both share `_sharded_leaf_step`. state: {"m": tree of
    (S,), "v": tree of (S,), "t": ()} from `init_zero_adam_tree`. Returns
    (new_params, new_state). Numerics match `ops/adam.py adam_step`
    exactly (elementwise update on a partition of the elements).
    """
    from ..ops.adam import adam_leaf_update, bias_corrections

    t = state["t"] + 1
    c1, c2 = bias_corrections(t, b1, b2)

    def upd(p_sh, g_sh, m, v):
        return adam_leaf_update(
            p_sh, g_sh, m, v, c1, c2, lr, b1, b2, eps, weight_decay
        )

    new_p, (new_m, new_v) = _sharded_leaf_step(
        params, grads, (state["m"], state["v"]), upd,
        axis_name=axis_name, grads_presummed=grads_presummed,
    )
    return new_p, {"m": new_m, "v": new_v, "t": t}


def make_overlap_grad_reducers(layout, axis_name: str, n_shards: int, *,
                               extra_axes=()):
    """(reduce_fn, finalize_fn) for the ZeRO shard-carry overlap schedule.

    Feeds `ops/schedule.py accumulate_fwd_bwd_overlap`: the scan body
    reduce-scatters each microbatch's local gradients per bucket
    (parallel/collectives.py `reduce_scatter_buckets`), so the
    accumulation carry holds only this device's 1/N bucket shards -
    O(D/N) instead of the end schedule's O(D) full-tree carry, which
    makes k-step accumulation memory-neutral with the ZeRO-1 state
    sharding. `finalize_fn` reassembles the averaged shards into the full
    replicated gradient tree with the invariant-typed bucket all-gather
    (`all_gather_buckets`), so the existing per-leaf optimizer path
    (`grads_presummed=True` slice in `_sharded_leaf_step`) consumes it
    unchanged. `extra_axes`: mesh axes beyond `axis_name` the gradients
    also reduce over (the seq axis on a dp x sp mesh) - psummed on the
    shard, at shard cost.
    """
    from .collectives import all_gather_buckets, reduce_scatter_buckets

    def reduce_fn(grads):
        return reduce_scatter_buckets(
            grads, layout, axis_name, axis_size=n_shards,
            extra_axes=tuple(extra_axes),
        )

    def finalize_fn(shards):
        return all_gather_buckets(
            shards, layout, axis_name, axis_size=n_shards
        )

    return reduce_fn, finalize_fn


def make_zero_split_step(
    *,
    mesh,
    fwd_bwd,
    specs,
    mom_spec,
    data_spec,
    optimizer: str,
    lr: float,
    momentum: float,
    weight_decay: float = 0.0,
    lr_schedule=None,
    clip_fn=None,
    axis_name: str = "data",
    check_vma: bool = True,
    with_health: bool = False,
    skip_nonfinite: bool = False,
    fault_plan=None,
    dynamics: bool = False,
    gns: bool = False,
):
    """Shared two-shard_map ZeRO-1 step orchestration.

    Used by BOTH the dp x sp x tp mesh path (train/lm.py) and the
    pipeline path (parallel/pipeline.py) so the protocol lives once:
    a vma-checked fwd/bwd shard_map (typed autodiff inserts the grad
    psums per `specs`), then the per-leaf ZeRO-1 update inside a
    check_vma=False shard_map - its all_gather reassembly produces
    values that are replicated in fact but "varying" to the checker,
    and no autodiff flows through the optimizer, so the typing buys
    nothing there.

    fwd_bwd(params, tokens, targets) -> (loss, grads), called inside
    shard_map. clip_fn(grads) -> grads, called inside the optimizer
    shard_map (pass the caller's specs-aware or plain clip). momentum
    doubles as Adam's b1 so a single --momentum flag drives every
    optimizer. Returns the jitted (params, mom, tokens, targets[, step])
    -> (params, mom, loss) with params/mom donated.

    Guard hooks (train/guard.py, mirroring train/lm.py's mesh path):
    zero forbids tp/ep, so between the two shard_maps the gradients are
    full replicated arrays at the jit level - the health bundle (loss,
    global grad-norm, derived finite flag), the in-jit skip gate, and
    fault injection all happen there with plain (non-collective) ops.
    One O(D) float32 norm reduction is added when health is on without
    clipping (with clip_fn the norm runs inside the optimizer shard_map
    regardless; the health norm is the same value computed where the
    bundle needs it). `fault_plan` forces the step-index signature.

    dynamics (train/dynamics.py): appends the dynamics bundle as the
    step's LAST output, computed at the jit level where the gradients
    are full replicated arrays - plain per-leaf squared norms, no
    collectives. `gns=True` declares that `fwd_bwd` carries the
    accumulation scan's third output (the mean per-microbatch squared
    grad norm, ops/schedule.py accumulate_fwd_bwd sq_norm_fn) and
    threads it into the bundle.
    """
    import jax.numpy as _jnp
    from jax.sharding import PartitionSpec as _P

    grad_fn = compat.shard_map(
        fwd_bwd,
        mesh=mesh,
        in_specs=(specs, data_spec, data_spec),
        out_specs=(_P(), specs) + ((_P(),) if gns else ()),
        check_vma=check_vma,
    )

    def opt_body(params, mom, grads, lr_t):
        if clip_fn is not None:
            grads = clip_fn(grads)
        if optimizer == "zero-adam":
            return zero_adam_step_sharded(
                params, mom, grads, lr_t, b1=momentum,
                weight_decay=weight_decay,
                axis_name=axis_name, grads_presummed=True,
            )
        new_p, new_m = zero_sgd_step_sharded(
            params, mom, grads, lr_t, momentum,
            axis_name=axis_name, grads_presummed=True,
        )
        from ..ops.schedule import apply_decoupled_weight_decay

        new_p = apply_decoupled_weight_decay(new_p, lr_t, weight_decay)
        return new_p, new_m

    opt_fn = compat.shard_map(
        opt_body,
        mesh=mesh,
        in_specs=(specs, mom_spec, specs, _P()),
        out_specs=(specs, mom_spec),
        check_vma=False,
    )

    if fault_plan is not None and not fault_plan:
        fault_plan = None
    want_health = with_health or skip_nonfinite

    def zero_step(params, mom, tokens, targets, step_i=None):
        msq_small = None
        if gns:
            loss, grads, msq_small = grad_fn(params, tokens, targets)
        else:
            loss, grads = grad_fn(params, tokens, targets)
        if fault_plan is not None:
            from .fault import inject_step_faults

            loss, grads = inject_step_faults(step_i, loss, grads, fault_plan)
        dyn = None
        if dynamics:
            # jit level: grads are full replicated arrays (zero forbids
            # tp/ep), so the per-leaf norms need no specs/collectives
            from ..train.dynamics import dynamics_bundle

            dyn = dynamics_bundle(grads, params)
            if gns:
                dyn["msq_small"] = msq_small
        health = None
        if want_health:
            from ..ops.schedule import global_norm, health_bundle

            health = health_bundle(loss, global_norm(grads))
        lr_t = _jnp.float32(lr) if lr_schedule is None else _jnp.float32(
            lr_schedule(step_i)
        )
        new_p, new_m = opt_fn(params, mom, grads, lr_t)
        if want_health and skip_nonfinite:
            from ..ops.schedule import tree_where

            ok = health["all_finite"]
            new_p = tree_where(ok, new_p, params)
            new_m = tree_where(ok, new_m, mom)
        if dynamics:
            from ..ops.schedule import per_leaf_sq_norms

            upd = jax.tree.map(
                lambda n, p: n.astype(_jnp.float32)
                - p.astype(_jnp.float32),
                new_p,
                params,
            )
            dyn["upd_sq"] = per_leaf_sq_norms(upd)
        out = (new_p, new_m, loss)
        if want_health:
            out = out + (health,)
        if dynamics:
            out = out + (dyn,)
        return out

    has_step = lr_schedule is not None or fault_plan is not None
    if has_step:
        return jax.jit(
            lambda p, m, a, b, s: zero_step(p, m, a, b, s),
            donate_argnums=(0, 1),
        )
    return jax.jit(
        lambda p, m, a, b: zero_step(p, m, a, b), donate_argnums=(0, 1)
    )


def zero_sgd_step(
    params,
    mom_shard,
    grads,
    lr,
    momentum,
    *,
    axis_name: str = "data",
    grads_presummed: bool = True,
):
    """One SGD(momentum) step with the momentum buffer sharded over
    `axis_name`. Call inside shard_map.

    params/grads: full (local) pytrees; mom_shard: this device's (pad(D)/N,)
    slice. Both gradient paths use the same convention - the update uses the
    GLOBAL gradient of a globally-normalized loss:
    `grads_presummed=True` means grads are already that global gradient,
    identical across the axis (shard_map's typed autodiff psum), and are
    just sliced; False means grads are per-device partials whose *sum* over
    the axis is the global gradient, reduced with the canonical
    psum_scatter. Returns (new_params, new_mom_shard).
    """
    n = compat.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    flat_p, unravel = ravel_pytree(params)
    flat_g, _ = ravel_pytree(grads)
    d = flat_p.shape[0]
    pad = _padded(d, n) - d
    if pad:
        flat_p = jnp.concatenate([flat_p, jnp.zeros((pad,), flat_p.dtype)])
        flat_g = jnp.concatenate([flat_g, jnp.zeros((pad,), flat_g.dtype)])
    shard = flat_p.shape[0] // n

    if grads_presummed:
        g_sh = jax.lax.dynamic_slice(flat_g, (me * shard,), (shard,))
    else:
        g_sh = jax.lax.psum_scatter(flat_g, axis_name, scatter_dimension=0,
                                    tiled=True)

    mom_new = momentum * mom_shard + g_sh
    p_sh = jax.lax.dynamic_slice(flat_p, (me * shard,), (shard,)) - lr * mom_new
    # reassemble: scatter own shard into zeros and psum - all-gather
    # semantics, but typed *invariant* over the axis (each position is
    # written by exactly one device), which shard_map's vma checker needs
    # for the replicated params output. XLA lowers the one-hot psum to an
    # all-gather-class collective.
    flat_new = jax.lax.psum(
        jax.lax.dynamic_update_slice(
            jnp.zeros_like(flat_p), p_sh, (me * shard,)
        ),
        axis_name,
    )
    return unravel(flat_new[:d]), mom_new
