"""Expert parallelism: mixture-of-experts dispatch/combine over a mesh axis.

The reference has no MoE or expert parallelism anywhere (SURVEY.md section 2:
expert parallelism explicitly absent; its only model is the 62K-param CNN at
`/root/reference/models/model.py:9-27`). This module is the framework's
expert-parallel capability, built TPU-first in the GShard/Switch style:

- **Static shapes everywhere.** Routing uses a fixed per-expert *capacity*;
  tokens that overflow an expert's capacity are dropped (their FFN
  contribution is zero, the residual stream passes them through). No
  data-dependent shapes, so the program never retraces.
- **Two dispatch implementations, one contract.** `dispatch_impl="dense"`
  materializes (T, E, C) one-hot dispatch/combine tensors and runs pure
  einsums - trivially correct, O(T*E*C) memory, the small-shape oracle.
  `dispatch_impl="sort"` (default; r2 VERDICT weak #4) computes each
  routed token's (expert, capacity-slot) coordinate with a one-hot cumsum
  in token order - the same priority order as the dense path, so numerics
  match - then scatter-adds tokens into the (E, C, d) slot tensor and
  gathers results back: O(T*k*E) routing work and O(T*k + E*C*d) memory,
  usable at real token/expert counts (tested at 64k tokens) where the
  dense tensors would be tens of GB.
- **Router z-loss** (ST-MoE): mean squared logsumexp of the router logits,
  weighted into the returned aux, keeps router logits from drifting to
  magnitudes where softmax saturates and bf16 rounds badly.
- **Expert parallelism = one all_to_all each way.** Experts are sharded over
  a mesh axis (conventionally the data axis, as in GShard); each device
  routes its local tokens, materializes per-expert capacity slots
  (E, C, d), and a single `jax.lax.all_to_all` re-shards slot tensors from
  token-major to expert-major: afterwards each device holds E/n experts'
  slots from *every* source device, runs its local expert FFNs as one
  batched einsum, and a second all_to_all sends results home.
- **Load balancing** via the Switch-Transformer auxiliary loss
  (E * sum_i fraction_routed_i * mean_router_prob_i), returned to the caller
  to be weighted into the training loss.

Pure functions designed for use inside `jax.shard_map`; with `ep_axis=None`
they run the identical math on one device (the parity oracle in
tests/test_moe.py).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def expert_capacity(n_tokens: int, n_experts: int, top_k: int, factor: float) -> int:
    """Per-source-device capacity slots per expert (static)."""
    return max(1, math.ceil(factor * top_k * n_tokens / n_experts))


def topk_dispatch(probs, top_k: int, capacity: int):
    """Greedy top-k routing with per-expert capacity.

    probs: (T, E) router probabilities. Returns (combine, dispatch, aux):
    combine (T, E, C) float weights, dispatch (T, E, C) 0/1 slot assignment,
    aux the Switch load-balancing loss. Position within each expert's
    capacity is assigned in token order (cumsum over the one-hot), the
    standard static-shape formulation. For top_k > 1 the k gates of each
    token are renormalized to sum to 1 over the *selected* experts.
    """
    t, e = probs.shape
    combine = jnp.zeros((t, e, capacity), probs.dtype)
    dispatch = jnp.zeros((t, e, capacity), probs.dtype)
    fill = jnp.zeros((e,), jnp.int32)
    masked = probs
    gate_sum = jnp.zeros((t,), probs.dtype)
    chosen = []  # per-round (onehot, gate, ok)
    for _ in range(top_k):
        idx = jnp.argmax(masked, axis=-1)
        onehot = jax.nn.one_hot(idx, e, dtype=probs.dtype)
        pos = jnp.cumsum(onehot, axis=0) - 1.0 + fill[None, :].astype(probs.dtype)
        pos_tok = (pos * onehot).sum(-1)
        ok = (pos_tok < capacity).astype(probs.dtype)
        gate = (probs * onehot).sum(-1)
        chosen.append((onehot, pos_tok, gate, ok))
        gate_sum = gate_sum + gate * ok
        fill = fill + (onehot * ok[:, None]).sum(0).astype(jnp.int32)
        masked = masked - 2.0 * onehot  # exclude chosen expert in later rounds
    denom = jnp.maximum(gate_sum, 1e-9)
    for onehot, pos_tok, gate, ok in chosen:
        slot = onehot[:, :, None] * jax.nn.one_hot(
            pos_tok.astype(jnp.int32), capacity, dtype=probs.dtype
        )[:, None, :] * ok[:, None, None]
        dispatch = dispatch + slot
        combine = combine + (gate / denom)[:, None, None] * slot

    # Switch aux loss from first-choice assignment: E * sum_i f_i * P_i
    first_onehot = chosen[0][0]
    frac = first_onehot.mean(0)
    mean_prob = probs.mean(0)
    aux = jnp.float32(e) * jnp.sum(frac * mean_prob)
    return combine, dispatch, aux


def sort_route(probs, top_k: int, capacity: int):
    """Coordinate-form top-k routing with per-expert capacity.

    probs: (T, E) router probabilities. Returns (expert_idx, slot_idx,
    weight, aux): each (k*T,) flat arrays in round-major order (all first
    choices in token order, then all second choices - the same priority
    the dense oracle uses), where `slot_idx` is the token's position in
    its expert's capacity buffer (== capacity when the token overflowed
    and must be dropped) and `weight` is the kept-gate renormalized
    combine weight (0 for dropped slots). O(T*k*E) work, no (T, E, C)
    tensor. aux is the Switch load-balancing loss.
    """
    t, e = probs.shape
    gates, experts = jax.lax.top_k(probs, top_k)  # (T, k), priority order
    flat_e = experts.T.reshape(-1)  # (k*T,) round-major
    flat_g = gates.T.reshape(-1)
    onehot = jax.nn.one_hot(flat_e, e, dtype=jnp.int32)  # (kT, E)
    # position among same-expert entries, in round-major (= dense) order
    pos = ((jnp.cumsum(onehot, axis=0) - 1) * onehot).sum(-1)  # (kT,)
    keep = pos < capacity
    slot = jnp.where(keep, pos, capacity)
    # renormalize each token's kept gates to sum to 1 (dense-path parity)
    kept_g = jnp.where(keep, flat_g, 0.0).reshape(top_k, t)
    denom = jnp.maximum(kept_g.sum(0), 1e-9)
    weight = (kept_g / denom[None, :]).reshape(-1)

    # Switch aux from first-choice assignment: E * sum_i f_i * P_i
    frac = onehot[:t].mean(0).astype(probs.dtype)
    aux = jnp.float32(e) * jnp.sum(frac * probs.mean(0))
    return flat_e, slot, weight, aux


def moe_ffn(
    x,
    wr,
    w1,
    b1,
    w2,
    b2,
    *,
    top_k: int = 2,
    capacity: int,
    ep_axis: str | None = None,
    tp_axis: str | None = None,
    dispatch_impl: str = "sort",
    z_loss_weight: float = 0.0,
):
    """Mixture-of-experts gelu FFN on a flat token batch.

    x: (T, d) local tokens. wr: (d, E) router (E = global expert count).
    w1 (E_local, d, F_local), b1 (E_local, F_local), w2 (E_local, F_local, d),
    b2 (E_local, d) - the local expert shard (E_local = E/|ep|, F_local =
    F/|tp|). Returns (y, aux) with y (T, d) in x.dtype; aux is the Switch
    load-balancing loss plus z_loss_weight * mean(logsumexp(logits)^2)
    (router z-loss; the caller's aux weight multiplies the whole thing).
    dispatch_impl: "sort" (scatter/gather, scalable) or "dense" (one-hot
    einsum oracle) - identical numerics, different memory scaling.
    """
    dt = x.dtype
    logits = x.astype(jnp.float32) @ wr.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    if dispatch_impl == "dense":
        combine, dispatch, aux = topk_dispatch(probs, top_k, capacity)
        xe = jnp.einsum("tec,td->ecd", dispatch.astype(dt), x)  # (E, C, d)
    elif dispatch_impl == "sort":
        k = top_k
        t, e = probs.shape
        flat_e, slot, weight, aux = sort_route(probs, top_k, capacity)
        x_rep = jnp.tile(x, (k, 1))  # (kT, d) round-major
        xe = jnp.zeros((e, capacity, x.shape[1]), dt)
        # slot == capacity for dropped tokens -> out of bounds -> 'drop';
        # slots are unique per expert, so add == set (combine applies the
        # gate weight, matching the 0/1 dense dispatch tensor)
        xe = xe.at[flat_e, slot].add(x_rep, mode="drop")
    else:
        raise ValueError(
            f"dispatch_impl must be 'sort' or 'dense', got {dispatch_impl!r}"
        )
    if ep_axis is not None:
        # token-major -> expert-major: device p gets slots for its E_local
        # experts from every source; (E, C, d) -> (E_local, n*C, d)
        xe = jax.lax.all_to_all(xe, ep_axis, split_axis=0, concat_axis=1, tiled=True)
    h = jnp.einsum("ecd,edf->ecf", xe, w1.astype(dt)) + b1.astype(dt)[:, None]
    h = jax.nn.gelu(h)
    y = jnp.einsum("ecf,efd->ecd", h, w2.astype(dt))
    if tp_axis is not None:
        y = jax.lax.psum(y, tp_axis)
    y = y + b2.astype(dt)[:, None]
    if ep_axis is not None:
        y = jax.lax.all_to_all(y, ep_axis, split_axis=1, concat_axis=0, tiled=True)
    if dispatch_impl == "dense":
        out = jnp.einsum("tec,ecd->td", combine.astype(dt), y)
    else:
        # dropped slots (slot == capacity) are out of bounds -> fill 0
        gathered = y.at[flat_e, slot].get(mode="fill", fill_value=0)
        out = (gathered * weight.astype(dt)[:, None]).reshape(
            top_k, t, x.shape[1]
        ).sum(0)
    if z_loss_weight:
        z = jax.scipy.special.logsumexp(logits, axis=-1)
        aux = aux + jnp.float32(z_loss_weight) * jnp.mean(z * z)
    return out, aux
