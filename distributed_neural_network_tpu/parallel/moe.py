"""Expert parallelism: mixture-of-experts dispatch/combine over a mesh axis.

The reference has no MoE or expert parallelism anywhere (SURVEY.md section 2:
expert parallelism explicitly absent; its only model is the 62K-param CNN at
`/root/reference/models/model.py:9-27`). This module is the framework's
expert-parallel capability, built TPU-first in the GShard/Switch style:

- **Static shapes everywhere.** Routing is expressed as dense one-hot
  dispatch/combine tensors with a fixed per-expert *capacity*; tokens that
  overflow an expert's capacity are dropped (their FFN contribution is zero,
  the residual stream passes them through). No gather/scatter with
  data-dependent shapes - everything is einsum, so XLA tiles it onto the MXU
  and the program never retraces.
- **Expert parallelism = one all_to_all each way.** Experts are sharded over
  a mesh axis (conventionally the data axis, as in GShard); each device
  routes its local tokens, materializes per-expert capacity slots
  (E, C, d), and a single `jax.lax.all_to_all` re-shards slot tensors from
  token-major to expert-major: afterwards each device holds E/n experts'
  slots from *every* source device, runs its local expert FFNs as one
  batched einsum, and a second all_to_all sends results home.
- **Load balancing** via the Switch-Transformer auxiliary loss
  (E * sum_i fraction_routed_i * mean_router_prob_i), returned to the caller
  to be weighted into the training loss.

Pure functions designed for use inside `jax.shard_map`; with `ep_axis=None`
they run the identical math on one device (the parity oracle in
tests/test_moe.py).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def expert_capacity(n_tokens: int, n_experts: int, top_k: int, factor: float) -> int:
    """Per-source-device capacity slots per expert (static)."""
    return max(1, math.ceil(factor * top_k * n_tokens / n_experts))


def topk_dispatch(probs, top_k: int, capacity: int):
    """Greedy top-k routing with per-expert capacity.

    probs: (T, E) router probabilities. Returns (combine, dispatch, aux):
    combine (T, E, C) float weights, dispatch (T, E, C) 0/1 slot assignment,
    aux the Switch load-balancing loss. Position within each expert's
    capacity is assigned in token order (cumsum over the one-hot), the
    standard static-shape formulation. For top_k > 1 the k gates of each
    token are renormalized to sum to 1 over the *selected* experts.
    """
    t, e = probs.shape
    combine = jnp.zeros((t, e, capacity), probs.dtype)
    dispatch = jnp.zeros((t, e, capacity), probs.dtype)
    fill = jnp.zeros((e,), jnp.int32)
    masked = probs
    gate_sum = jnp.zeros((t,), probs.dtype)
    chosen = []  # per-round (onehot, gate, ok)
    for _ in range(top_k):
        idx = jnp.argmax(masked, axis=-1)
        onehot = jax.nn.one_hot(idx, e, dtype=probs.dtype)
        pos = jnp.cumsum(onehot, axis=0) - 1.0 + fill[None, :].astype(probs.dtype)
        pos_tok = (pos * onehot).sum(-1)
        ok = (pos_tok < capacity).astype(probs.dtype)
        gate = (probs * onehot).sum(-1)
        chosen.append((onehot, pos_tok, gate, ok))
        gate_sum = gate_sum + gate * ok
        fill = fill + (onehot * ok[:, None]).sum(0).astype(jnp.int32)
        masked = masked - 2.0 * onehot  # exclude chosen expert in later rounds
    denom = jnp.maximum(gate_sum, 1e-9)
    for onehot, pos_tok, gate, ok in chosen:
        slot = onehot[:, :, None] * jax.nn.one_hot(
            pos_tok.astype(jnp.int32), capacity, dtype=probs.dtype
        )[:, None, :] * ok[:, None, None]
        dispatch = dispatch + slot
        combine = combine + (gate / denom)[:, None, None] * slot

    # Switch aux loss from first-choice assignment: E * sum_i f_i * P_i
    first_onehot = chosen[0][0]
    frac = first_onehot.mean(0)
    mean_prob = probs.mean(0)
    aux = jnp.float32(e) * jnp.sum(frac * mean_prob)
    return combine, dispatch, aux


def moe_ffn(
    x,
    wr,
    w1,
    b1,
    w2,
    b2,
    *,
    top_k: int = 2,
    capacity: int,
    ep_axis: str | None = None,
    tp_axis: str | None = None,
):
    """Mixture-of-experts gelu FFN on a flat token batch.

    x: (T, d) local tokens. wr: (d, E) router (E = global expert count).
    w1 (E_local, d, F_local), b1 (E_local, F_local), w2 (E_local, F_local, d),
    b2 (E_local, d) - the local expert shard (E_local = E/|ep|, F_local =
    F/|tp|). Returns (y, aux) with y (T, d) in x.dtype.
    """
    dt = x.dtype
    probs = jax.nn.softmax(x.astype(jnp.float32) @ wr.astype(jnp.float32), axis=-1)
    combine, dispatch, aux = topk_dispatch(probs, top_k, capacity)
    xe = jnp.einsum("tec,td->ecd", dispatch.astype(dt), x)  # (E, C, d)
    if ep_axis is not None:
        # token-major -> expert-major: device p gets slots for its E_local
        # experts from every source; (E, C, d) -> (E_local, n*C, d)
        xe = jax.lax.all_to_all(xe, ep_axis, split_axis=0, concat_axis=1, tiled=True)
    h = jnp.einsum("ecd,edf->ecf", xe, w1.astype(dt)) + b1.astype(dt)[:, None]
    h = jax.nn.gelu(h)
    y = jnp.einsum("ecf,efd->ecd", h, w2.astype(dt))
    if tp_axis is not None:
        y = jax.lax.psum(y, tp_axis)
    y = y + b2.astype(dt)[:, None]
    if ep_axis is not None:
        y = jax.lax.all_to_all(y, ep_axis, split_axis=1, concat_axis=0, tiled=True)
    out = jnp.einsum("tec,ecd->td", combine.astype(dt), y)
    return out, aux
