"""Multi-host bootstrap and hybrid ICI x DCN meshes.

The reference's multi-process story is `mpiexec -n N` on ONE machine
(`README.md:28`; SURVEY.md L0) - world discovery via MPI.COMM_WORLD and all
traffic through rank 0's pickle sends. The TPU-native equivalents:

- **Process bootstrap**: `initialize()` wraps `jax.distributed.initialize`,
  the JAX runtime's coordinator handshake that makes every host see the
  global device set (the `mpiexec` replacement). On single-host runs - and
  on TPU environments where the runtime auto-detects cluster config - it is
  a safe no-op. After it, the same SPMD program runs on every host; there
  is no rank-0 data plane.
- **Mesh topology**: within one TPU slice, devices talk over ICI;
  across slices (multislice) they talk over DCN, which is orders of
  magnitude thinner. `create_hybrid_mesh` builds a mesh whose *outer* axes
  map to DCN (put your lowest-frequency collective there - e.g. the
  once-per-epoch parameter pmean of this framework's regimes, or plain
  data parallelism) and whose *inner* axes stay inside a slice's ICI
  (tensor/sequence/pipeline axes, per-step collectives) - the standard
  multislice recipe, built directly from the devices' slice_index so the
  slice->dcn-position mapping is explicit and unit-testable.
- **Data feeding**: with multiple processes, each host holds only its local
  shard of a batch; `distribute_host_data` wraps
  `jax.make_array_from_process_local_data` to assemble the global sharded
  array the compiled step expects.

Everything degrades gracefully to single-process: the CI/test environment
exercises the single-slice paths on the 8-device CPU mesh, and the
multislice branch is validated by the mesh-shape/axis-order contract (real
DCN requires actual multi-host hardware).
"""

from __future__ import annotations

import inspect
import os
import time
from collections.abc import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# coordinator-handshake bounding (overridable per call or via env): without
# these, an unreachable coordinator hangs `jax.distributed.initialize`
# forever and a preempted/rescheduled pod never surfaces an error
DEFAULT_COORDINATOR_RETRIES = 5
DEFAULT_COORDINATOR_DEADLINE_S = 300.0
DEFAULT_COORDINATOR_BACKOFF_S = 1.0
_BACKOFF_CAP_S = 30.0


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    *,
    max_retries: int | None = None,
    deadline_s: float | None = None,
    backoff_s: float | None = None,
    log=print,
    _connect=None,
    _sleep=time.sleep,
    _clock=time.monotonic,
) -> bool:
    """Join the multi-host JAX runtime; returns True if it initialized.

    Safe to call unconditionally at program start (the CLI entry points
    do): explicit args > standard env vars (JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID) > single-process no-op. Idempotent.

    Must run before anything touches a JAX backend (jax.devices(),
    jax.process_count(), any computation): the runtime refuses to go
    multi-host once the single-process backend exists - which is also why
    this function decides the no-op case from the env alone instead of
    asking JAX.

    The coordinator handshake is BOUNDED: up to `max_retries` + 1
    connection attempts under exponential backoff (`backoff_s` doubling,
    capped at 30s) and a wall-clock `deadline_s` - an unreachable
    coordinator no longer hangs the process forever. Defaults come from
    DNN_TPU_COORDINATOR_RETRIES / DNN_TPU_COORDINATOR_DEADLINE_S /
    DNN_TPU_COORDINATOR_BACKOFF_S (falling back to 5 / 300s / 1s). On
    exhaustion a RuntimeError names the address, the attempts made, and
    the env vars to check. `_connect`/`_sleep`/`_clock` are test seams.
    """
    already = _already_initialized()
    if already is not None:
        return already
    addr = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    num = num_processes if num_processes is not None else _env_int("JAX_NUM_PROCESSES")
    pid = process_id if process_id is not None else _env_int("JAX_PROCESS_ID")
    if addr is None:
        # partial config must fail loudly, not silently degrade to N
        # independent runs - from either direction
        if num is not None and num > 1:
            raise ValueError(
                f"JAX_NUM_PROCESSES={num} is set but "
                "JAX_COORDINATOR_ADDRESS is not; set it to host0:port"
            )
        return False
    # a coordinator address means the operator intends multi-host
    if num is None:
        raise ValueError(
            "JAX_COORDINATOR_ADDRESS is set but JAX_NUM_PROCESSES is not; "
            "set it to the total host count"
        )
    if num <= 0:
        raise ValueError(f"JAX_NUM_PROCESSES must be positive, got {num}")
    if num == 1:
        return False
    if pid is None:
        raise ValueError(
            "JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES are set but "
            "JAX_PROCESS_ID is not; set it to this host's rank in "
            "[0, num_processes) (auto-detection only works on cloud "
            "TPU/Slurm/OpenMPI environments)"
        )
    _enable_cpu_collectives()
    _connect_with_retry(
        _connect if _connect is not None else jax.distributed.initialize,
        dict(coordinator_address=addr, num_processes=num, process_id=pid),
        addr=addr,
        max_retries=(
            max_retries if max_retries is not None
            else _env_int("DNN_TPU_COORDINATOR_RETRIES")
            if _env_int("DNN_TPU_COORDINATOR_RETRIES") is not None
            else DEFAULT_COORDINATOR_RETRIES
        ),
        deadline_s=(
            deadline_s if deadline_s is not None
            else _env_float(
                "DNN_TPU_COORDINATOR_DEADLINE_S",
                DEFAULT_COORDINATOR_DEADLINE_S,
            )
        ),
        backoff_s=(
            backoff_s if backoff_s is not None
            else _env_float(
                "DNN_TPU_COORDINATOR_BACKOFF_S",
                DEFAULT_COORDINATOR_BACKOFF_S,
            )
        ),
        log=log, sleep=_sleep, clock=_clock,
    )
    return True


def _connect_with_retry(
    connect, kwargs, *, addr, max_retries, deadline_s, backoff_s, log,
    sleep, clock,
):
    """Bounded-retry wrapper around the coordinator handshake.

    Each attempt gets the REMAINING deadline as its per-attempt
    `initialization_timeout` when the jax build supports the parameter
    (so one wedged TCP connect cannot eat the whole budget); failures
    back off exponentially. Raises an actionable RuntimeError on
    exhaustion - address, attempt count, elapsed time, and the env vars
    to check are all in the message.
    """
    try:
        takes_timeout = (
            "initialization_timeout" in inspect.signature(connect).parameters
        )
    except (TypeError, ValueError):
        takes_timeout = False
    start = clock()
    attempt = 0
    last = None
    while True:
        attempt += 1
        remaining = deadline_s - (clock() - start)
        if remaining <= 0:
            break
        call = dict(kwargs)
        if takes_timeout:
            call["initialization_timeout"] = max(int(remaining), 1)
        try:
            connect(**call)
            return attempt
        except Exception as e:  # noqa: BLE001 - retrying IS the handling
            last = e
            if attempt > max_retries:
                break
            remaining = deadline_s - (clock() - start)
            if remaining <= 0:
                break
            pause = min(
                backoff_s * (2 ** (attempt - 1)), _BACKOFF_CAP_S, remaining
            )
            log(
                f"(coordinator handshake attempt {attempt}/"
                f"{max_retries + 1} failed: {type(e).__name__}: {e}; "
                f"retrying in {pause:.1f}s)"
            )
            sleep(pause)
    raise RuntimeError(
        f"could not reach the JAX coordinator at {addr} after {attempt} "
        f"attempt(s) over {clock() - start:.1f}s (deadline {deadline_s:g}s, "
        f"retry budget {max_retries}). Check that the coordinator process "
        "is up and that JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / "
        "JAX_PROCESS_ID match on every host; raise "
        "DNN_TPU_COORDINATOR_DEADLINE_S or DNN_TPU_COORDINATOR_RETRIES for "
        f"slow cluster starts. Last error: {type(last).__name__ if last is not None else None}: {last}"
    ) from last


def _enable_cpu_collectives() -> None:
    """Select a cross-process collectives backend for CPU meshes.

    On the jax generations this repo pins, the CPU backend ships with NO
    multi-process collective implementation selected - a 2-process CPU
    mesh then fails at the first psum with "Multiprocess computations
    aren't implemented on the CPU backend". 'gloo' is the bundled
    implementation; newer jax selects it automatically (and eventually
    drops the config knob), so failures to set it are ignored. Only
    applied when the operator pinned JAX_PLATFORMS=cpu - real TPU/GPU
    runs keep their native ICI/NCCL collectives.
    """
    if os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip() != "cpu":
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass


def _already_initialized() -> bool | None:
    """True if the distributed client exists, None if undetermined."""
    try:
        from jax._src import distributed as _jd

        return True if _jd.global_state.client is not None else None
    except (ImportError, AttributeError):
        return None


def _env_int(name: str) -> int | None:
    v = os.environ.get(name)
    return int(v) if v else None


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    return float(v) if v else default


def create_hybrid_mesh(
    ici_axes: dict[str, int],
    dcn_axes: dict[str, int] | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Mesh with DCN-parallel axes outermost and ICI axes inner.

    ici_axes/dcn_axes: ordered {axis_name: size}. The resulting mesh's axis
    order is (*dcn, *ici), so per-step collectives (tp/sp/pp - put them in
    ici_axes) ride intra-slice ICI while low-frequency collectives (the
    epoch-edge parameter averaging of the dp regimes) cross DCN. With one
    slice (or on CPU), the same axis names/sizes are laid out over the flat
    device list, so calling code is portable between single- and
    multi-slice environments.
    """
    dcn_axes = dcn_axes or {}
    names = (*dcn_axes, *ici_axes)
    sizes = (*dcn_axes.values(), *ici_axes.values())
    if any(s <= 0 for s in sizes):
        raise ValueError(f"axis sizes must be positive: {dict(zip(names, sizes))}")
    devs = list(devices) if devices is not None else jax.devices()
    total = int(np.prod(sizes))
    if total > len(devs):
        raise ValueError(
            f"mesh {dict(zip(names, sizes))} needs {total} devices, "
            f"have {len(devs)}"
        )
    arr = _hybrid_device_array(
        devs, tuple(dcn_axes.values()), tuple(ici_axes.values())
    )
    return Mesh(arr, names)


def _hybrid_device_array(devices, dcn_sizes: tuple, ici_sizes: tuple) -> np.ndarray:
    """(*dcn, *ici)-shaped device array with slice boundaries on dcn axes.

    Multislice: devices are grouped by `slice_index`; the dcn axes must
    exactly cover the slice count, and each slice contributes its first
    ici-total devices - so every dcn-axis hop crosses DCN and every
    ici-axis hop stays inside a slice. Device selection happens per-slice
    (never by truncating the flat list, which would pull an uneven mix of
    slices); using a *subset* of slices requires an explicit `devices=`.
    Single slice (or CPU): the flat device order is used. Pure numpy over
    device objects - unit-testable with stubs.
    """
    dcn_total = int(np.prod(dcn_sizes)) if dcn_sizes else 1
    ici_total = int(np.prod(ici_sizes)) if ici_sizes else 1
    shape = (*dcn_sizes, *ici_sizes)
    groups: dict[int, list] = {}
    for d in devices:
        groups.setdefault(getattr(d, "slice_index", 0), []).append(d)
    if len(groups) <= 1:
        return np.asarray(devices[: dcn_total * ici_total]).reshape(shape)
    if len(groups) != dcn_total:
        raise ValueError(
            f"dcn axes {dcn_sizes} multiply to {dcn_total} but "
            f"{len(groups)} slices are present (slice count mismatch): the "
            "dcn axes must exactly cover the slices, or pass an explicit "
            "`devices=` subset to deliberately leave slices idle"
        )
    ordered = []
    for si in sorted(groups):
        g = groups[si]
        if len(g) < ici_total:
            raise ValueError(
                f"slice {si} has {len(g)} devices, ici axes {ici_sizes} "
                f"need {ici_total} (uneven slices cannot form this mesh)"
            )
        ordered.append(np.asarray(g[:ici_total]).reshape(ici_sizes))
    return np.stack(ordered).reshape(shape)


def distribute_host_data(host_array, mesh: Mesh, spec: P, *, full_copy: bool = True):
    """Place host data onto a (possibly multi-host) mesh sharding.

    Single-process: plain device_put. Multi-process with
    `full_copy=True` (the engine's mode - every host loaded the whole
    split): each host uploads only the pieces addressable to it, sliced
    from its full copy via `jax.make_array_from_callback`. With
    `full_copy=False`, `host_array` is this process's local rows only and
    the global array is stitched with
    `jax.make_array_from_process_local_data` - no host ever materializes
    the full batch (the >HBM streaming mode).
    """
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(host_array, sharding)
    if full_copy:
        host_array = np.asarray(host_array)
        return jax.make_array_from_callback(
            host_array.shape, sharding, lambda idx: host_array[idx]
        )
    return jax.make_array_from_process_local_data(sharding, host_array)
