"""Subpackage: parallel."""
