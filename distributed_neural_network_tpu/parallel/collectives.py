"""Mesh collectives: parameter synchronization and fault-masked averaging.

This module is the TPU-native replacement for the reference's entire L1+L2
communication/aggregation stack (SURVEY.md section 1): the parent's
send/recv/average loop (`data_parallelism_train.py:118,226-244`) collapses
into a single compiled weighted-psum over the mesh's data axis, executed on
ICI. No pickling, no star topology, no idle parent.

Fault-masked averaging implements SURVEY.md section 5.3's upgrade of the
reference straggler simulation: a per-epoch live mask drops dead devices'
contributions - `avg = sum(live_d * params_d) / sum(live_d)` - instead of
blocking the epoch on them. The degenerate all-dead epoch degrades to a
plain mean over all devices (no division by zero, no NaN poisoning).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .mesh import DATA_AXIS


def vary_like(x, *refs, extra=()):
    """Align `x`'s varying-axes (vma) type with the union of `refs`' vma
    plus the literal axis names in `extra`; no-op on jax versions without
    vma typing (pre-0.7 shard_map had no vma attribute on avals).

    This is THE vma shim for the whole framework - ring/zigzag attention
    and the pipeline scan all initialize loop carries from constants
    (vma-invariant) that must be promoted to device-varying before entering
    a fori_loop/scan whose body produces varying values, or shard_map's
    type checker rejects the carry. Centralized here so a jax API change
    (vma typing is version-sensitive) is a one-line fix, not a hunt
    (VERDICT r3 weak #7).
    """
    try:
        want = set(extra)
        for r in refs:
            want |= set(jax.typeof(r).vma)
        missing = tuple(a for a in want if a not in jax.typeof(x).vma)
    except AttributeError:  # vma-less jax version
        return x
    return jax.lax.pcast(x, missing, to="varying") if missing else x


def vma_union(*xs):
    """Union of the inputs' varying-axes sets, or None when vma typing is
    unavailable (outside shard_map, or a vma-less jax version). Callers that
    stamp output types (e.g. pallas_call out_shapes) skip the vma kwarg on
    None."""
    try:
        return frozenset().union(*(jax.typeof(x).vma for x in xs))
    except (AttributeError, TypeError):
        return None


def pvary_tree(tree, axis_name: str = DATA_AXIS):
    """Mark every leaf as device-varying along `axis_name` (no-op if already).

    Needed because shard_map's autodiff inserts an implicit psum for
    gradients w.r.t. *unvarying* (replicated) inputs - correct for sharded
    per-step DP, but silently wrong for this framework's faithful local-SGD
    regimes, where each device's epoch must be independent and parameters are
    synchronized only at the epoch edge. Leaves that are already varying
    (sharded feeds) pass through unchanged.
    """

    def vary(x):
        try:
            return jax.lax.pcast(x, axis_name, to="varying")
        except ValueError:  # already varying along axis_name
            return x

    return jax.tree.map(vary, tree)


def pmean_tree(tree, axis_name: str = DATA_AXIS):
    """Plain parameter averaging over the mesh axis.

    Exact analog of the parent's element-wise state-dict mean
    (`data_parallelism_train.py:238-240`), as one fused XLA collective.
    """
    return jax.tree.map(lambda x: jax.lax.pmean(x, axis_name), tree)


def masked_pmean_tree(tree, live: jax.Array, axis_name: str = DATA_AXIS):
    """Average over live devices only.

    `live` is this device's own {0,1} scalar weight (each device passes its
    entry of the epoch live-mask). Dead devices' parameters are overwritten
    with the survivors' average - they "rejoin" at the next epoch, the
    drop-and-continue semantics of SURVEY.md section 5.3. The degenerate
    all-dead epoch degrades to a plain mean over all devices (rather than
    keeping per-device values, which would leave parameters unreplicated).
    """
    w = live.astype(jnp.float32)
    n_live = jax.lax.psum(w, axis_name)
    w = jnp.where(n_live > 0, w, 1.0)
    denom = jax.lax.psum(w, axis_name)

    def avg(x):
        return jax.lax.psum(x * w.astype(x.dtype), axis_name) / denom.astype(x.dtype)

    return jax.tree.map(avg, tree)


def weighted_mean_scalar(
    value: jax.Array, weight: jax.Array, axis_name: str = DATA_AXIS
):
    """sum(value)/sum(weight) across the mesh - correctly-scaled loss mean.

    Replaces the reference's mis-scaled "Global Average Training Loss"
    (`data_parallelism_train.py:233,248` divides by 10*(N-1) state-dict keys,
    not batch count - documented fix per SURVEY.md section 2 quirks).
    """
    num = jax.lax.psum(value, axis_name)
    den = jax.lax.psum(weight, axis_name)
    return num / jnp.maximum(den, 1.0)
