"""Mesh collectives: parameter synchronization and fault-masked averaging.

This module is the TPU-native replacement for the reference's entire L1+L2
communication/aggregation stack (SURVEY.md section 1): the parent's
send/recv/average loop (`data_parallelism_train.py:118,226-244`) collapses
into a single compiled weighted-psum over the mesh's data axis, executed on
ICI. No pickling, no star topology, no idle parent.

Fault-masked averaging implements SURVEY.md section 5.3's upgrade of the
reference straggler simulation: a per-epoch live mask drops dead devices'
contributions - `avg = sum(live_d * params_d) / sum(live_d)` - instead of
blocking the epoch on them. The degenerate all-dead epoch degrades to a
plain mean over all devices (no division by zero, no NaN poisoning).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from .mesh import DATA_AXIS

# default gradient-bucket payload cap (bytes): large enough that a bucket's
# collective amortizes launch latency, small enough that XLA's latency-hiding
# scheduler can start bucket j's collective while later buckets' backward
# compute is still running (the Xu et al. / pjit-overlap discipline)
DEFAULT_BUCKET_BYTES = 4 * 2**20


def vary_like(x, *refs, extra=()):
    """Align `x`'s varying-axes (vma) type with the union of `refs`' vma
    plus the literal axis names in `extra`; no-op on jax versions without
    vma typing (pre-0.7 shard_map had no vma attribute on avals).

    This is THE vma shim for the whole framework - ring/zigzag attention
    and the pipeline scan all initialize loop carries from constants
    (vma-invariant) that must be promoted to device-varying before entering
    a fori_loop/scan whose body produces varying values, or shard_map's
    type checker rejects the carry. Centralized here so a jax API change
    (vma typing is version-sensitive) is a one-line fix, not a hunt
    (VERDICT r3 weak #7).
    """
    try:
        want = set(extra)
        for r in refs:
            want |= set(jax.typeof(r).vma)
        missing = tuple(a for a in want if a not in jax.typeof(x).vma)
    except AttributeError:  # vma-less jax version
        return x
    return jax.lax.pcast(x, missing, to="varying") if missing else x


def vma_union(*xs):
    """Union of the inputs' varying-axes sets, or None when vma typing is
    unavailable (outside shard_map, or a vma-less jax version). Callers that
    stamp output types (e.g. pallas_call out_shapes) skip the vma kwarg on
    None."""
    try:
        return frozenset().union(*(jax.typeof(x).vma for x in xs))
    except (AttributeError, TypeError):
        return None


def pvary_tree(tree, axis_name: str = DATA_AXIS):
    """Mark every leaf as device-varying along `axis_name` (no-op if already).

    Needed because shard_map's autodiff inserts an implicit psum for
    gradients w.r.t. *unvarying* (replicated) inputs - correct for sharded
    per-step DP, but silently wrong for this framework's faithful local-SGD
    regimes, where each device's epoch must be independent and parameters are
    synchronized only at the epoch edge. Leaves that are already varying
    (sharded feeds) pass through unchanged.
    """

    def vary(x):
        try:
            return jax.lax.pcast(x, axis_name, to="varying")
        except ValueError:  # already varying along axis_name
            return x
        except AttributeError:  # vma-less jax version: typing is vacuous
            return x

    return jax.tree.map(vary, tree)


def pmean_tree(tree, axis_name: str = DATA_AXIS):
    """Plain parameter averaging over the mesh axis.

    Exact analog of the parent's element-wise state-dict mean
    (`data_parallelism_train.py:238-240`), as one fused XLA collective.
    """
    return jax.tree.map(lambda x: jax.lax.pmean(x, axis_name), tree)


def masked_pmean_tree(tree, live: jax.Array, axis_name: str = DATA_AXIS):
    """Average over live devices only.

    `live` is this device's own {0,1} scalar weight (each device passes its
    entry of the epoch live-mask). Dead devices' parameters are overwritten
    with the survivors' average - they "rejoin" at the next epoch, the
    drop-and-continue semantics of SURVEY.md section 5.3. The degenerate
    all-dead epoch degrades to a plain mean over all devices (rather than
    keeping per-device values, which would leave parameters unreplicated).
    """
    w = live.astype(jnp.float32)
    n_live = jax.lax.psum(w, axis_name)
    w = jnp.where(n_live > 0, w, 1.0)
    denom = jax.lax.psum(w, axis_name)

    def avg(x):
        return jax.lax.psum(x * w.astype(x.dtype), axis_name) / denom.astype(x.dtype)

    return jax.tree.map(avg, tree)


# --------------------------------------------------------- leaf bucketing
#
# The overlapped gradient-sync schedule (ops/schedule.py
# accumulate_fwd_bwd_overlap; train/lm.py grad_sync="overlap") issues one
# collective per LEAF GROUP per microbatch instead of relying on one bulk
# tree-wide sync after the accumulation scan. The grouping lives here as a
# deterministic layout object so that the reduce-scatter issued inside the
# scan and the all-gather that reassembles full gradients after it agree
# bit-for-bit on where every leaf's elements sit.


@dataclasses.dataclass(frozen=True)
class BucketLayout:
    """Deterministic size-capped contiguous grouping of a pytree's leaves.

    Leaves keep their flatten order; a bucket is a contiguous [start, end)
    run of leaf indices whose raveled concatenation forms one flat buffer.
    Buckets never mix dtypes or caller-supplied group keys (e.g. leaves
    with different PartitionSpecs, whose collectives need different mesh
    axes or vma types), and close when the payload cap is reached - a
    single leaf larger than the cap gets its own bucket. The layout is a
    pure function of (tree structure, leaf shapes/dtypes, cap, keys), so
    every device plans the identical layout and the packed element order
    is shared by psum, reduce-scatter, and all-gather.
    """

    treedef: object
    shapes: tuple
    dtypes: tuple
    buckets: tuple  # ((start, end), ...) leaf-index ranges

    @property
    def n_buckets(self) -> int:
        return len(self.buckets)

    def leaf_sizes(self) -> tuple:
        import numpy as np

        return tuple(int(np.prod(s, dtype=np.int64)) for s in self.shapes)

    def bucket_elems(self) -> tuple:
        sizes = self.leaf_sizes()
        return tuple(
            sum(sizes[i] for i in range(lo, hi)) for lo, hi in self.buckets
        )

    def bucket_bytes(self) -> tuple:
        sizes = self.leaf_sizes()
        return tuple(
            sum(
                sizes[i] * jnp.dtype(self.dtypes[i]).itemsize
                for i in range(lo, hi)
            )
            for lo, hi in self.buckets
        )

    def shard_sizes(self, n_shards: int) -> tuple:
        """Per-device shard length of each bucket, ceil-padded to n."""
        return tuple(
            -(-e // n_shards) for e in self.bucket_elems()
        )


def plan_buckets(tree, *, bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                 group_keys=None) -> BucketLayout:
    """Plan the contiguous leaf buckets for `tree` (abstract or concrete).

    `group_keys`: optional leaf-aligned sequence (or pytree) of hashables;
    a bucket never spans a key change - pass e.g. str(PartitionSpec) per
    leaf so tensor/pipe-sharded leaves (whose gradients carry different
    vma types and sync axes) never share a buffer with replicated ones.
    Only shapes/dtypes are read, so tracers work - the layout can be
    planned inside jit from the parameter tree itself.
    """
    if bucket_bytes < 1:
        raise ValueError(f"bucket_bytes must be >= 1, got {bucket_bytes}")
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if group_keys is None:
        keys = [None] * len(leaves)
    else:
        keys = (
            treedef.flatten_up_to(group_keys)
            if not isinstance(group_keys, (list, tuple))
            else list(group_keys)
        )
        if len(keys) != len(leaves):
            raise ValueError(
                f"group_keys has {len(keys)} entries for {len(leaves)} leaves"
            )
    shapes = tuple(tuple(p.shape) for p in leaves)
    dtypes = tuple(jnp.dtype(p.dtype).name for p in leaves)
    buckets = []
    start, acc = 0, 0
    for i, p in enumerate(leaves):
        nbytes = int(p.size) * jnp.dtype(p.dtype).itemsize
        if i > start and (
            dtypes[i] != dtypes[start]
            or keys[i] != keys[start]
            or acc + nbytes > bucket_bytes
        ):
            buckets.append((start, i))
            start, acc = i, 0
        acc += nbytes
    if len(leaves):
        buckets.append((start, len(leaves)))
    return BucketLayout(
        treedef=treedef, shapes=shapes, dtypes=dtypes,
        buckets=tuple(buckets),
    )


def pack_buckets(layout: BucketLayout, tree) -> list:
    """Pack `tree`'s leaves into one flat 1-D buffer per bucket."""
    leaves = layout.treedef.flatten_up_to(tree)
    out = []
    for lo, hi in layout.buckets:
        parts = [leaves[i].reshape(-1) for i in range(lo, hi)]
        out.append(parts[0] if len(parts) == 1 else jnp.concatenate(parts))
    return out


def unpack_buckets(layout: BucketLayout, bufs) -> object:
    """Inverse of `pack_buckets`; buffers longer than the bucket's element
    count (ceil-padded reduce-scatter/all-gather round trips) are
    truncated, so the same layout serves padded and unpadded paths."""
    if len(bufs) != layout.n_buckets:
        raise ValueError(
            f"got {len(bufs)} buffers for {layout.n_buckets} buckets"
        )
    sizes = layout.leaf_sizes()
    leaves = [None] * len(layout.shapes)
    for (lo, hi), buf in zip(layout.buckets, bufs):
        off = 0
        for i in range(lo, hi):
            leaves[i] = buf[off:off + sizes[i]].reshape(layout.shapes[i])
            off += sizes[i]
    return jax.tree_util.tree_unflatten(layout.treedef, leaves)


def bucketed_psum(tree, layout: BucketLayout, axes, *, mean: bool = False):
    """psum (or pmean) of a pytree issued as one collective per bucket.

    Call inside shard_map. Equivalent elementwise to a per-leaf psum; the
    bucketed form gives XLA's latency-hiding scheduler independent
    collectives it can overlap with compute between buckets.
    """
    op = jax.lax.pmean if mean else jax.lax.psum
    return unpack_buckets(
        layout, [op(b, axes) for b in pack_buckets(layout, tree)]
    )


def reduce_scatter_buckets(tree, layout: BucketLayout, axis_name: str, *,
                           axis_size: int, extra_axes=()):
    """Reduce-scatter each bucket over `axis_name`: returns one (S_b,)
    shard per bucket (bucket ceil-padded to axis_size * S_b; layout order).

    `extra_axes` are additionally psummed on the shard (e.g. the seq axis
    when ZeRO shards over data but gradients also reduce over seq) - the
    full reduction at 1/N of the buffer footprint. Call inside shard_map;
    `axis_size` is the static mesh-axis size (passed in so the helper
    needs no version-sensitive axis introspection).
    """
    out = []
    for buf in pack_buckets(layout, tree):
        s = -(-buf.shape[0] // axis_size)
        pad = s * axis_size - buf.shape[0]
        if pad:
            buf = jnp.concatenate([buf, jnp.zeros((pad,), buf.dtype)])
        sh = jax.lax.psum_scatter(
            buf, axis_name, scatter_dimension=0, tiled=True
        )
        if extra_axes:
            sh = jax.lax.psum(sh, tuple(extra_axes))
        out.append(sh)
    return tuple(out)


def all_gather_buckets(shards, layout: BucketLayout, axis_name: str, *,
                       axis_size: int):
    """Reassemble `reduce_scatter_buckets` shards into the full tree.

    Implemented as the one-hot psum (each device scatters its shard into
    zeros and the psum fills every position exactly once): all-gather
    semantics whose output is *invariant*-typed over `axis_name`, so the
    result passes shard_map's vma checker as a replicated gradient - XLA
    lowers it to an all-gather-class collective (same trick as
    parallel/zero.py zero_sgd_step's reassembly).
    """
    me = jax.lax.axis_index(axis_name)
    bufs = []
    for sh in shards:
        s = sh.shape[0]
        full = jax.lax.psum(
            jax.lax.dynamic_update_slice(
                jnp.zeros((s * axis_size,), sh.dtype), sh, (me * s,)
            ),
            axis_name,
        )
        bufs.append(full)
    return unpack_buckets(layout, bufs)


def weighted_mean_scalar(
    value: jax.Array, weight: jax.Array, axis_name: str = DATA_AXIS
):
    """sum(value)/sum(weight) across the mesh - correctly-scaled loss mean.

    Replaces the reference's mis-scaled "Global Average Training Loss"
    (`data_parallelism_train.py:233,248` divides by 10*(N-1) state-dict keys,
    not batch count - documented fix per SURVEY.md section 2 quirks).
    """
    num = jax.lax.psum(value, axis_name)
    den = jax.lax.psum(weight, axis_name)
    return num / jnp.maximum(den, 1.0)
