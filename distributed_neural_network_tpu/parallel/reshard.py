"""Mesh-portable checkpoint resharding: load any saved layout onto any mesh.

The reference's only fault story is a simulated dead worker
(`data_parallelism_train.py:41-46`); this repo already survives bad steps
(train/guard.py) and sees trouble live (train/monitor.py), but a checkpoint
saved under one mesh shape could previously only be restored into the
identical shape - a preempted or shrunk device pool was fatal. This module
is the portable redistribution layer in the spirit of "Memory-efficient
array redistribution through portable collective communication"
(arXiv 2112.01075): combined with the guard's exact-resume cursor it turns
preemptions into reshard-and-continue events (the elastic-training property
the pjit/TPUv4 stack of arXiv 2204.06514 treats as table stakes).

Three layers:

- **Topology metadata** (`mesh_topology`, `topology_mismatch`,
  `spec_tree_to_json`): every checkpoint records the save-time mesh - axis
  names/sizes, device/process counts, the PartitionSpec tree, optimizer
  layout - so restore DETECTS a shape mismatch up front with a named diff
  instead of crashing deep inside pjit with an opaque sharding error.
- **Leaf-wise resharder** (`reshard_state`, `place_tree`,
  `convert_optimizer_state`): maps any saved layout onto any target mesh.
  Placement is memory-bounded - one leaf at a time via `device_put` /
  `make_array_from_callback` (each process uploads only its addressable
  slices), never a fully replicated device copy of the whole tree. The
  ZeRO-1 flat buffers are re-padded for the new data-axis size
  (`reshard_zero_tree`), and optimizer state converts between the
  replicated and ZeRO layouts of the same family (sgd <-> zero,
  adam <-> zero-adam) bitwise.
- **Device-level transfer program** (`make_zero_gather_fn`,
  `reshard_step_program`): the same-mesh collective form of the ZeRO
  reassembly (one tiled all_gather per leaf over the data axis) as a
  traceable StepProgram, so shardlint pins the resharder's collective
  bytes like every other program (analysis/configs.py
  `lm_reshard_zero_gather`).

Everything host-side here runs on any jax (no shard_map needed), which is
what makes the reshard path itself testable on the 8-device CPU mesh of
the pinned CI container.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

RESHARD_META_VERSION = 1

# optimizer families: state converts bitwise within a family (same logical
# values, different layout); across families there is nothing to map
_OPTIMIZER_FAMILY = {
    "sgd": "sgd", "zero": "sgd", "adam": "adam", "zero-adam": "adam",
}


# ------------------------------------------------- PartitionSpec (de)serde


def spec_to_json(spec) -> list:
    """One PartitionSpec as a JSON list (tuple entries become lists)."""
    return [list(e) if isinstance(e, tuple) else e for e in tuple(spec)]


def spec_from_json(entries) -> P:
    return P(*[tuple(e) if isinstance(e, list) else e for e in entries])


def _is_spec(s) -> bool:
    return isinstance(s, P)


def spec_tree_to_json(tree):
    """A pytree of PartitionSpecs as nested JSON; each spec leaf becomes
    ``{"__spec__": [...]}`` so subtrees and specs stay unambiguous."""
    return jax.tree.map(
        lambda s: {"__spec__": spec_to_json(s)}, tree, is_leaf=_is_spec
    )


def spec_tree_from_json(doc):
    def is_enc(d):
        return isinstance(d, dict) and "__spec__" in d

    return jax.tree.map(
        lambda d: spec_from_json(d["__spec__"]), doc, is_leaf=is_enc
    )


def spec_axes(spec) -> tuple:
    """Flattened mesh-axis names a PartitionSpec shards over (tuple
    entries - e.g. ``P(('pipe','data'))`` - are expanded)."""
    out = []
    for e in tuple(spec):
        if e is None:
            continue
        if isinstance(e, (tuple, list)):
            out.extend(e)
        else:
            out.append(e)
    return tuple(out)


# ----------------------------------------------------- topology metadata


def mesh_topology(
    mesh: Mesh, *, specs=None, optimizer: str | None = None, **extra
) -> dict:
    """The JSON-serializable save-time topology block for checkpoint meta.

    Records what a restore needs to (a) detect that the saved layout does
    not match the target mesh and (b) rebuild the saved state's abstract
    template (train/elastic.py `saved_state_template`): ordered axis
    names/sizes, device and process counts, the optimizer layout name,
    and the PartitionSpec tree the params were placed with. ``extra``
    lands verbatim (global batch, accum_steps, pp_interleave, ...).
    """
    topo = {
        "version": RESHARD_META_VERSION,
        "axes": {str(k): int(v) for k, v in mesh.shape.items()},
        "devices": int(mesh.devices.size),
        "process_count": int(jax.process_count()),
        "platform": str(mesh.devices.ravel()[0].platform),
    }
    if optimizer is not None:
        topo["optimizer"] = str(optimizer)
    if specs is not None:
        topo["specs"] = spec_tree_to_json(specs)
    topo.update(extra)
    return topo


def topology_mismatch(saved: dict, current: dict) -> list:
    """Human-readable differences between two `mesh_topology` blocks.

    Empty list == the saved layout drops onto the current mesh unchanged
    (plain sharded restore). Anything listed requires the resharder. The
    comparison is deliberately by *layout-bearing* fields only - platform
    changes (TPU save -> CPU restore) are already portable and not listed.
    """
    diffs = []
    if saved.get("version", 0) > RESHARD_META_VERSION:
        diffs.append(
            f"checkpoint mesh meta version {saved.get('version')} is newer "
            f"than this build's {RESHARD_META_VERSION}"
        )
    a, b = saved.get("axes") or {}, current.get("axes") or {}
    for name in sorted(set(a) | set(b)):
        sa, sb = int(a.get(name, 1)), int(b.get(name, 1))
        if sa != sb:
            diffs.append(f"mesh axis {name!r}: saved {sa}, target {sb}")
    if saved.get("devices") != current.get("devices"):
        diffs.append(
            f"device count: saved {saved.get('devices')}, "
            f"target {current.get('devices')}"
        )
    so, co = saved.get("optimizer"), current.get("optimizer")
    if so is not None and co is not None and so != co:
        diffs.append(f"optimizer layout: saved {so!r}, target {co!r}")
    si, ci = saved.get("pp_interleave", 1), current.get("pp_interleave", 1)
    if int(si) != int(ci):
        diffs.append(f"pp_interleave: saved {si}, target {ci}")
    return diffs


# ------------------------------------------------- memory-bounded placement


def put_leaf(x, sharding):
    """Place ONE leaf onto a sharding without a full replicated device copy.

    jax.Array input: a direct cross-sharding transfer (`device_put` moves
    shards over ICI/DCN without a host round trip). Host arrays on a
    multi-process mesh: `make_array_from_callback` so each process uploads
    only the slices addressable to it. Either way the peak footprint is
    one leaf, never the whole tree.
    """
    if isinstance(x, jax.Array) or jax.process_count() == 1:
        return jax.device_put(x, sharding)
    x = np.asarray(x)
    return jax.make_array_from_callback(x.shape, sharding, lambda idx: x[idx])


def place_tree(tree, shardings):
    """Leaf-wise `put_leaf` over a (host or device) pytree."""
    return jax.tree.map(put_leaf, tree, shardings)


# --------------------------------------------------- ZeRO layout transforms


def reshard_zero_leaf(buf, size: int, new_n: int):
    """Re-pad one flat ZeRO buffer for a new shard count.

    The buffer holds the leaf's `size` logical elements plus zero padding
    to a multiple of the OLD shard count (`parallel/zero.py
    leaf_shard_size`); the padding length changes with the shard count, so
    a dp change must unpad to the logical elements and re-pad - values are
    untouched (bitwise round trip).
    """
    from .zero import leaf_shard_size

    buf = np.asarray(buf)
    if buf.ndim != 1 or buf.shape[0] < size:
        raise ValueError(
            f"ZeRO buffer of shape {buf.shape} cannot hold {size} logical "
            "elements - not a flat per-leaf ZeRO buffer"
        )
    flat = buf[:size]
    total = leaf_shard_size(size, new_n) * new_n
    out = np.zeros((total,), buf.dtype)
    out[:size] = flat
    return out


def reshard_zero_tree(flat_tree, params_template, new_n: int):
    """`reshard_zero_leaf` over a per-leaf ZeRO buffer tree; logical sizes
    come from the aligned `params_template` leaves."""
    return jax.tree.map(
        lambda buf, ref: reshard_zero_leaf(buf, int(np.prod(ref.shape, dtype=np.int64)), new_n),
        flat_tree,
        params_template,
    )


def zero_tree_to_momentum(flat_tree, params_template):
    """ZeRO per-leaf flat buffers -> the replicated momentum tree (each
    leaf unpadded and reshaped to its parameter's shape). Values bitwise."""
    def leaf(buf, ref):
        size = int(np.prod(ref.shape, dtype=np.int64))
        buf = np.asarray(buf)
        if buf.shape[0] < size:
            raise ValueError(
                f"ZeRO buffer ({buf.shape[0]} elements) smaller than its "
                f"parameter ({size}) - layout mismatch"
            )
        return buf[:size].reshape(ref.shape)

    return jax.tree.map(leaf, flat_tree, params_template)


def momentum_to_zero_tree(mom_tree, n_shards: int):
    """Replicated momentum tree -> ZeRO per-leaf flat buffers padded for
    `n_shards` (inverse of `zero_tree_to_momentum`; f32, the ZeRO state
    dtype). Values bitwise."""
    from .zero import leaf_shard_size

    def leaf(m):
        m = np.asarray(m, np.float32).reshape(-1)
        total = leaf_shard_size(m.size, n_shards) * n_shards
        out = np.zeros((total,), np.float32)
        out[: m.size] = m
        return out

    return jax.tree.map(leaf, mom_tree)


# ------------------------------------------ ZeRO-under-pp layout transforms


def pp_zero_tree_to_momentum(flat_tree, params_template, pp_specs, pp: int):
    """ZeRO-under-pp per-leaf flat buffers -> the replicated momentum tree.

    The pipeline ZeRO layout (`parallel/pipeline.py init_pp_zero_state`,
    the DeepSpeed ZeRO-1 + PP convention) flattens each pipe-sharded leaf
    STAGE-MAJOR: pp segments of ``dp * ceil((size/pp)/dp)`` elements, each
    holding one stage's contiguous layer chunk plus per-stage dp padding.
    Unpadding each segment and concatenating in stage order recovers the
    row-major flattened logical leaf (the leading layer axis is the
    pipe-sharded one, so stage q's chunk IS elements
    ``[q*size/pp, (q+1)*size/pp)``). Pipe-replicated leaves (embed / head /
    final norm) carry the plain dp-padded layout and unpad like the mesh
    path. Values bitwise; `pp_specs` (pp_param_specs(cfg)) says which
    leaves carry the per-stage split.
    """
    def leaf(buf, ref, spec):
        buf = np.asarray(buf)
        size = int(np.prod(ref.shape, dtype=np.int64))
        if pp > 1 and "pipe" in spec_axes(spec):
            if size % pp or buf.shape[0] % pp:
                raise ValueError(
                    f"pipe-sharded leaf of {size} elements / buffer "
                    f"{buf.shape} does not split over {pp} stages"
                )
            local = size // pp
            seg = buf.shape[0] // pp
            if seg < local:
                raise ValueError(
                    f"ZeRO-under-pp segment ({seg} elements) smaller than "
                    f"its stage chunk ({local}) - layout mismatch"
                )
            flat = buf.reshape(pp, seg)[:, :local].reshape(-1)
        else:
            if buf.shape[0] < size:
                raise ValueError(
                    f"ZeRO buffer ({buf.shape[0]} elements) smaller than "
                    f"its parameter ({size}) - layout mismatch"
                )
            flat = buf[:size]
        return flat.reshape(ref.shape)

    return jax.tree.map(leaf, flat_tree, params_template, pp_specs)


def momentum_to_pp_zero_tree(mom_tree, pp_specs, pp: int, dp: int):
    """Replicated momentum tree -> ZeRO-under-pp per-leaf flat buffers
    (inverse of `pp_zero_tree_to_momentum`; f32, the ZeRO state dtype).
    Pipe-sharded leaves re-split stage-major with per-stage dp padding;
    pipe-replicated leaves pad like the mesh path. Values bitwise."""
    from .zero import leaf_shard_size

    def leaf(m, spec):
        m = np.asarray(m, np.float32).reshape(-1)
        if pp > 1 and "pipe" in spec_axes(spec):
            if m.size % pp:
                raise ValueError(
                    f"leaf of {m.size} elements does not split over {pp} "
                    "stages"
                )
            local = m.size // pp
            seg = dp * leaf_shard_size(local, dp)
            out = np.zeros((pp, seg), np.float32)
            out[:, :local] = m.reshape(pp, local)
            return out.reshape(-1)
        total = dp * leaf_shard_size(m.size, dp)
        out = np.zeros((total,), np.float32)
        out[: m.size] = m
        return out

    return jax.tree.map(leaf, mom_tree, pp_specs)


# ------------------------------------------- optimizer layout conversion


def convert_optimizer_state(
    mom, *, src: str, dst: str, params_template, src_dp: int, dst_dp: int,
    src_pp: int = 1, dst_pp: int = 1, pp_specs=None,
):
    """Map optimizer state between layouts (host-level, values bitwise).

    Within a family the state is the same logical values under a different
    partition: sgd <-> zero re-flattens/pads the momentum tree,
    adam <-> zero-adam does the same for both moment trees (the step
    counter passes through). Across families (sgd <-> adam) there is no
    meaningful mapping and a ValueError names the supported conversions.

    ``src_pp``/``dst_pp`` > 1 mark ZeRO state laid out under pipeline
    parallelism (the per-stage split of `init_pp_zero_state`); those
    conversions route through the canonical replicated momentum tree
    (`pp_zero_tree_to_momentum` / `momentum_to_pp_zero_tree` - still
    bitwise) and need ``pp_specs`` (the pipeline param-spec tree that says
    which leaves carry the split).
    """
    for name, o in (("saved", src), ("target", dst)):
        if o not in _OPTIMIZER_FAMILY:
            raise ValueError(f"unknown {name} optimizer {o!r}")
    if _OPTIMIZER_FAMILY[src] != _OPTIMIZER_FAMILY[dst]:
        raise ValueError(
            f"cannot convert optimizer state {src!r} -> {dst!r}: the "
            "layouts carry different quantities. Supported conversions: "
            "sgd<->zero, adam<->zero-adam, and any optimizer to itself "
            "across mesh shapes."
        )
    src_zero = src in ("zero", "zero-adam")
    dst_zero = dst in ("zero", "zero-adam")
    if (src_zero and src_pp > 1) or (dst_zero and dst_pp > 1):
        if pp_specs is None:
            raise ValueError(
                "ZeRO state under pipeline parallelism carries a per-stage "
                "split; pass pp_specs (parallel/pipeline.py "
                "pp_param_specs) so the converter knows which leaves "
                "split over 'pipe'"
            )
        if (src, src_dp, src_pp) == (dst, dst_dp, dst_pp):
            return mom

        def to_mom(flat):
            if src_pp > 1:
                return pp_zero_tree_to_momentum(
                    flat, params_template, pp_specs, src_pp
                )
            return zero_tree_to_momentum(flat, params_template)

        def to_zero(tree):
            if dst_pp > 1:
                return momentum_to_pp_zero_tree(
                    tree, pp_specs, dst_pp, dst_dp
                )
            return momentum_to_zero_tree(tree, dst_dp)

        if _OPTIMIZER_FAMILY[src] == "sgd":
            mid = to_mom(mom) if src_zero else mom
            return to_zero(mid) if dst_zero else mid
        mid_m = to_mom(mom["m"]) if src_zero else mom["m"]
        mid_v = to_mom(mom["v"]) if src_zero else mom["v"]
        if dst_zero:
            mid_m, mid_v = to_zero(mid_m), to_zero(mid_v)
        return {"m": mid_m, "v": mid_v, "t": mom["t"]}
    if src == dst:
        if src in ("zero", "zero-adam") and src_dp != dst_dp:
            if src == "zero":
                return reshard_zero_tree(mom, params_template, dst_dp)
            return {
                "m": reshard_zero_tree(mom["m"], params_template, dst_dp),
                "v": reshard_zero_tree(mom["v"], params_template, dst_dp),
                "t": mom["t"],
            }
        return mom
    if (src, dst) == ("zero", "sgd"):
        return zero_tree_to_momentum(mom, params_template)
    if (src, dst) == ("sgd", "zero"):
        return momentum_to_zero_tree(mom, dst_dp)
    if (src, dst) == ("zero-adam", "adam"):
        return {
            "m": zero_tree_to_momentum(mom["m"], params_template),
            "v": zero_tree_to_momentum(mom["v"], params_template),
            "t": mom["t"],
        }
    if (src, dst) == ("adam", "zero-adam"):
        return {
            "m": momentum_to_zero_tree(mom["m"], dst_dp),
            "v": momentum_to_zero_tree(mom["v"], dst_dp),
            "t": mom["t"],
        }
    raise AssertionError(f"unhandled conversion {src!r} -> {dst!r}")


def reshard_state(
    state,
    *,
    saved_optimizer: str,
    saved_dp: int,
    optimizer: str,
    dp: int,
    params_template,
    param_shardings=None,
    mom_shardings=None,
    saved_pp: int = 1,
    pp: int = 1,
    pp_specs=None,
):
    """The leaf-wise resharder: one saved ``{"params", "mom"}`` state tree
    (host or device arrays, any mesh of origin) onto a new layout.

    Parameters are layout-invariant logical arrays - only their placement
    changes. Optimizer state goes through `convert_optimizer_state`
    (ZeRO re-padding for the new data-axis size, replicated<->ZeRO within
    a family, the ZeRO-under-pp per-stage split rebuilt from
    ``saved_pp``/``pp`` + ``pp_specs``). With shardings given, leaves are
    placed memory-boundedly (`place_tree`); without, host trees come back
    for the caller to place.
    """
    params = state["params"]
    mom = convert_optimizer_state(
        state["mom"], src=saved_optimizer, dst=optimizer,
        params_template=params_template, src_dp=saved_dp, dst_dp=dp,
        src_pp=saved_pp, dst_pp=pp, pp_specs=pp_specs,
    )
    if param_shardings is not None:
        params = place_tree(params, param_shardings)
    if mom_shardings is not None:
        mom = place_tree(mom, mom_shardings)
    return {"params": params, "mom": mom}


# ----------------------------------------------- batch / accumulation math


def rescale_accum(global_batch: int, old_dp: int, new_dp: int, accum: int) -> int:
    """Gradient-accumulation steps after a dp change, global batch FIXED.

    The exact-resume cursor pins the data stream as a function of
    (seed, step, global batch) - so elasticity must never change the
    global batch. What can change is how it is sliced: prefer keeping the
    per-device microbatch row count constant (accum scales by
    old_dp/new_dp - a shrink accumulates more, a growth less, activation
    memory per device stays put); fall back to the old accum when the new
    dp still divides; last resort accum=1. Raises when `global_batch` is
    not divisible by `new_dp` at all (no slicing can preserve it).
    """
    for name, v in (
        ("global_batch", global_batch), ("old_dp", old_dp),
        ("new_dp", new_dp), ("accum", accum),
    ):
        if int(v) < 1:
            raise ValueError(f"{name} must be >= 1, got {v}")
    if global_batch % new_dp:
        raise ValueError(
            f"global batch {global_batch} does not divide over the new "
            f"data-parallel size {new_dp} - the elastic contract keeps the "
            "global batch (and so the data cursor) exact; choose a target "
            "dp that divides the batch"
        )
    scaled = accum * old_dp
    if scaled % new_dp == 0:
        k = scaled // new_dp
        if global_batch % (new_dp * k) == 0:
            return k
    if global_batch % (new_dp * accum) == 0:
        return accum
    return 1


# --------------------------------------------- engine (CNN) momentum stack


def reshard_momentum_stack(mom_stack, n_new: int):
    """The CNN engine's per-device momentum stack onto a new worker count.

    Shrink: the first `n_new` rows survive (their devices keep training
    with their own buffers - the buffers of removed workers are dropped
    with the workers). Grow: new workers start with ZERO momentum (the
    same fresh-optimizer state the reference's per-epoch SGD re-creation
    gives every worker every epoch). Host-level, leaf-wise.
    """
    if n_new < 1:
        raise ValueError(f"n_new must be >= 1, got {n_new}")

    def leaf(m):
        m = np.asarray(m)
        n_old = m.shape[0]
        if n_new <= n_old:
            return m[:n_new]
        pad = np.zeros((n_new - n_old, *m.shape[1:]), m.dtype)
        return np.concatenate([m, pad], axis=0)

    return jax.tree.map(leaf, mom_stack)


# --------------------------------- device-level transfer program (traced)


def make_zero_gather_fn(params_template, mesh: Mesh, axis_name: str = "data"):
    """Compiled same-mesh ZeRO reassembly: per-leaf flat dp-sharded buffers
    -> the replicated momentum tree, one tiled `all_gather` per leaf.

    This is the collective form of `zero_tree_to_momentum` (arXiv
    2112.01075's portable-collective redistribution on one mesh): each
    device contributes its 1/dp shard and the gather output is sliced to
    the logical size and reshaped. Runs outside autodiff, so it lives in a
    ``check_vma=False`` shard_map like the ZeRO optimizer itself
    (parallel/zero.py). Shardlint traces it via `reshard_step_program` to
    pin the transfer's collective bytes.
    """
    from .. import compat

    refs = jax.tree.map(
        lambda p: jax.ShapeDtypeStruct(tuple(p.shape), p.dtype),
        params_template,
    )

    def body(flat_tree):
        def leaf(buf, ref):
            full = jax.lax.all_gather(buf, axis_name, tiled=True)
            size = int(np.prod(ref.shape, dtype=np.int64))
            return full[:size].reshape(ref.shape).astype(jnp.float32)

        return jax.tree.map(leaf, flat_tree, refs)

    return jax.jit(
        compat.shard_map(
            body, mesh=mesh, in_specs=(P(axis_name),), out_specs=P(),
            check_vma=False,
        ),
        donate_argnums=(0,),
    )


def make_pp_zero_gather_fn(
    params_template,
    mesh: Mesh,
    *,
    data_axis: str = "data",
    pipe_axis: str = "pipe",
):
    """Compiled same-mesh ZeRO-under-pp reassembly: the per-stage flat
    dp-sharded buffers (`parallel/pipeline.py init_pp_zero_state`) -> the
    replicated momentum tree.

    The collective form of `pp_zero_tree_to_momentum`: per pipe-sharded
    leaf, one tiled `all_gather` over the data axis rebuilds each stage's
    padded segment, the per-stage padding is sliced off, and a second
    tiled `all_gather` over the pipe axis concatenates the stage chunks in
    stage order (two collectives, so the stage-major ordering is explicit
    rather than depending on a fused multi-axis gather's index order).
    Pipe-replicated leaves take the mesh path's single data-axis gather.
    Outside autodiff, so it lives in a ``check_vma=False`` shard_map like
    the ZeRO optimizer; shardlint traces it via `reshard_pp_step_program`.
    """
    from .. import compat
    from .pipeline import pp_optimizer_state_specs

    pp = int(mesh.shape.get(pipe_axis, 1))
    refs = jax.tree.map(
        lambda p: jax.ShapeDtypeStruct(tuple(p.shape), p.dtype),
        params_template,
    )
    # which leaves are pipe-sharded follows from the param tree's own
    # structure (the layer stack), not from a TransformerConfig
    specs = pp_param_specs_for_tree(params_template)
    state_specs = pp_optimizer_state_specs("zero", specs)

    def body(flat_tree):
        def leaf(buf, ref, spec):
            size = int(np.prod(ref.shape, dtype=np.int64))
            if pp > 1 and "pipe" in spec_axes(spec):
                local = size // pp
                seg = jax.lax.all_gather(buf, data_axis, tiled=True)
                flat = jax.lax.all_gather(
                    seg[:local], pipe_axis, tiled=True
                )
            else:
                full = jax.lax.all_gather(buf, data_axis, tiled=True)
                flat = full[:size]
            return flat.reshape(ref.shape).astype(jnp.float32)

        return jax.tree.map(leaf, flat_tree, refs, specs)

    return jax.jit(
        compat.shard_map(
            body, mesh=mesh, in_specs=(state_specs,), out_specs=P(),
            check_vma=False,
        ),
        donate_argnums=(0,),
    )


def pp_param_specs_for_tree(params_template):
    """The pipeline PartitionSpec tree for any transformer-shaped param
    tree: every `layers` leaf stage-sharded over 'pipe' on its leading
    (layer) axis, everything else replicated - the structural fact the
    ZeRO-under-pp reshard needs, derived from the tree itself so callers
    without a TransformerConfig (gather fns, templates built from saved
    arrays) never re-derive it by hand."""
    def sub(tree, piped: bool):
        def leaf(p):
            if piped:
                return P("pipe", *([None] * (len(np.shape(p)) - 1)))
            return P(*([None] * len(np.shape(p))))

        return jax.tree.map(leaf, tree)

    return {
        k: sub(v, k == "layers") for k, v in params_template.items()
    }


def reshard_pp_step_program(
    cfg, mesh: Mesh, *, name: str = "pp_reshard_zero_gather"
):
    """`make_pp_zero_gather_fn` packaged as a traceable StepProgram: the
    manifest pins the per-leaf gather pair (data-axis segment gather +
    pipe-axis stage concat for pipe-sharded leaves; single data gather for
    replicated ones) so a transfer-schedule regression in the
    ZeRO-under-pp reshard fails `shardlint --check` like
    `lm_reshard_zero_gather` does for the mesh path."""
    from ..models import transformer as tfm
    from ..train.program import StepProgram
    from .pipeline import (
        init_pp_zero_state,
        pp_optimizer_state_specs,
        pp_param_specs,
    )

    dp = int(mesh.shape.get("data", 1))
    pp = int(mesh.shape.get("pipe", 1))
    params = jax.eval_shape(
        lambda k: tfm.init_params(k, cfg),
        jax.ShapeDtypeStruct((2,), jnp.uint32),
    )
    specs = pp_param_specs(cfg)
    flat = jax.eval_shape(
        lambda p: init_pp_zero_state(p, specs, mesh, "zero"), params
    )
    fn = make_pp_zero_gather_fn(params, mesh)
    return StepProgram(
        name=name,
        fn=fn,
        mesh=mesh,
        abstract_args=(flat,),
        specs={"params": pp_optimizer_state_specs("zero", specs)},
        donate=(0,),
        donate_labels=("pp zero state shards",),
        meta={
            "family": "reshard",
            "optimizer": "zero",
            "mesh": {k: int(v) for k, v in mesh.shape.items()},
            "dp": dp,
            "pp": pp,
            # donated flat buffers free early; outputs are the reassembled
            # param-shaped tree, so no in-place alias exists by design
            "expect_alias": False,
        },
    )


def reshard_step_program(cfg, mesh: Mesh, *, name: str = "reshard_zero_gather"):
    """`make_zero_gather_fn` packaged as a traceable StepProgram
    (train/program.py) for the static analyzer: the manifest pins one
    all_gather over the data axis per state leaf at the padded buffer
    size, so a transfer-schedule regression (extra collective, de-tiled
    gather) fails `shardlint --check` like any training step would."""
    from ..models import transformer as tfm
    from ..train.program import StepProgram
    from .zero import init_zero_momentum_tree

    dp = int(mesh.shape.get("data", 1))
    params = jax.eval_shape(
        lambda k: tfm.init_params(k, cfg),
        jax.ShapeDtypeStruct((2,), jnp.uint32),
    )
    flat = jax.eval_shape(lambda p: init_zero_momentum_tree(p, dp), params)
    fn = make_zero_gather_fn(params, mesh, axis_name="data")
    return StepProgram(
        name=name,
        fn=fn,
        mesh=mesh,
        abstract_args=(flat,),
        specs={"params": P("data")},
        donate=(0,),
        donate_labels=("zero state shards",),
        meta={
            "family": "reshard",
            "optimizer": "zero",
            "mesh": {k: int(v) for k, v in mesh.shape.items()},
            "dp": dp,
            # the donated flat buffers are freed early; outputs are the
            # reassembled param-shaped tree, so no in-place alias exists
            # by design (same opt-out as the engine's sync program)
            "expect_alias": False,
        },
    )
