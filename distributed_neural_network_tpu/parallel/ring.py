"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no attention and no sequence axis at all (SURVEY.md
section 5.7: its model is a 5-layer CNN on 32x32 images; it scales only along
the batch axis). These primitives are the framework's long-context
capability, built mesh-first so sequences longer than one device's HBM split
across a `'seq'` mesh axis and scale over ICI:

- **ring_attention**: each device holds one sequence shard of Q/K/V. K/V
  blocks rotate around the ring via `jax.lax.ppermute` (XLA lowers the
  static ring permutation to ICI neighbor exchanges); each device
  accumulates its queries' attention over every block with the numerically
  stable blockwise-softmax recurrence (running max / denominator /
  numerator, the flash-attention update rule), so the full S x S score
  matrix never materializes anywhere and per-device memory stays
  O(S_local^2) compute / O(S_local) state per step.
- **ulysses_attention**: `jax.lax.all_to_all` re-shards from
  sequence-sharded to head-sharded (each device then owns H/n full-length
  heads), runs ordinary full attention locally, and re-shards back. One
  collective round-trip instead of n ring steps - better when H >= n and
  the all-to-all fits ICI; ring wins at extreme sequence lengths.

Both are pure functions designed to run inside `jax.shard_map` over the
sequence axis and are exact (up to float reassociation) w.r.t. single-device
attention - verified in tests/test_ring.py against the gathered reference.
Causality is handled with *global* positions (device offset + local index),
so a causal mask is consistent across shards.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

SEQ_AXIS = "seq"

_NEG_BIG = -1e30  # large-negative mask value; avoids -inf NaN propagation


def attention(q, k, v, *, causal: bool = False, q_offset=0, k_offset=0, scale=None):
    """Plain full (single-device) attention; the local/reference kernel.

    q: (B, Sq, H, D), k/v: (B, Sk, H, D) -> (B, Sq, H, D). Offsets give the
    global position of row 0 for causal masking across shards.
    """
    d = q.shape[-1]
    scale = (1.0 / jnp.sqrt(d)) if scale is None else scale
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def ring_attention(q, k, v, axis_name: str = SEQ_AXIS, *, causal: bool = False, scale=None):
    """Exact attention over a sequence sharded across `axis_name`.

    Call inside shard_map; q/k/v are the local (B, S_local, H, D) shards in
    ring order (shard i holds global positions [i*S_local, (i+1)*S_local)).
    Returns the local output shard. K/V blocks travel the ring n-1 hops;
    communication overlaps the next block's compute under XLA's latency
    hiding scheduler.
    """
    n = jax.lax.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    scale_ = (1.0 / jnp.sqrt(d)) if scale is None else scale
    qpos = me * s_local + jnp.arange(s_local)

    def block(qv, kv, vv, src):
        """One blockwise score/value contribution: returns (s, pv) with
        s: (B,H,Sq,Sk) masked scores, against kv/vv from ring slot `src`."""
        sc = jnp.einsum("bqhd,bkhd->bhqk", qv, kv) * scale_
        if causal:
            kpos = src * s_local + jnp.arange(s_local)
            mask = qpos[:, None] >= kpos[None, :]
            sc = jnp.where(mask[None, None], sc, _NEG_BIG)
        return sc

    perm = [(i, (i + 1) % n) for i in range(n)]

    def update(i, m, l, acc, k_blk, v_blk):
        src = (me - i) % n
        sc = block(q, k_blk, v_blk, src)
        m_new = jnp.maximum(m, sc.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(sc - m_new[..., None])
        l = l * alpha + p.sum(axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, v_blk)
        return m_new, l, acc

    def body(i, carry):
        m, l, acc, k_blk, v_blk = carry
        m, l, acc = update(i, m, l, acc, k_blk, v_blk)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return m, l, acc, k_blk, v_blk

    def vary(x):
        # initial accumulators are constants (vma-invariant) but the loop
        # carries values as device-varying as q itself (which may vary over
        # more mesh axes than the ring axis, e.g. a batch axis); align the
        # carry types up front
        try:
            want = jax.typeof(q).vma
            missing = tuple(a for a in want if a not in jax.typeof(x).vma)
        except AttributeError:  # vma-less jax version
            return x
        return jax.lax.pcast(x, missing, to="varying") if missing else x

    m0 = vary(jnp.full((b, h, s_local), _NEG_BIG, q.dtype))
    l0 = vary(jnp.zeros((b, h, s_local), q.dtype))
    acc0 = vary(jnp.zeros((b, h, s_local, d), q.dtype))
    # n-1 rotate-and-accumulate hops, then the final block without the
    # rotation (whose result nobody would consume - a full K/V shard of ICI
    # traffic per layer saved)
    m, l, acc, k_blk, v_blk = jax.lax.fori_loop(0, n - 1, body, (m0, l0, acc0, k, v))
    m, l, acc = update(n - 1, m, l, acc, k_blk, v_blk)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3)  # (B, Sq, H, D)


def ulysses_attention(q, k, v, axis_name: str = SEQ_AXIS, *, causal: bool = False, scale=None):
    """Sequence->head all-to-all attention (DeepSpeed-Ulysses pattern).

    Requires the head count H to be divisible by the axis size n. Each device
    trades its sequence shard of all heads for the full sequence of H/n
    heads, computes ordinary attention locally, and trades back.
    """
    n = jax.lax.axis_size(axis_name)
    h = q.shape[2]
    if h % n != 0:
        raise ValueError(f"ulysses needs heads ({h}) divisible by axis size ({n})")
    a2a = functools.partial(
        jax.lax.all_to_all, axis_name=axis_name, split_axis=2, concat_axis=1, tiled=True
    )
    back = functools.partial(
        jax.lax.all_to_all, axis_name=axis_name, split_axis=1, concat_axis=2, tiled=True
    )
    qf, kf, vf = a2a(q), a2a(k), a2a(v)  # (B, S_full, H/n, D)
    out = attention(qf, kf, vf, causal=causal, scale=scale)
    return back(out)
