"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no attention and no sequence axis at all (SURVEY.md
section 5.7: its model is a 5-layer CNN on 32x32 images; it scales only along
the batch axis). These primitives are the framework's long-context
capability, built mesh-first so sequences longer than one device's HBM split
across a `'seq'` mesh axis and scale over ICI:

- **ring_attention**: each device holds one sequence shard of Q/K/V. K/V
  blocks rotate around the ring via `jax.lax.ppermute` (XLA lowers the
  static ring permutation to ICI neighbor exchanges); each device
  accumulates its queries' attention over every block with the numerically
  stable blockwise-softmax recurrence (running max / denominator /
  numerator, the flash-attention update rule), so the full S x S score
  matrix never materializes anywhere and per-device memory stays
  O(S_local^2) compute / O(S_local) state per step.
- **ulysses_attention**: `jax.lax.all_to_all` re-shards from
  sequence-sharded to head-sharded (each device then owns H/n full-length
  heads), runs ordinary full attention locally, and re-shards back. One
  collective round-trip instead of n ring steps - better when H >= n and
  the all-to-all fits ICI; ring wins at extreme sequence lengths.

Both are pure functions designed to run inside `jax.shard_map` over the
sequence axis and are exact (up to float reassociation) w.r.t. single-device
attention - verified in tests/test_ring.py against the gathered reference.
Causality is handled with *global* positions (device offset + local index),
so a causal mask is consistent across shards.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .. import compat
from .collectives import vary_like

SEQ_AXIS = "seq"

_NEG_BIG = -1e30  # large-negative mask value; avoids -inf NaN propagation


def attention(q, k, v, *, causal: bool = False, q_offset=0, k_offset=0, scale=None):
    """Plain full (single-device) attention; the local/reference kernel.

    q: (B, Sq, H, D), k/v: (B, Sk, H, D) -> (B, Sq, H, D). Offsets give the
    global position of row 0 for causal masking across shards.

    H == 1 takes a squeezed 3-D contraction: XLA:CPU lowers the size-1-head
    4-D batched einsum ~2x SLOWER than the h=2 case despite half the FLOPs
    (measured, tools/ulysses_diag.json) - this was the entire
    lm_ulysses_sp_scaling_cpu8 sp=8 cliff (one head per device at H == sp;
    overhead 1.923 vs 0.897 at sp=4). Same math, same outputs.
    """
    d = q.shape[-1]
    scale = (1.0 / jnp.sqrt(d)) if scale is None else scale
    # all three must be single-head: squeezing on q alone would silently
    # attend k/v head 0 where the generic einsum raises a shape error
    squeeze = q.shape[2] == k.shape[2] == v.shape[2] == 1
    if squeeze:
        s = jnp.einsum("bqd,bkd->bqk", q[:, :, 0], k[:, :, 0]) * scale
    else:
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None] if squeeze else mask[None, None], s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    if squeeze:
        return jnp.einsum("bqk,bkd->bqd", p, v[:, :, 0])[:, :, None, :]
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def ring_attention(q, k, v, axis_name: str = SEQ_AXIS, *, causal: bool = False, scale=None):
    """Exact attention over a sequence sharded across `axis_name`.

    Call inside shard_map; q/k/v are the local (B, S_local, H, D) shards in
    ring order (shard i holds global positions [i*S_local, (i+1)*S_local)).
    Returns the local output shard. K/V blocks travel the ring n-1 hops;
    communication overlaps the next block's compute under XLA's latency
    hiding scheduler.
    """
    n = compat.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    scale_ = (1.0 / jnp.sqrt(d)) if scale is None else scale
    qpos = me * s_local + jnp.arange(s_local)

    def block(qv, kv, vv, src):
        """One blockwise score/value contribution: returns (s, pv) with
        s: (B,H,Sq,Sk) masked scores, against kv/vv from ring slot `src`."""
        sc = jnp.einsum("bqhd,bkhd->bhqk", qv, kv) * scale_
        if causal:
            kpos = src * s_local + jnp.arange(s_local)
            mask = qpos[:, None] >= kpos[None, :]
            sc = jnp.where(mask[None, None], sc, _NEG_BIG)
        return sc

    perm = [(i, (i + 1) % n) for i in range(n)]

    def update(i, m, l, acc, k_blk, v_blk):
        src = (me - i) % n
        sc = block(q, k_blk, v_blk, src)
        m_new = jnp.maximum(m, sc.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(sc - m_new[..., None])
        l = l * alpha + p.sum(axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, v_blk)
        return m_new, l, acc

    def body(i, carry):
        m, l, acc, k_blk, v_blk = carry
        m, l, acc = update(i, m, l, acc, k_blk, v_blk)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return m, l, acc, k_blk, v_blk

    def vary(x):
        # initial accumulators are constants (vma-invariant) but the loop
        # carries values as device-varying as q itself (which may vary over
        # more mesh axes than the ring axis, e.g. a batch axis); align the
        # carry types up front
        return vary_like(x, q)

    m0 = vary(jnp.full((b, h, s_local), _NEG_BIG, q.dtype))
    l0 = vary(jnp.zeros((b, h, s_local), q.dtype))
    acc0 = vary(jnp.zeros((b, h, s_local, d), q.dtype))
    # n-1 rotate-and-accumulate hops, then the final block without the
    # rotation (whose result nobody would consume - a full K/V shard of ICI
    # traffic per layer saved)
    m, l, acc, k_blk, v_blk = jax.lax.fori_loop(0, n - 1, body, (m0, l0, acc0, k, v))
    m, l, acc = update(n - 1, m, l, acc, k_blk, v_blk)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3)  # (B, Sq, H, D)


def zigzag_order(s: int, n: int):
    """Permutation putting a length-s sequence into zigzag shard layout.

    The sequence is cut into 2n equal chunks; device i's contiguous shard
    becomes [chunk_i, chunk_{2n-1-i}] - so under a plain P('seq') sharding
    each device holds one "early" and one "late" chunk and causal work is
    balanced across the ring (`zigzag_ring_attention`). Returns int32
    indices `perm` with x_zigzag = x[..., perm, :]; invert with
    `zigzag_inverse`.
    """
    if s % (2 * n):
        raise ValueError(f"seq len {s} must divide by 2*n ({2 * n})")
    h = s // (2 * n)
    chunks = np.arange(s).reshape(2 * n, h)
    order = []
    for i in range(n):
        order.append(chunks[i])
        order.append(chunks[2 * n - 1 - i])
    return np.concatenate(order).astype(np.int32)


def zigzag_inverse(s: int, n: int):
    """Inverse permutation of `zigzag_order` (zigzag -> natural)."""
    perm = zigzag_order(s, n)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(s, dtype=np.int32)
    return inv


def zigzag_positions(s_local: int, axis_name: str = SEQ_AXIS):
    """Global positions of the local rows under the zigzag layout."""
    n = compat.axis_size(axis_name)
    i = jax.lax.axis_index(axis_name)
    h = s_local // 2
    lo = i * h + jnp.arange(h)
    hi = (2 * n - 1 - i) * h + jnp.arange(h)
    return jnp.concatenate([lo, hi])


def zigzag_ring_attention(q, k, v, axis_name: str = SEQ_AXIS, *, scale=None):
    """Load-balanced CAUSAL ring attention over zigzag-sharded sequences.

    Plain causal ring attention computes every block and masks future ones
    away: device 0 does 1 useful block of n, device n-1 does n of n, and
    because the ring is lock-step the wasted blocks cost real wall-clock.
    With the zigzag layout (`zigzag_order`: device i holds chunks i and
    2n-1-i) every non-diagonal ring step needs exactly HALF a block and the
    need is identical on every device, so causal attention runs in ~half
    the FLOPs/wall-clock of the masked ring at scale.

    Per ring step with kv from chunk-pair j: if j < i both local query
    chunks attend k's early chunk fully; if j > i the local late query
    chunk attends both of k's chunks fully - either way two
    (S/2n x S/2n) unmasked products, selected by predicate, accumulated
    into the right query rows with a dynamic row offset. The diagonal step
    (t=0) is ordinary local causal attention under zigzag global positions.

    q/k/v: local zigzag shards (B, S_local, H, D) inside shard_map over
    `axis_name`. Exact (up to float reassociation) w.r.t. full causal
    attention on the unpermuted sequence - tests/test_ring.py.
    """
    n = compat.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    b, s_local, h_heads, d = q.shape
    if s_local % 2:
        raise ValueError(f"zigzag needs even local length, got {s_local}")
    half = s_local // 2
    scale_ = (1.0 / jnp.sqrt(d)) if scale is None else scale

    # (B, S, H, D) -> bhqd once; halves sliced as needed
    qT = q.transpose(0, 2, 1, 3)  # (B, Hh, S, D)

    def flash_update(m, l, acc, sc, v_blk, row0):
        """Online-softmax update of rows [row0, row0+rows) of the state.

        sc: (B, Hh, rows, cols) scores; v_blk: (B, cols, Hh, D).
        row0 is traced (device-dependent case selection).
        """
        rows = sc.shape[2]
        m_h = jax.lax.dynamic_slice_in_dim(m, row0, rows, axis=2)
        l_h = jax.lax.dynamic_slice_in_dim(l, row0, rows, axis=2)
        a_h = jax.lax.dynamic_slice_in_dim(acc, row0, rows, axis=2)
        m_new = jnp.maximum(m_h, sc.max(axis=-1))
        alpha = jnp.exp(m_h - m_new)
        p = jnp.exp(sc - m_new[..., None])
        l_h = l_h * alpha + p.sum(axis=-1)
        a_h = a_h * alpha[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, v_blk)
        return (
            jax.lax.dynamic_update_slice_in_dim(m, m_new, row0, axis=2),
            jax.lax.dynamic_update_slice_in_dim(l, l_h, row0, axis=2),
            jax.lax.dynamic_update_slice_in_dim(acc, a_h, row0, axis=2),
        )

    def vary(x):
        # constant-initialized flash state must carry q's varying axes
        # through the fori_loop (same alignment ring_attention needs)
        return vary_like(x, q)

    m = vary(jnp.full((b, h_heads, s_local), _NEG_BIG, q.dtype))
    l = vary(jnp.zeros((b, h_heads, s_local), q.dtype))
    acc = vary(jnp.zeros((b, h_heads, s_local, d), q.dtype))

    # --- diagonal step (t=0): local causal as THREE half-blocks, skipping
    # the q_lo x k_hi quadrant the causal mask would discard entirely
    # (chunk i never attends chunk 2n-1-i): lo x lo causal, hi x lo full,
    # hi x hi causal. The within-chunk causal mask is the same lower
    # triangle for both chunks (positions are contiguous inside a chunk).
    tri = jnp.arange(half)[:, None] >= jnp.arange(half)[None, :]
    sc_ll = jnp.einsum("bhqd,bkhd->bhqk", qT[:, :, :half], k[:, :half]) * scale_
    sc_ll = jnp.where(tri[None, None], sc_ll, _NEG_BIG)
    m, l, acc = flash_update(m, l, acc, sc_ll, v[:, :half], 0)
    sc_hl = jnp.einsum("bhqd,bkhd->bhqk", qT[:, :, half:], k[:, :half]) * scale_
    m, l, acc = flash_update(m, l, acc, sc_hl, v[:, :half], half)
    sc_hh = jnp.einsum("bhqd,bkhd->bhqk", qT[:, :, half:], k[:, half:]) * scale_
    sc_hh = jnp.where(tri[None, None], sc_hh, _NEG_BIG)
    m, l, acc = flash_update(m, l, acc, sc_hh, v[:, half:], half)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(t, carry):
        m, l, acc, k_blk, v_blk = carry
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        src = (me - t) % n
        early = src < me  # chunk indices decide causality, not ring distance
        # product 1: rows = early ? q_lo : q_hi ; cols = k_lo
        q1 = jnp.where(early, 0, half)
        sc1_q = jax.lax.dynamic_slice_in_dim(qT, q1, half, axis=2)
        sc1 = jnp.einsum(
            "bhqd,bkhd->bhqk", sc1_q, k_blk[:, :half]
        ) * scale_
        m, l, acc = flash_update(m, l, acc, sc1, v_blk[:, :half], q1)
        # product 2: rows = q_hi ; cols = early ? k_lo : k_hi
        k2 = jnp.where(early, 0, half)
        k2_blk = jax.lax.dynamic_slice_in_dim(k_blk, k2, half, axis=1)
        v2_blk = jax.lax.dynamic_slice_in_dim(v_blk, k2, half, axis=1)
        sc2 = jnp.einsum("bhqd,bkhd->bhqk", qT[:, :, half:], k2_blk) * scale_
        m, l, acc = flash_update(m, l, acc, sc2, v2_blk, half)
        return m, l, acc, k_blk, v_blk

    m, l, acc, _, _ = jax.lax.fori_loop(1, n, body, (m, l, acc, k, v))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3)


def ulysses_attention(q, k, v, axis_name: str = SEQ_AXIS, *, causal: bool = False, scale=None):
    """Sequence->head all-to-all attention (DeepSpeed-Ulysses pattern).

    Requires the head count H to be divisible by the axis size n. Each device
    trades its sequence shard of all heads for the full sequence of H/n
    heads, computes ordinary attention locally, and trades back.
    """
    n = compat.axis_size(axis_name)
    h = q.shape[2]
    if h % n != 0:
        raise ValueError(f"ulysses needs heads ({h}) divisible by axis size ({n})")
    a2a = functools.partial(
        jax.lax.all_to_all, axis_name=axis_name, split_axis=2, concat_axis=1, tiled=True
    )
    back = functools.partial(
        jax.lax.all_to_all, axis_name=axis_name, split_axis=1, concat_axis=2, tiled=True
    )
    qf, kf, vf = a2a(q), a2a(k), a2a(v)  # (B, S_full, H/n, D)
    out = attention(qf, kf, vf, causal=causal, scale=scale)
    return back(out)
