"""Device mesh construction - the TPU-native replacement for MPI.COMM_WORLD.

The reference discovers its world via `MPI.COMM_WORLD.Get_rank()/Get_size()`
(`data_parallelism_train.py:60-62`) and moves data over a star topology of
blocking point-to-point sends through rank 0. Here the world is a
`jax.sharding.Mesh` over the TPU slice's ICI fabric; collectives
(psum/pmean) replace the send/recv loops, and there is no parent rank.

Axes: the default mesh is 1-D ("data",) - the only parallelism axis the
reference exercises (SURVEY.md section 2: TP/PP/SP/EP absent). `create_mesh`
accepts a full axis spec so additional axes (e.g. ("data", "model")) can be
added without touching callers - the open door noted in SURVEY.md section 7.
"""

from __future__ import annotations

from collections.abc import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def device_count() -> int:
    return jax.device_count()


def create_mesh(
    n_devices: int | None = None,
    axis_names: Sequence[str] = (DATA_AXIS,),
    axis_sizes: Sequence[int] | None = None,
) -> Mesh:
    """Build a mesh over the first n_devices devices.

    `--nb-proc N` maps here: the reference's world size becomes the mesh's
    data-axis size. With axis_sizes given, the devices are reshaped to a
    multi-axis mesh (row-major, ICI-adjacent along the last axis).
    """
    devices = jax.devices()
    n = n_devices if n_devices is not None else len(devices)
    if n > len(devices):
        raise ValueError(
            f"requested {n} devices but only {len(devices)} available; "
            f"for CPU testing set XLA_FLAGS=--xla_force_host_platform_device_count={n}"
        )
    selected = np.asarray(devices[:n])
    if axis_sizes is None:
        axis_sizes = (n,) if len(axis_names) == 1 else None
    if axis_sizes is None or int(np.prod(axis_sizes)) != n:
        raise ValueError(f"axis_sizes {axis_sizes} must multiply to {n}")
    return Mesh(selected.reshape(tuple(axis_sizes)), tuple(axis_names))


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded across the data axis (leading dim split over devices)."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated - the analog of `jax.device_put_replicated` /
    the parent's state_dict broadcast loop (`data_parallelism_train.py:118`)."""
    return NamedSharding(mesh, P())
