"""Fault injection: seeded per-epoch device failure simulation plus
step-granularity chaos injectors for the guard layer.

Parity with `simulate_failure` (`data_parallelism_train.py:41-46`): each
epoch, each worker fails independently with probability
`--failure-probability`. The reference implements failure as an unseeded
host `time.sleep(--failure-duration)` which - because the parent's recv
blocks - stalls the *whole* epoch (straggler semantics, never benchmarked per
report section 6.2). This build upgrades the capability (SURVEY.md
section 5.3): a failed device's contribution is dropped from the epoch's
parameter average (see `collectives.masked_pmean_tree`) and the run
continues; `--failure-duration` is preserved as an optional host-side sleep
so the original straggler wall-clock semantics remain reproducible.

All randomness is explicit JAX PRNG (the reference's `np.random.rand()` at
`:43` is unseeded - SURVEY.md section 5.2 calls for seeding as the fix).

Step-granularity injectors (this repo's addition, for `train/guard.py`):
`StepFaultPlan` corrupts gradients/loss INSIDE the compiled step at chosen
step indices (so the guard's in-jit skip path is exercised under jit, not
simulated), and `ChaosMonkey` perturbs the host-side observation stream /
delivers a real SIGTERM at a step boundary - each host fault fires exactly
once, so a rollback that replays the step does not re-trip it (the
transient-fault model; a recurring fault is what the retry budget is for).
"""

from __future__ import annotations

import os
import signal as _signal
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp


def live_mask(key: jax.Array, n_devices: int, failure_probability: float):
    """(n_devices,) float32 {0,1}: 1 = device participates this epoch.

    Pure function of (key, p) - identical on every host/device, so the mask
    never needs broadcasting. p=0 (the reference default,
    `data_parallelism_train.py:266`) short-circuits to all-live without
    consuming randomness, keeping the fault-free path bit-identical whether
    or not fault simulation is compiled in.
    """
    if failure_probability <= 0.0:
        return jnp.ones((n_devices,), jnp.float32)
    fail = jax.random.bernoulli(key, failure_probability, (n_devices,))
    return (~fail).astype(jnp.float32)


def epoch_key(seed: int, epoch: int) -> jax.Array:
    """Deterministic per-epoch fault key, independent of the data PRNG stream."""
    return jax.random.fold_in(jax.random.key(seed ^ 0x5EED_FA17), epoch)


def straggler_sleep(mask_host, failure_duration: float, *, log=print,
                    tracer=None) -> None:
    """Optional host-side sleep preserving the reference's straggler timing.

    The reference sleeps inside the worker process (`:44`); here the epoch
    dispatch stalls for `failure_duration` seconds per failed EPOCH (one
    sleep total, however many devices failed), logging the same fail/wake
    lines per device. That matches the reference's observable wall-clock:
    its workers sleep CONCURRENTLY (each in its own process), so k
    simultaneous failures stall the epoch by one duration, not k - the
    per-device log lines describe who failed, not serialized stalls.

    `tracer` (utils/tracing.py Tracer) surfaces the stall as a
    ``straggler`` span on the ``fault`` track, so a Perfetto reader sees
    the dead time attributed to fault simulation instead of an
    unexplained gap between epochs (it is host wall time by construction
    - nothing is dispatched during the sleep).
    """
    if failure_duration <= 0.0:
        return
    failed = [d for d, live in enumerate(mask_host) if not live]
    if not failed:
        return
    for d in failed:
        log(
            f"Device {d} failed! Sleeping for {failure_duration} seconds."
        )
    if tracer is None:
        from ..utils import tracing as _tracing

        tracer = _tracing.NULL_TRACER
    with tracer.span(
        "straggler", track="fault", failed_devices=failed,
        duration_s=float(failure_duration),
    ):
        time.sleep(failure_duration)
    for d in failed:
        log(f"Device {d} woke up!")


# ---------------------------------------------------- step-level injectors


@dataclass(frozen=True)
class StepFaultPlan:
    """Compile-time plan for in-jit step faults (train/lm.py wires it into
    `make_lm_train_step(fault_plan=...)`; the step then requires the traced
    step index argument).

    nan_grads_at: step indices whose gradient tree is replaced with NaN
      AFTER the backward - the all-finite health flag drops and the 'skip'
      policy's in-jit `tree_where` must pass params/momentum through.
    spike_loss_at: step indices whose (reported) loss is multiplied by
      `spike_scale` inside the step - the EMA spike detector's in-band
      trigger. The gradients are left untouched (the simulated failure is
      a diverging loss signal, not a corrupted backward).
    nan_layer: restrict the NaN injection to gradient leaves whose
      `/`-joined tree path (parallel/rules.py named_leaves - the same
      paths the dynamics provenance reports) re.search-matches this
      pattern. The end-to-end provenance test: inject at a chosen layer,
      assert the guard names exactly that layer. None (default) NaNs the
      whole tree. The filter is trace-time static - un-matched leaves
      compile to the untouched gradient.
    """

    nan_grads_at: tuple = ()
    spike_loss_at: tuple = ()
    spike_scale: float = 100.0
    nan_layer: str | None = None

    def __post_init__(self):
        object.__setattr__(
            self, "nan_grads_at", tuple(int(s) for s in self.nan_grads_at)
        )
        object.__setattr__(
            self, "spike_loss_at", tuple(int(s) for s in self.spike_loss_at)
        )

    def __bool__(self):
        return bool(self.nan_grads_at or self.spike_loss_at)


def _at(step_i, steps: tuple):
    """Traced predicate: step_i is one of the (static) `steps`."""
    return (jnp.asarray(steps, jnp.int32) == jnp.asarray(step_i, jnp.int32)).any()


def inject_step_faults(step_i, loss, grads, plan: StepFaultPlan):
    """Apply `plan` to one step's (loss, grads) under jit/shard_map.

    `step_i` is the traced step index (invariant across the mesh), so the
    same fault fires on every device - no divergence. Returns (loss,
    grads) unchanged at un-listed steps; the fault-free program with an
    empty plan is the unmodified one (callers pass plan=None to compile
    nothing at all).
    """
    if plan.nan_grads_at:
        bad = _at(step_i, plan.nan_grads_at)
        if plan.nan_layer is None:
            grads = jax.tree.map(
                lambda g: jnp.where(bad, jnp.asarray(jnp.nan, g.dtype), g),
                grads,
            )
        else:
            # static per-leaf filter by the named_leaves path (the paths
            # the provenance reports): only matching leaves get the
            # traced where; the rest compile to the untouched gradient
            import re

            from .rules import named_leaves

            pat = re.compile(plan.nan_layer)
            flat = named_leaves(grads)
            if not any(pat.search(path) for path, _ in flat):
                raise ValueError(
                    f"nan_layer pattern {plan.nan_layer!r} matches no "
                    f"gradient leaf path (have: "
                    f"{[p for p, _ in flat]})"
                )
            leaves = [
                jnp.where(bad, jnp.asarray(jnp.nan, g.dtype), g)
                if pat.search(path)
                else g
                for path, g in flat
            ]
            grads = jax.tree.unflatten(
                jax.tree.structure(grads), leaves
            )
    if plan.spike_loss_at:
        spike = _at(step_i, plan.spike_loss_at)
        loss = jnp.where(spike, loss * plan.spike_scale, loss)
    return loss, grads


@dataclass
class ChaosMonkey:
    """Host-side chaos for the guard's observation path and the
    preemption handler - each listed fault fires EXACTLY ONCE, so a
    rollback that replays the step sees a healthy re-run (the transient
    model the rollback policy is designed for; in-jit `StepFaultPlan`
    faults, by contrast, recur on replay and exercise the retry budget).

    spike_at: step indices whose OBSERVED loss is multiplied by
      `spike_scale` before the guard sees it (plug `perturb` into
      `train/guard.py HealthPipe(perturb=...)`).
    sigterm_after: after this step completes, deliver a real SIGTERM to
      this process (`after_step`), driving the PreemptionGuard ->
      emergency-checkpoint -> exact-resume path end to end.
    stall_at: after these steps complete, sleep `stall_s` seconds inside
      the host step callback - the heartbeat stops while the loop is
      wedged, which is exactly the signature the stall watchdog
      (`train/monitor.py`) must flag as ``watchdog/stall`` within one
      detection window. Emitted as a ``straggler`` span on the ``fault``
      track when a tracer is attached (same in-band convention as the
      epoch-level straggler sleep above).
    shrink_at: after this step completes, raise a cooperative SHRINK
      preemption on the attached `preempt` guard (train/guard.py
      PreemptionGuard.request) - the elastic driver (`lm_train.py
      --chaos-shrink-at-step`) answers it by writing an emergency
      checkpoint, rebuilding the mesh from the surviving device subset,
      resharding params + optimizer state (parallel/reshard.py), and
      CONTINUING training - the full preempt -> checkpoint -> reshard ->
      resume path in one process.
    """

    spike_at: tuple = ()
    spike_scale: float = 100.0
    sigterm_after: int | None = None
    stall_at: tuple = ()
    stall_s: float = 2.0
    shrink_at: int | None = None
    preempt: object = None
    tracer: object = None
    log: object = print
    _fired: set = field(default_factory=set)

    def perturb(self, step, loss, grad_norm, all_finite):
        if step in self.spike_at and ("spike", step) not in self._fired:
            self._fired.add(("spike", step))
            self._flight("spike", step, scale=float(self.spike_scale))
            self.log(f"(chaos: spiking observed loss at step {step} "
                     f"x{self.spike_scale:g})")
            loss = loss * self.spike_scale
        return loss, grad_norm, all_finite

    @staticmethod
    def _flight(what, step, **fields):
        # chaos is exactly the event class a postmortem must show: the
        # injected fault sits in the ring right before the crash it causes
        from ..utils.obs import flight_event

        flight_event("chaos", step=int(step), what=what, **fields)

    def after_step(self, step) -> None:
        if step in self.stall_at and ("stall", step) not in self._fired:
            self._fired.add(("stall", step))
            self._flight("stall", step, seconds=float(self.stall_s))
            self.log(
                f"(chaos: stalling the step loop for {self.stall_s:g}s "
                f"after step {step})"
            )
            tracer = self.tracer
            if tracer is None:
                from ..utils import tracing as _tracing

                tracer = _tracing.NULL_TRACER
            with tracer.span(
                "straggler", track="fault", step=int(step),
                duration_s=float(self.stall_s), kind="stall",
            ):
                time.sleep(self.stall_s)
        if (
            self.shrink_at is not None
            and step == self.shrink_at
            and "shrink" not in self._fired
        ):
            self._fired.add("shrink")
            self._flight("shrink", step)
            self.log(
                f"(chaos: requesting SHRINK preemption after step {step})"
            )
            if self.preempt is not None:
                self.preempt.request("SHRINK")
        if (
            self.sigterm_after is not None
            and step == self.sigterm_after
            and "sigterm" not in self._fired
        ):
            self._fired.add("sigterm")
            self._flight("sigterm", step)
            self.log(f"(chaos: delivering SIGTERM after step {step})")
            os.kill(os.getpid(), _signal.SIGTERM)


# ------------------------------------------------ process-level injectors


@dataclass(frozen=True)
class KillEvent:
    """One process-level fault: deliver `sig` ('KILL' or 'TERM') to worker
    `rank` once its heartbeat reports step >= `at_step`. rank 0 is the
    process hosting the JAX coordinator service, so killing it is the
    coordinator-death scenario. Fires exactly once (the ChaosMonkey
    convention: the induced death is then handled - or not - by the
    supervisor's ordinary failure path)."""

    rank: int
    at_step: int = 0
    sig: str = "KILL"

    def __post_init__(self):
        if self.sig not in ("KILL", "TERM"):
            raise ValueError(
                f"KillEvent signal must be 'KILL' or 'TERM', got {self.sig!r}"
            )
        if self.rank < 0:
            raise ValueError(f"rank must be >= 0, got {self.rank}")

    @property
    def signum(self) -> int:
        return _signal.SIGKILL if self.sig == "KILL" else _signal.SIGTERM


@dataclass
class ProcessChaos:
    """Process-level fault plan driven by the SUPERVISOR
    (train/supervisor.py / tools/launch.py --chaos-kill-*), the real-OS
    sibling of the in-process ChaosMonkey: instead of perturbing an
    observation stream it actually kills group members - SIGKILL for a
    crash (no emergency checkpoint, the group restarts from the last
    periodic save), SIGTERM for a preemption notice (the worker's
    cooperative path writes its checkpoint first), rank 0 for coordinator
    death. The supervisor polls worker heartbeats and calls `due(steps)`
    each tick; every event fires once.
    """

    events: tuple = ()
    _fired: set = field(default_factory=set)

    def __post_init__(self):
        self.events = tuple(self.events)
        for e in self.events:
            if not isinstance(e, KillEvent):
                raise TypeError(f"ProcessChaos events must be KillEvent, got {e!r}")

    def __bool__(self):
        return bool(self.events)

    def due(self, steps: dict) -> list:
        """[(rank, signum)] for events whose rank has reached its step.

        `steps` maps rank -> last heartbeat step (None before the first
        beat). at_step=0 fires as soon as the rank heartbeats at all, so
        rendezvous itself can be chaos-tested.
        """
        out = []
        for i, e in enumerate(self.events):
            if i in self._fired or e.rank not in steps:
                continue
            step = steps[e.rank]
            if e.at_step <= 0 or (step is not None and step >= e.at_step):
                self._fired.add(i)
                out.append((e.rank, e.signum))
        return out
