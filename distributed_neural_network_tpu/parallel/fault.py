"""Fault injection: seeded per-epoch device failure simulation.

Parity with `simulate_failure` (`data_parallelism_train.py:41-46`): each
epoch, each worker fails independently with probability
`--failure-probability`. The reference implements failure as an unseeded
host `time.sleep(--failure-duration)` which - because the parent's recv
blocks - stalls the *whole* epoch (straggler semantics, never benchmarked per
report section 6.2). This build upgrades the capability (SURVEY.md
section 5.3): a failed device's contribution is dropped from the epoch's
parameter average (see `collectives.masked_pmean_tree`) and the run
continues; `--failure-duration` is preserved as an optional host-side sleep
so the original straggler wall-clock semantics remain reproducible.

All randomness is explicit JAX PRNG (the reference's `np.random.rand()` at
`:43` is unseeded - SURVEY.md section 5.2 calls for seeding as the fix).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp


def live_mask(key: jax.Array, n_devices: int, failure_probability: float):
    """(n_devices,) float32 {0,1}: 1 = device participates this epoch.

    Pure function of (key, p) - identical on every host/device, so the mask
    never needs broadcasting. p=0 (the reference default,
    `data_parallelism_train.py:266`) short-circuits to all-live without
    consuming randomness, keeping the fault-free path bit-identical whether
    or not fault simulation is compiled in.
    """
    if failure_probability <= 0.0:
        return jnp.ones((n_devices,), jnp.float32)
    fail = jax.random.bernoulli(key, failure_probability, (n_devices,))
    return (~fail).astype(jnp.float32)


def epoch_key(seed: int, epoch: int) -> jax.Array:
    """Deterministic per-epoch fault key, independent of the data PRNG stream."""
    return jax.random.fold_in(jax.random.key(seed ^ 0x5EED_FA17), epoch)


def straggler_sleep(mask_host, failure_duration: float, *, log=print) -> None:
    """Optional host-side sleep preserving the reference's straggler timing.

    The reference sleeps inside the worker process (`:44`); here the epoch
    dispatch stalls for `failure_duration` seconds per failed device's epoch
    if the caller opts in (duration > 0), logging the same fail/wake lines.
    """
    if failure_duration <= 0.0:
        return
    failed = [d for d, live in enumerate(mask_host) if not live]
    for d in failed:
        log(
            f"Device {d} failed! Sleeping for {failure_duration} seconds."
        )
    if failed:
        time.sleep(failure_duration)
        for d in failed:
            log(f"Device {d} woke up!")
