"""Lint rules over a StepProgram and its TraceFacts.

Four families, mirroring the failure classes that used to need real
hardware to surface (docs/STATIC_ANALYSIS.md):

- **spec lint** - every PartitionSpec in the program's wiring references
  only mesh axes that exist, never uses an axis twice, and shards only
  divisible dims (parallel/partition.py validators, applied to the
  abstract shapes).
- **donation audit** - the state arguments the builder promises to donate
  (params, optimizer state) are actually donated at the jit boundary, and
  every donated buffer has a shape/dtype-matching output XLA can alias
  (a donated-but-unaliasable arg silently doubles peak memory).
- **replication-leak check** - under the ZeRO overlap schedule the in-scan
  gradient accumulator must be O(D/dp): a full-size carry means the
  reduce-scatter sharding leaked back to replicated.
- **precision lint** - no f64 anywhere on the step (an accidental Python
  float promotion upcasts a whole tree); float upcasts (bf16->f32 etc.)
  are not errors but are pinned in the manifest, so growth fails --check.
  Quantized dtypes (int8 / fp8) are legal ONLY where the program declares
  them (``meta["quant"]``), and a declared-quantized step whose trace
  shows none is equally an error (the quantized path silently fell
  back). The fp8->f32 accumulate upcast of a quantized matmul is pinned
  in the manifest like every other upcast, so a silently-dropped wide
  accumulation fails ``--check``.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class Finding:
    severity: str  # "error" | "warn"
    code: str
    message: str

    def __str__(self):
        return f"[{self.severity}] {self.code}: {self.message}"


def lint_program(program, facts) -> list:
    """All lint findings for one traced program (errors first)."""
    findings = []
    findings += spec_lint(program)
    findings += donation_audit(program, facts)
    findings += replication_leak_lint(program, facts)
    findings += precision_lint(program, facts)
    findings += quantized_dtype_lint(program, facts)
    return sorted(findings, key=lambda f: (f.severity != "error", f.code))


# ------------------------------------------------------------- spec lint


def spec_lint(program) -> list:
    """Validate the program's PartitionSpec wiring against its mesh and
    abstract shapes (parallel/partition.py)."""
    from ..parallel.partition import validate_spec_tree

    mesh_axes = dict(program.mesh.shape)
    findings = []
    shaped = {
        "params": program.abstract_args[0] if program.abstract_args else None,
        "opt": program.abstract_args[1]
        if len(program.abstract_args) > 1 else None,
        "data": program.abstract_args[2]
        if len(program.abstract_args) > 2 else None,
    }
    for label, specs in (program.specs or {}).items():
        try:
            validate_spec_tree(
                specs, mesh_axes, shapes=shaped.get(label), root=label
            )
        except ValueError as e:
            findings.append(Finding("error", "spec-lint", str(e)))
    return findings


# -------------------------------------------------------- donation audit


def donation_audit(program, facts) -> list:
    """Donated-state coverage + XLA aliasability of every donated buffer."""
    findings = []
    donated = facts.donated_invars
    if donated is None:
        findings.append(
            Finding(
                "warn", "donation",
                f"{program.name}: no jit boundary with donated_invars found "
                "in the trace - donation cannot be audited",
            )
        )
        return findings
    import jax

    counts = program.arg_leaf_counts()
    if sum(counts) != len(donated):
        findings.append(
            Finding(
                "warn", "donation",
                f"{program.name}: trace has {len(donated)} flat args, the "
                f"program signature has {sum(counts)} - argument mapping "
                "out of sync, donation audit skipped",
            )
        )
        return findings
    offsets = [0]
    for c in counts:
        offsets.append(offsets[-1] + c)
    want = set(program.donate)
    for argnum, label in zip(
        range(len(counts)),
        list(program.donate_labels)
        + ["arg%d" % i for i in range(len(program.donate_labels), len(counts))],
    ):
        flags = donated[offsets[argnum]:offsets[argnum + 1]]
        if argnum in want and not all(flags):
            leaves = jax.tree_util.tree_flatten_with_path(
                program.abstract_args[argnum]
            )[0]
            bad = [
                jax.tree_util.keystr(p)
                for (p, _), f in zip(leaves, flags) if not f
            ]
            findings.append(
                Finding(
                    "error", "donation",
                    f"{program.name}: {label} (arg {argnum}) must be "
                    f"donated but {len(bad)}/{len(flags)} leaves are not "
                    f"(e.g. {bad[:3]}) - the step double-buffers its own "
                    "state; restore donate_argnums",
                )
            )
        if argnum not in want and any(flags):
            findings.append(
                Finding(
                    "warn", "donation",
                    f"{program.name}: arg {argnum} ({label}) is donated "
                    "but not part of the builder's donation contract",
                )
            )
    # aliasability: every donated input aval needs a matching output aval
    out_pool = {}
    for aval in facts.out_avals:
        if aval is not None and hasattr(aval, "shape"):
            key = (tuple(aval.shape), np.dtype(aval.dtype).name)
            out_pool[key] = out_pool.get(key, 0) + 1
    for flag, aval in zip(donated, facts.in_avals):
        if not flag or aval is None or not hasattr(aval, "shape"):
            continue
        key = (tuple(aval.shape), np.dtype(aval.dtype).name)
        if out_pool.get(key, 0) > 0:
            out_pool[key] -= 1
        else:
            # deliberate non-aliased donations exist (frees the buffer
            # early without in-place reuse - e.g. the engine's stacked
            # sync input); a program opts out of the error with
            # meta["expect_alias"] = False
            severity = (
                "error"
                if (program.meta or {}).get("expect_alias", True)
                else "warn"
            )
            findings.append(
                Finding(
                    severity, "donation-alias",
                    f"{program.name}: donated buffer {key[0]} {key[1]} has "
                    "no shape/dtype-matching output - XLA cannot alias it "
                    "in place (the donation only frees it early)",
                )
            )
    return findings


# -------------------------------------------------- replication-leak lint


def replication_leak_lint(program, facts) -> list:
    """ZeRO overlap schedule: the gradient-accumulation scan must carry the
    1/dp reduce-scattered shard, never the full O(D) tree."""
    meta = program.meta or {}
    if not (
        str(meta.get("optimizer", "")).startswith("zero")
        and meta.get("grad_sync") == "overlap"
        and int(meta.get("accum_steps", 1)) > 1
    ):
        return []
    dp = int(meta.get("dp", 1))
    d_bytes = program.param_bytes()
    carry = facts.reduce_scatter_carry_bytes
    if carry is None:
        return [
            Finding(
                "error", "zero-leak",
                f"{program.name}: optimizer={meta.get('optimizer')!r} with "
                "grad_sync='overlap' but no scan with an in-body "
                "reduce_scatter was found - the ZeRO shard-carry schedule "
                "is not running",
            )
        ]
    # shard carry ~= D/dp (+ per-bucket ceil padding + the loss scalar);
    # anything at half the full tree or more means the sharding leaked
    if carry >= d_bytes // 2 and dp > 1:
        return [
            Finding(
                "error", "zero-leak",
                f"{program.name}: in-scan gradient accumulator carries "
                f"{carry:,} B but the full parameter tree is only "
                f"{d_bytes:,} B (dp={dp}) - the ZeRO reduce-scatter carry "
                f"should be ~{d_bytes // max(dp, 1):,} B; a full-size "
                "intermediate has leaked into the scan",
            )
        ]
    return []


# --------------------------------------------------------- precision lint


def quantized_dtype_lint(program, facts) -> list:
    """int8/fp8 values are legal only in DECLARED quantized programs
    (``meta["quant"]`` - lm_step_program sets it from
    ``TransformerConfig.attn_quant``), and a declared program must
    actually show them: both directions of drift - an accidental
    low-precision cast sneaking into a full-precision step, and a
    quantized config whose fast path silently fell back to bf16 -
    fail statically."""
    declared = (program.meta or {}).get("quant")
    seen = getattr(facts, "quant_dtypes", None) or {}
    if seen and not declared:
        kinds = ", ".join(
            f"{k} x{v}" for k, v in sorted(seen.items())
        )
        return [
            Finding(
                "error", "quant-undeclared",
                f"{program.name}: quantized dtypes in the step ({kinds}) "
                "but the program declares no quantization "
                "(meta['quant']) - an accidental low-precision cast "
                "loses mantissa silently; declare attn_quant or remove "
                "the cast",
            )
        ]
    if declared and not seen:
        return [
            Finding(
                "error", "quant-missing",
                f"{program.name}: declared quant={declared!r} but the "
                "trace contains no int8/fp8 values - the quantized "
                "path silently fell back to full precision (the fast "
                "path is not running)",
            )
        ]
    return []


def precision_lint(program, facts) -> list:
    if facts.f64_sites:
        return [
            Finding(
                "error", "precision-f64",
                f"{program.name}: {facts.f64_sites} float64 value(s) in "
                "the step - an accidental f32->f64 promotion doubles "
                "bytes and runs off the MXU; cast the offending constant "
                "or disable x64",
            )
        ]
    return []
