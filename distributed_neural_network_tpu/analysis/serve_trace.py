"""servelint: static audit + cost model of the serving bucket programs.

The serving engine (serve/engine.py) compiles a GRID of jitted programs
- decode / chunked-prefill / speculative draft+verify families, each
over power-of-two (batch, table-width) buckets - and ``warmup()``
pre-compiles all of them so no live request ever pays an XLA compile.
Nothing guarded that grid statically: a dropped KV-pool donation
doubles the engine's largest allocation, a silent bf16->f32 upcast
doubles a bucket's bytes, and an accidental new bucket dimension
multiplies compile count - all invisible until a runtime regression.

This module is the serve-side mirror of the shardlint pipeline
(trace -> lint -> manifest -> CI check, docs/STATIC_ANALYSIS.md):

- ``enumerate_grid(ecfg)`` reproduces warmup()'s compile set from an
  `EngineConfig` alone - pinned equal to the engine's actual fn-cache
  keys by test (cache-miss counting, tests/test_servelint.py);
- ``bucket_programs`` wraps every grid entry as a `ServeBucketProgram`
  whose jaxpr ``jax.make_jaxpr`` traces abstractly (ShapeDtypeStruct
  args - no pools materialize, no execution);
- the shardlint walker (trace.collect_trace) audits donation
  (pools + int8 scales MUST be donated in decode/prefill/verify;
  params must NEVER be; the read-only drafter is exempt), upcasts,
  and quantized-dtype declarations (the PR 13 quant pin), while
  ``collect_serve_costs`` walks the same jaxpr for FLOPs and
  gather/scatter traffic (the paged addressing);
- ``build_serve_manifest`` pins per-bucket facts + the grid itself
  into analysis/manifests/serve_<config>.json; ``--check`` re-traces
  and diffs, naming the bucket and the fact that moved;
- the per-bucket bytes/flops feed ``cost.serve_tick_seconds`` (the
  HardwareModel roofline) and ``cost.serve_capacity`` - static
  tokens/s, prefill TTFT, and KV-capacity figures the fleet twin
  (analysis/fleetsim.py) and the autoscaler can consume as a capacity
  planner, validated against the measured ``measure_serving`` bench
  row by ``tools/servelint.py --validate``.

HBM byte convention (documented so manifests are comparable): per call,
``hbm_bytes = weight_bytes + gather out-bytes + scatter update-bytes +
non-pool I/O bytes``. Weights stream once per call (the layer scan
reads every layer's slice exactly once); the paged pools are charged
by what the program actually touches - the gathered table span and the
scattered updates - never by pool size, which is what makes a paged
decode step memory-cheap in the first place. Elementwise FLOPs are
excluded (matmul-dominated programs; ``flops`` counts dot_general
only, scan multiplicity folded in).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from .trace import _aval_bytes, _sub_jaxprs

SERVE_MANIFEST_SCHEMA = 1

# tiny trace geometry: structure is what manifests pin, so the canonical
# serve configs trace a minimal dense model (mirrors configs.py TRACE_*)
SERVE_TRACE_MODEL = dict(
    vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
)
# decode_impl pinned "xla": the pallas route is backend-dependent
# (auto only takes it on TPU), and a manifest must trace identically
# on the CPU CI host and a dev TPU
SERVE_TRACE_ENGINE = dict(
    max_batch=4, num_blocks=9, block_size=4, max_seq_len=32,
    prefill_chunk=4, decode_impl="xla",
)


@dataclass(frozen=True)
class ServeConfigSpec:
    """One canonical serve config: model geometry + engine knobs +
    the declared quantization (lint.py quantized_dtype_lint)."""

    name: str
    model: dict
    engine: dict
    quant: str | None = None
    note: str = ""


def _spec(name, quant=None, note="", **engine_overrides):
    return ServeConfigSpec(
        name=name,
        model=dict(SERVE_TRACE_MODEL),
        engine={**SERVE_TRACE_ENGINE, **engine_overrides},
        quant=quant,
        note=note,
    )


SERVE_CONFIGS = {
    "serve_bf16": _spec(
        "serve_bf16",
        note="bf16 pool + weights: the PR 12 baseline engine",
    ),
    "serve_int8_kv": _spec(
        "serve_int8_kv", kv_dtype="int8", quant="int8-kv",
        note="quantized KV pool (per-(block, head) f32 scales donated "
        "with it)",
    ),
    "serve_int8_w": _spec(
        "serve_int8_w", weight_dtype="int8", quant="int8-w",
        note="int8 weights (ops/quant.py prequantized codes + scales)",
    ),
    "serve_spec_k4": _spec(
        "serve_spec_k4", spec_decode=4, spec_draft_layers=1,
        note="speculative decoding: draft (read-only) + 5-position "
        "verify families ride the same grid",
    ),
}


def serve_config_names() -> list:
    return list(SERVE_CONFIGS)


# ------------------------------------------------------ grid enumeration


def _pow2s(limit: int) -> list:
    out, v = [], 1
    while v <= limit:
        out.append(v)
        v *= 2
    return out


def enumerate_grid(ecfg, *, max_width_blocks: int | None = None) -> dict:
    """The bucket grid ``warmup()`` compiles, from the `EngineConfig`
    alone - family -> [(bucket key), ...]. MUST mirror
    serve/engine.py warmup() exactly; the equality is pinned by
    cache-miss counting in tests/test_servelint.py (serving after
    warmup compiles zero new programs for every canonical config)."""
    from ..serve.engine import _bucket

    kv = ecfg.kv()
    widths = _pow2s(_bucket(max_width_blocks or kv.max_blocks_per_seq))
    batches = _pow2s(ecfg.max_batch)
    grid = {"decode": [(B, W) for B in batches for W in widths]}
    if ecfg.prefill_chunk > 1:
        grid["prefill"] = [
            (C, W)
            for C in _pow2s(ecfg.prefill_chunk)
            for W in widths
            if C <= W * ecfg.block_size
        ]
    if ecfg.spec_decode:
        grid["draft"] = [(B, W) for B in batches for W in widths]
        grid["verify"] = [(B, W) for B in batches for W in widths]
    return grid


def grid_total(grid: dict) -> int:
    return sum(len(v) for v in grid.values())


# --------------------------------------------------------- the programs


class _HostMesh:
    """Serve programs are single-device; lint's mesh interface reduces
    to an empty axis dict."""

    shape: dict = {}


@dataclass
class ServeBucketProgram:
    """One bucket's jitted program + enough structure for the shardlint
    lint families (duck-types train/program.py StepProgram)."""

    name: str
    family: str
    bucket: tuple
    fn: object
    abstract_args: tuple
    donate: tuple
    donate_labels: tuple
    meta: dict
    specs: dict = field(default_factory=dict)
    mesh: object = field(default_factory=_HostMesh)

    def make_jaxpr(self):
        import jax

        return jax.make_jaxpr(self.fn)(*self.abstract_args)

    def arg_leaf_counts(self) -> list:
        import jax

        return [
            len(jax.tree_util.tree_leaves(a)) for a in self.abstract_args
        ]

    def param_bytes(self) -> int:
        import jax

        return sum(
            int(np.prod(leaf.shape, dtype=np.int64))
            * np.dtype(leaf.dtype).itemsize
            for leaf in jax.tree_util.tree_leaves(self.abstract_args[0])
            if hasattr(leaf, "shape")
        )


def build_serve_engine(name_or_spec):
    """A real (tiny) engine for one canonical serve config: the bucket
    closures live on the engine, so tracing borrows them from exactly
    the object production serves with. Seeded params at trace geometry
    - tracing never looks at values, but int8-w prequantization needs
    real arrays to code."""
    import jax
    import jax.numpy as jnp

    from ..models.transformer import TransformerConfig, init_params
    from ..serve.engine import EngineConfig, ServeEngine

    spec = (
        SERVE_CONFIGS[name_or_spec]
        if isinstance(name_or_spec, str) else name_or_spec
    )
    cfg = TransformerConfig(dtype=jnp.bfloat16, **spec.model)
    params = init_params(jax.random.PRNGKey(0), cfg)
    engine = ServeEngine(params, cfg, EngineConfig(**spec.engine))
    return engine, spec


def _sds_tree(tree):
    import jax

    return jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a), a.dtype), tree
    )


def bucket_program(engine, family: str, key: tuple, *,
                   config: str = "serve", quant: str | None = None,
                   probe: str | None = None) -> ServeBucketProgram:
    """Wrap one (family, bucket) of a live engine as a traceable
    program: the engine's own jitted closure + ShapeDtypeStruct args
    mirroring warmup()'s call shapes. ``probe`` injects a known defect
    for acceptance testing ('drop-donation' re-jits the bucket without
    donate_argnums; 'upcast' adds a silent bf16->f32 round-trip on the
    pool output) - tools/servelint.py --probe, the CI probe legs."""
    import jax
    import jax.numpy as jnp

    i32, f32, u32 = jnp.int32, jnp.float32, jnp.uint32
    q = engine.quantized
    params = _sds_tree(
        engine.draft_params if family == "draft" else engine.params
    )
    pools = (_sds_tree(engine.k_pool), _sds_tree(engine.v_pool))
    scales = (
        (_sds_tree(engine.k_scale), _sds_tree(engine.v_scale)) if q else ()
    )

    def sds(shape, dt):
        return jax.ShapeDtypeStruct(shape, dt)

    if family == "decode":
        B, W = key
        fn = engine._decode_fn(B, W)
        tail = (
            sds((B,), i32), sds((B,), i32), sds((B, W), i32),
            sds((B,), f32), sds((B, 2), u32),
        )
        label = f"decode[B{B},W{W}]"
    elif family == "prefill":
        C, W = key
        fn = engine._prefill_fn(C, W)
        tail = (
            sds((C,), i32), sds((), i32), sds((W,), i32), sds((), i32),
        )
        label = f"prefill[C{C},W{W}]"
    elif family == "draft":
        B, W = key
        fn = engine._draft_fn(B, W)
        tail = (sds((B,), i32), sds((B,), i32), sds((B, W), i32))
        label = f"draft[B{B},W{W}]"
    elif family == "verify":
        B, W = key
        K = engine.spec_k + 1
        fn = engine._verify_fn(B, W)
        tail = (sds((B, K), i32), sds((B,), i32), sds((B, W), i32))
        label = f"verify[B{B},W{W}]"
    else:
        raise ValueError(f"unknown bucket family {family!r}")

    donate = () if family == "draft" else (1, 2, 3, 4) if q else (1, 2)
    labels = ("params", "k_pool", "v_pool") + (
        ("k_scale", "v_scale") if q else ()
    )
    if probe == "drop-donation" and family != "draft":
        # an outer jit swallows the inner boundary's donated_invars:
        # exactly what a refactor that loses donate_argnums looks like
        inner = fn
        fn = jax.jit(lambda *a: inner(*a))
    elif probe == "upcast" and family != "draft":
        # a silent widen-and-narrow round trip on the first floating
        # output (bf16 -> f32 -> bf16, or f32 -> bf16 -> f32 for the
        # int8 configs whose pool is not float): numerically a no-op
        # in shape/dtype, but the widening convert is exactly what the
        # manifest's upcast pin exists to catch
        inner = fn

        def fn(*a, _inner=inner):
            out = list(_inner(*a))
            for i, o in enumerate(out):
                if not jnp.issubdtype(o.dtype, jnp.floating):
                    continue
                if o.dtype == jnp.float32:
                    out[i] = o.astype(jnp.bfloat16).astype(jnp.float32)
                else:
                    out[i] = o.astype(jnp.float32).astype(o.dtype)
                break
            return tuple(out)

    return ServeBucketProgram(
        name=f"{config}:{label}",
        family=family,
        bucket=tuple(key),
        fn=fn,
        abstract_args=(params,) + pools + scales + tail,
        donate=donate,
        donate_labels=labels,
        meta={
            "family": family,
            "bucket": list(key),
            "kv_dtype": engine.kv_dtype_name(),
            "weight_dtype": engine.weight_dtype_name(),
            "quant": quant,
            "serve": True,
        },
    )


def bucket_programs(engine, *, config: str = "serve",
                    quant: str | None = None, probe: str | None = None,
                    max_width_blocks: int | None = None) -> list:
    """Every program of the engine's warmup grid, enumeration order
    (the order ``warmup()`` compiles them in)."""
    if probe == "extra-bucket":
        # simulate an accidental grid dimension: one more width octave
        # than max_seq_len needs -> every family grows a bucket column
        max_width_blocks = 2 * engine.kv.cfg.max_blocks_per_seq
    grid = enumerate_grid(
        engine.ecfg, max_width_blocks=max_width_blocks
    )
    return [
        bucket_program(
            engine, fam, key, config=config, quant=quant,
            probe=probe,
        )
        for fam, keys in grid.items()
        for key in keys
    ]


# ------------------------------------------------- serve-side cost walk


@dataclass
class ServeCosts:
    """Per-call compute/traffic facts of one bucket program (static
    multiplicity folded in, scan trip counts included)."""

    flops: int = 0              # dot_general only (2*M*N*K convention)
    gather_count: int = 0       # paged reads (gather + dynamic_slice)
    gather_bytes: int = 0       # gathered output bytes
    scatter_count: int = 0      # paged writes (scatter* + dyn. update)
    scatter_bytes: int = 0      # scattered update bytes
    weight_bytes: int = 0       # abstract param-tree bytes (as stored)
    io_bytes: int = 0           # non-pool, non-param boundary traffic

    @property
    def hbm_bytes(self) -> int:
        """The documented per-call HBM traffic model (module docstring):
        weights stream once, pools are charged by touched bytes only."""
        return (
            self.weight_bytes + self.gather_bytes + self.scatter_bytes
            + self.io_bytes
        )


def _dot_flops(eqn) -> int:
    (lhs_c, _), _ = eqn.params["dimension_numbers"]
    out = getattr(eqn.outvars[0], "aval", None)
    lhs = getattr(eqn.invars[0], "aval", None)
    if out is None or lhs is None:
        return 0
    contracted = 1
    for d in lhs_c:
        contracted *= int(lhs.shape[d])
    return 2 * int(np.prod(out.shape, dtype=np.int64)) * contracted


def collect_serve_costs(closed_jaxpr, program=None) -> ServeCosts:
    """Walk a bucket program's jaxpr for FLOPs and gather/scatter
    traffic, multiplying through scan trip counts like the shardlint
    walker. Purely structural - nothing executes."""
    import jax

    costs = ServeCosts()

    def walk(jaxpr, mult: int):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name == "dot_general":
                costs.flops += mult * _dot_flops(eqn)
            elif name in ("gather", "dynamic_slice"):
                costs.gather_count += mult
                costs.gather_bytes += mult * sum(
                    _aval_bytes(v) for v in eqn.outvars
                )
            elif name.startswith("scatter") or name == "dynamic_update_slice":
                costs.scatter_count += mult
                # invars = (operand, indices, updates): charge the
                # written update bytes, never the whole operand
                upd = eqn.invars[-1]
                costs.scatter_bytes += mult * _aval_bytes(upd)
            if name == "scan":
                walk(
                    eqn.params["jaxpr"].jaxpr,
                    mult * int(eqn.params["length"]),
                )
            else:
                for sub, _ in _sub_jaxprs(eqn):
                    if name != "scan":
                        walk(sub, mult)

    walk(closed_jaxpr.jaxpr, 1)

    if program is not None:
        costs.weight_bytes = program.param_bytes()
        # pool args by POSITION (donate_labels covers params + pools +
        # scales positionally), independent of donation - the read-only
        # drafter's pool inputs are still pool traffic, not I/O
        pool_args = {
            i for i, lab in enumerate(program.donate_labels)
            if lab != "params"
        }
        pool_keys: dict = {}
        pool_bytes = 0
        total_in = 0
        for i, arg in enumerate(program.abstract_args):
            b = 0
            for leaf in jax.tree_util.tree_leaves(arg):
                if not hasattr(leaf, "shape"):
                    continue
                b += (
                    int(np.prod(leaf.shape, dtype=np.int64))
                    * np.dtype(leaf.dtype).itemsize
                )
                if i in pool_args:
                    key = (tuple(leaf.shape), np.dtype(leaf.dtype).name)
                    pool_keys[key] = pool_keys.get(key, 0) + 1
            total_in += b
            if i in pool_args:
                pool_bytes += b
        # outputs: a pool-shaped output rides out in place (donated
        # alias); everything else - logits / next tokens / drafts - is
        # boundary traffic
        out_bytes = 0
        for v in closed_jaxpr.jaxpr.outvars:
            aval = getattr(v, "aval", None)
            if aval is not None and hasattr(aval, "shape"):
                key = (
                    tuple(aval.shape),
                    np.dtype(getattr(aval, "dtype", np.float32)).name,
                )
                if pool_keys.get(key, 0) > 0:
                    pool_keys[key] -= 1
                    continue
            out_bytes += _aval_bytes(v)
        costs.io_bytes = (
            max(0, total_in - costs.weight_bytes - pool_bytes) + out_bytes
        )
    return costs


# ------------------------------------------------------------ manifests


def serve_manifest_name(config: str) -> str:
    return config if config.startswith("serve_") else f"serve_{config}"


def build_serve_manifest(config: str, engine, results: list) -> dict:
    """The manifest document for one serve config: the grid itself,
    per-bucket facts, and the derived capacity block (informational -
    diff_serve_manifests compares facts, not prices)."""
    import jax

    from .. import compat
    from .cost import HARDWARE_MODELS, serve_capacity

    ecfg, kv = engine.ecfg, engine.kv.cfg
    grid = {
        fam: sorted({tuple(r["bucket"]) for r in results
                     if r["family"] == fam})
        for fam in {r["family"] for r in results}
    }
    doc = {
        "schema": SERVE_MANIFEST_SCHEMA,
        "config": config,
        "jax_version": jax.__version__,
        "trace_mode": compat.trace_mode(),
        "model": {
            "d_model": engine.cfg.d_model,
            "n_layers": engine.cfg.n_layers,
            "n_heads": engine.cfg.n_heads,
            "head_dim": engine.cfg.head_dim,
            "d_ff": engine.cfg.d_ff,
            "vocab_size": engine.cfg.vocab_size,
        },
        "engine": {
            "max_batch": ecfg.max_batch,
            "num_blocks": ecfg.num_blocks,
            "block_size": ecfg.block_size,
            "max_seq_len": ecfg.max_seq_len,
            "prefill_chunk": ecfg.prefill_chunk,
            "kv_dtype": ecfg.kv_dtype,
            "weight_dtype": ecfg.weight_dtype,
            "spec_decode": ecfg.spec_decode,
            "decode_impl": ecfg.decode_impl,
        },
        "kv": {
            "usable_blocks": kv.usable_blocks,
            "max_blocks_per_seq": kv.max_blocks_per_seq,
            "pool_slots": kv.pool_slots,
        },
        "weight_bytes": results[0]["weight_bytes"] if results else 0,
        "grid": {
            fam: [list(b) for b in buckets]
            for fam, buckets in sorted(grid.items())
        },
        "programs_total": len(results),
        "buckets": sorted(
            results, key=lambda r: (r["family"], r["bucket"])
        ),
    }
    # derived pricing (excluded from --check: pure arithmetic over the
    # pinned facts at a named hardware model - the capacity planner's
    # and fleetsim's consumable view)
    doc["capacity"] = {
        hw: serve_capacity(doc, HARDWARE_MODELS[hw])
        for hw in ("tpu-v5e", "cpu-host")
    }
    return doc


def bucket_doc(program, facts, costs) -> dict:
    donated = facts.donated_invars
    return {
        "family": program.family,
        "bucket": list(program.bucket),
        "name": program.name,
        "flops": int(costs.flops),
        "hbm_bytes": int(costs.hbm_bytes),
        "weight_bytes": int(costs.weight_bytes),
        "io_bytes": int(costs.io_bytes),
        "gather": {
            "count": int(costs.gather_count),
            "bytes": int(costs.gather_bytes),
        },
        "scatter": {
            "count": int(costs.scatter_count),
            "bytes": int(costs.scatter_bytes),
        },
        "upcasts": {k: dict(v) for k, v in sorted(facts.upcasts.items())},
        "quant_dtypes": {
            k: int(v) for k, v in sorted(facts.quant_dtypes.items())
        },
        "donation": {
            "argnums": list(program.donate),
            "n_donated": int(sum(donated)) if donated is not None else None,
            "n_args": len(donated) if donated is not None else None,
        },
    }


def serve_manifest_path(config: str, manifest_dir: str | None = None) -> str:
    from .manifest import manifest_path

    return manifest_path(serve_manifest_name(config), manifest_dir)


def save_serve_manifest(doc, config, manifest_dir=None) -> str:
    from .manifest import save_manifest

    return save_manifest(doc, serve_manifest_name(config), manifest_dir)


def load_serve_manifest(config, manifest_dir=None) -> dict:
    import json
    import os

    path = serve_manifest_path(config, manifest_dir)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"no serve manifest for config {config!r} at {path} - "
            f"generate one with: python tools/servelint.py --config "
            f"{config} --write-manifest"
        )
    with open(path) as f:
        return json.load(f)


def _fmt_bucket(fam: str, bucket) -> str:
    dims = "C" if fam == "prefill" else "B"
    return f"{fam}[{dims}{bucket[0]},W{bucket[1]}]"


def diff_serve_manifests(expected: dict, actual: dict) -> list:
    """Human-actionable differences (empty == conforming). Environment
    mismatches short-circuit with a regenerate instruction; the bucket
    GRID is diffed first (the budget lint: an accidental new bucket
    dimension names the exact buckets that appeared), then per-bucket
    facts - flops, HBM bytes, gather/scatter traffic, upcasts,
    quantized dtypes, and the donation contract."""
    msgs = []
    for key in ("jax_version", "trace_mode"):
        if expected.get(key) != actual.get(key):
            return [
                f"serve manifest for {expected.get('config')!r} was "
                f"written under {key}={expected.get(key)!r} but this "
                f"run has {key}={actual.get(key)!r}: traces are not "
                "comparable across jax generations - regenerate with "
                "--write-manifest (docs/STATIC_ANALYSIS.md)"
            ]
    for key in ("model", "engine"):
        if expected.get(key) != actual.get(key):
            return [
                f"{key} geometry mismatch: manifest {expected.get(key)} "
                f"vs traced {actual.get(key)} - regenerate or fix the "
                "config"
            ]
    # --- the bucket-grid budget lint
    eg = {
        (fam, tuple(b))
        for fam, buckets in (expected.get("grid") or {}).items()
        for b in buckets
    }
    ag = {
        (fam, tuple(b))
        for fam, buckets in (actual.get("grid") or {}).items()
        for b in buckets
    }
    for fam, b in sorted(ag - eg):
        msgs.append(
            f"EXTRA bucket not in manifest grid: {_fmt_bucket(fam, b)} "
            "- a new bucket dimension compiles un-warmed programs "
            "(compile-count budget grew)"
        )
    for fam, b in sorted(eg - ag):
        msgs.append(
            f"MISSING bucket from manifest grid: {_fmt_bucket(fam, b)} "
            "- warmup() no longer compiles it; live traffic at this "
            "shape would pay a first-request XLA compile"
        )
    ep = expected.get("programs_total")
    ap = actual.get("programs_total")
    if ep != ap:
        msgs.append(
            f"compiled-program budget changed: manifest {ep} vs "
            f"traced {ap} programs"
        )
    # --- per-bucket facts, on the buckets both sides know
    exp = {
        (r["family"], tuple(r["bucket"])): r
        for r in expected.get("buckets", [])
    }
    act = {
        (r["family"], tuple(r["bucket"])): r
        for r in actual.get("buckets", [])
    }
    for key in sorted(set(exp) & set(act)):
        e, a = exp[key], act[key]
        label = _fmt_bucket(*key)
        for fact in ("flops", "hbm_bytes"):
            if e.get(fact) != a.get(fact):
                msgs.append(
                    f"{label}: {fact} changed "
                    f"{e.get(fact):,} -> {a.get(fact):,}"
                )
        for fact in ("gather", "scatter"):
            if e.get(fact) != a.get(fact):
                msgs.append(
                    f"{label}: {fact} traffic changed "
                    f"{e.get(fact)} -> {a.get(fact)} (the paged "
                    "addressing moved)"
                )
        if e.get("upcasts") != a.get("upcasts"):
            msgs.append(
                f"{label}: dtype upcasts changed: manifest "
                f"{e.get('upcasts')} vs traced {a.get('upcasts')} - a "
                "silent widen doubles the bucket's bytes"
            )
        if (e.get("quant_dtypes") or {}) != (a.get("quant_dtypes") or {}):
            msgs.append(
                f"{label}: quantized dtypes changed: manifest "
                f"{e.get('quant_dtypes') or '{}'} vs traced "
                f"{a.get('quant_dtypes') or '{}'} - the low-precision "
                "contract moved (lint codes quant-undeclared / "
                "quant-missing)"
            )
        if e.get("donation") != a.get("donation"):
            msgs.append(
                f"{label}: donation contract changed: manifest "
                f"{e.get('donation')} vs traced {a.get('donation')} - "
                "an un-donated KV pool double-buffers the engine's "
                "largest allocation"
            )
    return msgs


# -------------------------------------------------------------- pricing


def static_decode_tokens_per_s(engine, hw="cpu-host") -> dict:
    """Static steady-state decode throughput of a LIVE engine's full
    decode bucket (max batch x max table width), priced on the
    HardwareModel roofline - the ``static_predicted_tokens_per_s``
    column measure_serving attaches next to the measured figure, and
    the quantity ``tools/servelint.py --validate`` gates."""
    from ..serve.engine import _bucket
    from .cost import HARDWARE_MODELS, serve_tick_seconds
    from .trace import collect_trace

    hw = HARDWARE_MODELS[hw] if isinstance(hw, str) else hw
    # the largest grid bucket: widest pow2 batch warmup compiles
    B = max(_pow2s(engine.ecfg.max_batch))
    W = _bucket(engine.kv.cfg.max_blocks_per_seq)
    program = bucket_program(engine, "decode", (B, W))
    traced = program.make_jaxpr()
    costs = collect_serve_costs(traced, program)
    facts = collect_trace(traced)
    tick = serve_tick_seconds(
        {"flops": costs.flops, "hbm_bytes": costs.hbm_bytes}, hw
    )
    return {
        "bucket": [B, W],
        "hw": hw.name,
        "tick_s": tick.step_s,
        "tokens_per_s": B / tick.step_s,
        "bound": tick.bound,
        "flops": int(costs.flops),
        "hbm_bytes": int(costs.hbm_bytes),
        "donated": (
            int(sum(facts.donated_invars))
            if facts.donated_invars is not None else None
        ),
    }


# --------------------------------------------------------------- driver


@dataclass
class ServeAnalysis:
    program: object
    facts: object
    costs: object
    findings: list

    @property
    def errors(self):
        return [f for f in self.findings if f.severity == "error"]


def analyze_serve_program(program) -> ServeAnalysis:
    from .lint import lint_program
    from .trace import _np_dtype, _quant_dtype_name, collect_trace

    traced = program.make_jaxpr()
    facts = collect_trace(traced)
    # the trace walker counts quantized EQN OUTPUTS (values produced in
    # the step) - enough for int8-kv, whose appends emit int8 codes.
    # int8-w is the dual: the codes arrive as INPUTS (prequantized
    # weights) and are only ever dequantized in-step, so fold the
    # quantized input avals in too or the quant pin would miss them
    for aval in facts.in_avals:
        q = _quant_dtype_name(_np_dtype(getattr(aval, "dtype", None)))
        if q is not None:
            facts.quant_dtypes[q] = facts.quant_dtypes.get(q, 0) + 1
    costs = collect_serve_costs(traced, program)
    return ServeAnalysis(
        program=program,
        facts=facts,
        costs=costs,
        findings=lint_program(program, facts),
    )


def run_servelint(
    names=None,
    *,
    mode: str = "lint",
    manifest_dir: str | None = None,
    verbose: bool = True,
    explain: bool = False,
    probe: str | None = None,
    hw: str = "cpu-host",
):
    """Analyze serve configs; mode 'lint' / 'write' / 'check' (shardlint
    house semantics). Returns (exit_code, report): 0 conforming, 1
    findings or manifest mismatch, 2 a config could not be built or
    traced. ``probe`` injects a known defect ('drop-donation',
    'upcast', 'extra-bucket') so the failure path itself is testable -
    the CI probe leg asserts rc 1 with the bucket named."""
    import time

    from .cost import HARDWARE_MODELS, serve_tick_seconds

    if mode not in ("lint", "write", "check"):
        raise ValueError(f"mode must be lint/write/check, got {mode!r}")
    if probe not in (None, "drop-donation", "upcast", "extra-bucket"):
        raise ValueError(f"unknown probe {probe!r}")
    names = list(names) if names else serve_config_names()
    hwm = HARDWARE_MODELS[hw]
    lines = []
    worst = 0

    def fail(rc):
        nonlocal worst
        worst = max(worst, rc)

    for name in names:
        t0 = time.perf_counter()
        try:
            engine, spec = build_serve_engine(name)
            programs = bucket_programs(
                engine, config=name, quant=spec.quant, probe=probe
            )
            results = [analyze_serve_program(p) for p in programs]
        except Exception as e:
            fail(2)
            lines.append(f"{name}: TRACE FAILED - {type(e).__name__}: {e}")
            continue
        dt = time.perf_counter() - t0
        docs = [
            bucket_doc(r.program, r.facts, r.costs) for r in results
        ]
        manifest = build_serve_manifest(name, engine, docs)
        fams = {}
        for p in programs:
            fams[p.family] = fams.get(p.family, 0) + 1
        full = max(
            (r for r in results if r.program.family == "decode"),
            key=lambda r: r.program.bucket,
        )
        tick = serve_tick_seconds(
            {"flops": full.costs.flops, "hbm_bytes": full.costs.hbm_bytes},
            hwm,
        )
        n_findings = sum(len(r.findings) for r in results)
        fb, fw = full.program.bucket
        lines.append(
            f"{name}: {len(programs)} bucket program(s) ("
            + ", ".join(f"{k} {v}" for k, v in sorted(fams.items()))
            + f"), {n_findings} finding(s); full decode bucket "
            f"[B{fb},W{fw}] ticks {tick.step_s * 1e3:.3f} ms on "
            f"{hwm.name} ({fb / tick.step_s:,.0f} tok/s static) "
            f"[{dt:.1f}s]"
        )
        if explain:
            lines.append(
                f"    {'bucket':<16} {'flops':>12} {'hbm B':>12} "
                f"{'gathers':>8} {'scatters':>9} {'tick ms':>9}"
            )
            for r in results:
                t = serve_tick_seconds(
                    {
                        "flops": r.costs.flops,
                        "hbm_bytes": r.costs.hbm_bytes,
                    },
                    hwm,
                )
                lines.append(
                    f"    {_fmt_bucket(r.program.family, r.program.bucket):<16} "
                    f"{r.costs.flops:>12,} {r.costs.hbm_bytes:>12,} "
                    f"{r.costs.gather_count:>8} "
                    f"{r.costs.scatter_count:>9} "
                    f"{t.step_s * 1e3:>9.3f}"
                )
        for r in results:
            for f in r.findings:
                lines.append(f"    {f}")
        if any(r.errors for r in results):
            fail(1)
        if mode == "write":
            if any(r.errors for r in results):
                lines.append(
                    f"    {name}: NOT writing manifest while lint "
                    "errors are outstanding"
                )
            else:
                path = save_serve_manifest(manifest, name, manifest_dir)
                lines.append(f"    wrote {path}")
        elif mode == "check":
            try:
                expected = load_serve_manifest(name, manifest_dir)
            except FileNotFoundError as e:
                fail(1)
                lines.append(f"    {e}")
                continue
            diffs = diff_serve_manifests(expected, manifest)
            if diffs:
                fail(1)
                lines.append(f"    {name}: MANIFEST MISMATCH:")
                lines.extend(f"      - {d}" for d in diffs)
            else:
                lines.append(
                    f"    manifest conforms "
                    f"({serve_manifest_name(name)}.json)"
                )
    status = {0: "OK", 1: "FAIL", 2: "TRACE ERROR"}[worst]
    lines.append(f"servelint: {len(names)} config(s), {status}")
    return worst, "\n".join(lines)


# ------------------------------------------------------------ --validate

# Documented tolerance of the static-vs-measured gate: the prediction
# prices ONLY the jitted tick (roofline compute/HBM + the hardware
# model's dispatch floor), while the measured open-loop bench rides the
# whole serving stack - HTTP, SSE, scheduler Python, partially-filled
# batches during ramp - so on the CPU host the measured figure sits
# well below the static ceiling. The gate requires agreement within a
# FACTOR (|log ratio| bound), not a percentage: a regression that
# breaks the cost model shows up as an order of magnitude, not a few
# percent. Calibration on the cpu-host reference bench (the
# measure_serving geometry run_validate uses) puts the static/measured
# ratio at ~17x: the static tick is ~1 ms (dispatch-floor bound) while
# the full stack delivers an effective ~17 ms/tick of scheduler+HTTP
# Python around it. Factor 32 covers that with ~2x machine-to-machine
# headroom while still failing on any order-of-magnitude cost-model
# regression; the jit-tick-only micro-bench (tests/test_servelint.py)
# sits near ratio 1 and is gated by the same factor.
VALIDATE_TOLERANCE_FACTOR = 32.0


def validate_prediction(predicted: float, measured: float,
                        tolerance_factor: float = VALIDATE_TOLERANCE_FACTOR,
                        ) -> dict:
    """The --validate verdict: static prediction vs measured tokens/s
    within a multiplicative tolerance. Pure arithmetic (testable
    without a bench run)."""
    if predicted <= 0 or measured <= 0:
        return {
            "ok": False,
            "predicted_tokens_per_s": float(predicted),
            "measured_tokens_per_s": float(measured),
            "ratio": None,
            "tolerance_factor": float(tolerance_factor),
            "why": "non-positive throughput figure",
        }
    ratio = predicted / measured
    ok = (1.0 / tolerance_factor) <= ratio <= tolerance_factor
    return {
        "ok": bool(ok),
        "predicted_tokens_per_s": float(predicted),
        "measured_tokens_per_s": float(measured),
        "ratio": round(ratio, 4),
        "tolerance_factor": float(tolerance_factor),
        "why": (
            "static prediction within the documented factor"
            if ok else
            f"static/measured ratio {ratio:.2f} outside "
            f"[1/{tolerance_factor:g}, {tolerance_factor:g}] - the "
            "cost model and the serving stack have drifted apart"
        ),
    }


def run_validate(*, hw: str = "cpu-host",
                 tolerance_factor: float = VALIDATE_TOLERANCE_FACTOR,
                 bench_row: dict | None = None,
                 **measure_kwargs):
    """Gate the static tokens/s prediction against a measured
    ``measure_serving`` row. With ``bench_row`` (a recorded bench JSON
    row carrying both figures) the comparison is offline; otherwise
    measure_serving runs in-process at a reduced geometry (a real
    HTTP+SSE open-loop run, ~a minute on the CPU host). Returns
    (exit_code, report)."""
    if bench_row is None:
        from ..train.measure import measure_serving

        kwargs = dict(
            d_model=64, n_layers=2, n_heads=4, d_ff=128, vocab=64,
            rate=16.0, requests=8, prompt_lens=(8, 16), max_new=16,
            max_batch=4, num_blocks=17, block_size=8, max_seq_len=64,
            prefill_chunk=8,
        )
        kwargs.update(measure_kwargs)
        bench_row = measure_serving(**kwargs)
    measured = float(bench_row.get("tokens_per_s") or 0.0)
    predicted = float(
        bench_row.get("static_predicted_tokens_per_s") or 0.0
    )
    verdict = validate_prediction(predicted, measured, tolerance_factor)
    lines = [
        f"servelint --validate ({hw}): static "
        f"{verdict['predicted_tokens_per_s']:,.1f} tok/s vs measured "
        f"{verdict['measured_tokens_per_s']:,.1f} tok/s "
        f"(ratio {verdict['ratio']}, tolerance x{tolerance_factor:g})",
        f"    {'OK' if verdict['ok'] else 'FAIL'}: {verdict['why']}",
    ]
    return (0 if verdict["ok"] else 1), "\n".join(lines)
