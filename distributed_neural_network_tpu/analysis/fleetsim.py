"""Fleet digital twin: a deterministic discrete-event goodput simulator
closed-loop-validated against the measured ledger.

PR 7's cost model prices a plan WITHOUT executing it; PR 10's goodput
ledger measures where wall-clock ACTUALLY went. This module connects
them: replay a supervisor policy (`train/supervisor.py SupervisorPolicy`
- the exact struct the real supervisor executes) over a synthetic
failure trace at 2..1000+ chips and emit a *predicted*, schema-compatible
goodput run record (`utils/goodput.py` taxonomy, capacity-seconds like
the fleet aggregation). Every robustness knob - checkpoint cadence,
restart budget, backoff, min-procs, grow hysteresis - becomes a search
problem for a fleet we don't own (ROADMAP item 5; failure-aware
efficiency as the first-class metric per arXiv 2204.06514, reshard and
restart costs as modeled quantities per arXiv 2112.01075).

**Inputs, in preference order:**

- *measured distributions* (`utils/goodput.py extract_distributions`,
  ``tools/goodput.py --distributions``): restart-gap / checkpoint-save /
  reshard / init / compile / steady-step durations sampled from real
  ``run_record.json`` events - the twin draws event durations from what
  this hardware actually does;
- *cost-model step times* (`analysis/cost.py step_seconds`): a roofline
  per-step seconds estimate from a plan's byte/flop terms, for plans and
  fleets never executed - which also gives autoshard its second scoring
  axis (`rank_plans_by_goodput`): plans ranked by goodput-under-failures
  instead of steady-state bytes alone;
- *policy fallbacks* (`SimPolicy` fields) when neither exists.

**Event model.** One elastic group, mirroring the supervisor's state
machine: generations run init -> compile -> (k steps + checkpoint)
cycles; a failure event loses the work since the last durable checkpoint
(a *preemption* event writes a cooperative emergency checkpoint first,
losing nothing), consumes one unit of the restart budget with the
policy's own exponential backoff, and restarts shrunk by one - or at the
same size when the event hits rank 0, the coordinator, taking the whole
group - charging the gap at the relaunched size plus the new
generation's init+compile into ``restart_gap`` (the fleet aggregation's
reclassification rule). Below ``min_procs`` or past ``max_restarts`` the
sim aborts exactly where the supervisor would. A shrunk group grows back
to target after ``grow_after_s`` healthy seconds (planned: emergency
checkpoints, no budget, no lost work). Conservation is ASSERTED like the
ledger's: the buckets must partition simulated capacity-seconds computed
independently from the generation windows.

**Closing the loop.** ``predict_from_ledger`` replays the ACTUAL failure
history recorded in a fleet record's generation list - measured
init/compile/exogenous stalls per rank, measured step time and
checkpoint cadence - and re-derives the bucket split from the event
model alone; `compare_records` asserts sim-vs-ledger bucket agreement
within tolerance (``tools/fleetsim.py --validate``, wired into the
2-proc chaos CI job so prediction drift fails the build). The optimal
checkpoint cadence from `cadence_search` is cross-checked against the
Young/Daly first-order optimum ``sqrt(2 * delta * MTBF)`` on synthetic
Poisson traces (tests/test_fleetsim.py).

Stdlib-only (no jax, no numpy): the twin runs in the supervisor, in CI,
and on a laptop; cost-model pricing imports `.cost` lazily. Determinism
is a contract: same seed + trace + policy -> bitwise-identical record
(`random.Random` over int seeds only; no wall-clock stamps).
Semantics: docs/OBSERVABILITY.md "Fleet digital twin".
"""

from __future__ import annotations

import dataclasses
import json
import math
import random
from dataclasses import dataclass

from ..train.supervisor import SupervisorPolicy
from ..utils.goodput import (
    CAUSES,
    GOODPUT_CAUSE,
    IDLE_CAUSE,
    RECORD_VERSION,
    extract_distributions,
    fleet_goodput_record,
    record_causes,
    validate_record,
)

_INF = float("inf")


# ---------------------------------------------------------- distributions


class Distributions:
    """Empirical event-duration distributions (the ``--distributions``
    document from `utils/goodput.py extract_distributions`). ``sample``
    draws uniformly from the quantile-preserving sample list -
    deterministic given the caller's seeded `random.Random` - and falls
    back to the recorded mean, then to the caller's default."""

    def __init__(self, doc: dict | None = None):
        doc = doc or {}
        if doc and doc.get("kind") not in (None, "distributions"):
            raise ValueError(
                f"not a distributions document (kind={doc.get('kind')!r}; "
                "produce one with tools/goodput.py --distributions)"
            )
        self.doc = doc
        self.causes = dict(doc.get("causes") or {})
        self.derived = dict(doc.get("derived") or {})

    @classmethod
    def from_records(cls, records) -> "Distributions":
        return cls(extract_distributions(records))

    @classmethod
    def load(cls, path: str) -> "Distributions":
        with open(path) as f:
            return cls(json.load(f))

    def has(self, cause: str) -> bool:
        return cause in self.causes

    def mean(self, cause: str, default: float = 0.0) -> float:
        info = self.causes.get(cause)
        if not info:
            return float(default)
        return float(info.get("mean_s") or default)

    def sample(self, cause: str, rng: random.Random,
               default: float = 0.0) -> float:
        info = self.causes.get(cause)
        if not info:
            return float(default)
        xs = info.get("samples_s")
        if xs:
            return float(xs[rng.randrange(len(xs))])
        return float(info.get("mean_s") or default)

    def step_overhead_s(self, default: float = 0.0) -> float:
        return float(self.derived.get("step_overhead_s") or default)


# -------------------------------------------------------- failure traces


@dataclass(frozen=True)
class FailureEvent:
    """One machine-level event on the failure trace. ``rank`` is taken
    modulo the CURRENT group size at fire time (a chip that fails still
    fails whoever runs on it after a shrink); rank 0 is the coordinator
    - its death takes the whole group (same-size restart), matching the
    supervisor's coordinator-death semantics. ``kind`` is ``failure``
    (work since the last checkpoint is lost) or ``preemption`` (a
    SIGTERM-style eviction: the cooperative emergency checkpoint lands
    first, so no work is lost - but the restart budget is still spent,
    exactly like a PREEMPT_RC worker exit)."""

    t_s: float
    rank: int
    kind: str = "failure"


def synthesize_failure_trace(
    n_chips: int,
    *,
    rate_per_chip_per_h: float,
    horizon_s: float,
    seed: int = 0,
    preempt_fraction: float = 0.0,
) -> list:
    """A seeded Poisson failure trace: exponential inter-arrivals at the
    aggregate rate ``n_chips * rate_per_chip_per_h`` with uniform victim
    ranks. Deterministic: same arguments -> identical trace (int-seeded
    `random.Random`; never the wall clock)."""
    if n_chips < 1:
        raise ValueError(f"n_chips must be >= 1, got {n_chips}")
    rate_s = n_chips * float(rate_per_chip_per_h) / 3600.0
    if rate_s <= 0:
        return []
    rng = random.Random(int(seed) * 2654435761 % (2**31) + 17)
    events = []
    t = 0.0
    while True:
        t += rng.expovariate(rate_s)
        if t >= horizon_s:
            return events
        kind = (
            "preemption" if rng.random() < preempt_fraction else "failure"
        )
        events.append(FailureEvent(round(t, 6), rng.randrange(n_chips), kind))


# --------------------------------------------------------------- policy


@dataclass
class SimPolicy:
    """One simulated configuration: the shared `SupervisorPolicy` (the
    struct the real supervisor runs) plus the workload knobs the
    supervisor does not own - checkpoint cadence and step pricing - and
    fallback durations used only where no empirical distribution sample
    exists."""

    supervisor: SupervisorPolicy
    checkpoint_every_steps: int = 0  # 0 = never checkpoint
    step_time_s: float = 1.0
    step_overhead_s: float = 0.0  # host time between steps (idle_other)
    tokens_per_step: float = 0.0
    # fallback durations (overridden by Distributions samples)
    init_s: float = 5.0
    compile_s: float = 10.0
    checkpoint_write_s: float = 1.0
    restart_gap_s: float = 10.0
    label: str = ""

    def __post_init__(self):
        if self.checkpoint_every_steps < 0:
            raise ValueError("checkpoint_every_steps must be >= 0")
        if self.step_time_s <= 0:
            raise ValueError("step_time_s must be > 0")

    def with_(self, **changes) -> "SimPolicy":
        """A copy with knobs changed; `SupervisorPolicy` field names
        route into the nested policy, so one sweep spec can mix both
        levels (``with_(checkpoint_every_steps=200, max_restarts=8)``)."""
        sup_fields = {f.name for f in dataclasses.fields(SupervisorPolicy)}
        sup_changes = {k: v for k, v in changes.items() if k in sup_fields}
        own = {k: v for k, v in changes.items() if k not in sup_fields}
        sup = (
            dataclasses.replace(self.supervisor, **sup_changes)
            if sup_changes else self.supervisor
        )
        return dataclasses.replace(self, supervisor=sup, **own)

    def describe(self) -> dict:
        doc = {
            f.name: getattr(self, f.name)
            for f in dataclasses.fields(SimPolicy)
            if f.name != "supervisor"
        }
        doc["supervisor"] = self.supervisor.policy_dict()
        return doc


def policy_variants(base: SimPolicy, sweep: dict) -> list:
    """The cartesian product of ``{knob: [values...]}`` over a base
    policy, each labeled with its deviating knobs - the grid
    `rank_policies` (and ``tools/fleetsim.py --sweep``) ranks."""
    variants = [base]
    for knob, values in sweep.items():
        variants = [
            v.with_(**{knob: val}) for v in variants for val in values
        ]
    for v in variants:
        if not v.label:
            v.label = ",".join(
                f"{k}={_fmt_knob(v, k)}" for k in sweep
            ) or "base"
    return variants


def _fmt_knob(policy: SimPolicy, knob: str):
    sup_fields = {f.name for f in dataclasses.fields(SupervisorPolicy)}
    src = policy.supervisor if knob in sup_fields else policy
    v = getattr(src, knob)
    return f"{v:g}" if isinstance(v, float) else v


# ------------------------------------------------------------- simulator


class _Sim:
    """One simulation run's state; `simulate()` is the public face."""

    def __init__(self, policy, trace, dists, horizon_s, target_steps, seed):
        self.p = policy
        self.sup = policy.supervisor
        self.dists = dists or Distributions()
        self.rng = random.Random((int(seed) * 1000003 + 1) % (2**31))
        self.horizon = float(horizon_s)
        self.target = target_steps
        self.events = sorted(trace, key=lambda e: (e.t_s, e.rank))
        self.ei = 0
        self.t = 0.0
        self.n = self.sup.nprocs
        self.gen = -1
        self.buckets = {c: 0.0 for c in CAUSES}
        self.wall_check = 0.0
        self.steps_executed = 0
        self.steps_done = 0  # unique frontier (reverts on lost work)
        self.last_ckpt = 0
        self.tokens = 0.0
        self.lost_steps = 0
        self.lost_capacity_s = 0.0
        self.restarts_used = 0
        self.failures_seen = 0
        self.preemptions_seen = 0
        self.grows = 0
        self.events_in_gaps = 0
        self.gaps = []
        self.aborted = None
        self.restart_reason = None

    # -------------------------------------------------------- primitives

    def charge(self, cause: str, dur: float) -> None:
        if dur > 0:
            self.buckets[cause] += dur * self.n

    def next_event_t(self) -> float:
        return self.events[self.ei].t_s if self.ei < len(self.events) else _INF

    def run_segment(self, cause: str, dur: float) -> str:
        """Advance through one non-step segment; a failure event or the
        horizon may interrupt it (the elapsed part is still charged)."""
        end = self.t + max(dur, 0.0)
        stop = min(self.next_event_t(), self.horizon)
        if end <= stop:
            self.charge(cause, end - self.t)
            self.t = end
            return "ok"
        self.charge(cause, max(stop - self.t, 0.0))
        self.t = stop
        return "horizon" if stop >= self.horizon else "failure"

    def charge_steps(self, m: int) -> None:
        self.charge(GOODPUT_CAUSE, m * self.p.step_time_s)
        self.charge(IDLE_CAUSE, m * self.p.step_overhead_s)
        self.steps_executed += m
        self.steps_done += m
        self.tokens += m * self.p.tokens_per_step

    def emergency_checkpoint(self) -> str:
        """Cooperative save before a planned stop / preemption exit: the
        unique-step frontier becomes durable."""
        ck = self.dists.sample(
            "checkpoint_save", self.rng, self.p.checkpoint_write_s
        )
        st = self.run_segment("checkpoint_save", ck)
        if st != "failure":
            self.last_ckpt = self.steps_done
        return st

    # -------------------------------------------------------- generation

    def run_gen(self):
        """One generation, start to teardown. Returns (status, event):
        status in done|horizon|failure|grow; on failure the event is
        consumed and lost work / the preemption checkpoint is already
        accounted - the restart DECISION belongs to the outer loop."""
        self.gen += 1
        gen_t0 = self.t
        n0 = self.n
        # events that fired while no worker existed hit nobody
        while self.ei < len(self.events) and self.events[self.ei].t_s <= self.t:
            self.ei += 1
            self.events_in_gaps += 1
        # a failure-relaunched generation's init+compile is restart cost
        # (the fleet aggregation's reclassification rule)
        startup_cause = (
            "restart_gap" if self.restart_reason == "failure" else None
        )
        st = self.run_segment(
            startup_cause or "init",
            self.dists.sample("init", self.rng, self.p.init_s),
        )
        if st == "ok":
            st = self.run_segment(
                startup_cause or "compile",
                self.dists.sample("compile", self.rng, self.p.compile_s),
            )
        healthy_t = self.t
        since_ckpt = 0
        k = self.p.checkpoint_every_steps
        cyc = self.p.step_time_s + self.p.step_overhead_s
        grow_t = (
            healthy_t + self.sup.grow_after_s
            if self.sup.grow_after_s > 0 and self.n < self.sup.nprocs
            else _INF
        )
        while st == "ok":
            if self.target is not None and self.steps_done >= self.target:
                st = "done"
                break
            if self.t >= grow_t:
                st = "grow"
                break
            rem = (
                self.target - self.steps_done
                if self.target is not None else None
            )
            r = k - since_ckpt if k > 0 else (rem if rem is not None else 4096)
            if rem is not None:
                r = min(r, rem)
            r = max(int(r), 1)
            stop = min(self.next_event_t(), self.horizon, grow_t)
            if self.t + r * cyc <= stop:
                self.charge_steps(r)
                self.t += r * cyc
                since_ckpt += r
                if k > 0 and since_ckpt >= k and not (
                    self.target is not None and self.steps_done >= self.target
                ):
                    st = self.run_segment(
                        "checkpoint_save",
                        self.dists.sample(
                            "checkpoint_save", self.rng,
                            self.p.checkpoint_write_s,
                        ),
                    )
                    if st == "ok":
                        self.last_ckpt = self.steps_done
                        since_ckpt = 0
                continue
            # an event/horizon/grow boundary lands inside the block
            avail = max(stop - self.t, 0.0)
            full = min(int(avail // cyc), r)
            if full > 0:
                self.charge_steps(full)
                since_ckpt += full
            part = avail - full * cyc
            if part > 0:
                # the interrupted step's partial wall was real compute;
                # it completed no step, so no progress is counted
                self.charge(GOODPUT_CAUSE, part)
            self.t = stop
            if stop >= self.horizon:
                st = "horizon"
            elif stop >= grow_t and stop < self.next_event_t():
                st = "grow"
            else:
                st = "failure"
        ev = None
        if st == "failure":
            ev = self.events[self.ei]
            self.ei += 1
            if ev.kind == "preemption":
                self.preemptions_seen += 1
                sub = self.emergency_checkpoint()
                if sub == "horizon":
                    st = "horizon"
            else:
                self.failures_seen += 1
                lost = self.steps_done - self.last_ckpt
                if lost > 0:
                    self.lost_steps += lost
                    self.lost_capacity_s += lost * self.p.step_time_s * n0
                    self.steps_done = self.last_ckpt
        elif st == "grow":
            sub = self.emergency_checkpoint()
            if sub == "horizon":
                st = "horizon"
            elif sub == "failure":
                st = "failure-during-grow"
        self.wall_check += (self.t - gen_t0) * n0
        return st, ev

    # -------------------------------------------------------------- run

    def run(self) -> dict:
        while True:
            st, ev = self.run_gen()
            if st in ("done", "horizon"):
                break
            if st == "grow":
                self.grows += 1
                # teardown -> respawn with no worker alive: the ledger
                # never measures this window for PLANNED restarts (no
                # restart_gaps entry), so no capacity is charged
                self.t += self.dists.sample(
                    "restart_gap", self.rng, self.p.restart_gap_s
                )
                self.n = self.sup.nprocs
                self.restart_reason = "grow"
                continue
            if st == "failure-during-grow":
                # the grow teardown collided with a failure event: the
                # emergency checkpoint did not land, so work since the
                # last durable one is lost - then the failure path runs
                ev = self.events[self.ei]
                self.ei += 1
                self.failures_seen += 1
                lost = self.steps_done - self.last_ckpt
                if lost > 0:
                    self.lost_steps += lost
                    self.lost_capacity_s += (
                        lost * self.p.step_time_s * self.n
                    )
                    self.steps_done = self.last_ckpt
            # ---- the supervisor's restart decision
            self.restarts_used += 1
            if self.restarts_used > self.sup.max_restarts:
                self.aborted = (
                    f"restart budget ({self.sup.max_restarts}) exhausted"
                )
                break
            whole_group = ev is not None and (ev.rank % self.n) == 0
            new_n = self.n if whole_group else self.n - 1
            if new_n < self.sup.min_procs:
                self.aborted = (
                    f"only {new_n} worker(s) survive but min_procs is "
                    f"{self.sup.min_procs}"
                )
                break
            pause = self.sup.backoff_for(self.restarts_used)
            gap = pause + self.dists.sample(
                "restart_gap", self.rng, self.p.restart_gap_s
            )
            gap = min(gap, max(self.horizon - self.t, 0.0))
            self.n = new_n
            self.charge("restart_gap", gap)
            self.wall_check += gap * new_n
            self.gaps.append({
                "seconds": round(gap, 6), "group_size": new_n,
                "generation": self.gen + 1, "backoff_s": round(pause, 6),
            })
            self.t += gap
            self.restart_reason = "failure"
            if self.t >= self.horizon:
                break
        return self.record()

    def record(self) -> dict:
        buckets = self.buckets
        wall = sum(buckets.values())
        if any(v < 0 for v in buckets.values()) or (
            abs(wall - self.wall_check) > max(1e-6 * max(wall, 1.0), 1e-9)
        ):
            raise AssertionError(
                "fleetsim conservation violated: buckets sum to "
                f"{wall:.9f} capacity-seconds but the generation windows "
                f"cover {self.wall_check:.9f} "
                f"({json.dumps({k: round(v, 6) for k, v in buckets.items()})})"
                " - a segment was charged twice or skipped; this is a "
                "simulator bug, please report it"
            )
        goodput = buckets[GOODPUT_CAUSE]
        effective = max(goodput - self.lost_capacity_s, 0.0)
        return {
            "version": RECORD_VERSION,
            "kind": "sim",
            "final": True,
            "steps": self.steps_executed,
            "goodput_steps": self.steps_executed,
            "tokens": round(self.tokens, 6),
            "wall_s": round(wall, 6),
            "goodput_s": round(goodput, 6),
            "goodput_ratio": round(goodput / wall, 6) if wall > 0 else None,
            "badput_s": {
                c: round(buckets[c], 6) for c in CAUSES
                if c != GOODPUT_CAUSE
            },
            "restart_gaps": self.gaps,
            "metrics": {
                "unique_steps": self.steps_done,
                "lost_steps": self.lost_steps,
                "lost_step_capacity_s": round(self.lost_capacity_s, 6),
                "effective_goodput_ratio": round(effective / wall, 6)
                if wall > 0 else None,
                "aborted": self.aborted is not None,
                "abort_reason": self.aborted,
                "restarts_used": self.restarts_used,
                "generations": self.gen + 1,
                "failures_seen": self.failures_seen,
                "preemptions_seen": self.preemptions_seen,
                "grows": self.grows,
                "events_in_gaps": self.events_in_gaps,
                "final_group_size": self.n,
                "horizon_s": self.horizon,
            },
        }


def simulate(
    policy: SimPolicy,
    trace,
    dists: Distributions | None = None,
    *,
    horizon_s: float,
    target_steps: int | None = None,
    seed: int = 0,
) -> dict:
    """Run one policy over one failure trace and return the predicted
    schema-compatible run record (``kind: "sim"``; renderable, diffable,
    and gateable by ``tools/goodput.py`` like any measured record).

    ``goodput_ratio`` mirrors the LEDGER's definition (every executed
    steady step counts, replays included - what a measured record would
    report); ``metrics.effective_goodput_ratio`` additionally subtracts
    the capacity-seconds of steps whose progress a later failure erased
    - the quantity policy search actually maximizes. Deterministic:
    same (policy, trace, seed) -> bitwise-identical record."""
    sim = _Sim(policy, trace, dists, horizon_s, target_steps, seed)
    rec = sim.run()
    rec["sim"] = {
        "mode": "forward",
        "seed": int(seed),
        "n_events": len(sim.events),
        "policy": policy.describe(),
    }
    return rec


# ------------------------------------------------------- policy ranking


def effective_ratio(rec: dict) -> float:
    v = (rec.get("metrics") or {}).get("effective_goodput_ratio")
    if v is None:
        v = rec.get("goodput_ratio")
    return float(v or 0.0)


def rank_policies(
    policies,
    dists: Distributions | None = None,
    *,
    n_chips: int,
    rate_per_chip_per_h: float,
    horizon_s: float,
    preempt_fraction: float = 0.0,
    seeds=(0, 1, 2),
) -> list:
    """Simulate every policy over the SAME seeded traces (common random
    numbers - policy deltas are not drowned by trace noise) and rank by
    mean effective goodput ratio, aborting policies last. Returns
    ``[{label, policy, effective_goodput_ratio, goodput_ratio, aborted,
    record}, ...]`` best first; ``record`` is the first seed's."""
    traces = [
        synthesize_failure_trace(
            n_chips, rate_per_chip_per_h=rate_per_chip_per_h,
            horizon_s=horizon_s, seed=s,
            preempt_fraction=preempt_fraction,
        )
        for s in seeds
    ]
    out = []
    for policy in policies:
        recs = [
            simulate(policy, tr, dists, horizon_s=horizon_s, seed=s)
            for s, tr in zip(seeds, traces)
        ]
        aborted = any(r["metrics"]["aborted"] for r in recs)
        out.append({
            "label": policy.label or "policy",
            "policy": policy.describe(),
            "effective_goodput_ratio": round(
                sum(effective_ratio(r) for r in recs) / len(recs), 6
            ),
            "goodput_ratio": round(
                sum(float(r.get("goodput_ratio") or 0.0) for r in recs)
                / len(recs), 6
            ),
            "aborted": aborted,
            "record": recs[0],
        })
    out.sort(key=lambda d: (d["aborted"], -d["effective_goodput_ratio"]))
    return out


# ------------------------------------------------------- cadence search


def young_daly_interval(mtbf_s: float, checkpoint_s: float) -> float:
    """The Young/Daly first-order optimal checkpoint interval
    ``sqrt(2 * delta * M)`` (seconds of work between checkpoints) for
    checkpoint cost ``delta`` and group MTBF ``M``."""
    return math.sqrt(2.0 * float(checkpoint_s) * float(mtbf_s))


def cadence_search(
    policy: SimPolicy,
    dists: Distributions | None = None,
    *,
    rate_per_chip_per_h: float,
    horizon_s: float,
    cadences=None,
    seeds=(0, 1),
    grid_ratio: float = 1.15,
) -> dict:
    """Derive the optimal checkpoint cadence for a policy by simulation,
    cross-checked against the Young/Daly approximation.

    The knob is isolated from elasticity: every synthesized event is
    remapped to rank 0 (whole-group, same-size restarts - the classic
    single-domain model Young/Daly assumes) and the restart budget is
    lifted. The default cadence grid is geometric between the checkpoint
    cost and the group MTBF (the a-priori bracket of the optimum).
    Returns ``{"results", "best", "young_daly"}`` where ``results`` is
    ``[(cadence_steps, interval_s, mean_effective_ratio), ...]``."""
    sup = dataclasses.replace(
        policy.supervisor, max_restarts=10**9, grow_after_s=0.0
    )
    base = dataclasses.replace(policy, supervisor=sup)
    n = sup.nprocs
    mtbf_s = 3600.0 / (n * rate_per_chip_per_h)
    delta = (dists or Distributions()).mean(
        "checkpoint_save", policy.checkpoint_write_s
    )
    cyc = policy.step_time_s + policy.step_overhead_s
    if cadences is None:
        cadences = []
        tau = max(delta, cyc)
        while tau <= mtbf_s:
            k = max(int(round(tau / cyc)), 1)
            if not cadences or k != cadences[-1]:
                cadences.append(k)
            tau *= grid_ratio
    traces = [
        [
            FailureEvent(e.t_s, 0, e.kind)
            for e in synthesize_failure_trace(
                n, rate_per_chip_per_h=rate_per_chip_per_h,
                horizon_s=horizon_s, seed=s,
            )
        ]
        for s in seeds
    ]
    results = []
    for k in cadences:
        cand = base.with_(checkpoint_every_steps=int(k))
        ratios = [
            effective_ratio(
                simulate(cand, tr, dists, horizon_s=horizon_s, seed=s)
            )
            for s, tr in zip(seeds, traces)
        ]
        results.append((
            int(k), round(k * cyc, 6),
            round(sum(ratios) / len(ratios), 6),
        ))
    best = max(results, key=lambda r: r[2]) if results else None
    yd_s = young_daly_interval(mtbf_s, delta)
    return {
        "results": results,
        "best": best,
        "young_daly": {
            "interval_s": round(yd_s, 6),
            "cadence_steps": max(int(round(yd_s / cyc)), 1),
            "mtbf_s": round(mtbf_s, 6),
            "checkpoint_s": round(delta, 6),
            "ratio_vs_best": round(best[1] / yd_s, 6)
            if best and yd_s > 0 else None,
        },
    }


# --------------------------------------------- closing the loop (validate)


def _fill_window(avail_s: float, step_s: float, overhead_s: float,
                 k: int, ck_mean_s: float):
    """The shared cadence arithmetic: how many steps + periodic
    checkpoints fit in ``avail_s`` seconds at ``step_s`` + per-step host
    ``overhead_s``, checkpointing every ``k`` steps at ``ck_mean_s``.
    Returns ``(steps, steady_s, checkpoint_s, idle_s)`` partitioning
    ``avail_s`` exactly."""
    if avail_s <= 0 or step_s <= 0:
        return 0, 0.0, 0.0, max(avail_s, 0.0)
    cyc = step_s + overhead_s
    if k > 0 and ck_mean_s > 0:
        block = k * cyc + ck_mean_s
        full = int(avail_s // block)
        rem = avail_s - full * block
        steps = full * k + min(int(rem // cyc), k)
        ckpts = full
    else:
        steps = int(avail_s // cyc)
        ckpts = 0
    steady = steps * step_s
    ck = ckpts * ck_mean_s
    return steps, steady, ck, max(avail_s - steady - ck, 0.0)


# badput causes the sim cannot predict from policy alone (injected chaos,
# input pipeline, elastic resharding, guard replays): replayed as
# exogenous inputs in validation so conservation closes
EXOGENOUS_CAUSES = ("stall", "data_wait", "reshard", "rollback_recompute")


def _predict_rank(rec: dict) -> dict:
    """Re-derive one rank record's bucket split from the event model +
    the record's own measured inputs (wall window, init/compile, mean
    step time, checkpoint cadence, exogenous chaos): the closed-loop
    consistency check - if the sim's cycle arithmetic or taxonomy
    semantics drift from the ledger's, the prediction diverges."""
    bad = dict(rec.get("badput_s") or {})
    events = rec.get("events") or {}
    wall = float(rec.get("wall_s") or 0.0)
    steps = int(rec.get("steps") or 0)
    gsteps = int(rec.get("goodput_steps") or 0)
    steady_ev = events.get("steady_step") or {}
    step_s = float(steady_ev.get("mean_s") or 0.0)
    if step_s <= 0 and gsteps > 0:
        step_s = float(rec.get("goodput_s") or 0.0) / gsteps
    init_s = float(bad.get("init") or 0.0)
    compile_s = float(bad.get("compile") or 0.0)
    exo = {c: float(bad.get(c) or 0.0) for c in EXOGENOUS_CAUSES}
    ck_ev = events.get("checkpoint_save") or {}
    ck_mean = float(ck_ev.get("mean_s") or 0.0)
    cfg = rec.get("config") or {}
    try:
        k = int(cfg.get("checkpoint_every") or 0)
    except (TypeError, ValueError):
        k = 0
    overhead = (
        float(bad.get(IDLE_CAUSE) or 0.0) / steps if steps > 0 else 0.0
    )
    avail = max(wall - init_s - compile_s - sum(exo.values()), 0.0)
    if ck_mean > 0 and k <= 0:
        # saves observed but no cadence recorded (non-lm CLI): price the
        # measured saves directly and fill the rest with steps
        ck_total = float(ck_ev.get("total_s") or 0.0)
        avail = max(avail - ck_total, 0.0)
        steps_pred, steady_s, _, idle_s = _fill_window(
            avail, step_s, overhead, 0, 0.0
        )
        ckpt_s = ck_total
    else:
        steps_pred, steady_s, ckpt_s, idle_s = _fill_window(
            avail, step_s, overhead, k, ck_mean
        )
    badput = {c: 0.0 for c in CAUSES if c != GOODPUT_CAUSE}
    badput.update({
        "init": round(init_s, 6),
        "compile": round(compile_s, 6),
        "checkpoint_save": round(ckpt_s, 6),
        IDLE_CAUSE: round(idle_s, 6),
    })
    badput.update({c: round(v, 6) for c, v in exo.items()})
    return {
        "version": RECORD_VERSION,
        "kind": "rank",
        "final": rec.get("final"),
        "rank": rec.get("rank"),
        "generation": rec.get("generation"),
        "steps": steps_pred,
        "goodput_steps": steps_pred,
        "tokens": 0.0,
        "wall_s": round(wall, 6),
        "goodput_s": round(steady_s, 6),
        "goodput_ratio": round(steady_s / wall, 6) if wall > 0 else None,
        "badput_s": badput,
    }


def predict_from_ledger(fleet_record: dict, rank_records) -> dict:
    """Replay the ACTUAL failure history a fleet record captured - its
    generation list, per-rank windows, and measured restart gaps -
    through the sim's event model, returning the predicted fleet record
    (``kind: "sim"``). Agreement with the measured record (within
    `compare_records` tolerances) is the closed-loop validation the CI
    chaos job gates on."""
    fleet = validate_record(fleet_record, "fleet record")
    gaps = list(fleet.get("restart_gaps") or ())
    restart_gens = {
        int(g["generation"]) for g in gaps
        if isinstance(g.get("generation"), int)
    }
    preds = [_predict_rank(validate_record(r)) for r in rank_records]
    if not preds:
        raise ValueError(
            "no rank records to replay (need the run dir's "
            "records/gen{g}_rank{r}.json write-through records)"
        )
    pred = fleet_goodput_record(
        preds, restart_gaps=gaps, restart_generations=restart_gens
    )
    pred["kind"] = "sim"
    pred["sim"] = {"mode": "validate", "n_rank_records": len(preds)}
    return pred


def compare_records(
    predicted: dict, measured: dict, *,
    ratio_tol: float = 0.1, share_tol: float = 0.1,
) -> list:
    """Bucket-level agreement check: |predicted - measured| of
    ``goodput_ratio`` within ``ratio_tol`` and of every cause's
    wall-clock SHARE within ``share_tol`` (absolute, both directions -
    the sim must neither flatter nor slander a bucket). Returns
    violation strings, empty = agree."""
    problems = []
    rp = predicted.get("goodput_ratio")
    rm = measured.get("goodput_ratio")
    if rp is None or rm is None:
        problems.append(
            "goodput_ratio missing from "
            + ("the prediction" if rp is None else "the measured record")
        )
    elif abs(rp - rm) > ratio_tol:
        problems.append(
            f"goodput_ratio: predicted {rp:.4f} vs measured {rm:.4f} "
            f"(|diff| {abs(rp - rm):.4f} > tol {ratio_tol:.3f})"
        )
    cp, cm = record_causes(predicted), record_causes(measured)
    tp = float(predicted.get("wall_s") or 0.0)
    tm = float(measured.get("wall_s") or 0.0)
    for c in sorted(set(list(cp) + list(cm))):
        sp = cp.get(c, 0.0) / tp if tp > 0 else 0.0
        sm = cm.get(c, 0.0) / tm if tm > 0 else 0.0
        if abs(sp - sm) > share_tol:
            problems.append(
                f"bucket '{c}': predicted share {sp:.2%} vs measured "
                f"{sm:.2%} (|diff| {abs(sp - sm):.2%} > tol "
                f"{share_tol:.2%})"
            )
    return problems


# --------------------------------------- autoshard's second scoring axis


def rank_plans_by_goodput(
    plan_docs,
    policy: SimPolicy,
    dists: Distributions | None = None,
    *,
    hw=None,
    flops_per_step: float = 0.0,
    rate_per_chip_per_h: float,
    horizon_s: float,
    seeds=(0, 1),
) -> list:
    """Rank autoshard plan manifests (``analysis/plans/*.json`` docs) by
    predicted goodput-under-failures instead of steady-state bytes: each
    plan's ``chosen`` byte terms are priced into per-step seconds by
    `analysis.cost.step_seconds` (the only lazy non-stdlib hop), then
    every plan is simulated over the SAME seeded failure traces under
    ``policy``.

    The ranking metric is **surviving progress per capacity-second**
    (``progress_steps_per_cap_s``: unique steps whose work no failure
    erased, over fleet capacity-seconds) - NOT the time-fraction
    ``goodput_ratio``, which cannot tell plans apart (a faster step does
    not earn a larger SHARE of wall-clock, it earns more steps per
    second; with a step-cadenced checkpoint policy a slower plan can
    even post a higher time-fraction by checkpointing less often per
    hour while making far less progress). Comparable across plans that
    share the global batch. Returns ``[{plan, config, step_s, step_why,
    progress_steps_per_cap_s, effective_goodput_ratio, goodput_ratio,
    score}, ...]`` best first."""
    from .cost import step_seconds

    candidates = []
    for doc in plan_docs:
        chosen = doc.get("chosen") if isinstance(doc, dict) else None
        if not chosen:
            raise ValueError(
                "not an autoshard plan manifest (no 'chosen' block); "
                "generate one with tools/autoshard.py --write-manifest"
            )
        st = step_seconds(chosen, hw, flops_per_step=flops_per_step)
        cand = policy.with_(step_time_s=max(st.step_s, 1e-9))
        cand.label = str(chosen.get("plan") or doc.get("config") or "plan")
        candidates.append((doc, chosen, st, cand))
    traces = [
        synthesize_failure_trace(
            policy.supervisor.nprocs,
            rate_per_chip_per_h=rate_per_chip_per_h,
            horizon_s=horizon_s, seed=s,
        )
        for s in seeds
    ]
    out = []
    for doc, chosen, st, cand in candidates:
        recs = [
            simulate(cand, tr, dists, horizon_s=horizon_s, seed=s)
            for s, tr in zip(seeds, traces)
        ]
        progress = [
            r["metrics"]["unique_steps"] / r["wall_s"]
            if r["wall_s"] > 0 else 0.0
            for r in recs
        ]
        out.append({
            "plan": cand.label,
            "config": doc.get("config"),
            "step_s": round(st.step_s, 9),
            "step_why": st.why(),
            "progress_steps_per_cap_s": round(
                sum(progress) / len(progress), 9
            ),
            "effective_goodput_ratio": round(
                sum(effective_ratio(r) for r in recs) / len(recs), 6
            ),
            "goodput_ratio": round(
                sum(float(r.get("goodput_ratio") or 0.0) for r in recs)
                / len(recs), 6
            ),
            "aborted": any(r["metrics"]["aborted"] for r in recs),
            "score": chosen.get("score"),
        })
    out.sort(
        key=lambda d: (d["aborted"], -d["progress_steps_per_cap_s"])
    )
    return out
